"""Sequence-parallelism tests: ring attention and Ulysses all_to_all
attention must equal single-device full attention on the concatenated
sequence (values AND gradients) — the reference test suite's distributed ==
single-process invariant (SURVEY.md section 4) applied to the new
long-context layer (section 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
)
from chainermn_tpu.parallel.ring_attention import make_ring_attention
from chainermn_tpu.parallel.ulysses import make_ulysses_attention

B, T, H, D = 2, 32, 8, 16  # T sharded 8-ways -> T_local = 4


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestLocalAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_matches_full(self, causal):
        q, k, v = _qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        blk = blockwise_attention(q, k, v, block_k=8, causal=causal)
        np.testing.assert_allclose(blk, ref, rtol=1e-5, atol=1e-5)

    def test_blockwise_grads_match_full(self):
        q, k, v = _qkv(1)

        def loss_ref(q, k, v):
            return dot_product_attention(q, k, v, causal=True).sum()

        def loss_blk(q, k, v):
            return blockwise_attention(q, k, v, block_k=8, causal=True).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4),
            g_blk,
            g_ref,
        )


class TestRingAttention:
    """Both impls must satisfy the distributed == single-device invariant:
    'einsum' is the autodiff reference; 'flash' is the Pallas block-kernel
    path with the hand-written ring backward (the production path)."""

    @pytest.mark.parametrize("impl", ["einsum", "flash"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, comm, causal, impl):
        q, k, v = _qkv(2)
        ref = dot_product_attention(q, k, v, causal=causal)

        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=causal, impl=impl
        )
        sharding = NamedSharding(comm.mesh, P(None, comm.axis_name))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("impl", ["einsum", "flash"])
    def test_grads_match_full_attention(self, comm, impl):
        q, k, v = _qkv(3)
        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=True, impl=impl
        )

        def loss_ring(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_ring,
            g_ref,
        )

    def test_zigzag_matches_full_attention(self, comm):
        """Zigzag layout (balanced causal ring): same values as dense causal
        attention on the ORIGINAL sequence order — ``make_ring_attention``
        converts to chunk-pair order and back internally."""
        q, k, v = _qkv(5)
        ref = dot_product_attention(q, k, v, causal=True)
        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=True, layout="zigzag"
        )
        sharding = NamedSharding(comm.mesh, P(None, comm.axis_name))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        np.testing.assert_allclose(
            np.asarray(fn(qs, ks, vs)), ref, rtol=1e-5, atol=1e-5
        )

    def test_zigzag_grads_match_full_attention(self, comm):
        q, k, v = _qkv(6)
        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=True, layout="zigzag"
        )

        def loss_ring(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_ring,
            g_ref,
        )

    def test_zigzag_layout_roundtrip(self):
        from chainermn_tpu.parallel.ring_attention import (
            from_zigzag,
            to_zigzag,
            zigzag_indices,
        )

        x = jnp.arange(64, dtype=jnp.float32).reshape(1, 32, 2)
        zz = to_zigzag(x, 8, axis=1)
        np.testing.assert_array_equal(np.asarray(from_zigzag(zz, 8, axis=1)),
                                      np.asarray(x))
        idx = zigzag_indices(4, 32)
        # shard 0 of 4 holds chunks 0 and 7 of 8 (chunk size 4)
        np.testing.assert_array_equal(idx[:8], [0, 1, 2, 3, 28, 29, 30, 31])

    def test_zigzag_requires_causal_flash(self, comm):
        from chainermn_tpu.parallel.ring_attention import ring_attention_local

        q = jnp.zeros((1, 4, 1, 8))
        with pytest.raises(ValueError, match="zigzag"):
            ring_attention_local(q, q, q, "seq", causal=False, layout="zigzag")
        with pytest.raises(ValueError, match="zigzag"):
            ring_attention_local(q, q, q, "seq", causal=True, impl="einsum",
                                 layout="zigzag")

    @pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
    def test_segment_ids_match_masked_dense(self, comm, layout):
        """Packed sequences across the ring: segment ids travel with their
        K/V blocks, so cross-document attention is masked even when the
        documents span shard boundaries. Values AND grads vs the dense
        masked reference."""
        q, k, v = _qkv(7)
        rng = np.random.RandomState(2)
        seg = np.zeros((B, T), np.int32)
        for b in range(B):
            cuts = sorted(rng.choice(np.arange(2, T - 2), 2, replace=False))
            seg[b, cuts[0]:cuts[1]] = 1
            seg[b, cuts[1]:] = 2
        seg = jnp.asarray(seg)

        fn = make_ring_attention(
            comm.mesh, comm.axis_name, causal=True, layout=layout,
            with_segments=True,
        )

        def loss_ring(q, k, v):
            return (fn(q, k, v, seg) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(
                q, k, v, causal=True, segment_ids=seg) ** 2).sum()

        np.testing.assert_allclose(
            np.asarray(fn(q, k, v, seg)),
            np.asarray(dot_product_attention(q, k, v, causal=True,
                                             segment_ids=seg)),
            rtol=1e-5, atol=1e-5,
        )
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_ring,
            g_ref,
        )

    def test_gqa_zigzag_grads(self, comm):
        """GQA × zigzag layout: the backward's zero-pads must use the KV
        head count where dk/dv concatenate (regression: q-head-shaped pads
        crashed the trace)."""
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, 2, D))
        v = jax.random.normal(ks[2], (B, T, 2, D))
        fn = make_ring_attention(comm.mesh, comm.axis_name, causal=True,
                                 layout="zigzag")
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v)),
            np.asarray(dot_product_attention(q, k, v, causal=True)),
            rtol=1e-5, atol=1e-5,
        )
        g = jax.grad(lambda a, b, c: (fn(a, b, c) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: (dot_product_attention(
                a, b, c, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )

    def test_gqa_kv_heads_rotate_small(self, comm):
        """GQA through the ring: kv blocks rotate at their own (smaller)
        head count; output matches the dense GQA reference."""
        ks = jax.random.split(jax.random.PRNGKey(9), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, 2, D))
        v = jax.random.normal(ks[2], (B, T, 2, D))
        fn = make_ring_attention(comm.mesh, comm.axis_name, causal=True)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(fn(q, k, v)), ref,
                                   rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda a, b, c: (fn(a, b, c) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: (dot_product_attention(
                a, b, c, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )

    def test_bf16_inputs_f32_accumulation(self, comm):
        q, k, v = _qkv(4, jnp.bfloat16)
        fn = make_ring_attention(comm.mesh, comm.axis_name)
        out = fn(q, k, v)
        assert out.dtype == jnp.bfloat16
        ref = dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), ref, rtol=2e-2, atol=2e-2
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, comm, causal):
        q, k, v = _qkv(5)
        ref = dot_product_attention(q, k, v, causal=causal)
        fn = make_ulysses_attention(comm.mesh, comm.axis_name, causal=causal)
        out = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    def test_grads_match_full_attention(self, comm):
        q, k, v = _qkv(6)
        fn = make_ulysses_attention(comm.mesh, comm.axis_name, causal=True)

        def loss_u(q, k, v):
            return (fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), b, rtol=1e-4, atol=1e-4
            ),
            g_u,
            g_ref,
        )

    def test_head_divisibility_enforced(self, comm):
        # H=6 not divisible by the 8-way axis
        q = jnp.zeros((B, T, 6, D))
        fn = make_ulysses_attention(comm.mesh, comm.axis_name)
        with pytest.raises(ValueError, match="not divisible"):
            fn(q, q, q)

    def test_segment_ids_match_masked_dense(self, comm):
        """Packed segments through Ulysses: local id slices are
        all-gathered for the head-sharded full-sequence kernel."""
        q, k, v = _qkv(8)
        rng = np.random.RandomState(3)
        seg = np.zeros((B, T), np.int32)
        for b in range(B):
            cut = rng.randint(4, T - 4)
            seg[b, cut:] = 1
        seg = jnp.asarray(seg)
        fn = make_ulysses_attention(
            comm.mesh, comm.axis_name, causal=True, with_segments=True
        )
        ref = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(fn(q, k, v, seg)), ref,
                                   rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda a, b_, c: (fn(a, b_, c, seg) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda a, b_, c: (dot_product_attention(
                a, b_, c, causal=True, segment_ids=seg) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b_: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )

    def test_gqa_kv_heads_reshard(self, comm):
        """GQA through Ulysses: 16 q heads with 8 kv heads (== axis size,
        the minimum reshardable count) — the reshard must keep head groups
        aligned with the kernel's kv-sharing index map. Values AND grads."""
        ks = jax.random.split(jax.random.PRNGKey(10), 3)
        q = jax.random.normal(ks[0], (B, T, 16, D))
        k = jax.random.normal(ks[1], (B, T, 8, D))
        v = jax.random.normal(ks[2], (B, T, 8, D))
        fn = make_ulysses_attention(comm.mesh, comm.axis_name, causal=True)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(fn(q, k, v)), ref,
                                   rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda a, b_, c: (fn(a, b_, c) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda a, b_, c: (dot_product_attention(
                a, b_, c, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b_: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )
        # a kv head count below the axis size is rejected with a clear error
        k2 = jnp.zeros((B, T, 2, D))
        with pytest.raises(ValueError, match="kv heads"):
            fn(q, k2, k2)


def test_zigzag_causal_work_is_balanced():
    """Structural evidence for VERDICT r2 item 4's done-criterion: under
    the zigzag layout every (shard, ring-step) dispatches to a branch
    costing the SAME 2 chunk-squared score evaluations, so causal-ring
    wall clock is the per-step constant times n — not the last shard's
    full-n work as in the contiguous layout. (Wall-clock itself is not
    honestly measurable on virtual CPU devices; the dispatch arithmetic
    is what the kernel schedule executes.)

    Uses the implementation's own `_zz_branch` dispatch; branch costs in
    chunk^2 units read off the kernel calls in
    `_zigzag_ring_flash_fwd_impl`: _past = full q x front kv = 2;
    _diag = 0.5 + 1 + 0.5 = 2; _future = back q x full kv = 2.
    """
    from chainermn_tpu.parallel.ring_attention import _zz_branch

    for n in (2, 4, 8):
        for my in range(n):
            hist = {0: 0, 1: 0, 2: 0}  # _past, _diag, _future
            for s in range(n):
                hist[int(_zz_branch(jnp.int32(my), jnp.int32(s), n))] += 1
            # Shard `my` must dispatch: `my` past steps, exactly ONE
            # diagonal, and n-1-my future steps — pinning the dispatch
            # itself, from which the constant cost follows (branch costs
            # read off the kernel calls are past=2, diag=0.5+1+0.5=2,
            # future=2 chunk^2, so any histogram summing to n gives the
            # same total; the histogram is the discriminating check).
            assert hist == {0: my, 1: 1, 2: n - 1 - my}, (n, my, hist)
    # (Contrast, not executable here: the CONTIGUOUS layout's causal ring
    # — step() at ring_attention.py:151 — gives shard s a cost of s full
    # blocks + 1 diagonal, a 15x last-vs-first spread at n=8; that is the
    # imbalance the zigzag layout removes.)


class TestSlidingWindowSP:
    """O(1)-communication sequence-parallel local attention: one neighbour
    -tail exchange must reproduce single-device windowed flash attention
    (values AND gradients) when window - 1 <= T_local."""

    def _dist(self, comm, window, seed=30, kv_heads=None, seg=None):
        from jax import shard_map

        from chainermn_tpu.parallel.local_attention import (
            sliding_window_attention_local,
        )

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        hkv = kv_heads or H
        k = jax.random.normal(ks[1], (B, T, hkv, D))
        v = jax.random.normal(ks[2], (B, T, hkv, D))

        def local(q, k, v, s):
            return sliding_window_attention_local(
                q, k, v, comm.axis_name, window=window,
                segment_ids=None if seg is None else s,
                block_q=4, block_k=4, interpret=True,
            )

        ax = comm.axis_name
        s_arg = (seg if seg is not None
                 else jnp.zeros((B, T), jnp.int32))
        out = jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(None, ax), P(None, ax), P(None, ax),
                          P(None, ax)),
                out_specs=P(None, ax), check_vma=False,
            )
        )(q, k, v, s_arg)
        return q, k, v, out

    def _ref(self, q, k, v, window, seg=None):
        from chainermn_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=True, window=window, segment_ids=seg,
            block_q=8, block_k=8, interpret=True,
        )

    @pytest.mark.parametrize("window", [2, 3, 5])  # T_local = 4: max W-1=4
    def test_matches_single_device_windowed(self, comm, window):
        q, k, v, out = self._dist(comm, window)
        ref = self._ref(q, k, v, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_window_one_no_comm(self, comm):
        q, k, v, out = self._dist(comm, 1)
        ref = self._ref(q, k, v, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa(self, comm):
        q, k, v, out = self._dist(comm, 4, kv_heads=2)
        ref = self._ref(q, k, v, 4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_single_device(self, comm):
        from jax import shard_map

        from chainermn_tpu.parallel.local_attention import (
            sliding_window_attention_local,
        )

        window = 4
        ks = jax.random.split(jax.random.PRNGKey(31), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, H, D))
        v = jax.random.normal(ks[2], (B, T, H, D))
        ax = comm.axis_name

        def loss_dist(q, k, v):
            def local(q, k, v):
                o = sliding_window_attention_local(
                    q, k, v, ax, window=window,
                    block_q=4, block_k=4, interpret=True,
                )
                return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), ax)

            return shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(None, ax),) * 3, out_specs=P(),
                check_vma=False,
            )(q, k, v)

        def loss_ref(q, k, v):
            o = self._ref(q, k, v, window)
            return (o.astype(jnp.float32) ** 2).sum()

        gd = jax.grad(loss_dist, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            gd, gr,
        )

    def test_packed_segments_cross_boundary(self, comm):
        """A document boundary NOT aligned to the shard cut: the tail's
        travelling segment ids must keep masking exact."""
        seg = np.zeros((B, T), np.int32)
        seg[:, 10:23] = 1  # cuts at 10 and 23 — neither on a 4-boundary
        seg[:, 23:] = 2
        seg = jnp.asarray(seg)
        window = 4
        q, k, v, out = self._dist(comm, window, seg=seg)
        ref = self._ref(q, k, v, window, seg=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("window", [6, 9, 13])  # m = 2, 2, 3
    def test_window_wider_than_shard(self, comm, window):
        """Multi-neighbour prefixes: the band spans several shard
        boundaries, gathered as one tail slice per predecessor."""
        q, k, v, out = self._dist(comm, window)
        ref = self._ref(q, k, v, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_window_covering_whole_sequence_is_full_causal(self, comm):
        q, k, v, out = self._dist(comm, T + 5)
        from chainermn_tpu.ops.flash_attention import flash_attention

        ref = flash_attention(q, k, v, causal=True,
                              block_q=8, block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_wide_window_grads_match_single_device(self, comm):
        from jax import shard_map

        from chainermn_tpu.parallel.local_attention import (
            sliding_window_attention_local,
        )

        window = 9  # spans 2 shard boundaries at T_local = 4
        ks = jax.random.split(jax.random.PRNGKey(35), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, H, D))
        v = jax.random.normal(ks[2], (B, T, H, D))
        ax = comm.axis_name

        def loss_dist(q, k, v):
            def local(q, k, v):
                o = sliding_window_attention_local(
                    q, k, v, ax, window=window,
                    block_q=4, block_k=4, interpret=True,
                )
                return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), ax)

            return shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(None, ax),) * 3, out_specs=P(),
                check_vma=False,
            )(q, k, v)

        def loss_ref(q, k, v):
            o = self._ref(q, k, v, window)
            return (o.astype(jnp.float32) ** 2).sum()

        gd = jax.grad(loss_dist, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            gd, gr,
        )


    def test_communication_volume_is_o_window(self, comm):
        """Structural certificate of the O(window) claim: one exchange
        per neighbour distance, NOT one per ring step. A distance-d
        exchange is one bundled shift of (k, v, ids) = 3 ppermute
        primitives, so the traced forward holds exactly 3m for
        m = ceil((W-1)/T_local); the grad program 8m (forward pass 3m +
        the backward's prefix rebuild 3m + the (dk, dv) slice returns
        2m) — all independent of mesh size, where the full causal ring
        issues a rotation per step."""
        from jax import shard_map

        from chainermn_tpu.parallel.local_attention import (
            sliding_window_attention_local,
        )

        ax = comm.axis_name

        def count_ppermutes(window, grad=False):
            def f(q, k, v):
                def local(q, k, v):
                    o = sliding_window_attention_local(
                        q, k, v, ax, window=window,
                        block_q=4, block_k=4, interpret=True,
                    )
                    return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(),
                                        ax)

                return shard_map(
                    local, mesh=comm.mesh, in_specs=(P(None, ax),) * 3,
                    out_specs=P(), check_vma=False,
                )(q, k, v)

            fn = jax.grad(f, argnums=(0, 1, 2)) if grad else f
            q = jnp.zeros((1, T, 2, 8))
            return str(jax.make_jaxpr(fn)(q, q, q)).count("ppermute")

        for window, m in ((3, 1), (5, 1), (9, 2), (13, 3)):
            assert count_ppermutes(window) == 3 * m, (window, m)
            assert count_ppermutes(window, grad=True) == 8 * m, (window, m)

    def test_even_window_keeps_banded_grid(self):
        """Regression (round-4 ADVICE): an EVEN window makes the extended
        K length T_local + W - 1 odd, which no power-of-two block divides
        — without tile padding ``_pick_block`` collapses to one whole-T
        K/V block (nk = 1), reverting the banded grid to O(T + W) DMA per
        query block and risking a VMEM-busting single block at long
        context. ``_pad_ext_to_block`` must restore an exact multiple of
        the requested block at realistic sizes."""
        from chainermn_tpu.ops.flash_attention import _pick_block
        from chainermn_tpu.parallel.local_attention import (
            _pad_ext_to_block,
        )

        for T_local, window, block_k in (
            (4096, 2048, 1024),   # the common even-window case
            (8192, 4096, 1024),
            (2048, 2048, 512),    # prefix == T_local - ... still odd ext
            (4096, 1000, 1024),   # non-power-of-two window
        ):
            prefix = window - 1
            T_ext = T_local + prefix
            # Demonstrate the degenerate case first: without padding,
            # _pick_block can only fall back to ONE whole-T block here.
            assert _pick_block(block_k, T_ext) == T_ext, (T_local, window)
            k = jnp.zeros((1, T_ext, 1, 8))
            seg = jnp.zeros((1, T_ext), jnp.int32)
            k_p, v_p, seg_p = _pad_ext_to_block(k, k, seg, block_k)
            T_pad = k_p.shape[1]
            b = _pick_block(block_k, T_pad)
            assert b == block_k, (T_local, window, T_pad, b)
            assert T_pad - T_ext < block_k  # pad is bounded by one block
            assert v_p.shape[1] == T_pad and seg_p.shape[1] == T_pad
            # The pad slots carry the wrap sentinel (belt-and-braces on
            # top of the causal mask).
            if T_pad > T_ext:
                assert int(seg_p[0, -1]) == jnp.iinfo(jnp.int32).min


class TestSeqRingLocal:
    """The plan-provider ring (ISSUE 13): statically unrolled, n-1
    forward K/V hops — same dist == single invariant as the scan rings,
    plus the hop-count pins the ParallelPlan acceptance rests on."""

    def _dist(self, comm, q, k, v, grad=False):
        from jax import shard_map

        from chainermn_tpu.parallel.ring_attention import (
            seq_ring_attention_local,
        )

        ax = comm.axis_name

        def fwd(q, k, v):
            def local(q, k, v):
                return seq_ring_attention_local(
                    q, k, v, ax, causal=True, block_q=4, block_k=4,
                    interpret=True,
                )

            return shard_map(
                local, mesh=comm.mesh, in_specs=(P(None, ax),) * 3,
                out_specs=P(None, ax), check_vma=False,
            )(q, k, v)

        if not grad:
            return jax.jit(fwd)(q, k, v)
        return jax.jit(jax.grad(
            lambda a, b, c: (fwd(a, b, c).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2),
        ))(q, k, v)

    def test_matches_full_attention_values_and_grads(self, comm):
        q, k, v = _qkv(40)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(self._dist(comm, q, k, v)), ref,
            rtol=1e-5, atol=1e-5,
        )
        g = self._dist(comm, q, k, v, grad=True)
        g_ref = jax.grad(
            lambda a, b, c: (dot_product_attention(
                a, b, c, causal=True).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )

    def test_gqa(self, comm):
        ks = jax.random.split(jax.random.PRNGKey(41), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, 2, D))
        v = jax.random.normal(ks[2], (B, T, 2, D))
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(self._dist(comm, q, k, v)), ref,
            rtol=1e-5, atol=1e-5,
        )
        g = self._dist(comm, q, k, v, grad=True)
        g_ref = jax.grad(
            lambda a, b, c: (dot_product_attention(
                a, b, c, causal=True).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g, g_ref,
        )

    def test_seq_ring_wire_event(self, comm):
        """Tracing a seq-ring program emits ONE trace-time ``seq_ring``
        wire layout event per compile: n-1 hops of the stacked (K, V)
        pair, overlapped=True (the hop is issued before the step's
        kernels) — what the observability overlap rollup groups under
        'seq_ring'."""
        from chainermn_tpu.observability import trace

        rec = trace.enable(None)
        try:
            q, k, v = _qkv(45)
            self._dist(comm, q, k, v)
            wires = [e for e in rec.events
                     if e.get("kind") == "wire"
                     and e.get("schedule") == "seq_ring"]
            assert len(wires) == 1
            w = wires[0]
            n = comm.size
            assert w["hops"] == n - 1
            # per hop: the stacked K+V local shards
            per_hop = 2 * (B * (T // n) * H * D) * 4
            assert w["nbytes"] == per_hop * (n - 1)
            assert w["overlapped"] is True
            ov = trace.summarize_overlap(rec.events)
            assert "seq_ring" in ov["schedules"]
        finally:
            trace.disable()

    def test_hop_counts_pinned(self, comm):
        """The structural claim the plan's acceptance rests on: n-1
        collective-permutes per FORWARD ring pass (each hop one permute
        of the stacked K/V pair — no homing rotation), and
        (n-1) + n per backward (kv hops + the travelling dk/dv
        accumulator's n hops: it starts home, visits all n shards, and
        needs one extra hop back). Counted in the jaxpr — the unrolled
        program shows every hop, unlike the scan rings' loop body."""
        from jax import shard_map

        from chainermn_tpu.parallel.ring_attention import (
            seq_ring_attention_local,
        )

        ax = comm.axis_name
        n = comm.size

        def fwd(q, k, v):
            def local(q, k, v):
                o = seq_ring_attention_local(
                    q, k, v, ax, causal=True, block_q=4, block_k=4,
                    interpret=True,
                )
                return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), ax)

            return shard_map(
                local, mesh=comm.mesh, in_specs=(P(None, ax),) * 3,
                out_specs=P(), check_vma=False,
            )(q, k, v)

        q = jnp.zeros((1, T, 2, 8))
        assert str(jax.make_jaxpr(fwd)(q, q, q)).count("ppermute") == n - 1
        n_grad = str(jax.make_jaxpr(
            jax.grad(fwd, argnums=(0, 1, 2))
        )(q, q, q)).count("ppermute")
        assert n_grad == (n - 1) + (n - 1) + n, n_grad


class TestSeqPlanAxis:
    """ISSUE 13 tentpole: the ``seq`` axis as a ParallelPlan spec
    provider — plan-compiled ``data x seq`` / ``seq x model`` steps must
    equal the single-device reference (values AND gradients), the ring's
    compiled HLO must carry exactly ``n_seq - 1`` collective-permutes
    per layer per forward pass, the jit cache stays at 1 with
    whole-state donation intact, and composing TP adds ZERO collectives
    beyond what the providers owe."""

    LM_KW = dict(vocab_size=32, num_layers=2, num_heads=4, d_model=16,
                 d_ff=32, max_len=64, compute_dtype=jnp.float32,
                 pos_encoding="rope", return_hidden=True)

    def _lm(self, attn_fn=None, **kw):
        from chainermn_tpu.models.transformer import TransformerLM

        cfg = dict(self.LM_KW)
        cfg.update(kw)
        return TransformerLM(**cfg, attention_fn=attn_fn)

    def _params_and_tokens(self, seed=4, kv_heads=None):
        ref = self._lm(num_kv_heads=kv_heads)
        tok = jax.random.randint(jax.random.PRNGKey(3), (4, 32), 0, 32)
        variables = ref.init(
            jax.random.PRNGKey(seed), tok[:, :4], train=False
        )
        return ref, {"params": variables["params"]}, tok

    def _losses(self, model, sp=False):
        def sp_loss(p, batch):
            from chainermn_tpu.parallel.plan import ParallelPlan

            pos = ParallelPlan.seq_local_positions(batch.shape[1])
            h = model.apply({"params": p["params"]}, batch,
                            positions=pos, train=False)
            return jnp.mean(h.astype(jnp.float32) ** 2)

        def ref_loss(p, batch):
            h = model.apply({"params": p["params"]}, batch, train=False)
            return jnp.mean(h.astype(jnp.float32) ** 2)

        return sp_loss if sp else ref_loss

    @pytest.mark.parametrize("impl,seq,kv_heads", [
        ("ring", 4, None),
        ("ring", 4, 2),      # GQA through the plan ring
        ("ulysses", 2, None),
        ("ulysses", 2, 2),   # GQA through the plan Ulysses (kvh % n == 0)
    ])
    def test_data_seq_plan_values_and_grads(self, impl, seq, kv_heads):
        from chainermn_tpu.parallel.plan import ParallelPlan

        devices = jax.devices("cpu")[:2 * seq]
        plan = ParallelPlan({"data": 2, "seq": seq}, devices=devices)
        attn_fn, rec = plan.seq_attention(
            heads=4, kv_heads=kv_heads, t_local=32 // seq, impl=impl
        )
        assert rec["winner"] == impl
        ref_model, params, tok = self._params_and_tokens(kv_heads=kv_heads)
        sp_model = self._lm(attn_fn, num_kv_heads=kv_heads)

        lr = 0.1
        import optax

        state = plan.create_train_state(params, optax.sgd(lr))
        step = plan.compile_train_step(
            self._losses(sp_model, sp=True), optax.sgd(lr), params
        )
        state, m = step(state, tok)
        l_ref, g_ref = jax.value_and_grad(
            lambda p: self._losses(ref_model)(p, tok)
        )(params)
        np.testing.assert_allclose(float(m["loss"]), float(l_ref),
                                   rtol=1e-4)
        # gradients certified through the sgd delta, every leaf
        after = jax.device_get(state.params)
        jax.tree.map(
            lambda p0, p1, g: np.testing.assert_allclose(
                (np.asarray(p0) - np.asarray(p1)) / lr, np.asarray(g),
                rtol=2e-3, atol=2e-5,
            ),
            params, after, g_ref,
        )
        assert step.cache_size() in (None, 1)

    def test_ring_hlo_ppermute_count_and_donation(self):
        """The compiled ``data x seq`` train step carries EXACTLY
        ``(n-1) + (n-1) + n`` collective-permutes per layer (forward
        ring + backward kv ring + accumulator homing), the forward-only
        program exactly ``n - 1`` per layer, donation aliases every
        state buffer, and the jit cache stays at 1 across steps."""
        import optax

        from chainermn_tpu.parallel.plan import ParallelPlan

        seq, layers = 4, 2
        plan = ParallelPlan({"data": 2, "seq": seq},
                            devices=jax.devices("cpu")[:8])
        attn_fn, _ = plan.seq_attention(heads=4, t_local=32 // seq,
                                        impl="ring")
        sp_model = self._lm(attn_fn)
        _, params, tok = self._params_and_tokens()
        loss = self._losses(sp_model, sp=True)
        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner)
        step = plan.compile_train_step(loss, inner, params)
        txt = step.lower(state, tok).compile().as_text()
        assert txt.count("collective-permute(") == (3 * seq - 2) * layers
        assert "input_output_alias" in txt
        n_alias = txt.count("may-alias") + txt.count("must-alias")
        assert n_alias >= len(jax.tree.leaves(state))

        # forward-only: n-1 per layer per ring pass, nothing else
        from jax import shard_map

        fwd = jax.jit(shard_map(
            lambda p, t: loss(p, t), mesh=plan.mesh,
            in_specs=(plan.param_specs(params), plan.batch_spec()),
            out_specs=P(), check_vma=False,
        ))
        fwd_txt = fwd.lower(params, tok).compile().as_text()
        assert fwd_txt.count("collective-permute(") == (seq - 1) * layers

        for _ in range(2):
            state, m = step(state, tok)
        assert step.cache_size() in (None, 1)
        assert np.isfinite(float(m["loss"]))

    def test_seq_model_plan_zero_extra_collectives(self):
        """``seq x model``: the plan-compiled step carries exactly the
        collectives the two providers owe — the ring's ppermutes plus
        TP's all-reduces plus the one seq gradient mean — pinned
        against the hand-wired shard_map of the same computation (the
        test_plan.py convention), with zero all-to-alls and zero
        ppermutes beyond the ring's."""
        import optax
        from jax import shard_map

        from chainermn_tpu.parallel.plan import ParallelPlan
        from chainermn_tpu.parallel.ring_attention import (
            seq_ring_attention_local,
        )
        from chainermn_tpu.parallel.tensor import stack_tp_params, tp_mlp

        seq = n_tp = 2
        d, Hh, Dh = 8, 2, 4
        plan = ParallelPlan({"seq": seq, "model": n_tp},
                            devices=jax.devices("cpu")[:4])
        attn_fn, _ = plan.seq_attention(heads=Hh, t_local=8, impl="ring")
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        wq = jax.random.normal(ks[0], (d, d)) * 0.3
        w1 = jax.random.normal(ks[1], (d, d)) * 0.3
        w2 = jax.random.normal(ks[2], (d, d)) * 0.3
        params = {
            "wq": wq,
            "w1": stack_tp_params(w1, n_tp, 1),
            "w2": stack_tp_params(w2, n_tp, 0),
            "b2": jnp.zeros((d,)),
        }
        specs = {"wq": P(), "w1": P("model"), "w2": P("model"), "b2": P()}
        x = jax.random.normal(ks[3], (2, 16, d))
        y = jnp.zeros((2, 16, d))
        lr = 0.1

        def loss_fn(p, batch):
            xb, yb = batch
            Bb, Tb, _ = xb.shape
            q = (xb @ p["wq"]).reshape(Bb, Tb, Hh, Dh)
            a = attn_fn(q, q, q, causal=True, scale=Dh ** -0.5)
            h = a.reshape(Bb * Tb, d)
            out = tp_mlp(h, p["w1"], None, p["w2"], p["b2"],
                         axis_name="model")
            return jnp.mean((out.reshape(Bb, Tb, d) - yb) ** 2)

        inner = optax.sgd(lr)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        plan_txt = step.lower(state, (x, y)).compile().as_text()
        plan_counts = {op: plan_txt.count(op) for op in
                       ("all-reduce(", "collective-permute(",
                        "all-to-all(", "reduce-scatter(", "all-gather(")}

        def hand_local(params, batch):
            p = {"wq": params["wq"], "w1": params["w1"][0],
                 "w2": params["w2"][0], "b2": params["b2"]}

            def loss(p):
                return loss_fn(p, batch)

            l, g = jax.value_and_grad(loss)(p)
            g = jax.lax.pmean(g, ("seq",))
            new = {
                "wq": p["wq"] - lr * g["wq"],
                "w1": (p["w1"] - lr * g["w1"])[None],
                "w2": (p["w2"] - lr * g["w2"])[None],
                "b2": p["b2"] - lr * g["b2"],
            }
            return new, jax.lax.pmean(l, ("seq",))

        pspec = {"wq": P(), "w1": P("model"), "w2": P("model"),
                 "b2": P()}
        hand = jax.jit(shard_map(
            hand_local, mesh=plan.mesh,
            in_specs=(pspec, P(None, "seq")),
            out_specs=(pspec, P()),
            check_vma=False,
        ))
        hand_txt = hand.lower(params, (x, y)).compile().as_text()
        hand_counts = {op: hand_txt.count(op) for op in plan_counts}
        assert plan_counts == hand_counts, (plan_counts, hand_counts)
        # the vocabulary: ring hops present, TP psums present, nothing
        # resharded head<->sequence (no all-to-all), no zero machinery
        assert plan_counts["collective-permute("] == 3 * seq - 2
        assert plan_counts["all-to-all("] == 0
        assert plan_counts["reduce-scatter("] == 0
        assert plan_counts["all-gather("] == 0
        assert plan_counts["all-reduce("] >= 2  # TP pair + grad mean

    def test_seq_attn_impl_forced_fallback_and_rejection(self,
                                                         monkeypatch):
        """Satellite: 'auto' resolving to ulysses with
        heads % seq_size != 0 force-falls back to ring with
        ``forced:heads-indivisible`` provenance; an EXPLICIT ulysses
        request is rejected at entry naming both numbers."""
        from chainermn_tpu.parallel.plan import ParallelPlan

        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "seq_attn_impl=ulysses")
        plan = ParallelPlan({"seq": 8}, devices=jax.devices("cpu")[:8])
        _, rec = plan.seq_attention(heads=4, t_local=4, impl="auto")
        assert rec["winner"] == "ring"
        assert rec["source"] == "forced:heads-indivisible"
        assert plan.decisions[-1] == rec
        assert plan.describe()["seq_attn_impl"] == "ring"
        # kv heads (GQA) gate the fallback too
        plan2 = ParallelPlan({"seq": 2}, devices=jax.devices("cpu")[:2])
        _, rec2 = plan2.seq_attention(heads=4, kv_heads=1, t_local=16,
                                      impl="auto")
        assert rec2["source"] == "forced:heads-indivisible"

        monkeypatch.delenv("CHAINERMN_TPU_AUTOTUNE_FORCE")
        plan3 = ParallelPlan({"seq": 8}, devices=jax.devices("cpu")[:8])
        with pytest.raises(ValueError) as e:
            plan3.seq_attention(heads=6, t_local=4, impl="ulysses")
        assert "6" in str(e.value) and "8" in str(e.value)

    def test_make_ulysses_rejects_at_entry(self, comm):
        """Satellite: the jitted Ulysses entry point rejects indivisible
        heads BEFORE the shard_map trace, naming both numbers."""
        fn = make_ulysses_attention(comm.mesh, comm.axis_name)
        q = jnp.zeros((B, T, 6, D))
        with pytest.raises(ValueError) as e:
            fn(q, q, q)
        assert "6" in str(e.value) and "8" in str(e.value)
        assert "not divisible" in str(e.value)

    def test_batch_spec_and_describe(self):
        from chainermn_tpu.parallel.plan import ParallelPlan

        plan = ParallelPlan({"data": 2, "seq": 4},
                            devices=jax.devices("cpu")[:8])
        assert plan.batch_spec() == P(("data",), "seq")
        desc = plan.describe()
        assert desc["mesh"] == {"data": 2, "seq": 4}
        assert desc["collectives"]["seq"] == (
            "collective-permute", "all-reduce",
        )
        plan2 = ParallelPlan({"seq": 8}, devices=jax.devices("cpu")[:8])
        assert plan2.batch_spec() == P(None, "seq")


def test_dryrun_phase_table_wires_seq_parallel_phase():
    """Satellite: dryrun phase N (8-device data x seq plan vs
    single-device ref + seq-parallel prefill streams == generate) is in
    __graft_entry__'s phase table, and tools/byte_audit.py carries the
    ring's per-hop K/V byte rows."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = open(os.path.join(root, "__graft_entry__.py")).read()
    assert "_phase_seq_parallel" in src
    assert ('"N:seq-axis plan + seq-parallel prefill", '
            "_phase_seq_parallel" in src)
    audit = open(os.path.join(root, "tools", "byte_audit.py")).read()
    assert "_seq_ring_bytes" in audit
    assert "per_hop_kv_bytes" in audit


class TestUlyssesWindow:
    def test_ulysses_window_matches_single_device(self, comm):
        from chainermn_tpu.parallel.ulysses import make_ulysses_attention

        window = 5
        ks = jax.random.split(jax.random.PRNGKey(70), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, H, D))
        v = jax.random.normal(ks[2], (B, T, H, D))
        fn = make_ulysses_attention(
            comm.mesh, comm.axis_name, causal=True, window=window
        )
        sharding = NamedSharding(comm.mesh, P(None, comm.axis_name))
        qs, ks_, vs = (jax.device_put(a, sharding) for a in (q, k, v))
        out = fn(qs, ks_, vs)

        from chainermn_tpu.ops.flash_attention import flash_attention

        ref = flash_attention(q, k, v, causal=True, window=window,
                              block_q=8, block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_window_rejected_with_custom_attn_fn(self, comm):
        from jax import shard_map

        from chainermn_tpu.parallel.ulysses import ulysses_attention_local

        q = jnp.zeros((B, T, H, D))
        with pytest.raises(ValueError, match="flash kernel"):
            jax.jit(shard_map(
                lambda a: ulysses_attention_local(
                    a, a, a, comm.axis_name, causal=True, window=4,
                    attn_fn=blockwise_attention,
                ),
                mesh=comm.mesh,
                in_specs=P(None, comm.axis_name),
                out_specs=P(None, comm.axis_name), check_vma=False,
            ))(q)

    def test_ulysses_window_grads_match_single_device(self, comm):
        from jax import shard_map

        from chainermn_tpu.ops.flash_attention import flash_attention
        from chainermn_tpu.parallel.ulysses import ulysses_attention_local

        window = 5
        ks = jax.random.split(jax.random.PRNGKey(71), 3)
        q = jax.random.normal(ks[0], (B, T, H, D))
        k = jax.random.normal(ks[1], (B, T, H, D))
        v = jax.random.normal(ks[2], (B, T, H, D))
        ax = comm.axis_name

        def loss_dist(q, k, v):
            def local(q, k, v):
                o = ulysses_attention_local(
                    q, k, v, ax, causal=True, window=window, interpret=True
                )
                return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), ax)

            return shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(None, ax),) * 3, out_specs=P(),
                check_vma=False,
            )(q, k, v)

        def loss_ref(q, k, v):
            o = flash_attention(q, k, v, causal=True, window=window,
                                block_q=8, block_k=8, interpret=True)
            return (o.astype(jnp.float32) ** 2).sum()

        # jit the distributed grad: the transposed all_to_all sets an XLA
        # sharding that eager grad-of-shard_map refuses to reconcile.
        gd = jax.jit(jax.grad(loss_dist, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            gd, gr,
        )
