"""FSDP (declarative parameter+state sharding) tests: the sharded step must
equal replicated data parallelism numerically, while actually holding 1/n of
the big parameters per device."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import create_multi_node_optimizer
from chainermn_tpu.parallel.fsdp import (
    create_fsdp_train_state,
    fsdp_shardings,
    make_fsdp_train_step,
)
from chainermn_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def _batch(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 10).astype(np.float32)
    y = (rng.randint(0, 4, size=n)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_fsdp_shardings_rules(comm):
    params = {
        "big": jnp.zeros((1024, 64)),     # sharded on dim 0 (largest, /8)
        "tall": jnp.zeros((63, 4096)),    # dim 0 not /8 -> shard dim 1
        "bias": jnp.zeros((64,)),         # too small -> replicated
        "odd": jnp.zeros((999, 999)),     # big but nothing divisible -> repl
    }
    sh = fsdp_shardings(params, comm.mesh, comm.axis_name, min_size=2**10)
    assert sh["big"].spec == jax.sharding.PartitionSpec("data", None)
    assert sh["tall"].spec == jax.sharding.PartitionSpec(None, "data")
    assert sh["bias"].spec == jax.sharding.PartitionSpec()
    assert sh["odd"].spec == jax.sharding.PartitionSpec()


def test_fsdp_step_matches_replicated_dp(comm):
    model = MLP(n_units=64, n_out=4)
    x, y = _batch()
    params = model.init(jax.random.key(0), x[:1])["params"]

    def loss_fn(p, batch):
        xb, yb = batch
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()

    # Replicated DP reference (shard_map path)
    opt_ref = create_multi_node_optimizer(optax.adamw(1e-2), comm)
    state_ref = create_train_state(params, opt_ref, comm)
    step_ref = make_train_step(loss_fn, opt_ref, comm, donate=False)

    # FSDP path (auto-SPMD): params + adam state sharded over 'data'
    opt = optax.adamw(1e-2)
    state, shardings = create_fsdp_train_state(
        params, opt, comm, min_size=2**8
    )
    # the 64x64 hidden kernel must actually be sharded
    hidden = state.params["Dense_1"]["kernel"]
    assert "data" in tuple(hidden.sharding.spec), hidden.sharding
    shard_rows = [s.data.shape for s in hidden.addressable_shards]
    assert all(sh != hidden.shape for sh in shard_rows), (
        "param shards should be strictly smaller than the global param"
    )
    step = make_fsdp_train_step(loss_fn, opt, comm, shardings, donate=False)

    for _ in range(3):
        state_ref, m_ref = step_ref(state_ref, (x, y))
        state, m = step(state, (x, y))
    np.testing.assert_allclose(
        float(m["loss"]), float(m_ref["loss"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(state_ref.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_fsdp_model_state_roundtrip(comm):
    """model_state (BN-style extras) rides along replicated."""
    model = MLP(n_units=32, n_out=4)
    x, y = _batch(16)
    params = model.init(jax.random.key(1), x[:1])["params"]

    def loss_fn(p, batch, model_state):
        xb, yb = batch
        logits = model.apply({"params": p}, xb)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        return loss, ({"acc": (logits.argmax(-1) == yb).mean()},
                      {"seen": model_state["seen"] + xb.shape[0]})

    opt = optax.sgd(1e-2)
    state, shardings = create_fsdp_train_state(
        params, opt, comm, model_state={"seen": jnp.int32(0)}, min_size=2**8
    )
    step = make_fsdp_train_step(loss_fn, opt, comm, shardings, donate=False)
    state, metrics = step(state, (x, y))
    assert int(state.model_state["seen"]) == 16
    assert np.isfinite(float(metrics["loss"]))
