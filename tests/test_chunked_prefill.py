"""Chunked prefill + SLO-aware scheduling invariants (ISSUE 11).

The acceptance pins, asserted structurally:

- **Stream equivalence** — chunked admission (``prefill_chunk > 0``,
  prompts written C tokens per mixed tick while other slots decode)
  produces token streams bit-identical to sequential ``generate``
  across dense == paged == tensor-parallel == single-device, prefix
  cache on/off, speculative decode on/off — the engine contract is
  layout- and schedule-independent.
- **One mixed program** — the mixed step's jit cache stays at ONE entry
  across every chunk/decode occupancy mix (fills joining/completing,
  decodes churning, the SLO cap throttling fill rows), and under TP its
  compiled HLO carries exactly the pre-chunking collective set: 2
  all-reduces per layer, nothing else.
- **Preemption equivalence** — a request preempted mid-stream and
  resumed (same scheduler, or re-routed to a second replica through the
  router) produces the identical stream, including with prefix-cache
  re-adoption of its own blocks and with speculative decode active.
- **Whole-journey stamps** — requeue/preemption keep the ORIGINAL
  arrival stamp (the ``keep_arrival`` helper all three submission paths
  share), so queue_wait/TTFT can never be silently reset.

Plus the SLO policy units (chunk-row interference cap, preempt events,
violation counters via the PR 6 tap) and the TPOT / ``slo_attainment``
rollup contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.serving import Request, Scheduler, ServingEngine
from chainermn_tpu.serving.scheduler import keep_arrival

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _requests(n, seed=0, max_prompt=9, max_new=6):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        # repetitive prompts give the n-gram drafter material, so the
        # spec arms actually accept drafts
        base = rs.randint(1, VOCAB, size=3).tolist()
        p = (base * 4)[: int(rs.randint(2, max_prompt))]
        out.append((p, int(rs.randint(1, max_new))))
    return out


def _generate_ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _engine(lm, *, impl="paged", prefix="off", spec=0, chunk=3,
            mesh=None, slots=2, **kw):
    model, params = lm
    return ServingEngine(
        model, params, num_slots=slots, max_len=32, decode_impl=impl,
        kv_block_size=8, prefill_buckets=(4, 8, 16), mesh=mesh,
        spec_tokens=spec, prefix_cache=prefix, prefill_chunk=chunk,
        **kw,
    )


def _run_stream(engine, reqs, policy="prefill_priority", **req_kw):
    sched = Scheduler(engine, policy=policy)
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g, **req_kw))
           for p, g in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids], sched


class TestChunkedStreamEquivalence:
    """Chunked == sequential generate, across layouts and features."""

    @pytest.mark.parametrize("impl,prefix,spec", [
        ("dense", "off", 0),
        ("dense", "off", 4),
        ("paged", "off", 0),
        ("paged", "on", 0),
        ("paged", "off", 4),
        ("paged", "on", 4),
    ])
    def test_chunked_matches_generate(self, lm, impl, prefix, spec):
        model, params = lm
        # 2 slots x 6 requests force staggered fills mid-decode of
        # other requests — every chunk/decode occupancy mix occurs.
        engine = _engine(lm, impl=impl, prefix=prefix, spec=spec)
        reqs = _requests(6, seed=0)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        assert engine.mixed_compile_count() in (None, 1)

    def test_chunked_equals_monolithic_streams(self, lm):
        """The same request set through prefill_chunk=0 and >0 engines
        yields byte-identical streams — chunking is a schedule, not a
        semantic."""
        reqs = _requests(5, seed=7)
        mono = _engine(lm, chunk=0, prefix="on")
        chunked = _engine(lm, chunk=5, prefix="on")
        s_mono, _ = _run_stream(mono, reqs)
        s_chunk, _ = _run_stream(chunked, reqs)
        assert s_mono == s_chunk

    @pytest.mark.parametrize("impl,spec", [
        ("dense", 0), ("paged", 0), ("paged", 4),
    ])
    def test_tp_chunked_matches_single_device(self, lm, impl, spec):
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
        reqs = _requests(5, seed=11)
        prefix = "on" if impl == "paged" else "off"
        single = _engine(lm, impl=impl, prefix=prefix, spec=spec,
                         slots=3)
        tp = _engine(lm, impl=impl, prefix=prefix, spec=spec, slots=3,
                     mesh=mesh)
        s_streams, _ = _run_stream(single, reqs)
        t_streams, _ = _run_stream(tp, reqs)
        assert t_streams == s_streams
        for (prompt, n_new), got in zip(reqs, t_streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        assert tp.mixed_compile_count() in (None, 1)

    def test_long_prompt_fill_interleaves_with_decode(self, lm):
        """The tentpole's point, measured not asserted: while a long
        prompt's fill is in progress, the other in-flight streams keep
        emitting tokens — the decode_step events BETWEEN the long
        request's first and last chunk carry nonzero token counts
        (monolithic prefill would freeze them for one big forward)."""
        engine = _engine(lm, prefix="off", chunk=2, slots=3)
        sched = Scheduler(engine, policy="prefill_priority")
        short = [sched.submit(Request(prompt=[i + 1, i + 2],
                                      max_new_tokens=12))
                 for i in range(2)]
        long_prompt = list(np.random.RandomState(3).randint(
            1, VOCAB, size=18))
        # admit the short pair and give them a tick first
        sched.tick()
        rid_long = sched.submit(Request(
            prompt=[int(t) for t in long_prompt], max_new_tokens=3))
        sched.run()
        evs = sched.event_window
        chunk_idx = [i for i, e in enumerate(evs)
                     if e.get("kind") == "prefill_chunk"
                     and e.get("request") == rid_long]
        assert len(chunk_idx) == 9  # 18 tokens / chunk 2
        between = [e for e in evs[chunk_idx[0]:chunk_idx[-1]]
                   if e.get("kind") == "serving"
                   and e.get("phase") == "decode_step"]
        assert between and any(e["tokens"] > 0 for e in between), (
            "decode starved during the chunked fill")
        assert short  # streams finished; equivalence covered above


class TestMixedStepStructure:
    def test_mixed_compiles_once_across_churn(self, lm):
        engine = _engine(lm, prefix="on", chunk=3)
        streams, _ = _run_stream(engine, _requests(6, seed=13))
        assert len(streams) == 6
        assert engine.mixed_compile_count() == 1

    def test_tp_mixed_collective_counts(self, lm):
        """Exactly 2 all-reduces per layer (the pre-chunking set),
        zero other collectives — chunk rows add nothing to the wire."""
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
        engine = _engine(lm, prefix="off", chunk=4, slots=3, mesh=mesh)
        args = (
            engine._cache, engine._vars,
            jnp.zeros((3, engine._mixed_T), jnp.int32),
            jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._mixed_step_jit.lower(*args).compile().as_text()
        n_ar = txt.count("all-reduce(")
        assert n_ar == 2 * model.num_layers, (
            f"expected {2 * model.num_layers} all-reduces, got {n_ar}")
        for op in ("all-gather(", "collective-permute(", "all-to-all(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op} in mixed step"

    def test_mixed_width_covers_chunk_and_verify_span(self, lm):
        assert _engine(lm, chunk=3, spec=0)._mixed_T == 3
        assert _engine(lm, chunk=3, spec=4)._mixed_T == 5
        assert _engine(lm, chunk=8, spec=4)._mixed_T == 8

    def test_fill_row_cap_is_host_only(self, lm):
        """max_fill_rows throttles which fills advance (SLO
        interference bound) without a second compile — and a capped
        fill makes no progress that tick."""
        engine = _engine(lm, prefix="off", chunk=2, slots=3)
        s0 = engine.chunked_join([1, 2, 3, 4, 5, 6])
        s1 = engine.chunked_join([7, 8, 9, 10, 11, 12])
        _, fills, _, _ = engine.mixed_step(max_fill_rows=1)
        assert [f["slot"] for f in fills] == [s0]
        assert engine._pending_fill[s1]["pos"] == 0
        _, fills2, _, _ = engine.mixed_step(max_fill_rows=0)
        assert fills2 == []
        _, fills3, _, _ = engine.mixed_step()
        assert {f["slot"] for f in fills3} == {s0, s1}
        assert engine.mixed_compile_count() == 1

    def test_chunked_join_defers_like_prefill_join(self, lm):
        """Deferral contract unchanged: pool exhaustion returns None
        with host state untouched, and the scheduler retry admits once
        capacity frees."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, num_blocks=4,  # 3 allocatable blocks
            prefill_buckets=(4, 8, 16), prefill_chunk=3,
            prefix_cache="off",
        )
        s0 = engine.chunked_join([1] * 17)  # needs 3 blocks
        assert s0 is not None
        v0 = engine._alloc.version
        assert engine.chunked_join([2] * 9) is None  # needs 2 more
        assert engine._alloc.version == v0  # rollback restored version
        assert engine.free_slot_count == 1
        assert engine.n_filling == 1

    def test_engine_validation(self, lm):
        with pytest.raises(ValueError, match="prefill_chunk"):
            _engine(lm, chunk=-1)
        # ISSUE 18: sampling no longer gates chunking — counter-based
        # keys make the chunked and monolithic schedules draw identical
        # tokens at every position (pinned in test_sampling.py).
        sampled = _engine(lm, chunk=4, temperature=0.7)
        assert sampled.prefill_chunk == 4
        eng = _engine(lm, chunk=0)
        with pytest.raises(RuntimeError, match="chunked_join"):
            eng.chunked_join([1, 2])
        with pytest.raises(RuntimeError, match="mixed_step"):
            eng.mixed_step()
        # explicit decision recorded with provenance
        eng2 = _engine(lm, chunk=4)
        recs = [d for d in eng2.decisions
                if d["name"] == "prefill_chunk"]
        assert recs and recs[0]["winner"] == "4"
        assert recs[0]["source"] == "explicit"


class TestPreemption:
    """Preempt → resume == uninterrupted, in every composition."""

    @pytest.mark.parametrize("chunk,spec,prefix", [
        (0, 0, "on"),   # monolithic + prefix re-adoption
        (3, 0, "on"),   # chunked + prefix re-adoption
        (3, 4, "on"),   # chunked + speculative decode
        (0, 0, "off"),  # no cache: full re-prefill, still identical
    ])
    def test_preempt_resume_matches_generate(self, lm, chunk, spec,
                                             prefix):
        model, params = lm
        engine = _engine(lm, prefix=prefix, spec=spec, chunk=chunk,
                         slots=1)
        sched = Scheduler(engine, policy="prefill_priority")
        base = [3, 5, 7]
        prompt = (base * 4)[:9]
        rid = sched.submit(Request(prompt=prompt, max_new_tokens=10))
        for _ in range(4):
            sched.tick()
        assert sched.in_flight == 1
        slot = next(iter(sched._inflight))
        arrival = sched._inflight[slot].request._arrival
        sched.preempt(slot)
        assert sched.pending == 1 and sched.in_flight == 0
        # the ORIGINAL arrival stamp survives the requeue (satellite)
        assert sched._queue[0]._arrival == arrival
        results = sched.run()
        assert results[rid]["tokens"] == _generate_ref(
            model, params, prompt, 10)
        assert sched.preemptions == 1

    def test_resume_readopts_own_blocks_through_trie(self, lm):
        """The preempted request's written FULL blocks re-adopt through
        the trie: the resume prefills at most the boundary tail, not
        the whole history (the 'resume re-prefills nothing' pin)."""
        engine = _engine(lm, prefix="on", chunk=0, slots=1)
        sched = Scheduler(engine, policy="prefill_priority")
        prompt = list(np.random.RandomState(5).randint(1, VOCAB, size=9))
        rid = sched.submit(Request(prompt=[int(t) for t in prompt],
                                   max_new_tokens=10))
        for _ in range(6):
            sched.tick()
        slot = next(iter(sched._inflight))
        history_len = len(sched._inflight[slot].stream)
        before = dict(engine.prefix_stats)
        sched.preempt(slot)
        sched.run()
        st = engine.prefix_stats
        assert st["hits"] == before["hits"] + 1
        resumed_prefill = (st["prefill_tokens"]
                           - before["prefill_tokens"])
        # KV exists for history_len - 1 positions; everything in full
        # blocks re-adopts, so the re-prefill is under one block + tail
        assert resumed_prefill <= (history_len - 1) % 8 + 8
        assert resumed_prefill < history_len - 1
        assert rid in sched.results

    def test_preempt_mid_fill_resumes_identically(self, lm):
        model, params = lm
        engine = _engine(lm, prefix="on", chunk=2, slots=1)
        sched = Scheduler(engine, policy="prefill_priority")
        prompt = list(range(1, 19))  # 18 tokens -> 9 chunks
        rid = sched.submit(Request(prompt=prompt, max_new_tokens=4))
        sched.tick()  # admit
        sched.tick()  # one chunk written
        assert sched.filling == 1
        slot = next(iter(sched._filling))
        sched.preempt(slot)
        assert engine.n_filling == 0 and sched.filling == 0
        results = sched.run()
        assert results[rid]["tokens"] == _generate_ref(
            model, params, prompt, 4)

    def test_preempt_resume_with_concurrent_streams(self, lm):
        """Preemption must not disturb the OTHER in-flight streams:
        everything still equals generate."""
        model, params = lm
        engine = _engine(lm, prefix="on", chunk=3, slots=2)
        sched = Scheduler(engine, policy="prefill_priority")
        reqs = _requests(3, seed=21, max_new=8)
        ids = [sched.submit(Request(prompt=p, max_new_tokens=g))
               for p, g in reqs]
        for _ in range(5):
            sched.tick()
        if sched._inflight:
            sched.preempt(next(iter(sched._inflight)))
        results = sched.run()
        for rid, (p, g) in zip(ids, reqs):
            assert results[rid]["tokens"] == _generate_ref(
                model, params, p, g)

    def test_router_preempt_reroutes_to_second_replica(self, lm):
        """Cross-replica migration: preempt on replica A, resume on
        replica B — stream identical to uninterrupted generate (resume
        state travels ON the request; B's trie is cold, so it simply
        re-prefills the history)."""
        from chainermn_tpu.serving.cluster import Router, make_replicas

        model, params = lm
        replicas = make_replicas(
            model, params, 2, tp=1, num_slots=2, max_len=32,
            decode_impl="paged", kv_block_size=8,
            prefill_buckets=(4, 8, 16), prefix_cache="on",
            prefill_chunk=3, spec_tokens=0,
        )
        router = Router(replicas, mode="colocated_chunked",
                        policy="least_loaded")
        prompt = (11, 12, 13) * 3
        req = Request(prompt=list(prompt), max_new_tokens=10)
        rid = router.submit(req)
        src = next(i for i, rep in router.replicas.items()
                   if rep.load() > 0)
        # drive the holding replica until the request is mid-stream
        for _ in range(6):
            router.replicas[src].tick()
        assert router.replicas[src].scheduler.in_flight == 1
        dst = router.preempt_request(rid)
        assert dst != src
        assert router.replicas[dst].scheduler.pending == 1
        results = router.run()
        assert results[rid]["tokens"] == _generate_ref(
            model, params, list(prompt), 10)

    def test_disagg_preempt_resumes_on_decode_replica(self, lm):
        """Review regression: in DISAGGREGATED mode a preempted request
        must resume on a decode replica's scheduler (honouring the
        parked stream), never re-enter the prefill-pump queue — which
        would regenerate from the original prompt and re-sample TTFT.
        Stream still == uninterrupted generate, exactly one TTFT sample
        across the cluster."""
        from chainermn_tpu.observability import trace as obs_trace
        from chainermn_tpu.serving.cluster import Router, make_replicas

        model, params = lm
        replicas = make_replicas(
            model, params, 2, tp=1, num_slots=2, max_len=32,
            decode_impl="paged", kv_block_size=8,
            prefill_buckets=(4, 8, 16), prefix_cache="on",
            spec_tokens=0,
        )
        router = Router(replicas, mode="disaggregated",
                        prefill_replicas=[0])
        rec = obs_trace.enable(None)
        try:
            for rep in replicas:
                rep.scheduler.start_window()
            prompt = [9, 8, 7, 6, 5]
            req = Request(prompt=prompt, max_new_tokens=10)
            rid = router.submit(req)
            # drive the handoff + a few decode ticks deterministically
            router._pump_prefill()
            router._pump_adopt()
            dec = router.replicas[1]
            for _ in range(3):
                dec.tick()
            assert dec.scheduler.in_flight == 1
            new_id = router.preempt_request(rid, exclude_replica=False)
            # only one decode replica: it resumes on ITS scheduler
            assert new_id == 1
            assert dec.scheduler.pending == 1
            assert all(len(q) == 0 for q in router._pqueues.values())
            results = router.run()
            assert results[rid]["tokens"] == _generate_ref(
                model, params, prompt, 10)
            ttft = [e for e in rec.events
                    if e.get("kind") == "serving"
                    and e.get("phase") == "prefill"
                    and e.get("request") == rid
                    and e.get("ttft_s") is not None]
            assert len(ttft) == 1, ttft
        finally:
            obs_trace.disable()

    def test_keep_arrival_helper_contract(self):
        r = Request(prompt=[1], max_new_tokens=1)
        assert r._arrival == 0.0
        keep_arrival(r)
        first = r._arrival
        assert first > 0.0
        keep_arrival(r)  # idempotent: re-submission never resets
        assert r._arrival == first


class TestSloPolicy:
    def test_policy_validation_and_targets(self, lm):
        engine = _engine(lm, chunk=0)
        with pytest.raises(ValueError, match="policy"):
            Scheduler(engine, policy="deadline")
        with pytest.raises(ValueError, match="ttft_target_ms"):
            Request(prompt=[1], max_new_tokens=1, ttft_target_ms=0.0)
        with pytest.raises(ValueError, match="tpot_target_ms"):
            Request(prompt=[1], max_new_tokens=1, tpot_target_ms=-1.0)

    def test_slo_preempts_overbudget_for_at_risk_head(self, lm):
        """slots=1: an in-flight stream with an unmeetable TPOT target
        blocks a head whose TTFT budget is burning — the slo policy
        preempts it (preempt event + counter), the head admits, both
        streams still equal generate."""
        model, params = lm
        engine = _engine(lm, prefix="on", chunk=0, slots=1)
        sched = Scheduler(engine, policy="slo")
        p1, p2 = [2, 4, 6, 8], [3, 5, 7]
        r1 = sched.submit(Request(prompt=p1, max_new_tokens=10,
                                  tpot_target_ms=1e-6))
        for _ in range(3):  # r1 in flight, generated >= 2, over budget
            sched.tick()
        r2 = sched.submit(Request(prompt=p2, max_new_tokens=3,
                                  ttft_target_ms=1e-6))
        results = sched.run()
        assert sched.preemptions >= 1
        assert results[r1]["tokens"] == _generate_ref(model, params,
                                                      p1, 10)
        assert results[r2]["tokens"] == _generate_ref(model, params,
                                                      p2, 3)
        evs = sched.event_window
        assert any(e.get("phase") == "preempt" for e in evs)
        # the preempted request's finish verdict records the TPOT miss
        fin = [e for e in evs if e.get("phase") == "finish"
               and e.get("request") == r1]
        assert fin and fin[0]["slo_tpot_ok"] is False

    def test_slo_never_preempts_targetless_streams(self, lm):
        """No over-budget victim (streams without targets) = no
        preemption, however starved the head is."""
        engine = _engine(lm, prefix="off", chunk=0, slots=1)
        sched = Scheduler(engine, policy="slo")
        sched.submit(Request(prompt=[2, 4], max_new_tokens=6))
        for _ in range(3):
            sched.tick()
        sched.submit(Request(prompt=[3, 5], max_new_tokens=2,
                             ttft_target_ms=1e-6))
        sched.run()
        assert sched.preemptions == 0

    def test_tpot_debt_caps_chunk_rows(self, lm):
        """While an in-flight stream is over its TPOT budget, only ONE
        fill row advances per mixed tick (the interference bound);
        with the debt cleared, every fill advances."""
        engine = _engine(lm, prefix="off", chunk=2, slots=4)
        sched = Scheduler(engine, policy="slo")
        rid = sched.submit(Request(prompt=[2, 4], max_new_tokens=12,
                                   tpot_target_ms=1e-6))
        for _ in range(4):
            sched.tick()  # fill + >= 2 tokens: over budget now
        assert sched.in_flight == 1
        assert sched._chunk_row_cap() == 1
        sched.submit(Request(prompt=list(range(1, 11)),
                             max_new_tokens=2))
        sched.submit(Request(prompt=list(range(11, 21)),
                             max_new_tokens=2))
        n_before = len([e for e in sched.event_window
                        if e.get("kind") == "prefill_chunk"])
        sched.tick()
        chunk_evs = [e for e in sched.event_window
                     if e.get("kind") == "prefill_chunk"][n_before:]
        assert len(chunk_evs) == 1, chunk_evs
        # targetless in-flight = no debt = no cap
        engine2 = _engine(lm, prefix="off", chunk=2, slots=4)
        sched2 = Scheduler(engine2, policy="slo")
        sched2.submit(Request(prompt=[2, 4], max_new_tokens=12))
        for _ in range(4):
            sched2.tick()
        assert sched2._chunk_row_cap() is None

    def test_violation_and_preemption_counters_via_tap(self, lm):
        from chainermn_tpu.observability import metrics
        from chainermn_tpu.observability import trace as obs_trace

        model, params = lm
        reg = metrics.install_tap()
        obs_trace.enable(None)  # the tap rides the recorder's sinks
        try:
            engine = _engine(lm, prefix="off", chunk=2, slots=1)
            sched = Scheduler(engine, policy="slo")
            sched.submit(Request(prompt=[2, 4, 6], max_new_tokens=8,
                                 tpot_target_ms=1e-6))
            for _ in range(4):
                sched.tick()
            sched.submit(Request(prompt=[3, 5], max_new_tokens=2,
                                 ttft_target_ms=1e-6))
            sched.run()
            snap = reg.snapshot()
            pre = {tuple(v.get("labels", {}).items()): v["value"]
                   for v in snap["serving_preemptions_total"]["values"]}
            assert sum(pre.values()) >= 1
            viol = {dict(v.get("labels", {})).get("kind"): v["value"]
                    for v in snap["serving_slo_violations_total"][
                        "values"]}
            assert viol.get("tpot", 0) >= 1
            assert snap["serving_chunk_tokens_total"]["values"][0][
                "value"] > 0
            assert "serving_chunk_rows" in snap
        finally:
            obs_trace.disable()
            metrics.uninstall_tap()


class TestRollups:
    def test_tpot_and_slo_attainment_rollup(self, lm):
        """Generous targets -> every verdict ok, slo_attainment 1.0;
        TPOT percentiles present in Scheduler.summary() (the
        summarize_serving owner — trace_report's section reads the same
        dict)."""
        engine = _engine(lm, prefix="off", chunk=3)
        streams, sched = _run_stream(
            engine, _requests(4, seed=9, max_new=6),
            ttft_target_ms=1e6, tpot_target_ms=1e6,
        )
        s = sched.summary()
        assert s["slo_requests"] == 4
        assert s["slo_attainment"] == 1.0
        assert s["tpot_ms_p50"] is not None
        assert s["tpot_ms_p99"] >= s["tpot_ms_p50"]
        ck = s.get("chunked_prefill")
        assert ck and ck["chunks"] >= 1 and ck["chunk_tokens"] >= 1
        fin = [e for e in sched.event_window
               if e.get("phase") == "finish"]
        assert all(e.get("slo_ttft_ok") and e.get("slo_tpot_ok")
                   for e in fin if e.get("generated", 0) > 1)

    def test_resume_never_reenters_ttft_percentile(self, lm):
        """A resumed request's re-prefill event carries resumed=True
        and NO ttft_s: exactly one TTFT sample per request, however
        many times it was preempted."""
        engine = _engine(lm, prefix="on", chunk=0, slots=1)
        sched = Scheduler(engine, policy="prefill_priority")
        sched.start_window()
        rid = sched.submit(Request(prompt=[2, 4, 6], max_new_tokens=8))
        for _ in range(3):
            sched.tick()
        sched.preempt(next(iter(sched._inflight)))
        # drain via ticks: run() would start a FRESH window and wipe
        # the pre-preemption events this test inspects
        for _ in range(30):
            if sched.drained:
                break
            sched.tick()
        assert sched.drained
        prefills = [e for e in sched.event_window
                    if e.get("kind") == "serving"
                    and e.get("phase") == "prefill"
                    and e.get("request") == rid]
        assert len(prefills) == 2
        with_ttft = [e for e in prefills if e.get("ttft_s") is not None]
        assert len(with_ttft) == 1
        resumed = [e for e in prefills if e.get("resumed")]
        assert len(resumed) == 1 and resumed[0].get("ttft_s") is None

    def test_mid_fill_preempt_emits_one_queue_wait(self, lm):
        """Review regression: a CHUNKED admission preempted mid-fill
        (no token sampled, no resume state) re-admits as a fresh join —
        it must not emit a second whole-journey queue_wait sample (the
        percentile would count the request twice, second sample
        inflated by the aborted fill)."""
        engine = _engine(lm, prefix="off", chunk=2, slots=1)
        sched = Scheduler(engine, policy="prefill_priority")
        sched.start_window()
        rid = sched.submit(Request(prompt=list(range(1, 15)),
                                   max_new_tokens=2))
        sched.tick()  # admit into a fill (queue_wait emitted)
        slot = next(iter(sched._filling))
        sched.preempt(slot)
        for _ in range(30):
            if sched.drained:
                break
            sched.tick()
        assert sched.drained
        qw = [e for e in sched.event_window
              if e.get("kind") == "serving"
              and e.get("phase") == "queue_wait"
              and e.get("request") == rid]
        assert len(qw) == 1
        # ...and exactly one TTFT sample (delivered on the resume-fill
        # completion — the request never had a first token before)
        ttft = [e for e in sched.event_window
                if e.get("phase") == "prefill"
                and e.get("request") == rid
                and e.get("ttft_s") is not None]
        assert len(ttft) == 1

    def test_evacuate_carries_filling_requests(self, lm):
        """Replica-loss path (ISSUE 8 composition): mid-fill chunked
        admissions evacuate like in-flight ones, arrival stamps
        intact."""
        engine = _engine(lm, prefix="off", chunk=2, slots=2)
        sched = Scheduler(engine, policy="prefill_priority")
        sched.submit(Request(prompt=list(range(1, 15)),
                             max_new_tokens=2))
        sched.tick()  # admit into a fill
        assert sched.filling == 1
        orphans = sched.evacuate()
        assert len(orphans) == 1
        assert orphans[0]._arrival > 0.0
        assert sched.drained
