"""Prefix-sharing KV cache invariants (ISSUE 7).

The acceptance pins, asserted structurally:

- **Equivalence** — shared and unshared execution produce bit-identical
  token streams: the same request set through ``prefix_cache='on'``
  equals ``'off'``, equals ``decode_impl='dense'``, equals sequential
  ``generate`` — across tensor-parallel decode, speculative decode
  (including an adversarial always-wrong drafter: a rejected draft must
  never COW-corrupt a shared ancestor block), a FORCED copy-on-write on
  the boundary block (full-prefix hit), and eviction under pool
  pressure.
- **No recompile / no new collectives** — the decode and verify jit
  caches stay at ONE entry across hit/miss/COW churn, and the compiled
  decode/verify programs carry exactly the same collectives as before
  sharing existed (2 all-reduces per layer under TP, nothing else):
  sharing is host metadata plus one block-copy program.
- **Measured prefill reduction** — the ``prefix_cache`` trace events
  carry the prefilled-token counts (a hit prefills only the unshared
  tail), the rollup/metrics planes aggregate them, and the allocator's
  refcount edges (trim-to-zero, double release, ensure-after-release
  hygiene) hold now that they are load-bearing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.serving import (
    BlockAllocator,
    PrefixCache,
    Request,
    Scheduler,
    ServingEngine,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=48, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _generate_ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _shared_prefix_requests(n_tails=4, shared_len=16, seed=0):
    """One shared full-block prefix (block_size 8 in these tests) +
    short unique tails, plus one EXACT-prefix request (the forced-COW
    case) and one unrelated miss."""
    rs = np.random.RandomState(seed)
    shared = rs.randint(1, VOCAB, size=shared_len).tolist()
    reqs = [(shared + rs.randint(1, VOCAB, size=int(t)).tolist(), 4)
            for t in rs.randint(2, 6, size=n_tails)]
    reqs.append((list(shared), 4))          # full-block-exact hit: COW
    reqs.append((rs.randint(1, VOCAB, size=5).tolist(), 3))  # miss
    return reqs


def _run_stream(engine, reqs, policy="prefill_priority"):
    sched = Scheduler(engine, policy=policy)
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g))
           for p, g in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids], sched


def _engine(lm, *, prefix_cache, num_slots=2, spec_tokens=0,
            decode_impl="paged", mesh=None, num_blocks=None, **kw):
    model, params = lm
    return ServingEngine(
        model, params, num_slots=num_slots, max_len=48,
        decode_impl=decode_impl, kv_block_size=8,
        prefill_buckets=(4, 8, 16), spec_tokens=spec_tokens, mesh=mesh,
        num_blocks=num_blocks, prefix_cache=prefix_cache, **kw,
    )


class _WrongDrafter:
    """Adversarial drafter: every proposal is wrong (argmax can match a
    constant only by accident on a random model) — maximal rollback
    pressure on the shared blocks."""

    def propose(self, history, k):
        return [(history[-1] + 1) % (VOCAB - 1) + 1] * k


class TestStreamEquivalence:
    """Shared == unshared, pinned bitwise (the core invariant)."""

    def test_shared_equals_unshared_equals_dense_equals_generate(self, lm):
        model, params = lm
        reqs = _shared_prefix_requests()
        on, sched = _run_stream(_engine(lm, prefix_cache="on"), reqs)
        off, _ = _run_stream(_engine(lm, prefix_cache="off"), reqs)
        dense, _ = _run_stream(
            _engine(lm, prefix_cache="auto", decode_impl="dense"), reqs
        )
        assert on == off == dense
        for (prompt, n_new), got in zip(reqs, on):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_forced_cow_on_boundary_block_keeps_both_streams(self, lm):
        """A full-prefix hit re-feeds the last prompt token into the
        boundary block — the ONE write that targets a shared block. The
        COW must fire (measured, not assumed), the adopter's stream
        must match generate, and the original request decoding from the
        SAME blocks must be unperturbed."""
        model, params = lm
        engine = _engine(lm, prefix_cache="on")
        prompt = [(i % (VOCAB - 1)) + 1 for i in range(16)]  # 2 blocks
        slot_a, tok_a, _ = engine.prefill_join(prompt)
        slot_b, tok_b, bucket_b = engine.prefill_join(prompt)
        info = engine.last_prefix_info
        assert info["hit_blocks"] == 2 and info["hit_tokens"] == 16
        assert info["prefill_tokens"] == 1 and info["cow_blocks"] == 1
        assert bucket_b == 4  # one-token tail, smallest bucket
        assert engine.prefix_stats["cow_blocks"] == 1
        stream_a, stream_b = list(prompt) + [tok_a], list(prompt) + [tok_b]
        for _ in range(6):
            toks, _dur = engine.decode_step()
            stream_a.append(int(toks[slot_a]))
            stream_b.append(int(toks[slot_b]))
        ref = _generate_ref(model, params, prompt, 7)
        assert stream_a == ref
        assert stream_b == ref

    def test_tp_shared_stream_matches_single_device(self, lm):
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
        reqs = _shared_prefix_requests(seed=5)
        tp, _ = _run_stream(_engine(lm, prefix_cache="on", mesh=mesh,
                                    num_slots=3), reqs)
        single, _ = _run_stream(_engine(lm, prefix_cache="on",
                                        num_slots=3), reqs)
        assert tp == single
        for (prompt, n_new), got in zip(reqs, tp):
            assert got == _generate_ref(model, params, prompt, n_new)

    @pytest.mark.parametrize("drafter", [None, _WrongDrafter()],
                             ids=["ngram", "always-wrong"])
    def test_speculative_decode_composes(self, lm, drafter):
        """Sharing + speculation: rollback is host-metadata-only, so a
        rejected draft's stale writes land in COW'd/private blocks —
        never in a shared ancestor. The always-wrong drafter maximises
        rejected spans across the shared/private boundary."""
        model, params = lm
        reqs = _shared_prefix_requests(seed=9)
        spec, _ = _run_stream(
            _engine(lm, prefix_cache="on", spec_tokens=3,
                    drafter=drafter), reqs
        )
        for (prompt, n_new), got in zip(reqs, spec):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_eviction_under_pressure_keeps_streams(self, lm):
        """A pool too small to cache every prefix: ensure-would-fail
        reclaims refcount-0 trie leaves (LRU) instead of deferring, the
        evicted prefix re-prefills as a miss, and every stream still
        matches generate — the cache can degrade, never corrupt."""
        model, params = lm
        rs = np.random.RandomState(11)
        p1, p2, p3 = (rs.randint(1, VOCAB, size=16).tolist()
                      for _ in range(3))
        # 5 allocatable blocks; a live 16-token request needs 3, and
        # each finished prefix caches 2 — the third distinct prefix can
        # only be admitted by evicting an earlier one.
        engine = _engine(lm, prefix_cache="on", num_slots=1,
                         num_blocks=6)
        reqs = [(p1, 4), (p2, 4), (p3, 4), (p1, 4)]
        streams, _ = _run_stream(engine, reqs, policy="fcfs")
        assert engine.prefix_evictions() > 0
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_full_hit_cow_exhaustion_defers_never_crashes(self, lm):
        """``ensure`` reserves the prompt span but the boundary-block
        COW needs one MORE block; at genuine exhaustion the join must
        DEFER — full rollback, scheduler-retryable — not raise and leak
        the slot (a stream a cache-off engine would have served)."""
        prompt = [(i % (VOCAB - 1)) + 1 for i in range(16)]  # 2 blocks
        # 3 allocatable blocks: request A takes all 3 (ensure 17) and
        # leaves 2 cached + 1 free; B full-hits — adopt(2) + ensure
        # takes the last free block, and the boundary COW has nothing
        # left (the cached blocks are now ADOPTED, unreclaimable).
        engine = _engine(lm, prefix_cache="on", num_slots=2,
                         num_blocks=4)
        join = engine.prefill_join(prompt)
        assert join is not None
        engine.leave(join[0])
        alloc = engine._alloc
        assert alloc.blocks_cached() == 2 and alloc.free_blocks == 1
        free_slots = list(engine._free)
        stats0 = dict(engine.prefix_stats)
        v0 = alloc.version
        assert engine.prefill_join(prompt) is None  # deferred
        # full rollback: pool, slot list, accounting AND the table
        # version untouched (a retry must not force an H2D re-upload
        # of an identical table)
        assert alloc.free_blocks == 1 and alloc.blocks_cached() == 2
        assert int(alloc.refcounts.sum()) == 0
        assert list(engine._free) == free_slots
        assert engine.prefix_stats == stats0
        assert alloc.version == v0
        # the engine still serves: a no-hit prompt fits the last block
        assert engine.prefill_join(prompt[:5]) is not None


class TestStructural:
    def test_jit_cache_pinned_across_hit_miss_cow_churn(self, lm):
        engine = _engine(lm, prefix_cache="on")
        streams, _ = _run_stream(engine, _shared_prefix_requests(seed=3))
        assert len(streams) == 6
        assert engine.prefix_stats["hits"] > 0
        assert engine.prefix_stats["cow_blocks"] >= 1
        assert engine.decode_compile_count() == 1
        assert engine.prefill_compile_count() <= 3  # the bucket ladder

        spec = _engine(lm, prefix_cache="on", spec_tokens=3)
        _run_stream(spec, _shared_prefix_requests(seed=4))
        assert spec.verify_compile_count() == 1

    def test_no_new_collectives_in_decode_and_verify(self, lm):
        """Sharing is host metadata + one block-copy program: the
        compiled decode/verify steps must carry exactly the pre-sharing
        collective set (2 all-reduces per layer, nothing else), and the
        COW copy program itself must be collective-free."""
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
        engine = _engine(lm, prefix_cache="on", num_slots=3, mesh=mesh,
                         spec_tokens=2)
        n = engine.num_slots
        args = (
            engine._cache, engine._vars,
            jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        assert txt.count("all-reduce(") == 2 * model.num_layers
        vargs = (
            engine._cache, engine._vars,
            jnp.zeros((n, 3), jnp.int32), jnp.zeros((n,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        vtxt = engine._verify_step_jit.lower(*vargs).compile().as_text()
        assert vtxt.count("all-reduce(") == 2 * model.num_layers
        ctxt = engine._cow_copy_jit.lower(
            engine._cache, engine._vars, jnp.int32(1), jnp.int32(2)
        ).compile().as_text()
        for op in ("all-reduce(", "all-gather(", "collective-permute(",
                   "all-to-all(", "reduce-scatter("):
            assert ctxt.count(op) == 0, f"{op} in the COW copy"
            assert txt.count(op) == 0 or op == "all-reduce("
            assert vtxt.count(op) == 0 or op == "all-reduce("

    def test_prefill_runs_only_the_unshared_tail_measured(self, lm):
        """The acceptance criterion's number: prefix_cache trace events
        carry the per-admission prefilled-token count, and for a hit it
        is the TAIL length, not the prompt length."""
        from chainermn_tpu.observability import trace as obs_trace

        engine = _engine(lm, prefix_cache="on")
        shared = [(i % (VOCAB - 1)) + 1 for i in range(16)]
        rec = obs_trace.enable(None)
        try:
            reqs = [(shared + [3, 7, 5], 3), (shared + [9, 2], 3)]
            _run_stream(engine, reqs)
            evs = [e for e in rec.events if e["kind"] == "prefix_cache"]
        finally:
            obs_trace.disable()
        assert [e["prefill_tokens"] for e in evs] == [19, 2]
        assert [e["hit_tokens"] for e in evs] == [0, 16]
        assert all(e["schema"] == obs_trace.TRACE_SCHEMA for e in evs)


class TestAllocatorEdges:
    """The refcount change makes these paths load-bearing (ISSUE 7
    satellite): trim to zero, double release, ensure-after-release
    hygiene, and the version (epoch) discipline."""

    def test_trim_to_zero_positions_releases_everything(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 13)  # 4 blocks
        v = a.version
        a.trim(0, 0)
        assert a.version == v + 1  # one mutation, one epoch bump
        assert a.owned_blocks(0) == []
        assert (a.tables[0] == a.SCRATCH).all()
        assert a.free_blocks == 8 and a.blocks_in_use == 0
        a.trim(0, 0)  # already empty: no-op, no epoch churn
        assert a.version == v + 1

    def test_double_release_is_idempotent(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(1, 9)
        a.release(1)
        v = a.version
        free = a.free_blocks
        a.release(1)  # second release: no-op
        assert a.version == v and a.free_blocks == free
        assert (a.refcounts >= 0).all()

    def test_ensure_after_release_table_hygiene(self):
        """Released entries point at scratch, a re-ensure hands out
        fresh refcount-1 blocks, and every mutation bumps the epoch
        exactly once (the engine's H2D re-upload key)."""
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 9)  # +1
        v = a.version
        a.release(0)  # +1
        assert a.version == v + 1
        assert (a.tables[0] == a.SCRATCH).all()
        assert a.ensure(0, 5)  # +1: one bump for the whole grow
        assert a.version == v + 2
        assert a.ensure(0, 5)  # covered: no growth, no bump
        assert a.version == v + 2
        owned = a.owned_blocks(0)
        assert len(owned) == 2
        assert all(a.refcounts[b] == 1 for b in owned)
        assert (a.tables[0][:2] > 0).all() and (a.tables[0][2:] == 0).all()

    def test_shared_release_keeps_blocks_for_the_other_slot(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 8)  # 2 blocks
        shared = a.owned_blocks(0)
        a.adopt(1, shared)
        assert a.blocks_shared() == 2
        a.release(0)
        # still referenced by slot 1: not freed, tables intact
        assert a.free_blocks == 6
        assert a.owned_blocks(1) == shared
        assert a.blocks_shared() == 0  # single reference each now
        a.release(1)
        assert a.free_blocks == 8

    def test_cow_replace_and_adopt_guards(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 4)
        blk = a.owned_blocks(0)[0]
        a.adopt(1, [blk])
        assert a.shared_for_write(blk)
        fresh = a.alloc_block()
        v = a.version
        old = a.cow_replace(1, 0, fresh)
        assert old == blk and a.version == v + 1
        assert a.tables[1, 0] == fresh and a.owned_blocks(1) == [fresh]
        assert not a.shared_for_write(blk)  # back to one reference
        with pytest.raises(ValueError, match="scratch"):
            a.adopt(0, [a.SCRATCH])
        with pytest.raises(ValueError, match="horizon"):
            a.adopt(0, [fresh] * a.max_blocks)


class TestPrefixTrie:
    def _setup(self, num_blocks=10, bs=4):
        a = BlockAllocator(num_blocks=num_blocks, block_size=bs,
                           num_slots=2, max_len=32)
        return a, PrefixCache(a)

    def test_lookup_is_full_block_granular(self):
        a, c = self._setup()
        assert a.ensure(0, 11)  # 3 blocks, last partial
        blocks = a.owned_blocks(0)
        tokens = list(range(1, 12))
        assert c.insert(tokens, blocks[:2]) == 2  # partial tail refused
        assert c.n_nodes == 2
        assert c.lookup(tokens) == blocks[:2]
        assert c.lookup(tokens[:8]) == blocks[:2]
        assert c.lookup(tokens[:7]) == blocks[:1]  # 7 < one full block*2
        assert c.lookup(tokens[:3]) == []
        # diverging second block: only the first matches
        assert c.lookup(tokens[:4] + [30, 30, 30, 30]) == blocks[:1]

    def test_insert_first_writer_wins(self):
        a, c = self._setup()
        assert a.ensure(0, 8) and a.ensure(1, 8)
        tokens = [5, 6, 7, 8, 9, 10, 11, 12]
        c.insert(tokens, a.owned_blocks(0))
        first = c.lookup(tokens)
        assert c.insert(tokens, a.owned_blocks(1)) == 0  # already cached
        assert c.lookup(tokens) == first

    def test_reclaim_evicts_lru_leaf_first_never_interior(self):
        a, c = self._setup()
        assert a.ensure(0, 12)  # chain of 3
        blocks = a.owned_blocks(0)
        tokens = list(range(1, 13))
        c.insert(tokens, blocks)
        a.release(0)
        c.lookup(tokens[:4])  # touch the root chunk: LRU says leaf first
        assert c.reclaim(1) == 1
        assert c.evictions == 1
        # deepest block went; the interior chain stays intact
        assert c.lookup(tokens) == blocks[:2]
        assert c.reclaim(5) == 2  # drains leaf-at-a-time until dry
        assert c.n_nodes == 0 and a.free_blocks == 9

    def test_referenced_leaves_are_not_evictable(self):
        a, c = self._setup()
        assert a.ensure(0, 8)
        tokens = list(range(1, 9))
        c.insert(tokens, a.owned_blocks(0))
        assert c.reclaim(4) == 0  # slot 0 still references both
        a.release(0)
        assert c.reclaim(4) == 2

    def test_can_cover_counts_only_freeable_subtrees(self):
        """``can_cover`` promises only what ``reclaim`` can deliver: a
        cached ancestor whose descendant is referenced by a live slot
        never becomes an evictable leaf, so it must not be counted —
        even though the ``blocks_cached`` gauge still includes it."""
        a, c = self._setup(num_blocks=6)  # 5 allocatable
        assert a.ensure(0, 8)  # chain of 2: ancestor -> deep
        tokens = list(range(1, 9))
        blocks = a.owned_blocks(0)
        c.insert(tokens, blocks)
        a.release(0)
        # whole chain evictable: 3 free + 2 reclaimable covers 5 blocks
        assert c.reclaimable() == 2
        assert a.can_cover(1, 20)
        # a live slot adopts the DEEP block: the cached ancestor is
        # pinned (interior node over a referenced descendant)
        a.adopt(1, [blocks[1]])
        assert c.reclaimable() == 0
        assert a.blocks_cached() == 1  # the gauge still counts it...
        assert not a.can_cover(1, 20)  # ...but the promise must not
        assert not a.ensure(1, 20)  # and ensure indeed fails

    def test_hopeless_ensure_keeps_the_cache(self):
        """An ensure that cannot succeed even after full eviction must
        not flush the hot cache on its way to False — every follower
        would re-prefill for an admission that deferred anyway."""
        a, c = self._setup(num_blocks=6)  # 5 allocatable
        assert a.ensure(0, 12)  # 3 blocks live
        assert a.ensure(1, 8)   # 2 blocks
        c.insert(list(range(1, 9)), a.owned_blocks(1))
        a.release(1)            # 2 cached, 0 free
        assert not a.ensure(1, 16)  # needs 4 > 0 free + 2 reclaimable
        assert c.evictions == 0 and a.blocks_cached() == 2

    def test_ensure_drives_reclaim_through_the_hook(self):
        a, c = self._setup(num_blocks=6)  # 5 allocatable
        assert a.ensure(0, 8)  # 2 blocks
        c.insert(list(range(1, 9)), a.owned_blocks(0))
        a.release(0)
        assert a.blocks_cached() == 2 and a.free_blocks == 3
        # needs 5 > 3 free: the hook evicts both cached blocks
        assert a.ensure(1, 20)
        assert c.evictions == 2 and a.blocks_cached() == 0


class TestAccountingPlanes:
    def test_rollup_and_summary_carry_the_prefix_section(self, lm):
        engine = _engine(lm, prefix_cache="on")
        shared = [(i % (VOCAB - 1)) + 1 for i in range(16)]
        reqs = [(shared + [4, 4], 3), (shared + [5], 3), (list(shared), 3)]
        _streams, sched = _run_stream(engine, reqs)
        px = sched.summary().get("prefix_cache")
        assert px is not None
        assert px["lookups"] == 3 and px["hits"] == 2
        assert px["hit_rate"] == round(2 / 3, 4)
        assert px["prompt_tokens"] == 18 + 17 + 16
        assert px["hit_tokens"] == 32
        assert px["prefilled_tokens"] == 18 + 1 + 1
        assert px["cow_blocks"] == 1
        # off engines emit no prefix events -> section absent, not empty
        off = _engine(lm, prefix_cache="off")
        _streams2, sched2 = _run_stream(off, reqs)
        assert "prefix_cache" not in sched2.summary()

    def test_metrics_tap_and_gauges(self, lm):
        from chainermn_tpu.observability import metrics
        from chainermn_tpu.observability import trace as obs_trace

        metrics.reset()
        reg = metrics.install_tap()
        rec = obs_trace.enable(None)
        try:
            engine = _engine(lm, prefix_cache="on")
            shared = [(i % (VOCAB - 1)) + 1 for i in range(16)]
            reqs = [(shared + [4, 4], 3), (list(shared), 3)]
            _run_stream(engine, reqs)
            assert reg.counter("kv_prefix_lookups_total").value() == 2.0
            assert reg.counter("kv_prefix_hits_total").value() == 1.0
            assert reg.counter(
                "kv_prefix_hit_tokens_total").value() == 16.0
            assert reg.counter(
                "kv_prefix_prefill_tokens_total").value() == 19.0
            assert reg.counter(
                "kv_prefix_cow_blocks_total").value() == 1.0
            # admit-time gauges (engine state, not events)
            assert reg.gauge("kv_prefix_hit_rate").value() == \
                pytest.approx(16.0 / 34.0)
            assert reg.gauge("kv_prefix_trie_blocks").value() == 2.0
            assert reg.gauge("kv_blocks_cached").value() is not None
            assert reg.gauge("kv_blocks_shared").value() is not None
        finally:
            obs_trace.disable()
            metrics.reset()

    def test_dense_engine_forces_prefix_off(self, lm):
        engine = _engine(lm, prefix_cache="auto", decode_impl="dense")
        assert not engine.prefix_cache_enabled
        assert engine.prefix_trie_blocks() is None
        d = {x["name"]: x for x in engine.decisions}
        assert d["prefix_cache"]["winner"] == "off"
        assert d["prefix_cache"]["source"] == "forced:dense"

    def test_validation(self, lm):
        with pytest.raises(ValueError, match="prefix_cache"):
            _engine(lm, prefix_cache="maybe")
        # same typo, same error on a DENSE engine — the forced-off
        # shortcut must not swallow validation
        with pytest.raises(ValueError, match="prefix_cache"):
            _engine(lm, prefix_cache="maybe", decode_impl="dense")
        with pytest.raises(ValueError, match="min_shared_blocks"):
            _engine(lm, prefix_cache="on", min_shared_blocks=0)

    def test_min_shared_blocks_gates_adoption(self, lm):
        engine = _engine(lm, prefix_cache="on", min_shared_blocks=2)
        first = [(i % (VOCAB - 1)) + 1 for i in range(8)]  # ONE block
        s0, _, _ = engine.prefill_join(first + [3])
        engine.leave(s0)
        _, _, _ = engine.prefill_join(first + [5, 6])
        info = engine.last_prefix_info
        assert info["hit_blocks"] == 0  # 1-block match < threshold
        assert engine.prefix_stats["hits"] == 0
