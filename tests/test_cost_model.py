"""α–β cost-model contract (chainermn_tpu.parallel.cost_model).

ISSUE 16's schedule search is only admissible if the model is audited,
never trusted blind — so the tests pin exactly that contract:

- stage terms reproduce the ring arithmetic (ar == rs>ag by
  construction, su free, ag prices the gathered size, bc prices
  tree_sends) and sliced pricing is the software pipeline's critical
  path (max within an issue tick, sum across);
- a fit ROUND-TRIPS the rows it was fitted from within its own stated
  ``fit_err_pct`` (the tolerance callers gate adoptions against), and
  recovers a synthetic ground-truth model near-exactly;
- rank order is deterministic across runs and candidate orderings;
- the UNCALIBRATED degrade is loud: no rows for the mesh shape →
  mode ``exhaustive``, provenance ``forced:uncalibrated``, every
  candidate measured — never a ranking off a default model;
- on THIS box's committed BENCH_DETAILS.json rows the predicted winner
  lands inside the measured spread gate of the measured best (the
  acceptance criterion);
- offline seeding adopts ``topk`` when the recorded model error sits
  inside the spread and ``exhaustive`` when it does not, with the
  predicted rows carried as evidence.
"""

import json
import os
import random

import pytest

from chainermn_tpu import tuning
from chainermn_tpu.parallel.composition import (
    canonical_axis_names,
    derive_compositions,
    tree_sends,
)
from chainermn_tpu.parallel.cost_model import (
    UNCALIBRATED,
    WIRE_ITEMSIZE,
    CostModel,
    fit_pipeline_rows,
    load_from_bench_details,
    model_error_pct,
    rank_compositions,
    stage_terms,
)
from chainermn_tpu.parallel.composition import compile_schedule

SHAPE3 = (2, 2, 2)
AXES3 = canonical_axis_names(3)
PAYLOAD = 1 << 20  # 1 MiB — the bench's composed-phase payload


def _model(alphas, betas, shape=SHAPE3, source="fit:test"):
    return CostModel(world_shape=tuple(shape), alphas=tuple(alphas),
                     betas=tuple(betas), source=source, fit_err_pct=0.0)


def _grid_sigs(shape=SHAPE3):
    axes = canonical_axis_names(len(shape))
    return [c.signature() for c in derive_compositions(axes)]


class TestStageTerms:
    def test_ar_equals_rs_ag_by_construction(self):
        """The ring arithmetic prices ar(X) and rs(X)>ag(X)
        identically — the model family cannot split them, so the rank
        tie-break (signature string) is what keeps order stable."""
        m = _model([0.1, 0.2, 0.5], [1e-6, 2e-6, 4e-6])
        assert m.predict("ar(a0+a1+a2)", PAYLOAD) == pytest.approx(
            m.predict("rs(a0+a1+a2)>ag(a0+a1+a2)", PAYLOAD))

    def test_su_is_free(self):
        m = _model([0.1, 0.2, 0.5], [1e-6, 2e-6, 4e-6])
        assert m.predict("rs(a0+a1+a2)>su>ag(a0+a1+a2)",
                         PAYLOAD) == pytest.approx(
            m.predict("rs(a0+a1+a2)>ag(a0+a1+a2)", PAYLOAD))

    def test_level_is_slowest_member(self):
        """A merged group rides its slowest member's wire: a0 is the
        slow level, so a group containing a0 prices off level 0."""
        comp = compile_schedule("rs(a2)>ar(a0+a1)>ag(a2)", AXES3)
        rows = stage_terms(comp, PAYLOAD // WIRE_ITEMSIZE, SHAPE3)
        assert [lvl for _, lvl, _, _ in rows] == [2, 0, 2]
        # only the level-0 alpha charged: ar over the merged (a0,a1)
        # pair has n=4 -> 2(n-1) = 6 steps
        slow = _model([1.0, 0.0, 0.0], [0.0, 0.0, 0.0])
        assert slow.predict("rs(a2)>ar(a0+a1)>ag(a2)",
                            PAYLOAD) == pytest.approx(6.0)

    def test_allgather_prices_output_size(self):
        """ag's wire bytes follow the GATHERED size: after rs(a0+a1+a2)
        the shard is 1/8, and ag moves (n-1)/n of the FULL buffer —
        identical wire to the rs leg, not 1/8th of it."""
        comp = compile_schedule("rs(a0+a1+a2)>ag(a0+a1+a2)", AXES3)
        rows = stage_terms(comp, PAYLOAD // WIRE_ITEMSIZE, SHAPE3)
        (_, _, _, wire_rs), (_, _, _, wire_ag) = rows
        assert wire_ag == pytest.approx(wire_rs)

    def test_bc_prices_tree_sends(self):
        m = _model([1.0, 0.0, 0.0], [0.0, 0.0, 0.0])
        # bc over all 3 axes: n=8, radix 2 -> tree_sends = 3 steps
        assert m.predict("bc(a0+a1+a2)", PAYLOAD) == pytest.approx(
            float(tree_sends(8, 2)))
        assert m.predict("bc(a0+a1+a2)@4", PAYLOAD) == pytest.approx(
            float(tree_sends(8, 4)))

    def test_sliced_is_critical_path_not_sum(self):
        """S slices of a 2-stage pipeline cost S+1 ticks, not 2S: the
        fast stage hides behind the slow one, which is exactly why the
        model can rank sliced arms without measuring them."""
        m = _model([1.0, 1.0, 1.0], [0.0, 0.0, 0.0])
        flat_sig = "rs(a2)>rs(a0+a1)>ag(a0+a1)>ag(a2)"
        flat = m.predict(flat_sig, PAYLOAD)
        sliced = m.predict(
            "rs(a2)[s0..3]>rs(a0+a1)>ag(a0+a1)>ag(a2)", PAYLOAD)
        # flat: per-stage steps [1,3,3,1] -> 8. Sliced S=4: ticks 0..6
        # cost max-of-members [1,3,3,3,3,3,1] -> 17, NOT the 32 a
        # serial rendering of 4 slices would pay.
        assert flat == pytest.approx(8.0)
        assert sliced == pytest.approx(17.0)

    def test_zigzag_prices_like_contiguous(self):
        """Zigzag changes the cut pattern, not the per-slice sizes —
        the model must price the layouts identically."""
        m = _model([0.3, 0.2, 0.1], [1e-6, 2e-6, 3e-6])
        a = m.predict("rs(a2)[s0..3]>rs(a0+a1)>ag(a0+a1)>ag(a2)", PAYLOAD)
        b = m.predict("rs(a2)[z0..3]>rs(a0+a1)>ag(a0+a1)>ag(a2)", PAYLOAD)
        assert a == pytest.approx(b)


class TestFit:
    def test_recovers_synthetic_ground_truth(self):
        """Rows generated BY a known model fit back to near-zero
        residual — the fit's sanity anchor."""
        truth = _model([0.12, 0.25, 0.56],
                       [9e-7, 9.5e-7, 1.1e-6])
        rows = {s: truth.predict(s, PAYLOAD) for s in _grid_sigs()}
        fitted = fit_pipeline_rows(rows, SHAPE3, PAYLOAD)
        assert fitted.fit_err_pct < 0.1
        for s, ms in rows.items():
            assert fitted.predict(s, PAYLOAD) == pytest.approx(
                ms, rel=1e-3)

    def test_round_trips_within_stated_tolerance(self):
        """THE contract: a fitted model reproduces the rows it was
        fitted from within its own stated fit_err_pct — noisy rows
        included."""
        truth = _model([0.12, 0.25, 0.56], [9e-7, 9.5e-7, 1.1e-6])
        rng = random.Random(7)
        rows = {s: truth.predict(s, PAYLOAD) * rng.uniform(0.85, 1.15)
                for s in _grid_sigs()}
        fitted = fit_pipeline_rows(rows, SHAPE3, PAYLOAD)
        # fit_err_pct is rounded to 3 decimals of a percent — allow
        # exactly that rounding slack, nothing more
        tol = (fitted.fit_err_pct + 1e-3) / 100.0
        for s, ms in rows.items():
            assert abs(fitted.predict(s, PAYLOAD) - ms) <= tol * abs(ms)
        assert fitted.fit_rows == tuple(sorted(rows))

    def test_coefficients_are_physical(self):
        """Non-negative α/β even on adversarial rows: a step or a byte
        never pays back time."""
        rng = random.Random(3)
        rows = {s: rng.uniform(1.0, 10.0) for s in _grid_sigs()}
        fitted = fit_pipeline_rows(rows, SHAPE3, PAYLOAD)
        assert all(a >= 0.0 for a in fitted.alphas)
        assert all(b >= 0.0 for b in fitted.betas)

    def test_refuses_underdetermined(self):
        from chainermn_tpu.parallel.composition import CompositionError

        with pytest.raises(CompositionError, match=">= 2"):
            fit_pipeline_rows({"ar(a0+a1+a2)": 3.2}, SHAPE3, PAYLOAD)


class TestRank:
    def test_deterministic_across_runs_and_orderings(self):
        m = _model([0.12, 0.25, 0.56], [9e-7, 9.5e-7, 1.1e-6])
        sigs = _grid_sigs()
        first = rank_compositions(m, sigs, PAYLOAD, k=3)
        again = rank_compositions(m, sigs, PAYLOAD, k=3)
        shuffled = list(sigs)
        random.Random(11).shuffle(shuffled)
        reordered = rank_compositions(m, shuffled, PAYLOAD, k=3)
        assert first.order == again.order == reordered.order
        assert first.predicted_ms == reordered.predicted_ms
        assert first.measured == first.order[:3]
        assert first.skipped == first.order[3:]
        assert first.mode == "topk"
        assert first.provenance == "cost_model:fit:test"
        # no silent coverage loss: every skipped arm keeps its price
        assert all(s in first.predicted_ms for s in first.skipped)

    def test_uncalibrated_degrades_loudly(self):
        """model=None → exhaustive with forced:uncalibrated — a
        ranking is never built on a default-initialized model."""
        sigs = _grid_sigs()
        r = rank_compositions(None, sigs, PAYLOAD, k=3)
        assert r.mode == "exhaustive"
        assert r.provenance == UNCALIBRATED
        assert r.measured == tuple(sigs)
        assert r.skipped == ()
        assert r.predicted_ms == {}

    def test_exhaustive_requested(self):
        m = _model([0.1, 0.2, 0.5], [1e-6, 2e-6, 4e-6])
        r = rank_compositions(m, _grid_sigs(), PAYLOAD, mode="exhaustive")
        assert r.mode == "exhaustive"
        assert r.provenance == "exhaustive:requested"
        assert r.skipped == ()


class TestBenchDetailsRows:
    """The acceptance criterion, on THIS box's committed rows."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    DETAILS = os.path.join(REPO, "BENCH_DETAILS.json")

    def _rows(self):
        with open(self.DETAILS) as f:
            data = json.load(f)
        rows = data.get("composed_schedule_ms")
        if not isinstance(rows, dict) or len(rows) < 2:
            pytest.skip("no composed rows in BENCH_DETAILS.json")
        return data, rows

    def test_fit_loads_and_round_trips(self):
        data, rows = self._rows()
        model = load_from_bench_details(self.DETAILS)
        assert model is not None
        assert model.source == "fit:bench_details"
        assert model.world_shape == tuple(data["composed_world_shape"])
        payload = int(float(data.get("composed_payload_mb", 1)) * (1 << 20))
        # fit_err_pct is stored round(err*100, 3): it can understate the
        # true worst residual by half an ULP of that rounding (5e-4 pct
        # points), so allow exactly that margin on top.
        tol = (model.fit_err_pct + 5e-4) / 100.0
        for s, ms in rows.items():
            assert abs(model.predict(s, payload) - float(ms)) <= (
                tol * abs(float(ms)))

    def test_predicted_winner_inside_spread_gate(self):
        """rank_compositions reproduces the measured winner INSIDE the
        spread gate: the predicted-best arm's measured time is within
        measured-best · (1 + spread/100)."""
        data, rows = self._rows()
        model = load_from_bench_details(self.DETAILS)
        payload = int(float(data.get("composed_payload_mb", 1)) * (1 << 20))
        spread = float(data.get("composed_spread_pct", 10.0)) or 10.0
        r = rank_compositions(model, list(rows), payload, k=3)
        assert r.mode == "topk"
        best_measured = min(float(v) for v in rows.values())
        predicted_winner_measured = float(rows[r.measured[0]])
        gate = best_measured * (1.0 + spread / 100.0)
        assert predicted_winner_measured <= gate, (
            f"predicted winner {r.measured[0]} measured "
            f"{predicted_winner_measured} vs gate {gate}")
        # and the model's own audit number on these rows sits inside
        # the spread (the topk-adoption condition the seeding uses)
        err = model_error_pct(r.predicted_ms, rows)
        assert err is not None and err <= spread

    def test_shape_mismatch_returns_none(self):
        assert load_from_bench_details(
            self.DETAILS, world_shape=(4, 4)) is None

    def test_missing_file_returns_none(self, tmp_path):
        assert load_from_bench_details(str(tmp_path / "nope.json")) is None

    def test_rowless_file_returns_none(self, tmp_path):
        p = tmp_path / "empty.json"
        p.write_text(json.dumps({"device_kind": "cpu"}))
        assert load_from_bench_details(str(p)) is None

    def test_underdetermined_rows_return_none(self, tmp_path):
        """A prior TOP-K capture leaves only the arms it measured; an
        interpolating fit over < 2k+1 rows would round-trip perfectly
        while extrapolating garbage to the skipped arms — the one
        failure mode the audit cannot see. load refuses it."""
        p = tmp_path / "topk.json"
        p.write_text(json.dumps({
            "composed_schedule_ms": {
                "ar(a0+a1+a2)": 3.2,
                "rs(a0+a1+a2)>ag(a0+a1+a2)": 3.3,
                "rs(a1+a2)>ar(a0)>ag(a1+a2)": 3.6,
                "rs(a2)>ar(a0+a1)>ag(a2)": 3.9,
            },
            "composed_world_shape": [2, 2, 2],
            "composed_payload_mb": 1,
        }))
        assert load_from_bench_details(str(p)) is None


class TestModelError:
    def test_max_relative_error(self):
        err = model_error_pct({"a": 1.0, "b": 2.0}, {"a": 1.1, "b": 2.0})
        assert err == pytest.approx(100.0 / 11.0, abs=0.01)

    def test_no_overlap_is_none(self):
        assert model_error_pct({"a": 1.0}, {"b": 1.0}) is None


class TestSchedSearchTraceEvent:
    """The search's audit record on the trace plane: emit -> one
    ``sched_search`` event; summarize_overlap turns it into the
    predicted-vs-measured rows (skipped arms still priced) and the
    composition rows above gain the predicted_ms column."""

    def test_emit_and_summarize(self):
        from chainermn_tpu.observability import trace
        from chainermn_tpu.parallel.cost_model import (
            emit_sched_search_event,
        )

        model = _model([1.0] * 3, [0.0] * 3)
        sigs = _grid_sigs()
        rank = rank_compositions(model, sigs, PAYLOAD, k=2)
        rec = trace.enable(None)
        try:
            measured = {s: rank.predicted_ms[s] * 1.05
                        for s in rank.measured}
            err = emit_sched_search_event(rank, measured,
                                          spread_pct=10.0)
            # |pred - meas| / meas = 0.05/1.05
            assert err == pytest.approx(100 * 0.05 / 1.05, abs=0.01)
            evs = [e for e in rec.events
                   if e.get("kind") == "sched_search"]
            assert len(evs) == 1
            ev = evs[0]
            assert ev["mode"] == "topk"
            assert ev["provenance"] == "cost_model:fit:test"
            assert ev["err_pct"] == err
            assert ev["spread_pct"] == 10.0
            # summarizer: rows for every arm, skipped flagged, and a
            # composition row picks up the predicted column
            wire = {"kind": "wire", "composition": rank.measured[0],
                    "schedule": rank.measured[0],
                    "stage": rank.measured[0], "stage_op": "all-reduce",
                    "nbytes": 64, "stage_index": 0}
            ov = trace.summarize_overlap([wire] + rec.events)
            ss = ov["sched_search"]
            assert ss["mode"] == "topk" and ss["err_pct"] == err
            assert set(ss["rows"]) == set(sigs)
            for s in rank.skipped:
                assert ss["rows"][s]["skipped"] is True
                assert "predicted_ms" in ss["rows"][s]
            comp_row = ov["compositions"][rank.measured[0]]
            assert comp_row["predicted_ms"] == pytest.approx(
                rank.predicted_ms[rank.measured[0]], abs=1e-3)
        finally:
            trace.disable()

    def test_no_recorder_still_returns_error(self):
        from chainermn_tpu.observability import trace
        from chainermn_tpu.parallel.cost_model import (
            emit_sched_search_event,
        )

        assert trace.active() is None
        model = _model([1.0] * 3, [0.0] * 3)
        rank = rank_compositions(model, _grid_sigs(), PAYLOAD, k=2)
        err = emit_sched_search_event(
            rank, {s: rank.predicted_ms[s] for s in rank.measured})
        assert err == 0.0


class TestSchedSearchSeeding:
    """Offline seeding of the sched_search decision from the bench's
    model-audit keys — topk inside the spread, exhaustive past it."""

    @pytest.fixture(autouse=True)
    def _isolated_cache(self, tmp_path, monkeypatch):
        # conftest pins AUTOTUNE=off for hermeticity; re-enable cache
        # resolution against a tmp cache so choice() can hit the seed
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        monkeypatch.delenv("CHAINERMN_TPU_AUTOTUNE", raising=False)
        monkeypatch.delenv("CHAINERMN_TPU_AUTOTUNE_FORCE", raising=False)

    def _seed(self, tmp_path, err, spread=32.1):
        details = {
            "device_kind": "cpu", "n_devices": 8,
            "measured_at": "2026-08-07T00:00:00Z",
            "composed_world_shape": [2, 2, 2],
            "composed_payload_mb": 1,
            "composed_spread_pct": spread,
            "cost_model_err_pct": err,
            "sched_search_selected": "topk",
            "sched_search_predicted_ms": {"ar(a0+a1+a2)": 3.23,
                                          "rs(a2)>ag(a2)": 4.0},
            "sched_search_skipped": ["rs(a2)>ag(a2)"],
        }
        p = tmp_path / "details.json"
        p.write_text(json.dumps(details))
        return tuning.seed_from_bench_details(str(p))

    def test_error_inside_spread_seeds_topk(self, tmp_path):
        seeded = self._seed(tmp_path, err=21.08)
        assert any(s.startswith("sched_search|") and s.endswith("topk")
                   for s in seeded)
        key = tuning.decision_key("cpu", shape=(2, 2, 2, 1),
                                  dtype="search")
        assert tuning.choice("sched_search", ("topk", "exhaustive"),
                             key) == "topk"
        rec = [r for r in tuning.decisions_taken()
               if r["key"] == key][-1]
        assert rec["source"].startswith("cache:seeded")
        # the full audit rides the cache ENTRY as evidence
        from chainermn_tpu.tuning.cache import lookup_entry

        ev = lookup_entry("sched_search", key)
        assert ev["cost_model_err_pct"] == pytest.approx(21.08)
        assert ev["spread_pct"] == pytest.approx(32.1)
        assert ev["predicted_ms"]["ar(a0+a1+a2)"] == pytest.approx(3.23)
        assert ev["skipped"] == ["rs(a2)>ag(a2)"]
        assert ev["selected"] == "topk"

    def test_error_past_spread_seeds_exhaustive(self, tmp_path):
        seeded = self._seed(tmp_path, err=55.0)
        assert any(s.startswith("sched_search|")
                   and s.endswith("exhaustive") for s in seeded)

    def test_no_audit_keys_seeds_nothing(self, tmp_path):
        p = tmp_path / "details.json"
        p.write_text(json.dumps({"device_kind": "cpu", "n_devices": 8}))
        assert not any(s.startswith("sched_search|")
                       for s in tuning.seed_from_bench_details(str(p)))
