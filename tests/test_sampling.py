"""Counter-based sampling (ISSUE 18): one derivation rule, every
schedule.

Token *i* of a request draws from ``fold_in(fold_in(base_key,
request_seed), i)`` — a pure function of (base key, request seed,
stream position), not of which compiled program emitted it or how many
times keys were split before it. That single property is what this file
pins, path by path:

- ``generate`` at a fixed ``(rng, seeds)`` is bit-reproducible;
- the serving engine's sampled streams == ``generate`` under staggered
  join/leave churn (the greedy stream-equivalence invariant extended to
  temperature > 0);
- speculative verify, chunked prefill, chunked+spec mixed, and
  sequence-parallel prefill each emit the SAME sampled stream as the
  monolithic single-token schedule (these combinations used to raise
  "greedy-only" — the gate this issue deleted);
- preempt/resume and export_kv/import_kv migration resume the stream
  bit-identically (the seed rides the request / the payload, and the
  resumed position re-derives the same counter key);
- the rejection-sampling acceptance rule is distribution-exact: the
  committed-token marginal equals the target softmax regardless of
  what the deterministic drafter proposed (TV-distance bound);
- the scheduler's derived per-request seeds are deterministic
  (``crc32(request_id)``), so re-running a workload reproduces it.

See docs/serving.md "Sampling" for the derivation contract.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models.transformer import (
    TransformerLM,
    _tempered_filtered,
    generate,
    stream_sample_keys,
)
from chainermn_tpu.serving import Request, Scheduler, ServingEngine
from chainermn_tpu.serving.speculate import rejection_accept_length

VOCAB = 64
PROMPT = [3, 5, 7, 2, 9, 11, 4, 8, 1, 6]
SEED = 123
N_TOKENS = 12
TEMP = 0.8


@pytest.fixture(scope="module")
def lm():
    model = TransformerLM(vocab_size=VOCAB, num_layers=2, num_heads=4,
                          d_model=16, d_ff=32, max_len=64,
                          compute_dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)
    return model, params


@pytest.fixture(scope="module")
def ref(lm):
    """The monolithic sampled stream every schedule must reproduce."""
    model, params = lm
    return np.asarray(generate(
        model, params, jnp.asarray([PROMPT], jnp.int32),
        len(PROMPT) + N_TOKENS, temperature=TEMP,
        rng=jax.random.PRNGKey(42), seeds=jnp.array([SEED], jnp.int32),
    ))[0, len(PROMPT):].tolist()


def _engine(lm, **kw):
    model, params = lm
    cfg = dict(num_slots=4, decode_impl="paged", kv_block_size=8,
               prefill_buckets=(8, 16), temperature=TEMP,
               rng=jax.random.PRNGKey(42), prefix_cache="off")
    cfg.update(kw)
    return ServingEngine(model, params, **cfg)


def _drive_plain(eng, n, seed=SEED, prompt=PROMPT):
    slot, tok, _ = eng.prefill_join(prompt, seed=seed)
    s = [tok]
    while len(s) < n:
        toks, _ = eng.decode_step()
        s.append(int(toks[slot]))
    return slot, s


def _drive_mixed(eng, slot, n):
    s = []
    for _ in range(64):
        committed, fills, _d, _st = eng.mixed_step()
        for f in fills:
            if f["slot"] == slot and f["done"]:
                s.append(f["first_tok"])
        if slot in committed:
            s.extend(committed[slot])
        if len(s) >= n:
            break
    return s[:n]


# ----------------------------------------------------------------------
# generate: the derivation rule itself
# ----------------------------------------------------------------------


def test_generate_fixed_seed_reproducible(lm):
    model, params = lm
    def run(base, seed):
        return np.asarray(generate(
            model, params, jnp.asarray([PROMPT], jnp.int32),
            len(PROMPT) + N_TOKENS, temperature=TEMP,
            rng=jax.random.PRNGKey(base),
            seeds=jnp.array([seed], jnp.int32),
        ))[0].tolist()
    assert run(42, SEED) == run(42, SEED)
    assert run(42, SEED) != run(42, SEED + 1)  # seed reaches the keys
    assert run(42, SEED) != run(43, SEED)      # base key does too


def test_stream_sample_keys_match_scalar_fold_in():
    """The vmapped batch derivation == per-row fold_in chains (Threefry
    batch invariance — the property that lets one grid sample stand in
    for T sequential single-token samples)."""
    base = jax.random.PRNGKey(7)
    seeds = jnp.array([1, 9, 1], jnp.int32)
    counters = jnp.array([4, 4, 5], jnp.int32)
    got = stream_sample_keys(base, seeds, counters)
    for i in range(3):
        want = jax.random.fold_in(
            jax.random.fold_in(base, int(seeds[i])), int(counters[i]))
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(want))


# ----------------------------------------------------------------------
# engine schedules: every path emits the monolithic stream
# ----------------------------------------------------------------------


def test_sampled_engine_matches_generate(lm, ref):
    _slot, s = _drive_plain(_engine(lm), N_TOKENS)
    assert s == ref


def test_sampled_spec_matches_monolithic(lm, ref):
    eng = _engine(lm, spec_tokens=3)
    slot, tok, _ = eng.prefill_join(PROMPT, seed=SEED)
    s = [tok]
    stats = None
    while len(s) < N_TOKENS:
        committed, _d, stats = eng.verify_step()
        s.extend(committed[slot])
    assert s[:N_TOKENS] == ref
    assert stats["mode"] == "sampled"


def test_sampled_chunked_matches_monolithic(lm, ref):
    eng = _engine(lm, prefill_chunk=4)
    slot = eng.chunked_join(PROMPT, seed=SEED)
    assert _drive_mixed(eng, slot, N_TOKENS) == ref


def test_sampled_spec_plus_chunked_matches_monolithic(lm, ref):
    eng = _engine(lm, spec_tokens=3, prefill_chunk=4)
    slot = eng.chunked_join(PROMPT, seed=SEED)
    assert _drive_mixed(eng, slot, N_TOKENS) == ref


def test_sampled_seq_parallel_prefill_matches_monolithic(lm, ref):
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    eng = _engine(lm, mesh=mesh, prefill_seq_parallel="on")
    _slot, s = _drive_plain(eng, N_TOKENS)
    assert eng.last_prefill_seq_parallel
    assert s == ref


# ----------------------------------------------------------------------
# the seed rides the request: preemption, migration
# ----------------------------------------------------------------------


def test_sampled_preempt_resume_bit_identical(lm, ref):
    eng = _engine(lm)
    slot, tok, _ = eng.prefill_join(PROMPT, seed=SEED)
    s = [tok]
    for _ in range(4):
        toks, _ = eng.decode_step()
        s.append(int(toks[slot]))
    eng.preempt(slot)
    # Resume = re-prefill prompt + emitted history with the SAME seed:
    # the first resumed sample's counter is the re-prefilled length —
    # exactly the uninterrupted stream's counter at that position.
    history = PROMPT + s
    slot2, tok2, _ = eng.prefill_join(history, seed=SEED)
    s.append(tok2)
    while len(s) < N_TOKENS:
        toks, _ = eng.decode_step()
        s.append(int(toks[slot2]))
    assert s == ref


def test_sampled_migration_bit_identical(lm, ref):
    src = _engine(lm)
    slot, tok, _ = src.prefill_join(PROMPT, seed=SEED)
    s = [tok]
    for _ in range(4):
        toks, _ = src.decode_step()
        s.append(int(toks[slot]))
    payload = src.export_kv(slot)
    assert payload["seed"] == SEED  # the seed rides the payload
    dst = _engine(lm)
    slot2, _last = dst.import_kv(payload)
    while len(s) < N_TOKENS:
        toks, _ = dst.decode_step()
        s.append(int(toks[slot2]))
    assert s == ref


# ----------------------------------------------------------------------
# scheduler plumbing: derived seeds, end-to-end streams
# ----------------------------------------------------------------------


def test_scheduler_sampled_streams_match_generate(lm):
    """Staggered joins/leaves (2 slots, 4 requests) at temperature > 0:
    every request's engine stream == its own ``generate`` stream at the
    request's seed — churn cannot perturb a neighbouring stream."""
    model, params = lm
    eng = _engine(lm, num_slots=2)
    sched = Scheduler(eng)
    rs = np.random.RandomState(11)
    reqs = [(rs.randint(1, VOCAB, size=int(rs.randint(2, 8))).tolist(),
             int(rs.randint(2, 6)), 1000 + i) for i in range(4)]
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g, seed=sd))
           for p, g, sd in reqs]
    results = sched.run()
    for (prompt, n_new, sd), rid in zip(reqs, ids):
        want = np.asarray(generate(
            model, params, jnp.asarray([prompt], jnp.int32),
            len(prompt) + n_new, temperature=TEMP,
            rng=jax.random.PRNGKey(42),
            seeds=jnp.array([sd], jnp.int32),
        ))[0].tolist()
        assert results[rid]["tokens"] == want


def test_scheduler_derives_deterministic_seeds(lm):
    """No explicit seed -> ``crc32(request_id)``: reproducible across
    runs (replayable workload), distinct across requests (streams must
    not correlate)."""
    eng = _engine(lm, num_slots=2)
    sched = Scheduler(eng)
    r1 = Request(prompt=[1, 2, 3], max_new_tokens=2)
    r2 = Request(prompt=[1, 2, 3], max_new_tokens=2)
    id1, id2 = sched.submit(r1), sched.submit(r2)
    assert r1.seed == zlib.crc32(str(id1).encode()) & 0x7FFFFFFF
    assert r2.seed == zlib.crc32(str(id2).encode()) & 0x7FFFFFFF
    assert r1.seed != r2.seed
    explicit = Request(prompt=[4], max_new_tokens=1, seed=9)
    sched.submit(explicit)
    assert explicit.seed == 9  # explicit seeds are never overwritten


# ----------------------------------------------------------------------
# acceptance rule: deterministic AND distribution-exact
# ----------------------------------------------------------------------


def test_rejection_acceptance_matches_greedy_rule_on_point_drafts():
    # Maximal coupling against a point-mass drafter reduces to exact
    # match: accept d with probability p(d) <=> accept iff x == d for
    # x ~ p. The shared implementation is the proof made structural.
    assert rejection_accept_length([3, 5, 9], [3, 5, 2, 7]) == 2
    assert rejection_accept_length([3, 5, 9], [3, 5, 9, 7], room=2) == 2
    assert rejection_accept_length([1], [2, 3]) == 0


def test_committed_marginal_is_target_distribution():
    """Distribution-exactness, measured: commit tokens through the
    counter-keyed sample + rejection rule against an ADVERSARIAL
    deterministic drafter (always drafts the modal token), and the
    committed-token marginal still equals softmax(logits/T) within a
    TV-distance bound. N=4096 counters stand in for 4096 stream
    positions."""
    n, v = 4096, 16
    logits = jnp.asarray(np.random.RandomState(0).randn(v) * 1.5,
                         jnp.float32)
    base = jax.random.PRNGKey(5)
    keys = stream_sample_keys(base, jnp.zeros((n,), jnp.int32),
                              jnp.arange(n, dtype=jnp.int32))
    filt = _tempered_filtered(jnp.tile(logits[None], (n, 1)), TEMP,
                              None, None)
    sampled = np.asarray(jax.vmap(jax.random.categorical)(keys, filt))
    draft = int(jnp.argmax(logits))  # modal draft: worst-case coupling
    committed = np.array([
        # accept -> commit the draft; reject -> commit the sample.
        draft if rejection_accept_length([draft], [x, 0]) else x
        for x in sampled
    ])
    target = np.asarray(jax.nn.softmax(logits / TEMP))
    emp = np.bincount(committed, minlength=v) / n
    tv = 0.5 * np.abs(emp - target).sum()
    assert tv < 0.05, f"TV distance {tv:.4f} vs target distribution"


def test_sampled_spec_is_deterministic(lm):
    def run():
        eng = _engine(lm, spec_tokens=2)
        slot, tok, _ = eng.prefill_join(PROMPT, seed=SEED)
        s = [tok]
        while len(s) < 8:
            committed, _d, _st = eng.verify_step()
            s.extend(committed[slot])
        return s[:8]
    assert run() == run()
