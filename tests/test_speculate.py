"""Speculative draft-and-verify serving invariants (ISSUE 5).

The acceptance pins, asserted structurally:

- **Stream equivalence** — greedy speculative token streams are
  bit-identical to sequential ``generate`` across dense == paged ==
  tensor-parallel == single-device, for rope/learned positions, GQA and
  windowed variants, under forced staggered slot churn AND forced-low
  acceptance (an adversarial drafter whose every proposal is wrong):
  speculation is a throughput lever, never a sampling change.
- **One compiled verify program** — the verify-step jit cache stays at
  ONE entry across request churn and acceptance variation, and the
  compiled TP verify step carries exactly 2 all-reduces per layer
  regardless of K (collectives amortized, not multiplied) — HLO
  -counted.
- **Host-only rollback** — rejected drafts rewind positions/tables on
  the host; the paged pool redirects beyond-horizon span writes to
  scratch, and a slot leave → same-slot rejoin with a shorter prompt
  never reads a stale block (position-rewind guarantee).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.serving import (
    ModelDrafter,
    NgramDrafter,
    Request,
    Scheduler,
    ServingEngine,
    accept_length,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _requests(n, seed=0, max_prompt=7, max_new=6):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        p_len = int(rs.randint(1, max_prompt))
        out.append((rs.randint(1, VOCAB, size=p_len).tolist(),
                    int(rs.randint(1, max_new))))
    return out


def _generate_ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _run_stream(engine, reqs, policy="fcfs"):
    sched = Scheduler(engine, policy=policy)
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g))
           for p, g in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids], sched


class _AdversarialDrafter:
    """Forced-low acceptance: knows each request's true greedy
    continuation (precomputed reference streams) and proposes the WRONG
    token at every position — acceptance must be exactly zero and the
    output stream must still be exactly the greedy stream."""

    def __init__(self, ref_streams):
        self.refs = [list(r) for r in ref_streams]

    def propose(self, history, k):
        h = list(history)
        for ref in self.refs:
            if ref[:len(h)] == h and len(ref) > len(h):
                nxt = ref[len(h):len(h) + k]
                return [(int(t) + 1) % VOCAB for t in nxt]
        return [0] * k


class TestDrafters:
    def test_ngram_proposes_continuation_of_most_recent_match(self):
        d = NgramDrafter(max_ngram=3)
        h = [1, 2, 3, 4, 1, 2, 3]
        assert d.propose(h, 3) == [4, 1, 2]
        assert d.propose(h, 2) == [4, 1]
        # the MOST RECENT earlier match wins, not the first
        h2 = [1, 2, 9, 1, 2, 7, 1, 2]
        assert NgramDrafter(max_ngram=2).propose(h2, 2) == [7, 1]

    def test_ngram_no_match_and_degenerate_inputs(self):
        d = NgramDrafter(max_ngram=3)
        assert d.propose([1, 2, 3, 4, 5], 4) == []
        assert d.propose([7], 4) == []
        assert d.propose([1, 2, 1], 0) == []
        with pytest.raises(ValueError, match="max_ngram"):
            NgramDrafter(max_ngram=0)
        with pytest.raises(ValueError, match="max_scan"):
            NgramDrafter(max_scan=1)

    def test_ngram_scan_window_bounds_the_lookback(self):
        """The hot-path scan must not grow with stream length: a match
        that lies entirely outside the max_scan window is invisible,
        while the same match inside the window is found."""
        match = [1, 2, 3, 4]
        h = match + [9] * 16 + [1, 2, 3]
        assert NgramDrafter(max_ngram=3, max_scan=8).propose(h, 1) == []
        assert NgramDrafter(max_ngram=3, max_scan=64).propose(h, 1) == [4]

    def test_model_drafter_matches_greedy_continuation(self, lm):
        model, params = lm
        drafter = ModelDrafter(model, params, prefill_buckets=(4, 8, 16))
        h = [3, 1, 4, 1, 5]
        ref = _generate_ref(model, params, h, 4)
        assert drafter.propose(h, 4) == ref[len(h):]
        # bucketed forwards: one compile per bucket, not per length
        h2 = [2, 7, 1]
        ref2 = _generate_ref(model, params, h2, 2)
        assert drafter.propose(h2, 2) == ref2[len(h2):]

    def test_model_drafter_validation(self, lm):
        model, params = lm
        with pytest.raises(TypeError, match="TransformerLM"):
            ModelDrafter(object(), params)
        with pytest.raises(ValueError, match="return_hidden"):
            ModelDrafter(tiny_lm(return_hidden=True), params)

    def test_accept_length_prefix_and_room_cap(self):
        assert accept_length([5, 6, 7], [5, 6, 8], None) == 2
        assert accept_length([5, 6, 7], [5, 6, 7], None) == 3
        assert accept_length([9], [5, 6], None) == 0
        assert accept_length([5, 6, 7], [5, 6, 7], 1) == 1
        assert accept_length([], [5], None) == 0


class TestSpecStreamEquivalence:
    """THE invariant: speculation changes throughput, never tokens."""

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    @pytest.mark.parametrize("k", [2, 4])
    def test_staggered_stream_matches_sequential_generate(self, lm, impl,
                                                          k):
        model, params = lm
        # 2 slots x 6 requests: staggered joins/leaves mid-verify.
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8, 16), spec_tokens=k,
        )
        reqs = _requests(6, seed=0)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        # ONE compiled verify program across all that churn/acceptance
        assert engine.verify_compile_count() == 1

    def test_rope_gqa_stream_matches(self):
        model = tiny_lm(pos_encoding="rope", num_kv_heads=2)
        params = model.init(
            jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8), spec_tokens=4,
        )
        reqs = _requests(4, seed=3)
        streams, _ = _run_stream(engine, reqs, policy="prefill_priority")
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_windowed_model_stream_matches(self):
        model = tiny_lm(window=6)
        params = tiny_lm().init(
            jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="dense",
            prefill_buckets=(4, 8, 16), spec_tokens=2,
        )
        reqs = _requests(3, seed=5, max_prompt=10, max_new=8)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_adversarial_drafter_zero_acceptance_same_stream(self, lm):
        """Forced-low acceptance: every proposal wrong -> zero accepted
        drafts, one (bonus) token per tick, and the STREAM is still
        bit-identical — the degenerate case is plain decode at verify
        prices, never wrong tokens."""
        model, params = lm
        reqs = _requests(4, seed=7)
        refs = [_generate_ref(model, params, p, g) for p, g in reqs]
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8), spec_tokens=4,
            drafter=_AdversarialDrafter(refs),
        )
        streams, sched = _run_stream(engine, reqs)
        assert streams == refs
        sp = sched.summary()["speculation"]
        assert sp["accepted"] == 0
        assert sp["drafted"] > 0
        assert set(sp["accept_len_hist"]) == {"0"}

    def test_repetitive_stream_actually_accepts(self, lm):
        """The n-gram drafter must WIN on its home turf (repetitive
        histories) — otherwise every speculation test is vacuous."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8, 16), spec_tokens=4,
        )
        reqs = [([5, 6, 7, 5, 6, 7, 5, 6], 8), ([9, 3, 9, 3, 9], 6)]
        streams, sched = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        sp = sched.summary()["speculation"]
        assert sp["accepted"] > 0
        assert sp["drafted"] >= sp["accepted"]

    def test_model_drafter_end_to_end(self, lm):
        """Draft model == target model -> near-total acceptance, same
        stream (the small-draft-model path wired through the engine)."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="dense",
            prefill_buckets=(4, 8), spec_tokens=2,
            drafter=ModelDrafter(model, params, prefill_buckets=(4, 8, 16)),
        )
        reqs = _requests(3, seed=9, max_new=5)
        streams, sched = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        sp = sched.summary()["speculation"]
        # a perfect drafter is only ever cut short by request budgets
        assert sp["accepted"] > 0

    def test_near_horizon_span_is_capped_not_wrong(self, lm):
        """A verify span overhanging max_len: acceptance is capped at
        the horizon (dense writes drop, paged writes redirect to
        scratch) and the stream still matches generate exactly."""
        model, params = lm
        for impl in ("dense", "paged"):
            engine = ServingEngine(
                model, params, num_slots=1, max_len=32, decode_impl=impl,
                kv_block_size=8, prefill_buckets=(8,), spec_tokens=8,
            )
            prompt = list(range(1, 9))  # 8 tokens + 24 new == max_len
            sched = Scheduler(engine)
            rid = sched.submit(Request(prompt=prompt, max_new_tokens=24))
            results = sched.run()
            assert results[rid]["tokens"] == _generate_ref(
                model, params, prompt, 24
            )


class TestSpecTensorParallel:
    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices("cpu")[:2]), ("model",))

    def test_tp_spec_stream_matches_single_device(self, lm, mesh):
        model, params = lm
        reqs = _requests(5, seed=11)
        single = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8), spec_tokens=2,
        )
        tp = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8), spec_tokens=2,
            mesh=mesh,
        )
        s_streams, _ = _run_stream(single, reqs)
        t_streams, _ = _run_stream(tp, reqs)
        assert t_streams == s_streams
        for (prompt, n_new), got in zip(reqs, t_streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        assert tp.verify_compile_count() == 1

    @pytest.mark.parametrize("k", [2, 4])
    def test_tp_verify_collective_counts_independent_of_k(self, lm, mesh,
                                                          k):
        """The amortization claim, HLO-counted: the K+1-token verify
        step carries exactly the same 2 all-reduces per layer as the
        one-token step — collectives per TICK are constant, so
        collectives per TOKEN divide by the accepted length."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4,), mesh=mesh,
            spec_tokens=k,
        )
        args = (
            engine._cache, engine._vars,
            jnp.zeros((3, k + 1), jnp.int32), jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._verify_step_jit.lower(*args).compile().as_text()
        n_ar = txt.count("all-reduce(")
        assert n_ar == 2 * model.num_layers, (
            f"K={k}: expected {2 * model.num_layers} all-reduces "
            f"(2 per layer), got {n_ar}"
        )
        for op in ("all-gather(", "collective-permute(", "all-to-all(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op} in verify step"


class TestRollbackAndPagedEdges:
    def test_paged_update_overhang_redirects_to_scratch(self):
        """A span write beyond the table horizon must land in the
        SCRATCH block — the naive gather clamp would write into the
        row's LAST table entry, which is a live block."""
        from chainermn_tpu.ops.paged_kv import paged_update

        pool = jnp.zeros((3, 2, 1, 1), jnp.float32)
        tables = jnp.asarray([[1, 2]], jnp.int32)
        new = jnp.asarray([[[[1.0]]], [[[2.0]]]], jnp.float32)[None]
        new = new.reshape(1, 2, 1, 1)  # [B=1, T=2, kvh=1, dh=1]
        # positions [3]: token 0 -> logical 1 offset 1 (block 2);
        # token 1 -> logical 2 == beyond max_blocks -> scratch.
        out = np.asarray(paged_update(
            pool, tables, jnp.asarray([3], jnp.int32), new
        ))
        assert out[2, 1, 0, 0] == 1.0  # in-horizon write landed
        assert out[0, 0, 0, 0] == 2.0  # overhang went to scratch...
        assert out[2, 0, 0, 0] == 0.0  # ...NOT clamped into block 2
        assert (out[1] == 0).all()

    def test_oversubscribed_pool_degrades_to_plain_rate(self, lm):
        """A pool too small for the full K-span: the engine reserves
        the plain-decode minimum, caps acceptance, and the stream is
        still exact — speculation degrades to decode_step throughput,
        never to an error or a wrong token."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=1, max_len=32, decode_impl="paged",
            kv_block_size=8, num_blocks=2,  # ONE allocatable block
            prefill_buckets=(4,), spec_tokens=4,
        )
        prompt = [3, 1, 4]
        sched = Scheduler(engine)
        rid = sched.submit(Request(prompt=prompt, max_new_tokens=4))
        results = sched.run()
        assert results[rid]["tokens"] == _generate_ref(
            model, params, prompt, 4
        )
        assert engine._alloc.num_blocks == 2  # never grew

    def test_spec_span_reservation_never_starves_plain_minimum(self, lm):
        """Review regression: speculative block reservations are made
        slot by slot, so an earlier slot's optional K-span extension
        could grab the pool's last free blocks and leave a later slot
        unable to reserve even its PLAIN p+1 write — crashing a pool
        that plain decode serves fine. The two-pass reservation pins
        the contract: any workload that completes at spec_tokens=0
        completes (identically) at spec_tokens>0."""
        model, params = lm
        reqs = [(list(range(1, 6)), 4), (list(range(2, 9)), 6)]
        refs = [_generate_ref(model, params, p, g) for p, g in reqs]

        def build(k):
            return ServingEngine(
                model, params, num_slots=2, max_len=32,
                decode_impl="paged", kv_block_size=4, num_blocks=6,
                prefill_buckets=(8,), spec_tokens=k,
                drafter=_AdversarialDrafter(refs) if k else None,
            )

        plain_streams, _ = _run_stream(build(0), reqs)
        assert plain_streams == refs
        spec_streams, _ = _run_stream(build(4), reqs)  # raised pre-fix
        assert spec_streams == refs

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_leave_rejoin_same_slot_shorter_prompt(self, lm, impl):
        """ISSUE 5 satellite: slot leave -> rejoin at the SAME slot with
        a SHORTER prompt must never read a stale row/block — the
        position rewind is host metadata, so the proof is (a) values:
        the rejoined stream matches generate exactly though the cache
        still physically holds the deeper request's rows; (b)
        structural: the paged table was rewound to scratch on release
        and re-covers only the new request's real span."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=1, max_len=32, decode_impl=impl,
            kv_block_size=4, prefill_buckets=(4, 8, 16), spec_tokens=4,
        )
        # Request A: long prompt, driven deep into the cache.
        long_prompt = [7, 3, 7, 3, 7, 3, 7, 3, 7, 3]
        res = engine.prefill_join(long_prompt)
        assert res is not None and res[0] == 0
        for _ in range(4):
            engine.verify_step()
        assert int(engine._positions[0]) > len(long_prompt)
        engine.leave(0)
        if impl == "paged":
            assert engine._alloc.blocks_in_use == 0
            assert (engine._alloc.tables[0] == 0).all()  # rewound
        # Request B: SAME slot, much shorter prompt.
        short_prompt = [9, 2]
        res_b = engine.prefill_join(short_prompt)
        assert res_b is not None and res_b[0] == 0  # same slot reused
        assert int(engine._positions[0]) == len(short_prompt)  # rewound
        if impl == "paged":
            # re-covers only the new request's real span (P+1 tokens),
            # not A's old depth
            assert engine._alloc.blocks_in_use == \
                engine._alloc.blocks_for(len(short_prompt) + 1)
        stream = list(short_prompt) + [res_b[1]]
        while len(stream) < len(short_prompt) + 8:
            committed, _, _ = engine.verify_step()
            stream.extend(committed[0])
        ref = _generate_ref(model, params, short_prompt, 8)
        assert stream[:len(ref)] == ref


class TestValidationAndResolution:
    def test_spec_with_sampling_accepted(self, lm):
        """ISSUE 18: the greedy-only gate is gone — sampled speculative
        decoding constructs and serves (acceptance is the rejection-
        sampling rule over the counter-keyed verify grid; stream
        equivalence is pinned in tests/test_sampling.py). The old
        combination that raised now builds a working engine."""
        model, params = lm
        engine = ServingEngine(model, params, num_slots=1, max_len=32,
                               decode_impl="dense", temperature=0.8,
                               rng=jax.random.PRNGKey(0), spec_tokens=2)
        slot, tok, _ = engine.prefill_join([3, 1, 4, 1, 5], seed=7)
        committed, _, stats = engine.verify_step()
        assert len(committed[slot]) >= 1
        assert stats["mode"] == "sampled"
        # greedy + spec and sampling + no-spec still construct fine
        g = ServingEngine(model, params, num_slots=1, max_len=32,
                          decode_impl="dense", spec_tokens=2)
        assert g.spec_tokens == 2
        ServingEngine(model, params, num_slots=1, max_len=32,
                      decode_impl="dense", temperature=0.8,
                      rng=jax.random.PRNGKey(0), spec_tokens=0)

    def test_spec_tokens_bounds_and_drafter_contract(self, lm):
        model, params = lm
        with pytest.raises(ValueError, match="spec_tokens"):
            ServingEngine(model, params, num_slots=1, max_len=32,
                          spec_tokens=-1)
        with pytest.raises(ValueError, match="spec_tokens"):
            ServingEngine(model, params, num_slots=1, max_len=32,
                          spec_tokens=32)
        with pytest.raises(TypeError, match="propose"):
            ServingEngine(model, params, num_slots=1, max_len=32,
                          spec_tokens=2, drafter=object())

    def test_verify_step_requires_spec(self, lm):
        model, params = lm
        engine = ServingEngine(model, params, num_slots=1, max_len=32,
                               decode_impl="dense", spec_tokens=0)
        with pytest.raises(RuntimeError, match="spec_tokens"):
            engine.verify_step()

    def test_auto_resolves_through_registry_with_provenance(self, lm):
        """Under the suite's table-only mode 'auto' resolves to the
        documented default 0 (speculation must EARN adoption through a
        bench capture) and the decision is recorded with provenance."""
        model, params = lm
        engine = ServingEngine(model, params, num_slots=1, max_len=32,
                               decode_impl="dense", spec_tokens="auto")
        assert engine.spec_tokens == 0
        recs = [d for d in engine.decisions if d["name"] == "spec_tokens"]
        assert recs and recs[-1]["winner"] == "0"
        assert recs[-1]["source"] == "table"

    def test_forced_resolution(self, lm, monkeypatch):
        model, params = lm
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE", "spec_tokens=4")
        engine = ServingEngine(model, params, num_slots=1, max_len=32,
                               decode_impl="dense", spec_tokens="auto")
        assert engine.spec_tokens == 4
        recs = [d for d in engine.decisions if d["name"] == "spec_tokens"]
        assert recs and recs[-1]["source"] == "forced"