"""Multicast tree fan-out on the serving host plane (ISSUE 16).

Pins the tree-push contract end to end:

- the send schedule is the composition DSL's broadcast walk
  (``tree_depth`` rounds, ``n-1`` total sends, every source a holder);
- :func:`tree_push` delivers over the loopback hub with O(log N)
  donor sends (vs the N-1 sequential baseline) and emits the
  ``tree_push`` trace event;
- :func:`push_adapter` lands BIT-IDENTICAL adapter rows on every
  replica's own bank (same rows a direct register produces);
- :func:`warm_prefix_trie` makes every replica's trie answer the
  shared prefix after ONE donor prefill, scratch slots released;
- a bankless fleet member refuses the push loudly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.observability import trace
from chainermn_tpu.parallel.composition import tree_depth, tree_sends
from chainermn_tpu.serving import Scheduler, ServingEngine
from chainermn_tpu.serving.adapters import AdapterBank, random_adapter
from chainermn_tpu.serving.cluster import (
    LoopbackHub,
    Replica,
    push_adapter,
    tree_push,
    tree_rounds,
    warm_prefix_trie,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=64, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


ENGINE_KW = dict(num_slots=2, max_len=32, decode_impl="paged",
                 kv_block_size=8, prefill_buckets=(4, 8, 16),
                 spec_tokens=0, prefill_chunk=0,
                 prefill_seq_parallel="off", adapter_impl="gather")


def _fleet(lm, n, *, banked=True, **kw):
    """n replicas, each with its OWN bank (the cluster reality —
    cross-replica state moves over the host plane only)."""
    model, params = lm
    cfg = dict(ENGINE_KW)
    cfg.update(kw)
    reps = []
    for r in range(n):
        bank = (AdapterBank(model, capacity=4, rank=2)
                if banked else None)
        eng = ServingEngine(model, params, adapter_bank=bank,
                            **(cfg if banked else
                               {k: v for k, v in cfg.items()
                                if k != "adapter_impl"}))
        reps.append(Replica(eng, Scheduler(eng), r))
    return reps


class TestTreeSchedule:
    def test_rounds_match_broadcast_walk(self):
        for n, r in [(2, 2), (4, 2), (8, 2), (8, 4), (5, 2), (7, 3)]:
            rounds = tree_rounds(n, r)
            assert len(rounds) == tree_depth(n, r), (n, r)
            pairs = [p for rnd in rounds for p in rnd]
            # every non-root receives exactly once
            assert sorted(d for _, d in pairs) == list(range(1, n))
            # every source holds the payload when its round starts
            holders = 1
            for rnd in rounds:
                assert all(s < holders for s, _ in rnd)
                holders *= r

    def test_radix_validation(self):
        with pytest.raises(ValueError, match="radix"):
            tree_rounds(4, 1)


class TestTreePush:
    def test_delivers_with_log_donor_sends(self):
        hub = LoopbackHub()
        ranks = [3, 7, 1, 0, 5, 2, 6, 4]  # order/ids arbitrary
        endpoints = {r: hub.endpoint(r) for r in ranks}
        rec = trace.enable(None)
        received, stats = tree_push(
            {"x": 1}, endpoints, ranks, root=3, payload_kind="probe")
        assert set(received) == set(ranks)
        assert all(v == {"x": 1} for v in received.values())
        assert stats["sends"] == 7 == stats["seq_sends"]
        assert stats["rounds"] == tree_depth(8, 2) == 3
        assert stats["donor_sends"] == 3  # one per round at radix 2
        ev = [e for e in rec.events if e["kind"] == "tree_push"]
        assert len(ev) == 1 and ev[0]["payload_kind"] == "probe"
        assert ev[0]["donor_sends"] == 3 and ev[0]["seq_sends"] == 7
        trace.disable()

    def test_radix4_flattens_the_tree(self):
        hub = LoopbackHub()
        ranks = list(range(8))
        endpoints = {r: hub.endpoint(r) for r in ranks}
        _, stats = tree_push("p", endpoints, ranks, radix=4)
        assert stats["rounds"] == tree_depth(8, 4) == 2
        assert stats["sends"] == 7
        # donor sends 3 in round one (holders 1..3) + 1 in round two
        assert stats["donor_sends"] == 4 == tree_sends(8, 4)

    def test_unknown_root_refused(self):
        hub = LoopbackHub()
        endpoints = {r: hub.endpoint(r) for r in (0, 1)}
        with pytest.raises(ValueError, match="root"):
            tree_push("p", endpoints, [0, 1], root=9)


class TestPushAdapter:
    def test_bit_identical_rows_everywhere(self, lm):
        model, _ = lm
        reps = _fleet(lm, 4)
        adapter = random_adapter(model, 2, seed=11, scale=1.5)
        hub = LoopbackHub()
        stats = push_adapter(adapter, "t1", reps, hub)
        assert stats["donor_sends"] == 2  # ceil(log2 4) rounds x 1
        # reference: a direct local register of the same adapter
        ref = AdapterBank(model, capacity=4, rank=2)
        ref_row = ref.register("t1", adapter)
        for rep in reps:
            bank = rep.engine.adapter_bank
            row = bank.row_of("t1")
            for li in range(model.num_layers):
                for tgt in bank.targets:
                    for k in (0, 1):  # A stack, B stack (scale folded)
                        np.testing.assert_array_equal(
                            bank._stacks[li][tgt][k][row],
                            ref._stacks[li][tgt][k][ref_row],
                            err_msg=f"replica {rep.replica_id} "
                                    f"layer {li} {tgt}",
                        )
            assert rep.engine.adapter_resident("t1")

    def test_bankless_member_refuses(self, lm):
        model, _ = lm
        reps = _fleet(lm, 2)
        reps += _fleet(lm, 1, banked=False)
        reps[2].replica_id = 2
        adapter = random_adapter(model, 2, seed=3)
        with pytest.raises(ValueError, match="adapter_bank"):
            push_adapter(adapter, "t1", reps, LoopbackHub())


class TestWarmPrefixTrie:
    def test_one_prefill_warms_every_trie(self, lm):
        reps = _fleet(lm, 4, banked=False, prefix_cache="on",
                      num_slots=4)
        shared = list(range(1, 17))  # 2 full blocks @ kv_block_size 8
        donor = reps[0].engine
        slot, _, _ = donor.prefill_join(shared + [20, 21])
        free_before = [r.engine.free_slot_count for r in reps[1:]]
        hub = LoopbackHub()
        stats = warm_prefix_trie(reps, slot, hub)
        assert stats["donor_sends"] == 2 and stats["sends"] == 3
        assert sorted(stats["adopted"]) == [1, 2, 3]
        for rep in reps[1:]:
            assert rep.engine.prefix_match_depth(shared) == 2, (
                rep.replica_id)
        # scratch slots released — warmth without held slots
        assert [r.engine.free_slot_count for r in reps[1:]] == \
            free_before
        # donor slot untouched (caller owns its lifecycle)
        donor.leave(slot)
