"""The BASELINE north-star topology, rehearsed literally (round 5).

BASELINE.json's target is ≥90% scaling efficiency at 32 chips (8 hosts
x 4 chips). Real multi-chip hardware is unreachable from this
environment, so this is the closest executable rehearsal: a
32-virtual-device CPU mesh factorised (inter=8, intra=4) — the exact
member count and (dcn, ici) shape — driving the TwoDimensionalCommunicator
trainer end-to-end, with the suite's core invariant applied at that
scale: the 32-member step equals the single-device step (values), and
the topology-aware int8 wire executes on the same mesh.

The session-wide conftest pins an 8-device platform, so the 32-device
mesh runs in a scrubbed subprocess (same pattern as dryrun_multichip).
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SNIPPET = r"""
import numpy as np
import jax, jax.numpy as jnp, optax
from jax.sharding import Mesh
from chainermn_tpu.communicators.xla_communicator import (
    TwoDimensionalCommunicator,
)
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import create_multi_node_optimizer
from chainermn_tpu.training.train_step import (
    create_train_state, make_train_step,
)

devs = np.array(jax.devices()[:32]).reshape(8, 4)  # 8 hosts x 4 chips
comm = TwoDimensionalCommunicator(mesh=Mesh(devs, ("inter", "intra")))
# inter_size/intra_size report PROCESS topology (1 process here); the
# reduction pipeline follows the MESH axes, which carry the 8x4 shape.
assert comm.size == 32
assert comm.mesh.shape["inter"] == 8 and comm.mesh.shape["intra"] == 4

model = MLP(n_units=16, n_out=4)
rng = np.random.default_rng(5)
x = jnp.asarray(rng.standard_normal((64, 10)), jnp.float32)
y = jnp.asarray(rng.integers(0, 4, 64), jnp.int32)
params = model.init(jax.random.PRNGKey(0), x[:1])["params"]

def loss_fn(p, batch, ms):
    xb, yb = batch
    logits = model.apply({"params": p}, xb)
    return (optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            .mean(), ({}, ms))

# (1) Equivalence at 32 members: f32 wire == the single-device step.
opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
state = create_train_state(params, opt, comm, model_state={})
step = make_train_step(loss_fn, opt, comm, donate=False)
state, m = step(state, (x, y))

def single_device_step(p):
    loss, grads = jax.value_and_grad(
        lambda pp: loss_fn(pp, (x, y), {})[0])(p)
    return jax.tree.map(lambda a, g: a - 0.1 * g, p, grads), loss

ref_params, ref_loss = jax.jit(single_device_step)(params)
np.testing.assert_allclose(float(m["loss"]), float(ref_loss), rtol=1e-5)
for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(ref_params)):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-6)

# (2) The topology-aware int8 wire executes at the north-star shape.
opt_q = create_multi_node_optimizer(
    optax.sgd(0.1), comm, allreduce_grad_dtype=jnp.int8)
state_q = create_train_state(params, opt_q, comm, model_state={})
step_q = make_train_step(loss_fn, opt_q, comm, donate=False)
state_q, mq = step_q(state_q, (x, y))
assert np.isfinite(float(mq["loss"]))
print("NORTH_STAR_OK")
"""


def test_32_member_north_star_shape():
    sys.path.insert(0, _REPO)
    try:
        from _driver_env import cpu_scrubbed_env
    finally:
        sys.path.pop(0)

    env = cpu_scrubbed_env(
        32, cache_dir=os.path.join(_REPO, ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, "-c", _SNIPPET], env=env, cwd=_REPO,
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0 and "NORTH_STAR_OK" in proc.stdout, (
        proc.stdout[-2000:] + proc.stderr[-2000:]
    )
