"""Direct unit coverage of the in-program collective primitives
(:mod:`chainermn_tpu.parallel.collectives`) — the L0/L2-equivalent layer
every communicator and parallelism module builds on (SURVEY.md section 1).
Most are exercised transitively by the communicator/parallelism suites;
these tests pin the primitive semantics themselves."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.parallel import collectives as C

N = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices("cpu")[:N]), ("x",))


def _run(mesh, fn, *args, in_specs=None, out_specs=P("x")):
    in_specs = in_specs if in_specs is not None else (P("x"),) * len(args)
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    )(*args)


def test_allreduce_ops(mesh):
    x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    for op, want in [("sum", x.sum()), ("max", x.max()), ("min", x.min()),
                     ("mean", x.mean())]:
        out = _run(mesh, lambda v: C.allreduce(v, "x", op=op), x)
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.full(N, float(want)), rtol=1e-6)
    with pytest.raises(ValueError):
        _run(mesh, lambda v: C.allreduce(v, "x", op="prod"), x)


def test_shift_rotates_ring(mesh):
    x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    fwd = _run(mesh, lambda v: C.shift(v, "x", 1), x)
    # shard i's value travels to shard i+1: shard j now holds j-1's value
    np.testing.assert_array_equal(
        np.asarray(fwd).ravel(), np.roll(np.arange(N), 1)
    )
    back = _run(mesh, lambda v: C.shift(v, "x", -1), x)
    np.testing.assert_array_equal(
        np.asarray(back).ravel(), np.roll(np.arange(N), -1)
    )
    # a full loop restores the input
    def loop(v):
        for _ in range(N):
            v = C.shift(v, "x", 1)
        return v

    same = _run(mesh, loop, x)
    np.testing.assert_array_equal(np.asarray(same), np.asarray(x))


def test_reduce_scatter_matches_psum_slice(mesh):
    rows = jnp.asarray(
        np.random.RandomState(0).randn(N, N, 3), np.float32
    )  # per-shard [N, 3] contribution

    def local(v):
        return C.reduce_scatter(v[0], "x")

    out = _run(mesh, local, rows)
    want = np.asarray(rows).sum(axis=0)  # [N, 3]; shard i keeps row i
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_bcast_root_value_everywhere(mesh):
    x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    out = _run(mesh, lambda v: C.bcast(v, "x", root=3), x)
    np.testing.assert_array_equal(np.asarray(out).ravel(), np.full(N, 3.0))


def test_axes_bound_inside_and_outside(mesh):
    assert C.axes_bound("x") is False  # eager: no axis context

    def local(v):
        assert C.axes_bound("x")
        assert C.axes_bound(("x",))
        assert not C.axes_bound("nope")
        return v

    _run(mesh, local, jnp.zeros((N, 1)))


def test_two_level_allreduce_sum_op():
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    mesh2 = Mesh(devs, ("inter", "intra"))
    x = jnp.asarray(np.random.RandomState(1).randn(8, 5), np.float32)

    def local(v):
        return C.two_level_allreduce(v[0], "intra", "inter", op="sum")[None]

    out = jax.jit(shard_map(
        local, mesh=mesh2, in_specs=P(("inter", "intra")),
        out_specs=P(("inter", "intra")), check_vma=False,
    ))(x)
    want = np.asarray(x).sum(axis=0)
    for row in np.asarray(out):
        np.testing.assert_allclose(row, want, rtol=1e-5)
