"""The live telemetry plane (ISSUE 6):

- shared nearest-rank percentile rule (observability.stats) — the
  ceil(q*n) pin;
- metrics registry mechanics (counter/gauge/histogram, streaming
  quantiles from fixed log buckets, snapshot/exposition round-trip);
- the recorder tap: traced sites populate metrics with zero new call
  sites, including the live ``trace_dropped_events`` counter;
- exporter golden contract: scrape ``/metrics``, parse every line,
  TYPE/HELP well-formedness, monotone counters across steps,
  ``/healthz`` and ``/trace/tail``;
- hang watchdog: a deliberately stalled fake collective produces a
  dump naming the op; a healthy beating run does NOT fire;
- the STRUCTURAL guarantee extended to the FULL plane: recorder tap +
  metrics + exporter + flight markers active produce an identical
  traced program (tests/test_trace.py pattern);
- trace_report's loud warning on a lossy (dropped-events) trace.
"""

import json
import os
import re
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu import create_communicator
from chainermn_tpu.observability import exporter, flight, metrics, trace
from chainermn_tpu.observability.stats import (
    nearest_rank,
    nearest_rank_index,
)


@pytest.fixture(autouse=True)
def _isolated_plane():
    """Every test starts and ends with the whole plane torn down."""
    trace.disable()
    metrics.reset()
    flight.reset()
    exporter.stop()
    yield
    trace.disable()
    metrics.reset()
    flight.reset()
    exporter.stop()


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode()


# ----------------------------------------------------------------------
# stats: the shared nearest-rank rule
# ----------------------------------------------------------------------


def test_nearest_rank_pins_ceil_rule():
    """The ceil(q*n) 1-based-rank rule (ISSUE 6 satellite: ONE owner
    for the serving rollup and the histogram quantiles)."""
    vals = [40.0, 10.0, 30.0, 20.0]  # order-insensitive
    assert nearest_rank(vals, 0.5) == 20.0   # ceil(0.5*4)=2 -> 2nd
    assert nearest_rank(vals, 0.75) == 30.0  # ceil(3)=3 -> 3rd
    assert nearest_rank(vals, 0.99) == 40.0  # ceil(3.96)=4 -> 4th
    assert nearest_rank(vals, 0.0) == 10.0   # clamped to rank 1
    assert nearest_rank([7.0], 0.99) == 7.0
    assert nearest_rank([], 0.5) is None
    assert nearest_rank_index(5, 0.5) == 2   # ceil(2.5)=3 -> index 2
    with pytest.raises(ValueError):
        nearest_rank_index(0, 0.5)


def test_summarize_serving_uses_shared_rule():
    """trace.summarize_serving's percentiles ARE the shared rule (the
    dedup satellite: the local pct() closure is gone)."""
    events = [
        {"kind": "serving", "phase": "decode_step", "dur_s": d,
         "tokens": 1, "n_active": 1, "n_slots": 2}
        for d in (0.010, 0.020, 0.030, 0.040)
    ]
    s = trace.summarize_serving(events)
    assert s["token_ms_p50"] == pytest.approx(20.0)
    assert s["token_ms_p99"] == pytest.approx(40.0)


# ----------------------------------------------------------------------
# registry mechanics
# ----------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = metrics.MetricsRegistry()
    c = reg.counter("requests_total", "help text")
    c.inc()
    c.inc(2.0, op="a")  # distinct label set = independent series
    assert c.value() == 1.0 and c.value(op="a") == 2.0
    c.inc(1.0, op="a")
    assert c.value(op="a") == 3.0
    with pytest.raises(ValueError):
        c.inc(-1.0)
    g = reg.gauge("depth")
    g.set(4)
    g.inc(-1)
    assert g.value() == 3.0
    assert g.value(missing="x") is None
    # same name, different kind -> loud failure, not silent sharing
    with pytest.raises(ValueError):
        reg.gauge("requests_total")

    h = reg.histogram("lat_seconds", buckets=(0.001, 0.01, 0.1, 1.0))
    for _ in range(3):
        h.observe(0.0005)
    h.observe(0.05)
    assert h.count() == 4
    # nearest-rank over cumulative counts, bucket UPPER bound reported
    assert h.quantile(0.5) == 0.001   # rank 2 of 4 -> first bucket
    assert h.quantile(0.99) == 0.1    # rank 4 -> the 0.05 sample's bucket
    h.observe(50.0)  # overflow bucket
    assert h.quantile(1.0) == float("inf")
    assert h.quantile(0.5, other="label") is None  # unseen labels


def test_log_buckets_fixed_ladder():
    bs = metrics.log_buckets(1e-3, 1.0, per_decade=2)
    assert bs[0] == pytest.approx(1e-3)
    assert bs[-1] >= 1.0
    assert all(b2 > b1 for b1, b2 in zip(bs, bs[1:]))
    with pytest.raises(ValueError):
        metrics.log_buckets(1.0, 0.1)


def test_snapshot_and_exposition_roundtrip():
    reg = metrics.MetricsRegistry()
    reg.counter("c_total", "a counter").inc(5, op="x")
    reg.gauge("g").set(2.5)
    h = reg.histogram("h_seconds", "a histogram",
                      buckets=(0.01, 0.1))
    h.observe(0.005)
    h.observe(42.0)
    snap = reg.snapshot()
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["values"][0] == {
        "labels": {"op": "x"}, "value": 5.0
    }
    hrow = snap["h_seconds"]["values"][0]
    assert hrow["count"] == 2
    assert hrow["buckets"][-1] == ["+Inf", 2]
    # inf quantiles sanitised for strict-JSON consumers
    assert hrow["quantiles"]["p99"] is None
    json.dumps(snap)  # JSON-able end to end

    text = reg.exposition()
    parsed = metrics.parse_exposition(text)
    assert parsed[("c_total", (("op", "x"),))] == 5.0
    assert parsed[("g", ())] == 2.5
    assert parsed[("h_seconds_count", ())] == 2.0
    assert parsed[("h_seconds_bucket", (("le", "+Inf"),))] == 2.0

    # peer snapshots render with an added rank label
    text2 = metrics.render_exposition(snap, extra_snapshots=[(1, snap)])
    p2 = metrics.parse_exposition(text2)
    assert p2[("c_total", (("op", "x"), ("rank", "1")))] == 5.0


def test_label_escape_roundtrip():
    """Escape-order pin: backslash+'n' in a label value must survive
    render->parse (a sequential unescape chain turned its escaped form
    into backslash+newline)."""
    reg = metrics.MetricsRegistry()
    hairy = 'back\\slash \\n quote" newline\n end'
    reg.counter("c_total", "c").inc(3, path=hairy)
    parsed = metrics.parse_exposition(reg.exposition())
    assert parsed[("c_total", (("path", hairy),))] == 3.0


# ----------------------------------------------------------------------
# recorder tap: zero new call sites
# ----------------------------------------------------------------------


def test_tap_populates_from_traced_collectives(comm):
    reg = metrics.install_tap()
    trace.enable(None)
    n = comm.size
    comm.allreduce(jnp.ones((n, 4)))
    c = reg.counter("wire_bytes_total")
    assert c.value(op="allreduce", plane="device") == n * 4 * 4
    assert reg.counter("wire_events_total").value(
        op="allreduce", plane="device") == 1.0
    assert reg.histogram("collective_seconds").count(
        op="allreduce", plane="device") == 1

    comm.bcast_obj({"meta": 1})
    assert reg.counter("wire_events_total").value(
        op="bcast_obj", plane="host") == 1.0


def test_tap_serving_and_step_events():
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.event("step", iteration=7, phases={"compute": 0.01,
                                           "data_wait": 0.002})
    rec.event("serving", phase="prefill", dur_s=0.01, ttft_s=0.03)
    rec.event("serving", phase="decode_step", dur_s=0.004, tokens=3,
              n_active=3, n_slots=4)
    rec.event("serving", phase="finish", dur_s=0.1)
    rec.event("speculate", drafted=4, accepted=2, dur_s=0.002)
    assert reg.counter("train_steps_total").value() == 1.0
    assert reg.gauge("train_iteration").value() == 7.0
    assert reg.histogram("step_phase_seconds").count(phase="compute") == 1
    assert reg.counter("serving_tokens_total").value() == 4.0  # 1 + 3
    assert reg.counter("serving_requests_total").value() == 1.0
    assert reg.histogram("serving_ttft_seconds").count() == 1
    assert reg.counter("speculate_drafted_total").value() == 4.0
    assert reg.counter("speculate_accepted_total").value() == 2.0


def test_spec_accept_rate_by_mode():
    """ISSUE 18: verify ticks carry their sampling mode; the per-mode
    acceptance-rate gauge splits what the unlabeled counters (pinned
    above at their pre-sampling values) aggregate."""
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.event("speculate", drafted=4, accepted=4, mode="greedy")
    rec.event("speculate", drafted=4, accepted=1, mode="sampled")
    rec.event("speculate", drafted=4, accepted=2, mode="sampled")
    # mode-less events (pre-ISSUE-18 traces) fold into greedy
    rec.event("speculate", drafted=2, accepted=2)
    assert metrics.spec_accept_rates() == {
        "greedy": 1.0, "sampled": round(3 / 8, 6)}
    # the unlabeled aggregates are untouched by the split
    assert reg.counter("speculate_drafted_total").value() == 14.0
    assert reg.counter("speculate_accepted_total").value() == 9.0
    # gauge is derived at snapshot time via the collect hook
    snap = reg.snapshot()
    vals = {tuple(sorted(v["labels"].items())): v["value"]
            for v in snap["serving_spec_accept_rate"]["values"]}
    assert vals[(("mode", "greedy"),)] == 1.0
    assert vals[(("mode", "sampled"),)] == round(3 / 8, 6)
    # reset() clears the totals (test isolation contract)
    metrics.reset()
    assert metrics.spec_accept_rates() == {}


def test_trace_dropped_events_counter_is_live(monkeypatch):
    """ISSUE 6 satellite: Recorder.dropped used to surface only in the
    close() meta event — the collect hook exports it on every
    snapshot/scrape while the run is still alive."""
    monkeypatch.setattr(trace, "MAX_BUFFERED_EVENTS", 3)
    reg = metrics.install_tap()
    rec = trace.enable(None)
    for i in range(6):
        rec.event("step", iteration=i)
    assert rec.dropped > 0
    first = rec.dropped
    snap = reg.snapshot()
    assert snap["trace_dropped_events"]["values"][0]["value"] == first
    assert snap["trace_buffered_events"]["values"][0]["value"] == 3
    # ...and ACCUMULATES across recorder generations: a fresh recorder
    # restarts its own `dropped` at 0 — a second lossy run must move
    # the counter, not hide behind the first recorder's larger total.
    trace.disable()
    rec2 = trace.enable(None)
    for i in range(4):
        rec2.event("step", iteration=i)
    assert 0 < rec2.dropped < first + rec2.dropped
    snap2 = reg.snapshot()
    assert snap2["trace_dropped_events"]["values"][0]["value"] == \
        first + rec2.dropped


def test_scheduler_direct_gauges_without_engine_events():
    """Direct gauges (state planes with no events): a fake engine
    drives the scheduler; queue depth / occupancy gauges move even
    though this engine emits nothing itself."""
    from chainermn_tpu.serving.scheduler import Request, Scheduler

    class FakeEngine:
        num_slots = 2
        max_len = 64
        spec_tokens = 0

        def __init__(self):
            self._active = {}
            self._next = 0

        @property
        def n_active(self):
            return len(self._active)

        @property
        def free_slot_count(self):
            return self.num_slots - len(self._active)

        def prefill_join(self, prompt):
            if len(self._active) >= self.num_slots:
                return None
            slot = min(s for s in range(self.num_slots)
                       if s not in self._active)
            self._active[slot] = True
            return slot, 1, 8

        def decode_step(self):
            return [2] * self.num_slots, 0.001

        def leave(self, slot):
            del self._active[slot]

    reg = metrics.registry()
    trace.enable(None)
    metrics.install_tap()
    sched = Scheduler(FakeEngine(), policy="prefill_priority")
    for _ in range(3):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=2))
    assert reg.gauge("serving_queue_depth").value() == 3.0
    sched.run()
    assert reg.gauge("serving_queue_depth").value() == 0.0
    assert reg.gauge("serving_inflight").value() == 0.0
    assert reg.gauge("serving_active_slots").value() == 0.0
    assert reg.gauge("serving_slots").value() == 2.0
    # the tap saw the scheduler's own phase events too
    assert reg.counter("serving_requests_total").value() == 3.0


def test_trainer_beat_and_iteration_gauge(comm):
    from chainermn_tpu.training.trainer import Trainer

    def step_fn(state, batch):
        return state + 1, {"loss": jnp.float32(1.0)}

    data = [[(np.zeros((2,), np.float32), np.int32(0))] for _ in range(3)]

    class It:
        def __iter__(self):
            return iter(data)

    reg = metrics.registry()
    beats = []
    tr = Trainer(step_fn, jnp.float32(0), It(), comm, log_interval=10,
                 out=open(os.devnull, "w"))
    tr.extend(lambda t: beats.append(flight.last_beat()))
    tr.run(3)
    assert reg.gauge("train_iteration").value() == 3.0
    # beats landed during the run (one per step, carrying the iteration)...
    assert [b["step"] for b in beats if b is not None] == [1, 2, 3]
    # ...and run() quiesced on return: the finished loop's stale beat
    # must not read as a hang to the watchdog.
    assert flight.last_beat() is None
    assert flight.progress_age() is None


# ----------------------------------------------------------------------
# exporter golden contract
# ----------------------------------------------------------------------


def test_exporter_metrics_contract():
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.collective("allreduce", nbytes=256, dur_s=0.002)
    rec.event("step", iteration=1, phases={"compute": 0.01})
    exp = exporter.start(port=0, registry=reg)
    try:
        body1 = _scrape(exp.port)
        # every line parses (parse_exposition raises on malformed) ...
        parsed1 = metrics.parse_exposition(body1)
        assert parsed1
        # ... and every sample's family carries a TYPE declaration
        # BEFORE its first sample, with a legal kind
        seen_types = {}
        for line in body1.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ", 3)
                assert kind in ("counter", "gauge", "histogram"), line
                seen_types[name] = kind
            elif line.startswith("# HELP "):
                assert line.split(" ", 3)[3]  # non-empty help text
            elif not line.startswith("#"):
                name = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)", line)[1]
                family = re.sub(r"_(bucket|sum|count)$", "", name)
                assert name in seen_types or family in seen_types, line
        # histogram internal consistency: cumulative buckets end at
        # _count, and the +Inf bucket equals it
        cs = "collective_seconds"
        labels = (("op", "allreduce"), ("plane", "device"))
        count = parsed1[(cs + "_count", labels)]
        inf_key = tuple(sorted(labels + (("le", "+Inf"),)))
        assert parsed1[(cs + "_bucket", inf_key)] == count == 1.0
        # monotone counters across two steps
        rec.collective("allreduce", nbytes=256, dur_s=0.002)
        rec.event("step", iteration=2, phases={"compute": 0.01})
        parsed2 = metrics.parse_exposition(_scrape(exp.port))
        key = ("wire_bytes_total", labels)
        assert parsed2[key] == parsed1[key] + 256
        assert parsed2[("train_steps_total", ())] == 2.0
    finally:
        exp.close()


def test_exporter_healthz_and_trace_tail():
    reg = metrics.registry()
    rec = trace.enable(None)
    flight.beat(41)
    for i in range(7):
        rec.event("step", iteration=i)
    exp = exporter.start(port=0, registry=reg)
    try:
        health = json.loads(_scrape(exp.port, "/healthz"))
        assert health["ok"] is True
        assert health["rank"] == 0 and health["pid"] == os.getpid()
        assert health["step"] == 41
        assert health["last_beat_age_s"] >= 0
        assert health["last_event_age_s"] >= 0
        assert health["spec_accept"] == {}  # no verify ticks yet
        tail = json.loads(_scrape(exp.port, "/trace/tail?n=3"))
        assert len(tail) == 3
        assert [e["iteration"] for e in tail] == [4, 5, 6]
        with pytest.raises(urllib.error.HTTPError):
            _scrape(exp.port, "/nope")
    finally:
        exp.close()


def test_exporter_env_gate(monkeypatch):
    """Port env contract: unset -> None (and never re-probed); '0' ->
    ephemeral port with the tap installed."""
    monkeypatch.delenv("CHAINERMN_TPU_METRICS_PORT", raising=False)
    exporter.stop()
    assert exporter.maybe_start_from_env() is None
    exporter.stop()
    monkeypatch.setenv("CHAINERMN_TPU_METRICS_PORT", "0")
    exp = exporter.maybe_start_from_env()
    try:
        assert exp is not None and exp.port > 0
        assert exporter.maybe_start_from_env() is exp  # idempotent
        # the autostart installed the tap: a traced event reaches the
        # endpoint with no further setup
        rec = trace.enable(None)
        rec.collective("bcast", nbytes=64, dur_s=0.001)
        parsed = metrics.parse_exposition(_scrape(exp.port))
        assert parsed[("wire_bytes_total",
                       (("op", "bcast"), ("plane", "device")))] == 64.0
    finally:
        exporter.stop()


def test_exporter_peer_merge_single_process(comm):
    reg = metrics.registry()
    reg.counter("c_total").inc()
    exp = exporter.start(port=0, registry=reg)
    try:
        # collective form: on a single process there are no peers
        assert exp.merge_peer_snapshots(comm) == 0
        assert json.loads(_scrape(exp.port, "/healthz"))[
            "peer_snapshots"] == 0
    finally:
        exp.close()


# ----------------------------------------------------------------------
# hang watchdog
# ----------------------------------------------------------------------


def test_watchdog_dumps_on_stalled_collective(tmp_path):
    flight.collective_entered("allreduce", nbytes=4096,
                              axes=["inter", "intra"], plane="device")
    wd = flight.HangWatchdog(stall_s=0.2, out_dir=str(tmp_path),
                             poll_s=0.05)
    wd.start()
    deadline = time.time() + 5
    while wd.dump_path is None and time.time() < deadline:
        time.sleep(0.02)
    wd.join(timeout=2)
    assert wd.dump_path, "watchdog never fired on a stalled collective"
    dump = json.load(open(wd.dump_path))
    assert os.path.basename(wd.dump_path) == "hang_dump_0.json"
    assert dump["schema"] == flight.HANG_DUMP_SCHEMA
    assert dump["in_flight"]["op"] == "allreduce"
    assert dump["in_flight"]["nbytes"] == 4096
    assert dump["in_flight"]["age_s"] >= 0.2
    # all-thread stacks present and non-trivial
    assert dump["threads"]
    assert any("test_metrics" in "".join(frames) or frames
               for frames in dump["threads"].values())
    flight.collective_exited()


def test_watchdog_silent_on_healthy_run(tmp_path):
    wd = flight.HangWatchdog(stall_s=0.3, out_dir=str(tmp_path),
                             poll_s=0.05)
    wd.start()
    # steady beats + completing collectives: progress never ages out
    for i in range(12):
        flight.beat(i)
        flight.collective_entered("allreduce")
        flight.collective_exited()
        time.sleep(0.05)
    wd.stop()
    wd.join(timeout=2)
    assert wd.dump_path is None
    assert not list(tmp_path.glob("hang_dump_*.json"))


def test_inflight_marker_nests():
    """Composite collectives nest (bcast runs a host bcast_obj inside
    it; allreduce_grad a per-leaf allreduce): the inner exit must not
    clear the outer marker — a wedge AFTER the inner leg still names
    the outer op."""
    flight.collective_entered("bcast", nbytes=64)
    flight.collective_entered("bcast_obj", plane="host")
    assert flight.in_flight()["op"] == "bcast_obj"  # innermost named
    assert [e["op"] for e in flight.in_flight_stack()] == [
        "bcast", "bcast_obj"]
    flight.collective_exited()
    got = flight.in_flight()
    assert got is not None and got["op"] == "bcast", \
        "inner exit cleared the outer marker"
    flight.collective_exited()
    assert flight.in_flight() is None
    flight.collective_exited()  # unbalanced exit: tolerated, no raise
    assert flight.in_flight() is None


def test_inflight_marker_exception_safe(comm):
    """A collective that RAISES must not leak its marker: the caller
    may catch and carry on healthy, and a phantom in-flight entry would
    spend the fire-once watchdog's single dump on a non-hang (review
    finding). Every ``_mark`` site is a context manager that balances
    on the raise."""
    x = jnp.arange(comm.size * 2, dtype=jnp.float32).reshape(comm.size, 2)
    comm.allreduce(x)  # prime: healthy path clears
    assert flight.in_flight() is None
    with pytest.raises(KeyError):
        comm.allreduce(x, op="nope")  # raises inside the marked region
    assert flight.in_flight() is None, "allreduce leaked its marker"
    # recv's recoverable kind-mismatch branch balances through the same
    # context (a well-formed non-ndarray message on the channel):
    comm.send_obj(("pickle", False, [], []), comm.rank, tag=77)
    with pytest.raises(RuntimeError, match="expected an ndarray"):
        comm.recv(comm.rank, tag=77)
    assert flight.in_flight() is None, "recv leaked its marker"
    # and the channel still works after the recovered error:
    comm.send(np.ones(3, np.float32), comm.rank, tag=78)
    np.testing.assert_array_equal(
        comm.recv(comm.rank, tag=78), np.ones(3, np.float32)
    )
    assert flight.in_flight() is None


def test_watchdog_silent_after_quiesce(tmp_path):
    """A loop that ENDED (Trainer.run returned, scheduler drained)
    calls quiesce(): the stale last-beat must not read as a hang, but
    a collective still in flight past the threshold must."""
    flight.beat(7)
    flight.quiesce()
    wd = flight.HangWatchdog(stall_s=0.1, out_dir=str(tmp_path),
                             poll_s=0.03)
    wd.start()
    time.sleep(0.3)
    assert wd.dump_path is None, "quiesced process must not dump"
    assert not list(tmp_path.glob("hang_dump_*.json"))
    # the in-flight rule is independent of beats: still fires
    flight.collective_entered("allgather", nbytes=128)
    deadline = time.time() + 5
    while wd.dump_path is None and time.time() < deadline:
        time.sleep(0.02)
    wd.join(timeout=2)
    flight.collective_exited()
    assert wd.dump_path, "in-flight rule must survive quiesce"
    assert json.load(open(wd.dump_path))["in_flight"]["op"] == "allgather"


def test_collective_after_quiesce_does_not_rearm(tmp_path):
    """A one-off collective in an intentionally idle process (post-run
    weight refresh, a peer-snapshot merge) completes and the process
    goes back to waiting: its exit must not re-arm the no-progress
    rule — the fire-once watchdog would spend its single dump on a
    healthy idle and miss the real hang hours later (review finding)."""
    flight.beat(3)
    flight.quiesce()
    flight.collective_exited(
        flight.collective_entered("bcast_obj", plane="host")
    )
    assert flight.progress_age() is None, \
        "collective exit re-armed a quiesced progress chain"
    wd = flight.HangWatchdog(stall_s=0.1, out_dir=str(tmp_path),
                             poll_s=0.03)
    wd.start()
    time.sleep(0.3)
    wd.stop()
    wd.join(timeout=2)
    assert wd.dump_path is None, "idle process dumped after a one-off"
    assert not list(tmp_path.glob("hang_dump_*.json"))


def test_inflight_markers_are_per_thread():
    """Concurrent collectives (the async double-buffered host reducer
    completes the previous step's exchange on a background thread while
    the main thread marks its own): each thread's exit removes its OWN
    marker — one shared stack would pop whichever entry was pushed
    last, and the dump would name the wrong op (review finding)."""
    import threading

    entered = threading.Event()
    release = threading.Event()

    def bg():
        tok = flight.collective_entered("allgather_obj", plane="host")
        entered.set()
        release.wait(5)
        flight.collective_exited(tok)

    th = threading.Thread(target=bg, name="async-host-reducer")
    th.start()
    assert entered.wait(5)
    main_tok = flight.collective_entered("allreduce", nbytes=256)
    assert {e["op"] for e in flight.in_flight_stack()} == {
        "allgather_obj", "allreduce"}
    # Background thread finishes FIRST while the main thread's marker
    # is globally newest: it must remove its own entry, not main's.
    release.set()
    th.join(5)
    got = flight.in_flight()
    assert got is not None and got["op"] == "allreduce", \
        "background exit popped the main thread's marker"
    assert [e["op"] for e in flight.in_flight_stack()] == ["allreduce"]
    flight.collective_exited(main_tok)
    assert flight.in_flight() is None


def test_marker_exit_idempotent_by_token():
    """Sync-mode ``_wire_event`` can raise AFTER its collective's
    marker was already removed; the enclosing ``finally`` then exits
    again with the same token — the second exit must be a no-op, never
    popping an ENCLOSING composite's marker (review finding)."""
    outer = flight.collective_entered("allreduce_grad")
    inner = flight.collective_entered("allreduce")
    flight.collective_exited(inner)
    flight.collective_exited(inner)  # double exit: idempotent
    got = flight.in_flight()
    assert got is not None and got["op"] == "allreduce_grad", \
        "double inner exit popped the outer marker"
    flight.collective_exited(outer)
    assert flight.in_flight() is None


def test_watchdog_ignores_idle_process(tmp_path):
    """A process that never trained and never entered a collective must
    not dump on mere existence."""
    wd = flight.HangWatchdog(stall_s=0.1, out_dir=str(tmp_path),
                             poll_s=0.03)
    wd.start()
    time.sleep(0.3)
    wd.stop()
    wd.join(timeout=2)
    assert wd.dump_path is None


def test_watchdog_env_gate(monkeypatch, tmp_path):
    monkeypatch.delenv("CHAINERMN_TPU_HANG_DUMP_S", raising=False)
    assert flight.maybe_start_from_env() is None
    monkeypatch.setenv("CHAINERMN_TPU_HANG_DUMP_S", "120")
    monkeypatch.setenv("CHAINERMN_TPU_HANG_DUMP_DIR", str(tmp_path))
    wd = flight.maybe_start_from_env()
    try:
        assert wd is not None and wd.stall_s == 120.0
        assert wd.out_dir == str(tmp_path)
        assert flight.maybe_start_from_env() is wd  # idempotent
    finally:
        flight.stop_watchdog()
    with pytest.raises(ValueError):
        flight.HangWatchdog(stall_s=0)


def test_flight_ring_follows_recorder():
    rec = trace.enable(None)
    for i in range(5):
        rec.event("step", iteration=i)
    t = flight.tail(3)
    assert [e["iteration"] for e in t] == [2, 3, 4]
    assert flight.tail(0) == []


# ----------------------------------------------------------------------
# structural: the FULL plane adds zero device-plane collectives
# ----------------------------------------------------------------------


def _two_dim_comm():
    from jax.sharding import Mesh

    from chainermn_tpu.communicators.xla_communicator import (
        TwoDimensionalCommunicator,
    )

    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    return TwoDimensionalCommunicator(mesh=Mesh(devs, ("inter", "intra")))


def test_full_plane_adds_zero_device_collectives():
    """ISSUE 6 acceptance: recorder tap + metrics + live exporter +
    flight markers all active produce an IDENTICAL traced program to
    everything-off — the whole plane is host-side (the test_trace.py
    certificate, extended)."""
    from chainermn_tpu.testing import count_primitives

    comm = _two_dim_comm()
    tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
    env = [("inter", 2), ("intra", 4)]

    def counts():
        return count_primitives(
            lambda t: comm.reduce_gradients_in_jit(
                t, compress_dtype=jnp.bfloat16
            ),
            tree, axis_env=env,
        )

    off = counts()
    reg = metrics.install_tap()
    trace.enable(None)
    exp = exporter.start(port=0, registry=reg)
    try:
        on = counts()
        _scrape(exp.port)  # a live scrape mid-compile changes nothing
        on2 = counts()
    finally:
        exp.close()
    assert on == off
    assert on2 == off
    # not vacuous: the reduction pipeline is in there
    assert on.get("reduce_scatter") == 1
    assert on.get("psum") == 1
    assert on.get("all_gather") == 1


def test_eager_collective_numerics_with_plane_on(comm):
    """Values unchanged with the full plane enabled, and the flight
    marker is cleared after every eager collective."""
    reg = metrics.install_tap()
    trace.enable(None)
    exp = exporter.start(port=0, registry=reg)
    try:
        rs = np.random.RandomState(0)
        stacked = jnp.asarray(rs.randn(comm.size, 3, 2), jnp.float32)
        out = comm.allreduce(stacked)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(stacked).sum(0),
            rtol=1e-6, atol=1e-6,
        )
        assert flight.in_flight() is None
        assert reg.counter("wire_events_total").value(
            op="allreduce", plane="device") == 1.0
    finally:
        exp.close()


# ----------------------------------------------------------------------
# trace_report: loud on lossy traces
# ----------------------------------------------------------------------


def _report_mod():
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "trace_report.py",
    )
    spec = importlib.util.spec_from_file_location("_trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_warns_on_dropped_events(tmp_path):
    """ISSUE 6 satellite: a summarized file carrying dropped_events
    meta events (recorder overflow at close()) produces a LOUD warning
    — previously silently ignored."""
    tr = _report_mod()
    events = [
        {"schema": 1, "kind": "meta", "t": 1.0, "pid": 1, "rank": 0},
        {"schema": 1, "kind": "collective", "t": 2.0, "pid": 1,
         "rank": 0, "op": "allreduce", "plane": "device", "nbytes": 64,
         "dur_s": 0.001},
        {"schema": 1, "kind": "meta", "t": 3.0, "pid": 1, "rank": 0,
         "dropped_events": 17},
        {"schema": 1, "kind": "meta", "t": 3.0, "pid": 2, "rank": 1,
         "dropped_events": 5},
    ]
    s = tr.summarize(events)
    assert s["meta"]["dropped_events"] == 22  # accumulates per recorder
    text = tr.render_text(s)
    assert "WARNING" in text and "22" in text
    assert text.index("WARNING") < text.index("trace:")  # loud = first

    # clean trace: no warning
    s2 = tr.summarize(events[:2])
    assert "dropped_events" not in s2["meta"]
    assert "WARNING" not in tr.render_text(s2)


def test_metrics_dump_formats_saved_scrape(tmp_path, capsys):
    """tools/metrics_dump.py offline mode: format a saved exposition
    without any endpoint (and without importing jax)."""
    import importlib.util

    reg = metrics.MetricsRegistry()
    reg.counter("wire_bytes_total", "bytes").inc(512, op="allreduce")
    reg.histogram("serving_ttft_seconds", "ttft",
                  buckets=(0.01, 0.1)).observe(0.05)
    prom = tmp_path / "saved.prom"
    prom.write_text(reg.exposition())

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "metrics_dump.py",
    )
    spec = importlib.util.spec_from_file_location("_metrics_dump", path)
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)

    assert md.main([str(prom)]) == 0
    out = capsys.readouterr().out
    assert "wire_bytes_total" in out and "512" in out
    assert "serving_ttft_seconds" in out and "n=1" in out
    # unreachable endpoint -> exit 1, quiet enough for the capture gate
    assert md.main(["--port", "1", "--timeout", "0.2"]) == 1


def test_tap_route_and_kv_transfer_events():
    """ISSUE 8: the cluster router's ``route``/``kv_transfer`` events
    populate rank-labeled placement counters and transfer byte/block
    accounting through the recorder tap — zero new call sites."""
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.event("route", request="c0", replica=1, policy="prefix_aware",
              requeue=False)
    rec.event("route", request="c1", replica=0, policy="prefix_aware",
              requeue=True)
    rec.event("kv_transfer", request="c0", src=0, dst=1, nbytes=4096,
              blocks=3, dur_s=0.002)
    assert reg.counter("cluster_routes_total").value(rank="1") == 1.0
    assert reg.counter("cluster_routes_total").value(rank="0") == 1.0
    assert reg.counter("cluster_requeues_total").value() == 1.0
    assert reg.counter("kv_transfer_total").value() == 1.0
    assert reg.counter("kv_transfer_bytes_total").value() == 4096.0
    assert reg.counter("kv_transfer_blocks_total").value() == 3.0
    assert reg.histogram("kv_transfer_seconds").count() == 1


def test_tap_moe_dispatch_event():
    """ISSUE 20: MoE dispatch observations mirror as drop/pad counters
    and per-expert load gauges through the recorder tap — the counters
    accumulate the token flow, the gauges snapshot the LATEST
    histogram (a sum would hide router collapse behind history)."""
    reg = metrics.install_tap()
    rec = trace.enable(None)
    rec.event("moe_dispatch", layer=0, expert_load=[6.0, 2.0],
              n_experts=2, dropped=1.0, padded=3.0, capacity=4.0)
    rec.event("moe_dispatch", layer=0, expert_load=[4.0, 4.0],
              n_experts=2, dropped=0.5, padded=0.0, capacity=4.0)
    assert reg.counter("moe_dropped_tokens_total").value() == 1.5
    assert reg.counter("moe_padded_tokens_total").value() == 3.0
    assert reg.gauge("moe_expert_load").value(
        expert="0", layer="0") == 4.0
    assert reg.gauge("moe_expert_load").value(
        expert="1", layer="0") == 4.0
    assert reg.gauge("moe_capacity").value(layer="0") == 4.0
    # layer-less events (aggregated emission) land unlabeled
    rec.event("moe_dispatch", expert_load=[1.0], n_experts=1,
              dropped=0.0, padded=0.0, capacity=2.0)
    assert reg.gauge("moe_expert_load").value(expert="0") == 1.0


def test_record_moe_dispatch_emits_event():
    """The host-side emission helper: routing_stats out of a jitted
    step -> one ``moe_dispatch`` trace event with host scalars (and a
    no-op, never an exception, when tracing is off)."""
    from chainermn_tpu.parallel import record_moe_dispatch, routing_stats

    logits = jnp.array([[2.0, 0.0], [1.5, 0.0], [1.0, 0.0],
                        [0.0, 2.0]], jnp.float32)
    stats = routing_stats(logits, capacity=2, k=1)
    record_moe_dispatch(stats, layer=3)  # tracing off: silent no-op

    reg = metrics.install_tap()
    rec = trace.enable(None)
    record_moe_dispatch(stats, layer=3)
    evs = [e for e in rec.events if e.get("kind") == "moe_dispatch"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["layer"] == 3 and ev["n_experts"] == 2
    assert ev["expert_load"] == [2.0, 1.0]  # 3rd expert-0 token dropped
    assert ev["dropped"] == 1.0
    assert ev["capacity"] == 2.0
    # and the tap mirrored it
    assert reg.counter("moe_dropped_tokens_total").value() == 1.0


def test_metrics_dump_merges_replica_ports(capsys):
    """ISSUE 8 satellite: ``--ports a,b,c`` fetches several replica
    endpoints and merges them into ONE port-labeled table; endpoints
    that are down are skipped with a stderr note and the exit code is
    1 only when none answered."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "metrics_dump.py",
    )
    spec = importlib.util.spec_from_file_location("_metrics_dump2", path)
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)

    r1 = metrics.MetricsRegistry()
    r1.counter("serving_tokens_total", "tokens").inc(5)
    r2 = metrics.MetricsRegistry()
    r2.counter("serving_tokens_total", "tokens").inc(9)
    e1 = exporter.start(port=0, registry=r1)
    e2 = exporter.start(port=0, registry=r2)
    try:
        # one dead port in the list: merged output still lands, rc 0
        rc = md.main(["--ports", f"{e1.port},{e2.port},1",
                      "--timeout", "2"])
        captured = capsys.readouterr()
        assert rc == 0
        assert f"port={e1.port}" in captured.out
        assert f"port={e2.port}" in captured.out
        assert "unreachable" in captured.err

        rc = md.main(["--ports", f"{e1.port},{e2.port}", "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        parsed = json.loads(out)
        vals = {v for k, v in parsed.items()
                if k.startswith("serving_tokens_total")}
        assert vals == {5.0, 9.0}

        # merged health: one JSON object keyed by port
        rc = md.main(["--ports", f"{e1.port},1", "--health",
                      "--timeout", "2"])
        health = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert health[str(e1.port)]["ok"]
        assert health["1"] == {"error": "unreachable"}
    finally:
        e1.close()
        e2.close()
    # every listed endpoint down -> rc 1
    assert md.main(["--ports", "1,2", "--timeout", "0.2"]) == 1
    capsys.readouterr()
