"""Device-aware dispatch + autotune cache (chainermn_tpu.tuning).

Covers the subsystem's contracts hermetically (no hardware):

- cache round-trip / corrupt-file tolerance / shape-bucket keying;
- offline seeding from a BENCH_DETAILS-shaped artifact — the on-chip
  MoE entry (einsum-competitive, 1.63x) is adopted for the TPU device
  kind while LIVE measurement on the CPU mesh picks sort (the 167.8x
  side of the crossover) — the acceptance demo for the whole mechanism;
- dist==single equivalence (values AND grads) for BOTH sides of every
  tuned choice (MoE dispatch impls, attention variants, wire dtypes,
  double-buffering semantics);
- a structural assertion that the auto-selected MoE path on the CPU
  mesh is the sort path (scatter in the lowering, decision recorded).

Every test pins the cache to a tmp path — the repo's own seeded
``.autotune_cache.json`` must never leak into hermetic assertions.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu import tuning
from chainermn_tpu.parallel.moe import (
    dispatch_einsum,
    dispatch_sort,
    make_expert_params,
    moe_layer_local,
    top1_route,
)

D = 8


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    """Every test gets its own cache file and a clean decision log."""
    monkeypatch.setenv(
        "CHAINERMN_TPU_AUTOTUNE_CACHE", str(tmp_path / "cache.json")
    )
    monkeypatch.delenv("CHAINERMN_TPU_AUTOTUNE", raising=False)
    monkeypatch.delenv("CHAINERMN_TPU_AUTOTUNE_FORCE", raising=False)
    tuning.reset_decisions()
    yield
    tuning.reset_decisions()


def expert_fn(params, x):
    w1, w2 = params
    return jnp.tanh(x @ w1) @ w2


def _expert_init(rng):
    k1, k2 = jax.random.split(rng)
    return (
        jax.random.normal(k1, (D, 16)) / 4.0,
        jax.random.normal(k2, (16, D)) / 4.0,
    )


# ---------------------------------------------------------------------------
# Registry + cache mechanics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_cache_round_trip(self):
        key = tuning.decision_key("TPU v5 lite", shape=(4096, 8), dtype="bf16")
        tuning.store_entry(
            "moe_dispatch", key,
            {"winner": "einsum", "source": "test",
             "candidates_ms": {"einsum": 1.0, "sort": 2.0}},
        )
        got = tuning.choice("moe_dispatch", ("sort", "einsum"), key)
        assert got == "einsum"
        d = {(r["name"], r["key"]): r for r in tuning.decisions_taken()}
        assert d[("moe_dispatch", key)]["source"] == "cache:test"
        # and the file itself is well-formed JSON with provenance
        doc = tuning.load_cache()
        entry = doc["decisions"][f"moe_dispatch|{key}"]
        assert entry["source"] == "test" and "measured_at" in entry

    def test_corrupt_cache_is_empty_not_fatal(self, tmp_path, monkeypatch):
        bad = tmp_path / "bad.json"
        bad.write_text("{nope")
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE", str(bad))
        key = tuning.decision_key("cpu", shape=(8,), dtype="grad")
        # falls through to the table, never raises
        assert tuning.choice("allreduce_wire", ("f32", "bf16", "int8"),
                             key) == "bf16"

    def test_shape_bucket_keying(self):
        # nearby shapes share a bucket; far shapes do not
        assert tuning.shape_bucket((2000, 8, 60)) == "2048x8x64"
        assert tuning.shape_bucket((2048, 8, 64)) == "2048x8x64"
        assert tuning.shape_bucket((16384, 16, 512)) != \
            tuning.shape_bucket((2048, 8, 64))
        k1 = tuning.decision_key("cpu", shape=(1500, 7, 33), dtype="bf16")
        k2 = tuning.decision_key("cpu", shape=(2048, 8, 64), dtype="bf16")
        assert k1 == k2
        with pytest.raises(ValueError):
            tuning.shape_bucket((0,))

    def test_seeded_key_matches_registry_key(self):
        # cache._bucketed_key (jax-free seeding) and registry.decision_key
        # are duplicated-by-contract; they must produce the same string.
        from chainermn_tpu.tuning.cache import _bucketed_key

        assert _bucketed_key("TPU v5 lite", (16384, 16, 512), "bfloat16") \
            == tuning.decision_key("TPU v5 lite", shape=(16384, 16, 512),
                                   dtype=jnp.bfloat16)

    def test_forced_override_wins_and_validates(self, monkeypatch):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "moe_dispatch=einsum")
        key = tuning.decision_key("cpu", shape=(64, 8, 8), dtype="float32")
        assert tuning.choice("moe_dispatch", ("sort", "einsum"),
                             key) == "einsum"
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "moe_dispatch=bogus")
        with pytest.raises(ValueError, match="bogus"):
            tuning.choice("moe_dispatch", ("sort", "einsum"), key)

    def test_spread_dominated_measurement_falls_back_to_table(self):
        # candidates whose medians differ by less than their spread:
        # the autotuner must refuse to adopt noise as a winner.
        a = iter([10.0, 10.5, 12.0])
        b = iter([10.2, 10.4, 11.8])
        key = tuning.decision_key("cpu", shape=(64, 2, 8), dtype="bf16")
        winner = tuning.choice(
            "attention", ("flash", "xla"), key,
            measure={"flash": lambda: next(a), "xla": lambda: next(b)},
        )
        assert winner == "xla"  # the CPU table entry, not the coin flip
        rec = tuning.decisions_taken()[-1]
        assert rec["source"] == "table:spread-dominated"
        # nothing was persisted: a later lookup still has no cache entry
        assert tuning.load_cache()["decisions"] == {}

    def test_one_shot_measurement_persists(self):
        calls = {"fast": 0, "slow": 0}

        def mk(name, ms):
            def f():
                calls[name] += 1
                return ms
            return f

        key = tuning.decision_key("cpu", shape=(256,), dtype="bf16")
        w1 = tuning.choice(
            "attention", ("fast", "slow"), key,
            measure={"fast": mk("fast", 1.0), "slow": mk("slow", 9.0)},
        )
        assert w1 == "fast" and calls == {"fast": 3, "slow": 3}
        # second resolution: cache hit, measurement NOT re-run
        w2 = tuning.choice(
            "attention", ("fast", "slow"), key,
            measure={"fast": mk("fast", 1.0), "slow": mk("slow", 9.0)},
        )
        assert w2 == "fast" and calls == {"fast": 3, "slow": 3}


# ---------------------------------------------------------------------------
# Offline seeding: the acceptance demo (no hardware)
# ---------------------------------------------------------------------------


_FAKE_DETAILS = {
    # CPU-proxy top level (the r5 shape of BENCH_DETAILS.json)
    "device_kind": "cpu", "n_devices": 8,
    "moe_dispatch_shape": "T2048xE8xD64_cap320_top2",
    "moe_dispatch_einsum_ms": 96.063, "moe_dispatch_sort_ms": 0.572,
    "moe_dispatch_spread_pct": 12.4,
    "attn_shape": "B1xT256xH2xD64_bf16_causal",
    "flash_fwdbwd_ms": 4.893, "xla_fwdbwd_ms": 2.739,
    "double_buffer_speedup": 0.752, "double_buffer_spread_pct": 19.4,
    # ISSUE 3: the overlap phase's per-schedule medians + key material
    "overlap_schedule_ms": {"flat": 11.3, "two_level": 11.8, "zero": 9.4},
    "overlap_schedule_spread_pct": 8.5,
    "overlap_world_shape": [8], "overlap_payload_mb": 1,
    "last_good_tpu": {
        # a 4-chip-shaped blob so the wire seeding (gated on a real
        # multi-member axis) is exercised
        "device_kind": "TPU v5 lite", "n_devices": 4,
        "measured_at": "2026-08-01T08:46:00Z",
        "moe_dispatch_shape": "T16384xE16xD512_cap1280_top2",
        "moe_dispatch_einsum_ms": 11.362, "moe_dispatch_sort_ms": 6.981,
        "attn_shape": "B4xT4096xH8xD128_bf16_causal",
        "flash_fwdbwd_ms": 13.605, "xla_fwdbwd_ms": 41.08,
        "double_buffer_speedup": 0.85,
        "overlap_schedule_ms": {"flat": 5.0, "two_level": 3.9,
                                "zero": 4.4},
        "overlap_schedule_spread_pct": 2.0,
        "overlap_world_shape": [4], "overlap_payload_mb": 128,
        "allreduce_curve": [
            {"mib": 128, "dtype": "bfloat16", "mode": "fused",
             "busbw_gbps": 101.6},
            {"mib": 512, "dtype": "bfloat16", "mode": "bucketed",
             "busbw_gbps": 99.0},
            {"mib": 256, "dtype": "float32", "mode": "int8",
             "busbw_gbps": 55.0},
        ],
    },
}


class TestSeeding:
    def _seed(self, tmp_path, details=None):
        p = tmp_path / "details.json"
        p.write_text(json.dumps(details or _FAKE_DETAILS))
        return tuning.seed_from_bench_details(str(p))

    def test_seeding_adopts_onchip_choice_cpu_measurement_picks_sort(
        self, tmp_path
    ):
        """THE acceptance demo: one cache, both backends, no hardware.

        Seeded from the artifact, the TPU entry reproduces the on-chip
        choice — sort, but einsum-COMPETITIVE (1.63x, vs 167.8x on the
        proxy) — under the TPU device kind; a LIVE measurement of the
        real dispatch impls on this CPU host picks sort by a margin no
        spread can dominate."""
        seeded = self._seed(tmp_path)
        assert any("moe_dispatch|TPU v5 lite" in s for s in seeded)

        # 1) the seeded cache answers for the TPU device kind without
        #    re-measuring, and carries the einsum-competitive evidence
        tpu_key = tuning.decision_key(
            "TPU v5 lite", shape=(16384, 16, 512), dtype="bfloat16"
        )
        assert tuning.choice("moe_dispatch", ("sort", "einsum"),
                             tpu_key) == "sort"
        rec = [r for r in tuning.decisions_taken()
               if r["key"] == tpu_key][-1]
        assert rec["source"].startswith("cache:seeded")
        ms = rec["evidence"]["candidates_ms"]
        ratio = ms["einsum"] / ms["sort"]
        assert 1.0 < ratio < 2.0, f"on-chip einsum not competitive: {ratio}"

        # 2) live CPU measurement of the REAL impls picks sort
        T, E, d = 512, 8, 32
        capacity = int(T / E * 1.25)
        x = jax.random.normal(jax.random.PRNGKey(0), (T, d), jnp.float32)
        logits = jax.random.normal(jax.random.PRNGKey(1), (T, E))

        def timed(fn):
            @jax.jit
            def run(x, logits):
                q, combine = fn(x, logits, capacity, 2)
                return jnp.sum(combine(q).astype(jnp.float32))

            run(x, logits).block_until_ready()  # compile outside timing

            def sample():
                import time

                t0 = time.perf_counter()
                run(x, logits).block_until_ready()
                return (time.perf_counter() - t0) * 1e3

            return sample

        cpu_key = tuning.decision_key(shape=(T, E, d), dtype=jnp.float32)
        winner = tuning.choice(
            "moe_dispatch", ("sort", "einsum"), cpu_key,
            measure={"einsum": timed(dispatch_einsum),
                     "sort": timed(dispatch_sort)},
        )
        assert winner == "sort"
        rec = [r for r in tuning.decisions_taken()
               if r["key"] == cpu_key][-1]
        # measured decisively (the 100x+ side of the crossover), or —
        # only if this box is pathologically noisy — the table, which
        # ALSO says sort; either way the cpu choice is sort.
        assert rec["source"] in ("measured", "table:spread-dominated")
        # and both coexist in one cache file keyed by device kind
        doc = tuning.load_cache()
        assert f"moe_dispatch|{tpu_key}" in doc["decisions"]

    def test_seeding_covers_attention_wire_and_double_buffering(
        self, tmp_path
    ):
        self._seed(tmp_path)
        doc = tuning.load_cache()["decisions"]
        # attention: flash on chip (3.0x), xla on the cpu proxy (0.56x)
        tpu_attn = tuning.decision_key("TPU v5 lite", shape=(4096, 8, 128),
                                       dtype="bfloat16")
        cpu_attn = tuning.decision_key("cpu", shape=(256, 2, 64),
                                       dtype="bfloat16")
        assert doc[f"attention|{tpu_attn}"]["winner"] == "flash"
        assert doc[f"attention|{cpu_attn}"]["winner"] == "xla"
        # wire: best busbw on the 4-chip curve is bf16 fused
        wire_key = tuning.decision_key("TPU v5 lite", shape=(4,),
                                       dtype="grad")
        assert doc[f"allreduce_wire|{wire_key}"]["winner"] == "bf16"
        # bucketed within 10% of fused -> keep the 64 MB discipline
        assert doc[f"allreduce_bucket_mb|{wire_key}"]["winner"] == "64"
        # ...but the CPU proxy's micro-bucket rows and n=1 curves must
        # seed NEITHER a wire nor a bucket decision
        assert not any(k.startswith("allreduce") and "|cpu|" in k
                       for k in doc)
        # double buffering measured a loss on both backends
        for koff in (
            tuning.decision_key("cpu", shape=(8,), dtype="step"),
            tuning.decision_key("TPU v5 lite", shape=(4,), dtype="step"),
        ):
            assert doc[f"double_buffering|{koff}"]["winner"] == "off"
        # reduction schedule (ISSUE 3): each backend's overlap rows seed
        # ITS winner under its own (world-shape, payload-MB) key — the
        # exact key MultiNodeOptimizer's 'auto' resolution asks for.
        cpu_sched = tuning.decision_key("cpu", shape=(8, 1), dtype="sched")
        assert doc[f"reduction_schedule|{cpu_sched}"]["winner"] == "zero"
        assert doc[f"reduction_schedule|{cpu_sched}"]["candidates_ms"][
            "two_level"] == 11.8
        tpu_sched = tuning.decision_key(
            "TPU v5 lite", shape=(4, 128), dtype="sched"
        )
        assert doc[f"reduction_schedule|{tpu_sched}"]["winner"] == (
            "two_level"
        )
        # and the seeded entry answers resolve_schedule without
        # re-measuring (the 'auto' front door)
        from chainermn_tpu.parallel.reduction_schedule import (
            resolve_schedule,
        )

        winner, rec = resolve_schedule("cpu", 1 << 20, (8,))
        assert winner == "zero"
        assert rec["source"].startswith("cache:seeded")

    def test_seeding_from_repo_details_is_self_consistent(self):
        """The REAL BENCH_DETAILS.json seeds without error and its
        on-chip MoE row reproduces the einsum-competitive choice."""
        import os

        details = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_DETAILS.json")
        seeded = tuning.seed_from_bench_details(details)
        moe = [s for s in seeded if s.startswith("moe_dispatch|TPU")]
        assert moe, seeded
        assert moe[0].endswith("-> sort")


# ---------------------------------------------------------------------------
# Call-site wiring + structural selection
# ---------------------------------------------------------------------------


class TestCallSites:
    def _moe_lowered(self, comm, impl):
        ax = comm.axis_name

        def local(x, rw, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            return moe_layer_local(
                x, rw, expert_fn, params, ax,
                capacity_factor=2.0, dispatch_impl=impl,
            )

        n = comm.size
        x = jnp.zeros((8 * n, D))
        rw = jnp.zeros((D, n))
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(0), n)
        fn = jax.jit(shard_map(
            local, mesh=comm.mesh, in_specs=(P(), P(), P(ax)),
            out_specs=P(), check_vma=False,
        ))
        return fn.lower(x, rw, stacked).as_text()

    def test_moe_auto_selects_sort_path_on_cpu_mesh(self, comm):
        """STRUCTURAL: the auto-dispatched program on the CPU mesh IS
        the sort program (index scatter present, and no decision other
        than sort recorded), not the dense einsum one."""
        auto_txt = self._moe_lowered(comm, "auto")
        sort_txt = self._moe_lowered(comm, "sort")
        einsum_txt = self._moe_lowered(comm, "einsum")
        assert "scatter" in auto_txt  # the sort path's queue assembly
        assert "scatter" not in einsum_txt
        assert auto_txt == sort_txt
        recs = [r for r in tuning.decisions_taken()
                if r["name"] == "moe_dispatch"]
        assert recs and all(r["winner"] == "sort" for r in recs)

    def test_moe_dist_equals_single_for_both_sides(self, comm):
        """dist==single (values AND grads) for BOTH tuned candidates:
        the einsum and sort programs over the 8-way mesh each equal the
        same single-device dense evaluation."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(0), (tokens, D))
        rw = jax.random.normal(jax.random.PRNGKey(1), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(2), n)
        capacity = tokens  # generous: no drops

        def single(x, rw, stacked):
            # single-device dense evaluation of the same routing
            logits = x @ rw
            dispatch, combine = top1_route(logits, capacity)
            queues = jnp.einsum("td,tec->ecd", x, dispatch)
            outs = jax.vmap(expert_fn)(stacked, queues)
            return jnp.einsum("ecd,tec->td", outs, combine)

        def dist(impl):
            def local(x, rw, stacked):
                params = jax.tree.map(lambda l: l[0], stacked)
                return moe_layer_local(
                    x, rw, expert_fn, params, ax,
                    capacity_factor=float(n), dispatch_impl=impl,
                )

            return jax.jit(shard_map(
                local, mesh=comm.mesh, in_specs=(P(), P(), P(ax)),
                out_specs=P(), check_vma=False,
            ))

        ref = single(x, rw, stacked)
        g_ref = jax.grad(
            lambda xx, rr, ss: (single(xx, rr, ss) ** 2).mean(),
            argnums=(0, 1, 2),
        )(x, rw, stacked)
        for impl in ("einsum", "sort"):
            out = dist(impl)(x, rw, stacked)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
            g = jax.grad(
                lambda xx, rr, ss, i=impl: (dist(i)(xx, rr, ss) ** 2).mean(),
                argnums=(0, 1, 2),
            )(x, rw, stacked)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
                ),
                g, g_ref,
            )

    def test_attention_both_sides_equal(self):
        """Both sides of the attention choice (and of the windowed
        choice) compute the same function — values AND grads."""
        from chainermn_tpu.ops.attention import attention

        q = jax.random.normal(jax.random.PRNGKey(3), (1, 64, 2, 8),
                              jnp.float32)

        for kwargs in ({"causal": True}, {"causal": True, "window": 16}):
            o_x = attention(q, q, q, impl="xla", **kwargs)
            flash_impl = "windowed" if "window" in kwargs else "flash"
            o_f = attention(q, q, q, impl=flash_impl, interpret=True,
                            **kwargs)
            np.testing.assert_allclose(np.asarray(o_x), np.asarray(o_f),
                                       rtol=2e-5, atol=2e-5)

            def loss(fn_impl, interp):
                def f(qq):
                    return jnp.sum(
                        attention(qq, qq, qq, impl=fn_impl,
                                  interpret=interp, **kwargs) ** 2
                    )
                return jax.grad(f)(q)

            np.testing.assert_allclose(
                np.asarray(loss("xla", None)),
                np.asarray(loss(flash_impl, True)),
                rtol=2e-4, atol=2e-5,
            )

    def test_attention_auto_records_decision(self):
        from chainermn_tpu.ops.attention import attention

        q = jnp.zeros((1, 32, 2, 8), jnp.float32)
        attention(q, q, q, causal=True)  # auto -> xla on cpu
        recs = [r for r in tuning.decisions_taken()
                if r["name"] == "attention"]
        assert recs and recs[-1]["winner"] == "xla"

    def test_wire_both_sides_dist_equals_single(self, comm):
        """Both sides of the tuned wire (bf16 vs the f32 master wire,
        plus the int8 wire the cache may adopt): the in-mesh mean of
        per-shard grads equals the single-device numpy mean within each
        wire's tolerance."""
        from chainermn_tpu.optimizers import allreduce_gradients

        n = comm.size
        ax = comm.axis_name
        g = jax.random.normal(jax.random.PRNGKey(4), (n, 64), jnp.float32)
        expect = np.asarray(g).mean(axis=0)

        def run(compress):
            def local(gs):
                return allreduce_gradients(
                    gs[0], axis_names=(ax,), compress_dtype=compress
                )[None]

            return jax.jit(shard_map(
                local, mesh=comm.mesh, in_specs=(P(ax),),
                out_specs=P(ax), check_vma=False,
            ))(g)

        for compress, tol in ((None, 1e-6), (jnp.bfloat16, 2e-2),
                              (jnp.int8, 6e-2)):
            out = np.asarray(run(compress))
            for i in range(n):
                np.testing.assert_allclose(out[i], expect, rtol=tol,
                                           atol=tol)

    def test_auto_wire_resolution_and_bucket(self, comm):
        from chainermn_tpu.communicators.xla_communicator import (
            NaiveCommunicator,
        )
        from chainermn_tpu.parallel.collectives import tuned_bucket_bytes

        c = NaiveCommunicator(allreduce_grad_dtype="auto")
        assert c.allreduce_grad_dtype == jnp.dtype(jnp.bfloat16)
        assert tuned_bucket_bytes(c.device_kind, c.size) == 64 << 20
        # a cache entry flips the wire for this exact topology key
        key = tuning.decision_key(c.device_kind, shape=(c.size,),
                                  dtype="grad")
        tuning.store_entry("allreduce_wire", key,
                           {"winner": "int8", "source": "test"})
        c2 = NaiveCommunicator(allreduce_grad_dtype="auto")
        assert c2.allreduce_grad_dtype == jnp.dtype(jnp.int8)

    def test_double_buffering_advisory_warns_not_overrides(self, comm):
        """The advisory warns when the flag is enabled on a backend
        where a cache/measured record says it loses — but NOT on the
        blanket table fallback (an unmeasured topology has no evidence
        to cite) — and semantics stay faithful staleness-1 (first
        update applies the zero bank, banking this step's grads)."""
        import optax

        from chainermn_tpu import create_multi_node_optimizer

        # empty cache -> table fallback: recorded, but NO warning
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            create_multi_node_optimizer(
                optax.sgd(0.1), comm, double_buffering=True
            )
        assert not any("double_buffering" in str(x.message) for x in w)

        # a measured record for THIS backend: the advisory fires
        key = tuning.decision_key(comm.device_kind, shape=(comm.size,),
                                  dtype="step")
        tuning.store_entry(
            "double_buffering", key,
            {"winner": "off", "source": "measured:bench",
             "double_buffer_speedup": 0.752},
        )
        tuning.reset_decisions()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            opt = create_multi_node_optimizer(
                optax.sgd(0.1), comm, double_buffering=True
            )
        assert any("double_buffering" in str(x.message) for x in w)
        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        grads = {"w": jnp.full((4,), 2.0)}
        updates, state = opt.update(grads, state, params)
        # staleness-1: the FIRST update applies the zero bank...
        np.testing.assert_allclose(np.asarray(updates["w"]),
                                   np.zeros(4), atol=0)
        # ...and banks this step's (identity-reduced) grads
        np.testing.assert_allclose(
            np.asarray(state.communicated_grads["w"]),
            np.asarray(grads["w"]), atol=1e-6,
        )
