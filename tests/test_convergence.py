"""End-to-end wire-dtype convergence drill (round-5 VERDICT ask #5).

The unit invariants (``test_optimizer.py``: cumulative-error bounds,
per-rank residuals) say the int8/EF machinery is wired right; THIS file
says it matters at training level: the same model trained on the
8-device mesh through the STANDARD trainer under each gradient wire —
f32, bf16, int8, int8+EF (+ the topology-aware int8 wire on a 2-axis
mesh) — and the loss curves compared.

The task is deliberately quantization-hostile via DATA HETEROGENEITY,
the realistic failure mode for a quantized wire: every rank's batch
carries one adversarial sample whose huge residual (sign alternating
across ranks, exactly cancelling in the mean) pins that rank's stage-1
quantization amax ~130x above the honest gradient signal. The honest
gradients are sub-quantum once training has halved their error, so
deterministic round-to-nearest kills them EVERY step (the data is fixed
→ the rounding repeats exactly): bare int8 stalls at a loss floor f32
never sees, while error feedback accumulates exactly what rounding
dropped and releases it every few steps — the EF curve must track f32.

Upstream capability analog: the reference's compressed allreduce
(``allreduce_grad_dtype='float16'``, ``pure_nccl_communicator.py`` †)
shipped with convergence evidence on MNIST; int8 is beyond-reference and
gets the sharper drill. Guidance on when the int8 wire pays (DCN-bound
deployments, with EF) lives in docs/parallelism.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)

N = 8
DIM = 16          # coord 0 is the adversarial channel; 1..15 are honest
PER_RANK = 9      # 8 honest samples + 1 adversarial per rank
STEPS = 240
LR = 0.2
B_ADV = 3.0       # adversarial feature magnitude (keeps curvature tame)
S_ADV = 100.0     # adversarial target magnitude (sets the amax)


def _per_rank_data():
    """Fixed per-rank batches, rank-major [N*PER_RANK, DIM].

    Honest samples: x ~ N(0,1) on coords 1..15 (coord 0 dead), target
    x @ w* with w* = (0, 1, ..., 1). Adversarial sample per rank:
    x = B_ADV * e0, target eps_r * S_ADV with eps = +1 on the first
    half of the ranks and -1 on the second (total sum 0, but each
    CONTIGUOUS half sums to +-4 — so on the 2-axis (inter=2, intra=4)
    mesh the exact intra stage does NOT cancel it and the int8 inter
    leg still faces the heterogeneity-pinned amax). Its per-rank
    gradient lives only on coord 0, magnitude ~B_ADV*S_ADV/PER_RANK
    ≈ 33 — the persistent amax — while its MEAN over all ranks is
    exactly 0: no optimum shift, no trainable escape."""
    rng = np.random.RandomState(11)
    xs, ys = [], []
    eps = np.array([+1] * (N // 2) + [-1] * (N // 2), np.float32)
    for r in range(N):
        xh = np.zeros((PER_RANK - 1, DIM), np.float32)
        xh[:, 1:] = rng.randn(PER_RANK - 1, DIM - 1)
        yh = xh[:, 1:].sum(axis=1)  # w* = 1 on honest coords
        xa = np.zeros((1, DIM), np.float32)
        xa[0, 0] = B_ADV
        ya = np.array([eps[r] * S_ADV], np.float32)
        xs.append(np.concatenate([xh, xa]))
        ys.append(np.concatenate([yh, ya]))
    return jnp.asarray(np.concatenate(xs)), jnp.asarray(np.concatenate(ys))


def _drill(comm, opt, steps=STEPS):
    """ONE trainer harness for every drill in this file (wire configs and
    local SGD alike): train, return (loss curve, final weight vector)."""
    x, y = _per_rank_data()

    def loss_fn(params, batch, model_state):
        xb, yb = batch
        pred = xb @ params["w"]
        return 0.5 * jnp.mean((pred - yb) ** 2), ({}, model_state)

    params = {"w": jnp.zeros((DIM,), jnp.float32)}
    state = create_train_state(params, opt, comm, model_state={})
    step = make_train_step(loss_fn, opt, comm)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, (x, y))
        losses.append(float(metrics["loss"]))
    return np.asarray(losses), np.asarray(jax.tree.leaves(state.params)[0])


def _train(comm, *, wire, error_feedback=False, steps=STEPS):
    return _drill(
        comm,
        create_multi_node_optimizer(
            optax.sgd(LR), comm,
            allreduce_grad_dtype=wire,
            error_feedback=error_feedback,
        ),
        steps=steps,
    )


# Every wire pays the same irreducible floor: the adversarial residuals
# (+-S_ADV at w0=0) contribute S_ADV^2/(2*PER_RANK) to each rank's batch
# loss. Comparisons below therefore use EXCESS loss over the f32 curve.
_FLOOR = S_ADV**2 / (2 * PER_RANK)


@pytest.fixture(scope="module")
def curves():
    comm = create_communicator("naive")
    return {
        "f32": _train(comm, wire=None),
        "bf16": _train(comm, wire=jnp.bfloat16),
        "int8": _train(comm, wire=jnp.int8),
        "int8_ef": _train(comm, wire=jnp.int8, error_feedback=True),
    }


class TestWireConvergence:
    def test_f32_converges_to_the_floor(self, curves):
        losses, w = curves["f32"]
        assert abs(losses[-1] - _FLOOR) < 0.05, (losses[-1], _FLOOR)
        np.testing.assert_allclose(w[1:], np.ones(DIM - 1), atol=3e-2)
        assert abs(w[0]) < 0.05  # the adversarial channel stays put

    def test_bf16_tracks_f32(self, curves):
        # bf16 covers the task's dynamic range: indistinguishable from
        # f32 at curve level (the reference's fp16 claim, sharper).
        excess = curves["bf16"][0][-1] - curves["f32"][0][-1]
        assert abs(excess) < 0.05, excess

    def test_ef_tracks_f32(self, curves):
        """The headline: EF's whole TAIL tracks f32 — not just the
        final point."""
        f32, ef = curves["f32"][0], curves["int8_ef"][0]
        tail = slice(STEPS - 50, STEPS)
        excess = ef[tail] - f32[tail]
        assert np.max(np.abs(excess)) < 0.1, np.max(np.abs(excess))

    def test_bare_int8_stalls_above_ef(self, curves):
        """Deterministic rounding against the heterogeneity-pinned amax
        kills the honest gradients: bare int8 plateaus at an excess
        loss orders of magnitude above EF's."""
        f32 = curves["f32"][0][-1]
        ex_int8 = curves["int8"][0][-1] - f32
        ex_ef = abs(curves["int8_ef"][0][-1] - f32)
        assert ex_int8 > 50 * max(ex_ef, 1e-4), (ex_int8, ex_ef)

    def test_int8_stall_is_the_honest_coordinates(self, curves):
        """Mechanism check, not just outcome: int8's shortfall is the
        honest coordinates stuck ~one quantum from the optimum, and EF
        recovered exactly those."""
        quantum = (B_ADV * S_ADV / PER_RANK) / 127.0  # ~0.26
        w = curves["int8"][1]
        stall = np.abs(w[1:] - 1.0)
        assert stall.max() > quantum / 4, stall.max()
        w_ef = curves["int8_ef"][1]
        assert np.abs(w_ef[1:] - 1.0).max() < quantum / 4


class TestTopologyAwareWireConvergence:
    def test_two_level_int8_trains_on_two_axis_mesh(self):
        """The topology-aware wire (exact intra reduction, int8 only on
        the inter axis) through the same drill on a REAL (2, 4) mesh —
        the default single-process two_dimensional factorisation is the
        degenerate (1, 8), whose inter leg never quantizes anything.
        Each intra group carries one sign of the adversarial eps (the
        block pattern is chosen for exactly this grouping), so the int8
        inter leg faces the full heterogeneity-pinned amax: it trains
        the super-quantum part of the signal AND shows the same
        sub-quantum stall as the flat wire — the measured reason the
        docs say 'pair int8 with EF'."""
        from jax.sharding import Mesh

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:N]).reshape(2, 4)
        comm2 = TwoDimensionalCommunicator(
            mesh=Mesh(devs, ("inter", "intra"))
        )
        losses, w = _train(comm2, wire=jnp.int8, steps=120)
        f32_losses, w_f32 = _train(comm2, wire=None, steps=120)
        # Real progress: nearly all of the trainable loss (the part
        # above the irreducible adversarial floor) is gone...
        trainable0 = losses[0] - _FLOOR
        ex = losses[-1] - f32_losses[-1]
        assert trainable0 > 1.0  # the task starts with real signal
        assert ex < 0.05 * trainable0, (ex, trainable0)
        # ...f32 on the same mesh fully converges (sanity)...
        np.testing.assert_allclose(w_f32[1:], 1.0, atol=3e-2)
        # ...and the inter leg genuinely quantized: the sub-quantum
        # stall is present, unlike the degenerate (1, 8) mesh where the
        # int8 stage is a no-op and w would match f32 exactly.
        quantum = (B_ADV * S_ADV / PER_RANK) / 127.0
        assert np.abs(w[1:] - 1.0).max() > quantum / 8

    def test_shard_level_ef_recovers_the_stall(self):
        """Round 5's shard-level EF: error feedback AT the topology-aware
        wire's only lossy stage (the int8 inter leg), with shard-shaped
        residual state carried through the standard trainer. It must
        recover exactly the coordinates bare topo-int8 stalls on and
        track f32 — the same headline the flat-wire EF test enforces,
        now WITHOUT giving up the exact-ICI property."""
        from jax.sharding import Mesh

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:N]).reshape(2, 4)
        comm2 = TwoDimensionalCommunicator(
            mesh=Mesh(devs, ("inter", "intra"))
        )
        ef_losses, w_ef = _train(
            comm2, wire=jnp.int8, error_feedback=True, steps=120)
        f32_losses, _ = _train(comm2, wire=None, steps=120)
        quantum = (B_ADV * S_ADV / PER_RANK) / 127.0
        # EF recovers the honest coordinates bare topo-int8 leaves
        # ~one quantum out (see the test above)...
        assert np.abs(w_ef[1:] - 1.0).max() < quantum / 4
        # ...and the loss tail tracks f32.
        ex = abs(ef_losses[-1] - f32_losses[-1])
        assert ex < 0.1, ex


def _train_local_sgd(comm, *, sync_every):
    """Same task, same harness, periodic parameter averaging instead of a
    per-step wire."""
    from chainermn_tpu import create_local_sgd

    return _drill(
        comm, create_local_sgd(optax.sgd(LR), comm, sync_every=sync_every)
    )


class TestLocalSGDConvergence:
    """Training-level drill for periodic parameter averaging, on the SAME
    heterogeneous-rank task as the wire drill: between syncs each rank's
    adversarial sample drags its local w0 toward ±S_ADV (the per-step
    mean no longer cancels it), so client drift is real here — the sync
    must absorb it."""

    def test_sync_every_1_equals_per_step_f32(self, curves):
        comm = create_communicator("naive")
        local, w = _train_local_sgd(comm, sync_every=1)
        f32, w_f32 = curves["f32"]
        np.testing.assert_allclose(local, f32, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(w, w_f32, rtol=1e-4, atol=1e-4)

    def test_sync_every_8_converges_despite_client_drift(self, curves):
        comm = create_communicator("naive")
        local, w = _train_local_sgd(comm, sync_every=8)
        f32, _ = curves["f32"]
        # Converges to (near) the same irreducible floor: the drift the
        # adversarial channel induces between syncs is averaged away.
        tail_excess = local[-20:].mean() - _FLOOR
        f32_excess = f32[-20:].mean() - _FLOOR
        assert tail_excess < 5 * max(f32_excess, 0) + 2.0, (
            tail_excess, f32_excess)
        # Honest coordinates learned; the adversarial coordinate's
        # synced mean stays near zero (per-rank drift cancels).
        np.testing.assert_allclose(w[1:], np.ones(DIM - 1), atol=0.05)
        assert abs(w[0]) < 0.5, w[0]

    def test_local_sgd_on_two_axis_mesh(self):
        """Local SGD's cond'd pmean over a TUPLE of axes: on the real
        (inter=2, intra=4) mesh the sync means over both axes at once —
        sync_every=1 with a linear inner must equal the per-step f32
        wire on the same mesh."""
        from jax.sharding import Mesh

        from chainermn_tpu import create_local_sgd
        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:N]).reshape(2, 4)
        comm2 = TwoDimensionalCommunicator(
            mesh=Mesh(devs, ("inter", "intra"))
        )
        local, w = _drill(
            comm2, create_local_sgd(optax.sgd(LR), comm2, sync_every=1),
            steps=120,
        )
        f32, w_f32 = _train(comm2, wire=None, steps=120)
        np.testing.assert_allclose(local, f32, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(w, w_f32, rtol=1e-4, atol=1e-4)
