"""Pipeline-parallel engine tests: the GPipe schedule over the 8-way CPU
mesh must equal sequential application of the stages on one device —
values and gradients (SURVEY.md section 4 invariant). The reference had no
such engine (MultiNodeChainList chained send/recv without micro-batching,
SURVEY.md section 2.2) so these tests define the new contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.parallel.pipeline import (
    make_pipeline,
    pipeline_local,
    stack_stage_params,
)

DIM = 8


def stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _params(seed, n_stages):
    ks = jax.random.split(jax.random.PRNGKey(seed), n_stages)
    return [
        (
            jax.random.normal(k, (DIM, DIM)) / jnp.sqrt(DIM),
            jnp.zeros((DIM,)),
        )
        for k in ks
    ]


def _sequential(params_list, x):
    for p in params_list:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    @pytest.mark.parametrize("n_micro", [8, 16])
    def test_matches_sequential(self, comm, n_micro):
        n_stages = comm.size
        params_list = _params(0, n_stages)
        stacked = stack_stage_params(params_list)
        batch = 32
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, DIM))

        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name,
            n_microbatches=n_micro,
        )
        out = fn(stacked, x)
        ref = _sequential(params_list, x)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)

    def test_grads_match_sequential(self, comm):
        n_stages = comm.size
        params_list = _params(2, n_stages)
        stacked = stack_stage_params(params_list)
        batch = 16
        x = jax.random.normal(jax.random.PRNGKey(3), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(4), (batch, DIM))

        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name, n_microbatches=8
        )

        def loss_pipe(stacked):
            return ((fn(stacked, x) - y) ** 2).mean()

        def loss_seq(stacked):
            params_list = [
                jax.tree.map(lambda l: l[i], stacked)
                for i in range(n_stages)
            ]
            return ((_sequential(params_list, x) - y) ** 2).mean()

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g_pipe,
            g_seq,
        )

    def test_batch_divisibility_enforced(self, comm):
        stacked = stack_stage_params(_params(5, comm.size))
        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name, n_microbatches=7
        )
        x = jnp.zeros((16, DIM))
        with pytest.raises(ValueError, match="not divisible"):
            fn(stacked, x)


def test_remat_stages_matches_plain(comm):
    """remat_stages recomputes in the backward; values and grads must be
    identical to the stored-activation schedule."""
    from chainermn_tpu.parallel.pipeline import (
        make_pipeline,
        stack_stage_params,
    )

    n = comm.size
    d = 4

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    keys = jax.random.split(jax.random.key(5), n)
    stacked = stack_stage_params(
        [jax.random.normal(k, (d, d)) * 0.5 for k in keys]
    )
    x = jax.random.normal(jax.random.key(6), (2 * n, d))

    def loss(pipe):
        return lambda p, x: jnp.mean(pipe(p, x) ** 2)

    plain = make_pipeline(stage_fn, comm.mesh, axis_name=comm.axis_name,
                          n_microbatches=n)
    remat = make_pipeline(stage_fn, comm.mesh, axis_name=comm.axis_name,
                          n_microbatches=n, remat_stages=True)
    l1, g1 = jax.value_and_grad(loss(plain))(stacked, x)
    l2, g2 = jax.value_and_grad(loss(remat))(stacked, x)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)
