"""Pipeline-parallel engine tests: the GPipe schedule over the 8-way CPU
mesh must equal sequential application of the stages on one device —
values and gradients (SURVEY.md section 4 invariant). The reference had no
such engine (MultiNodeChainList chained send/recv without micro-batching,
SURVEY.md section 2.2) so these tests define the new contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.parallel.pipeline import (
    make_pipeline,
    pipeline_local,
    stack_stage_params,
)

DIM = 8


def stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _params(seed, n_stages):
    ks = jax.random.split(jax.random.PRNGKey(seed), n_stages)
    return [
        (
            jax.random.normal(k, (DIM, DIM)) / jnp.sqrt(DIM),
            jnp.zeros((DIM,)),
        )
        for k in ks
    ]


def _sequential(params_list, x):
    for p in params_list:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    @pytest.mark.parametrize("n_micro", [8, 16])
    def test_matches_sequential(self, comm, n_micro):
        n_stages = comm.size
        params_list = _params(0, n_stages)
        stacked = stack_stage_params(params_list)
        batch = 32
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, DIM))

        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name,
            n_microbatches=n_micro,
        )
        out = fn(stacked, x)
        ref = _sequential(params_list, x)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)

    def test_grads_match_sequential(self, comm):
        n_stages = comm.size
        params_list = _params(2, n_stages)
        stacked = stack_stage_params(params_list)
        batch = 16
        x = jax.random.normal(jax.random.PRNGKey(3), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(4), (batch, DIM))

        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name, n_microbatches=8
        )

        def loss_pipe(stacked):
            return ((fn(stacked, x) - y) ** 2).mean()

        def loss_seq(stacked):
            params_list = [
                jax.tree.map(lambda l: l[i], stacked)
                for i in range(n_stages)
            ]
            return ((_sequential(params_list, x) - y) ** 2).mean()

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g_pipe,
            g_seq,
        )

    def test_batch_divisibility_enforced(self, comm):
        stacked = stack_stage_params(_params(5, comm.size))
        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name, n_microbatches=7
        )
        x = jnp.zeros((16, DIM))
        with pytest.raises(ValueError, match="not divisible"):
            fn(stacked, x)


class TestInterleavedPipeline:
    """Virtual-stage (interleaved) schedule: v model chunks per physical
    stage on the looped conveyor — VERDICT r2 item 7."""

    @pytest.mark.parametrize("v,n_micro", [(2, 8), (2, 16), (3, 8)])
    def test_matches_sequential(self, comm, v, n_micro):
        from chainermn_tpu.parallel.pipeline import (
            stack_interleaved_stage_params,
        )

        n = comm.size
        params_list = _params(7, n * v)  # n*v global stages
        stacked = stack_interleaved_stage_params(params_list, n, v)
        batch = 32
        x = jax.random.normal(jax.random.PRNGKey(8), (batch, DIM))
        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name,
            n_microbatches=n_micro, virtual_stages=v,
        )
        ref = _sequential(params_list, x)
        np.testing.assert_allclose(np.asarray(fn(stacked, x)), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match_sequential(self, comm):
        from chainermn_tpu.parallel.pipeline import (
            stack_interleaved_stage_params,
        )

        n, v = comm.size, 2
        params_list = _params(9, n * v)
        stacked = stack_interleaved_stage_params(params_list, n, v)
        batch = 16
        x = jax.random.normal(jax.random.PRNGKey(10), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(11), (batch, DIM))
        fn = make_pipeline(
            stage_fn, comm.mesh, axis_name=comm.axis_name,
            n_microbatches=8, virtual_stages=v,
        )

        order = [j * n + s for s in range(n) for j in range(v)]
        inv = [order.index(g) for g in range(n * v)]

        def loss_pipe(stacked):
            return ((fn(stacked, x) - y) ** 2).mean()

        def loss_seq(stacked):
            params_list = [
                jax.tree.map(lambda l: l[inv[g]], stacked)
                for g in range(n * v)
            ]
            return ((_sequential(params_list, x) - y) ** 2).mean()

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g_pipe,
            g_seq,
        )

    def test_bubble_fraction_shrinks(self):
        """The schedule-length formula: interleaving amortises the same
        (n-1)-tick fill over v× more (1/v-sized) ticks, so the bubble
        fraction drops from (n-1)/(m+n-1) to (n-1)/(v*m+n-1)."""
        from chainermn_tpu.parallel.pipeline import pipeline_total_ticks

        n, m = 8, 32
        for v in (1, 2, 4):
            total = pipeline_total_ticks(n, m, v)
            assert total == v * m + n - 1  # n | m — clean waves
            bubble = (n - 1) / total
            assert abs(bubble - (n - 1) / (v * m + n - 1)) < 1e-12
        t1 = pipeline_total_ticks(n, m, 1)
        t4 = pipeline_total_ticks(n, m, 4)
        # Wall-clock: a v-chunk tick is 1/v of a full-stage tick.
        assert t4 / 4 < t1
        # Partial waves occupy a full wave slot.
        assert pipeline_total_ticks(4, 6, 2) == 2 * 4 * 2 + 3

    def test_stacking_layout_validates(self):
        from chainermn_tpu.parallel.pipeline import (
            stack_interleaved_stage_params,
        )

        with pytest.raises(ValueError, match="stage params"):
            stack_interleaved_stage_params(_params(0, 6), 4, 2)


def test_remat_stages_matches_plain(comm):
    """remat_stages recomputes in the backward; values and grads must be
    identical to the stored-activation schedule."""
    from chainermn_tpu.parallel.pipeline import (
        make_pipeline,
        stack_stage_params,
    )

    n = comm.size
    d = 4

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    keys = jax.random.split(jax.random.key(5), n)
    stacked = stack_stage_params(
        [jax.random.normal(k, (d, d)) * 0.5 for k in keys]
    )
    x = jax.random.normal(jax.random.key(6), (2 * n, d))

    def loss(pipe):
        return lambda p, x: jnp.mean(pipe(p, x) ** 2)

    plain = make_pipeline(stage_fn, comm.mesh, axis_name=comm.axis_name,
                          n_microbatches=n)
    remat = make_pipeline(stage_fn, comm.mesh, axis_name=comm.axis_name,
                          n_microbatches=n, remat_stages=True)
    l1, g1 = jax.value_and_grad(loss(plain))(stacked, x)
    l2, g2 = jax.value_and_grad(loss(remat))(stacked, x)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


class Test1F1B:
    """1F1B schedule == sequential fwd+bwd: loss and per-stage grads. The
    per-microbatch-loss semantics: total loss = mean over microbatches of
    the microbatch loss."""

    def _loss_grad_fn(self):
        def mb_loss(y, t):
            return ((y - t) ** 2).mean()

        return jax.value_and_grad(mb_loss)

    @pytest.mark.parametrize("n_micro", [8, 16])
    def test_loss_and_grads_match_sequential(self, comm, n_micro):
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        n_stages = comm.size
        params_list = _params(7, n_stages)
        stacked = stack_stage_params(params_list)
        batch = 32
        x = jax.random.normal(jax.random.PRNGKey(8), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(9), (batch, DIM))

        fn = make_pipeline_1f1b(
            stage_fn, self._loss_grad_fn(), comm.mesh,
            axis_name=comm.axis_name, n_microbatches=n_micro,
        )
        loss, grads = fn(stacked, x, y)

        mb = batch // n_micro

        def loss_seq(stacked):
            params_list = [
                jax.tree.map(lambda l: l[i], stacked)
                for i in range(n_stages)
            ]
            out = _sequential(params_list, x)
            # mean over microbatches of per-microbatch mean loss == full
            # batch mean here (equal microbatch sizes)
            losses = ((out - y) ** 2).reshape(n_micro, mb, DIM)
            return losses.mean(axis=(1, 2)).mean()

        ref_loss, ref_grads = jax.value_and_grad(loss_seq)(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            grads,
            ref_grads,
        )

    def test_one_microbatch_degenerate(self, comm):
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        n_stages = comm.size
        params_list = _params(10, n_stages)
        stacked = stack_stage_params(params_list)
        x = jax.random.normal(jax.random.PRNGKey(11), (4, DIM))
        y = jax.random.normal(jax.random.PRNGKey(12), (4, DIM))
        fn = make_pipeline_1f1b(
            stage_fn, self._loss_grad_fn(), comm.mesh,
            axis_name=comm.axis_name, n_microbatches=1,
        )
        loss, grads = fn(stacked, x, y)

        def loss_seq(stacked):
            pl = [jax.tree.map(lambda l: l[i], stacked) for i in range(n_stages)]
            return ((_sequential(pl, x) - y) ** 2).mean()

        ref_loss, ref_grads = jax.value_and_grad(loss_seq)(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            grads,
            ref_grads,
        )

    def test_loss_with_pole_at_zero_stays_finite(self, comm):
        """Warmup/drain ticks must never evaluate the loss head on the
        zero-initialised output buffer: a loss with a pole at y=0 (e.g.
        log-likelihood) must still give finite, correct grads."""
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        n_stages = comm.size

        def pos_stage(params, x):
            w, b = params
            return jax.nn.sigmoid(x @ w + b) + 0.5  # outputs in [0.5, 1.5]

        def mb_loss(y, t):
            return -(t * jnp.log(y)).mean()  # pole at y == 0

        params_list = _params(13, n_stages)
        stacked = stack_stage_params(params_list)
        x = jax.random.normal(jax.random.PRNGKey(14), (16, DIM))
        t = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(15), (16, DIM)))

        fn = make_pipeline_1f1b(
            pos_stage, jax.value_and_grad(mb_loss), comm.mesh,
            axis_name=comm.axis_name, n_microbatches=8,
        )
        loss, grads = fn(stacked, x, t)

        def loss_seq(stacked):
            pl = [jax.tree.map(lambda l: l[i], stacked) for i in range(n_stages)]
            out = x
            for p in pl:
                out = pos_stage(p, out)
            per_mb = (-(t * jnp.log(out))).reshape(8, 2 * DIM).mean(axis=1)
            return per_mb.mean()

        ref_loss, ref_grads = jax.value_and_grad(loss_seq)(stacked)
        assert np.isfinite(float(loss))
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            grads,
            ref_grads,
        )

    def test_trainable_head_and_input_grads(self, comm):
        """head_params grads and input grads from the 1F1B engine equal
        jax.grad of the sequential computation — the full-model training
        contract (embed before, head after the pipelined region)."""
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        n_stages = comm.size
        params_list = _params(20, n_stages)
        stacked = stack_stage_params(params_list)
        batch, n_micro = 16, 8
        x = jax.random.normal(jax.random.PRNGKey(21), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(22), (batch, DIM))
        w_head = jax.random.normal(jax.random.PRNGKey(23), (DIM, DIM)) * 0.3

        def head_loss(w, y_mb, t_mb):
            return (((y_mb @ w) - t_mb) ** 2).mean()

        # loss_grad_fn with head: (loss, (dhead, dy))
        def loss_grad_fn(w, y_mb, t_mb):
            loss, (dw, dy) = jax.value_and_grad(head_loss, argnums=(0, 1))(
                w, y_mb, t_mb
            )
            return loss, (dw, dy)

        fn = make_pipeline_1f1b(
            stage_fn, loss_grad_fn, comm.mesh,
            axis_name=comm.axis_name, n_microbatches=n_micro,
        )
        loss, grads, head_grads, x_grads = fn(
            stacked, x, y, w_head, collect_input_grads=True
        )

        def loss_seq(stacked, w, x):
            pl = [jax.tree.map(lambda l: l[i], stacked) for i in range(n_stages)]
            out = _sequential(pl, x)
            mb = batch // n_micro
            per = (((out @ w) - y) ** 2).reshape(n_micro, mb * DIM).mean(1)
            return per.mean()

        ref_loss, (g_ref, h_ref, x_ref) = jax.value_and_grad(
            loss_seq, argnums=(0, 1, 2)
        )(stacked, w_head, x)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            grads, g_ref,
        )
        np.testing.assert_allclose(
            np.asarray(head_grads), np.asarray(h_ref), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(x_grads), np.asarray(x_ref), rtol=1e-4, atol=1e-6
        )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_example_converges(schedule):
    """The example CLI trains the full model (embed + pipelined stages +
    head) to high accuracy under both schedules."""
    import examples.pipeline.train_pipeline_mlp as ex

    acc = ex.main([
        "--iterations", "120", "--batchsize", "64", "--width", "64",
        "--schedule", schedule,
    ])
    assert acc > 0.9, f"{schedule} did not converge: acc={acc}"


def test_1f1b_uses_less_temp_memory_than_gpipe(comm):
    """The 1F1B memory claim, measured by XLA's own buffer assignment:
    with many microbatches and fat boundary activations, the interleaved
    schedule's temp allocation must be well below GPipe+remat+autodiff
    (which keeps O(n_micro) boundary tensors for the transposed replay)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel import pipeline as pl

    mesh = comm.mesh
    ax = comm.axis_name
    D, B, M = 1024, 512, 32

    def stage_fn(w, x):
        return x + jnp.tanh(x @ w)

    ks = jax.random.split(jax.random.key(30), comm.size)
    stacked = stack_stage_params(
        [jax.random.normal(k, (D, D)) * 0.02 for k in ks]
    )
    x = jax.random.normal(jax.random.key(31), (B, D))
    t = jnp.zeros((B, D))

    pipe = pl.make_pipeline(stage_fn, mesh, axis_name=ax,
                            n_microbatches=M, remat_stages=True)
    g = (
        jax.jit(jax.value_and_grad(
            lambda s, x: jnp.mean((pipe(s, x) - t) ** 2)))
        .lower(stacked, x).compile().memory_analysis()
    )

    lg = jax.value_and_grad(lambda y, tt: jnp.mean((y - tt) ** 2))

    def local(sp, x, tt):
        params = jax.tree.map(lambda p: p[0], sp)
        xm = x.reshape((M, B // M, D))
        tm = tt.reshape((M, B // M, D))
        res = pl.pipeline_1f1b_local(stage_fn, lg, params, xm, tm, ax)
        return res[0], jax.tree.map(lambda gg: gg[None], res[1])

    f = (
        jax.jit(shard_map(local, mesh=mesh,
                          in_specs=(P(ax), P(), P()),
                          out_specs=(P(), P(ax)), check_vma=False))
        .lower(stacked, x, t).compile().memory_analysis()
    )
    # measured ~2x at this config; assert a conservative margin
    assert f.temp_size_in_bytes < 0.8 * g.temp_size_in_bytes, (
        f"1F1B temp {f.temp_size_in_bytes/1e6:.1f}MB not below GPipe "
        f"{g.temp_size_in_bytes/1e6:.1f}MB"
    )


class TestDataParallelComposition:
    """dp x pp on a (data=2, stage=4) mesh == sequential on the full
    batch."""

    def _mesh2d(self):
        from jax.sharding import Mesh

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        return Mesh(devs, ("data", "stage"))

    def test_gpipe_apply_values_with_batch_axis(self):
        mesh = self._mesh2d()
        params_list = _params(40, 4)
        stacked = stack_stage_params(params_list)
        x = jax.random.normal(jax.random.PRNGKey(41), (32, DIM))

        fn = make_pipeline(stage_fn, mesh, axis_name="stage",
                           n_microbatches=4, batch_axis="data")
        out = fn(stacked, x)
        ref = _sequential(params_list, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_1f1b_dp_grads_match_sequential(self):
        from chainermn_tpu.parallel.pipeline import make_pipeline_1f1b

        mesh = self._mesh2d()
        n_stages, n_micro, batch = 4, 8, 32
        params_list = _params(42, n_stages)
        stacked = stack_stage_params(params_list)
        x = jax.random.normal(jax.random.PRNGKey(43), (batch, DIM))
        y = jax.random.normal(jax.random.PRNGKey(44), (batch, DIM))

        lg = jax.value_and_grad(lambda o, t: ((o - t) ** 2).mean())
        fn = make_pipeline_1f1b(stage_fn, lg, mesh, axis_name="stage",
                                n_microbatches=n_micro, batch_axis="data")
        loss, grads = fn(stacked, x, y)

        # sequential reference: mean over (data shards x microbatches) of
        # per-microbatch mean losses == full-batch mean (equal sizes)
        def loss_seq(stacked):
            pl = [jax.tree.map(lambda l: l[i], stacked)
                  for i in range(n_stages)]
            out = _sequential(pl, x)
            return ((out - y) ** 2).mean()

        ref_loss, ref_grads = jax.value_and_grad(loss_seq)(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            grads, ref_grads,
        )


def test_3d_composition_dp_pp_tp():
    """The composability capstone: dp(2) x pp(2) x tp(2) in ONE jitted
    program — 1F1B pipeline schedule over 'stage', each stage's MLP
    hidden-sharded over 'model', batch sharded over 'data'; loss and all
    gradients equal the sequential single-device computation."""
    from jax.sharding import Mesh

    from chainermn_tpu.parallel.tensor import stack_tp_params, tp_mlp

    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "stage", "model"))
    D, FF, batch, n_micro = 8, 16, 16, 4

    # Per-stage params, each tp-sharded over 'model': leaves
    # [n_stages, n_model, ...].
    def full_stage_params(seed):
        return {
            "w1": jax.random.normal(jax.random.key(seed), (D, FF)) * 0.3,
            "w2": jax.random.normal(jax.random.key(seed + 1), (FF, D)) * 0.3,
        }

    fulls = [full_stage_params(60), full_stage_params(62)]
    stacked = stack_stage_params([
        {
            "w1": stack_tp_params(p["w1"], 2, 1),
            "w2": stack_tp_params(p["w2"], 2, 0),
        }
        for p in fulls
    ])  # leaves [stage=2, model=2, ...]

    def stage_fn(p, x):
        return x + tp_mlp(x, p["w1"], None, p["w2"], None,
                          axis_name="model")

    lg = jax.value_and_grad(lambda o, t: ((o - t) ** 2).mean())

    # make_pipeline_1f1b's P(axis_name) spec only shards the leading
    # (stage) dim; shard the model dim explicitly with a custom wrapper.
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel import pipeline as pl

    def local(sp, x, t):
        params = jax.tree.map(lambda leaf: leaf[0, 0], sp)
        xm = x.reshape((n_micro, x.shape[0] // n_micro, D))
        tm = t.reshape((n_micro, t.shape[0] // n_micro, D))
        loss, grads = pl.pipeline_1f1b_local(
            stage_fn, lg, params, xm, tm, "stage"
        )
        loss = jax.lax.pmean(loss, "data")
        grads = jax.lax.pmean(grads, "data")
        return loss, jax.tree.map(lambda g: g[None, None], grads)

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P("stage", "model"), P("data"), P("data")),
        out_specs=(P(), P("stage", "model")),
        check_vma=False,
    ))

    x = jax.random.normal(jax.random.key(64), (batch, D))
    t = jax.random.normal(jax.random.key(65), (batch, D))
    loss, grads = fn(stacked, x, t)

    def seq_loss(fulls_flat):
        f1, f2 = fulls_flat
        out = x
        for p in (f1, f2):
            out = out + jax.nn.gelu(out @ p["w1"]) @ p["w2"]
        return ((out - t) ** 2).mean()

    ref_loss, ref_grads = jax.value_and_grad(seq_loss)(tuple(fulls))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

    # Reassemble [stage, model, ...] shards into full weights per stage.
    g = np.asarray(grads["w1"])  # [2, 2, D, FF/2]
    for s in range(2):
        np.testing.assert_allclose(
            np.concatenate(list(g[s]), axis=1),
            np.asarray(ref_grads[s]["w1"]), rtol=1e-4, atol=1e-5,
        )
    g2 = np.asarray(grads["w2"])  # [2, 2, FF/2, D]
    for s in range(2):
        np.testing.assert_allclose(
            np.concatenate(list(g2[s]), axis=0),
            np.asarray(ref_grads[s]["w2"]), rtol=1e-4, atol=1e-5,
        )


def test_1f1b_switch_survives_to_hlo(comm):
    """The engine's claim that each tick runs exactly ONE op via a true
    per-stage `lax.switch` (docstring) needs compiler-level evidence, as
    with MultiNodeChainList's cond gating: the compiled module must
    retain real HLO conditionals rather than lowering to execute-all-
    branches selects."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel import pipeline as pl

    mesh = comm.mesh
    ax = comm.axis_name
    D, B, M = 16, 16, 4

    def sf(w, x):
        return jnp.tanh(x @ w)

    stacked = stack_stage_params(
        [jax.random.normal(jax.random.key(70 + i), (D, D)) * 0.2
         for i in range(comm.size)]
    )
    lg = jax.value_and_grad(lambda y, t: jnp.mean((y - t) ** 2))

    def local(sp, x, t):
        params = jax.tree.map(lambda p: p[0], sp)
        xm = x.reshape((M, B // M, D))
        tm = t.reshape((M, B // M, D))
        loss, grads = pl.pipeline_1f1b_local(sf, lg, params, xm, tm, ax)
        return loss, jax.tree.map(lambda g: g[None], grads)

    fn = jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(ax), P(), P()),
        out_specs=(P(), P(ax)), check_vma=False,
    ))
    x = jnp.ones((B, D))
    txt = fn.lower(stacked, x, x).compile().as_text()
    n_cond = sum(
        1 for ln in txt.splitlines()
        if "conditional(" in ln and "branch_computations" in ln
    )
    assert n_cond >= 1, (
        "expected the 1F1B tick's lax.switch to survive as an HLO "
        f"conditional; found {n_cond}:\n" + txt[:1500]
    )


class TestHeteroPipeline:
    """Per-stage functions (VERDICT r2 weak #5): embedding and head run
    INSIDE the pipeline — feed is int32 token ids, the conveyor carries
    activations, the bank holds logits of a different shape."""

    T, D, V = 4, 8, 16

    def _stages(self, n_stages, seed=11):
        ks = jax.random.split(jax.random.PRNGKey(seed), n_stages)

        def embed_fn(params, tok):
            return params["emb"][tok]

        def block_fn(params, h):
            return h + jnp.tanh(h @ params["w"] + params["b"])

        def head_fn(params, h):
            return h @ params["out"]

        params = [{"emb": jax.random.normal(ks[0], (self.V, self.D)) * 0.5}]
        fns = [embed_fn]
        for k in ks[1:-1]:
            params.append({
                "w": jax.random.normal(k, (self.D, self.D)) / jnp.sqrt(self.D),
                "b": jnp.zeros((self.D,)),
            })
            fns.append(block_fn)
        params.append(
            {"out": jax.random.normal(ks[-1], (self.D, self.V)) * 0.1}
        )
        fns.append(head_fn)
        return fns, tuple(params)

    def _sequential(self, fns, params, tok):
        h = tok
        for f, p in zip(fns, params):
            h = f(p, h)
        return h

    def test_matches_sequential(self, comm):
        from chainermn_tpu.parallel.pipeline import make_pipeline_hetero

        fns, params = self._stages(comm.size)
        batch = 16
        tok = jax.random.randint(
            jax.random.PRNGKey(5), (batch, self.T), 0, self.V
        )
        fn = make_pipeline_hetero(
            fns, comm.mesh, axis_name=comm.axis_name, n_microbatches=8
        )
        out = fn(params, tok)
        ref = self._sequential(fns, params, tok)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_grads_match_sequential(self, comm):
        from chainermn_tpu.parallel.pipeline import make_pipeline_hetero

        fns, params = self._stages(comm.size, seed=12)
        batch = 16
        tok = jax.random.randint(
            jax.random.PRNGKey(6), (batch, self.T), 0, self.V
        )
        y = jax.random.randint(
            jax.random.PRNGKey(7), (batch, self.T), 0, self.V
        )
        fn = make_pipeline_hetero(
            fns, comm.mesh, axis_name=comm.axis_name, n_microbatches=8,
            remat_stages=True,
        )

        def _xent(logits):
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, y[..., None], axis=-1)
            )

        g_pipe = jax.grad(lambda ps: _xent(fn(ps, tok)))(params)
        g_ref = jax.grad(
            lambda ps: _xent(self._sequential(fns, ps, tok))
        )(params)
        for gp, gr in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(gp), np.asarray(gr), rtol=1e-4, atol=1e-6
            )

    def test_conveyor_shape_break_raises(self, comm):
        from chainermn_tpu.parallel.pipeline import make_pipeline_hetero

        def widen(params, h):  # breaks activation homogeneity
            return jnp.concatenate([h, h], axis=-1)

        fns, params = self._stages(comm.size)
        fns[2] = widen
        fn = make_pipeline_hetero(fns, comm.mesh, axis_name=comm.axis_name)
        tok = jnp.zeros((16, self.T), jnp.int32)
        with pytest.raises(ValueError, match="conveyor"):
            fn(params, tok)


def test_hetero_pipeline_with_batch_axis():
    """dp x pp composition for the heterogeneous engine: 2-way data
    parallel, 4 hetero stages (embed / 2 blocks / head) — values must
    match the sequential single-device computation."""
    from jax.sharding import Mesh

    from chainermn_tpu.parallel.pipeline import make_pipeline_hetero

    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "stage"))
    T, D, V = 4, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(50), 4)

    def embed_fn(p, tok):
        return p["emb"][tok]

    def block_fn(p, h):
        return h + jnp.tanh(h @ p["w"])

    def head_fn(p, h):
        return h @ p["out"]

    fns = [embed_fn, block_fn, block_fn, head_fn]
    params = (
        {"emb": jax.random.normal(ks[0], (V, D)) * 0.5},
        {"w": jax.random.normal(ks[1], (D, D)) / jnp.sqrt(D)},
        {"w": jax.random.normal(ks[2], (D, D)) / jnp.sqrt(D)},
        {"out": jax.random.normal(ks[3], (D, V)) * 0.1},
    )
    tok = jax.random.randint(jax.random.PRNGKey(51), (16, T), 0, V)

    fn = make_pipeline_hetero(fns, mesh, axis_name="stage",
                              n_microbatches=4, batch_axis="data")
    out = fn(params, tok)

    h = params[0]["emb"][tok]
    for p in params[1:3]:
        h = h + jnp.tanh(h @ p["w"])
    ref = h @ params[3]["out"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
