"""Link tests — analogs of ``tests/link_tests/test_multi_node_chain_list.py``
(dagger) and ``test_batch_normalization.py`` (dagger) (SURVEY.md section 4):
cross-rank model graphs (chains, branches, merges, cycle rejection) equal the
single-device composition; sync-BN equals single-process BN on the
concatenated batch.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator
from chainermn_tpu.links import MultiNodeBatchNormalization, MultiNodeChainList

N = 8


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


# ---------------------------------------------------------------------------
# MultiNodeChainList
# ---------------------------------------------------------------------------


def _dense_fn(w_key):
    import zlib

    def fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    def init(rng, x):
        # crc32, NOT hash(): str hash is randomized per process
        # (PYTHONHASHSEED), which made every run draw different params —
        # any numeric flake became unreproducible by construction.
        k1, k2 = jax.random.split(
            jax.random.fold_in(rng, zlib.crc32(w_key.encode()) % 1000)
        )
        d_in = x.shape[-1]
        return {
            "w": jax.random.normal(k1, (d_in, 4)) * 0.5,
            "b": jax.random.normal(k2, (4,)) * 0.1,
        }

    return fn, init


def test_two_stage_chain_equals_sequential(comm):
    fn1, init1 = _dense_fn("a")
    fn2, init2 = _dense_fn("b")

    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(fn1, rank=0, rank_out=1, init_fn=init1)
    model.add_link(fn2, rank=1, rank_in=0, init_fn=init2)

    x = jax.random.normal(jax.random.key(0), (5, 3))
    params = model.init(jax.random.key(1), x)
    fwd = model.build()
    out = fwd(params, x)

    ref = fn2(params[1], fn1(params[0], x))
    # output lives on stage 1's shard; stacked out_spec P(None) keeps the
    # terminal value replicated-summed... we asked out_specs=P(None): each
    # shard returns its local value; only stage 1's is nonzero.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_three_stage_pipeline(comm):
    fns = [_dense_fn(k) for k in "abc"]
    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(fns[0][0], rank=0, rank_out=1, init_fn=fns[0][1])
    model.add_link(fns[1][0], rank=1, rank_in=0, rank_out=2, init_fn=fns[1][1])
    model.add_link(fns[2][0], rank=2, rank_in=1, init_fn=fns[2][1])

    x = jax.random.normal(jax.random.key(2), (4, 3))
    params = model.init(jax.random.key(3), x)
    out = model.build()(params, x)
    ref = fns[2][0](params[2], fns[1][0](params[1], fns[0][0](params[0], x)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_branch_and_merge(comm):
    """Stage 0 multicasts to 1 and 2; stage 3 merges both — the reference's
    branching/merging graphs."""
    f0, i0 = _dense_fn("root")
    f1, i1 = _dense_fn("left")
    f2, i2 = _dense_fn("right")

    def merge_fn(params, xs):
        a, b = xs
        return a + b @ params["w"]

    def merge_init(rng, xs):
        return {"w": jnp.eye(4)}

    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(f0, rank=0, rank_out=[1, 2], init_fn=i0)
    model.add_link(f1, rank=1, rank_in=0, rank_out=3, init_fn=i1)
    model.add_link(f2, rank=2, rank_in=0, rank_out=3, init_fn=i2)
    model.add_link(merge_fn, rank=3, rank_in=[1, 2], init_fn=merge_init)

    x = jax.random.normal(jax.random.key(4), (2, 3))
    params = model.init(jax.random.key(5), x)
    out = model.build()(params, x)

    h = f0(params[0], x)
    ref = merge_fn(params[3], (f1(params[1], h), f2(params[2], h)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_forward_reference_rejected(comm):
    fn, init = _dense_fn("x")
    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(fn, rank=0, rank_in=1, init_fn=init)  # from a later stage
    model.add_link(fn, rank=1, rank_in=None, rank_out=0, init_fn=init)
    x = jnp.zeros((2, 3))
    with pytest.raises(ValueError, match="no earlier component"):
        model.build()(([{"w": jnp.zeros((3, 4)), "b": jnp.zeros(4)}] * 2), x)


def test_no_terminal_component_rejected(comm):
    fn, init = _dense_fn("x")
    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(fn, rank=0, rank_out=1, init_fn=init)
    with pytest.raises(ValueError, match="terminal"):
        model.build()([{"w": jnp.zeros((3, 4)), "b": jnp.zeros(4)}], jnp.zeros((2, 3)))


def test_chain_gradients_flow_across_stages(comm):
    """Backward crosses the stage boundary (Send.backward==Recv duality)."""
    fn1, init1 = _dense_fn("g1")
    fn2, init2 = _dense_fn("g2")
    model = MultiNodeChainList(comm, axis_name="data")
    model.add_link(fn1, rank=0, rank_out=1, init_fn=init1)
    model.add_link(fn2, rank=1, rank_in=0, init_fn=init2)

    x = jax.random.normal(jax.random.key(6), (3, 3))
    params = model.init(jax.random.key(7), x)
    mesh = comm.mesh

    @jax.jit
    def loss_dist(params):
        def body(p, v):
            out = model.apply(p, v)
            # terminal output is on stage 1; sum over shards collapses zeros
            return jax.lax.psum(jnp.sum(out**2), "data")

        return shard_map(
            body, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )(params, x)

    def loss_ref(params):
        return jnp.sum(fn2(params[1], fn1(params[0], x)) ** 2)

    g_dist = jax.grad(loss_dist)(params)
    g_ref = jax.grad(loss_ref)(params)
    for gd, gr in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4)


# ---------------------------------------------------------------------------
# MultiNodeBatchNormalization
# ---------------------------------------------------------------------------


def test_sync_bn_equals_big_batch_bn(comm):
    """The reference's headline BN invariant: sync-BN over N shards ==
    single-process BN over the concatenated batch."""
    feat = 6
    per_shard = 4
    rng = np.random.RandomState(0)
    x = rng.randn(N * per_shard, feat).astype(np.float32) * 3 + 1

    sync_bn = MultiNodeBatchNormalization(
        use_running_average=False, axis_name="data", momentum=0.9
    )
    plain_bn = nn.BatchNorm(use_running_average=False, momentum=0.9)

    variables = plain_bn.init(jax.random.key(0), x)

    # distributed: each shard normalizes its slice with synced stats
    mesh = comm.mesh

    @jax.jit
    def dist(x):
        def body(xl):
            y, _ = sync_bn.apply(
                variables, xl, mutable=["batch_stats"]
            )
            return y

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        )(x)

    y_dist = np.asarray(dist(x))
    y_ref, _ = plain_bn.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(y_dist, np.asarray(y_ref), rtol=1e-4, atol=1e-5)


def test_sync_bn_running_stats_match_global(comm):
    feat = 3
    rng = np.random.RandomState(1)
    x = rng.randn(N * 2, feat).astype(np.float32) * 2 - 1

    sync_bn = MultiNodeBatchNormalization(
        use_running_average=False, axis_name="data", momentum=0.0
    )
    variables = sync_bn.init(jax.random.key(0), x[:2])
    mesh = comm.mesh

    @jax.jit
    def dist(x):
        def body(xl):
            _, upd = sync_bn.apply(variables, xl, mutable=["batch_stats"])
            return upd["batch_stats"]["mean"], upd["batch_stats"]["var"]

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=(P(None), P(None)),
            check_vma=False,
        )(x)

    mean, var = dist(x)
    np.testing.assert_allclose(np.asarray(mean), x.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), x.var(0), rtol=1e-3, atol=1e-4)


def test_for_communicator_uses_grad_axes(comm):
    bn = MultiNodeBatchNormalization.for_communicator(
        comm, use_running_average=False
    )
    assert bn.axis_name == "data"


def test_chain_list_compute_gating_is_true_conditional(comm):
    """VERDICT round-1 item 9: the cond-gated stages must survive to the
    compiled module as real HLO `conditional` ops (each shard executes only
    its branch at runtime -> the compute IS distributed), not be lowered to
    select (both branches executed everywhere)."""
    import re

    from chainermn_tpu.links.multi_node_chain_list import MultiNodeChainList

    mnc = MultiNodeChainList(comm, axis_name=comm.axis_name)

    def stage(p, x):
        return jnp.tanh(x @ p)

    mnc.add_link(stage, rank=0, rank_out=1,
                 init_fn=lambda r, x: jax.random.normal(r, (16, 32)) * 0.1)
    mnc.add_link(stage, rank=1, rank_in=0,
                 init_fn=lambda r, x: jax.random.normal(r, (32, 8)) * 0.1)
    x = jnp.ones((4, 16))
    params = mnc.init(jax.random.key(0), x)
    txt = mnc.build().lower(params, x).compile().as_text()

    conds = [ln for ln in txt.splitlines()
             if "conditional(" in ln and "branch_computations" in ln]
    assert len(conds) >= 2, (
        "expected one HLO conditional per gated stage; compiled module has "
        f"{len(conds)} — cond was lowered away:\n" + txt[:2000]
    )
    # The stage activations must not be produced by `select` over both
    # branches' results (the both-branches-execute lowering).
    assert not re.search(r"select\(f32\[4,(32|8)\]", txt), (
        "stage outputs selected from both branches — compute not distributed"
    )


# ---------------------------------------------------------------------------
# create_mnbn_model
# ---------------------------------------------------------------------------


class _PlainBnNet(nn.Module):
    """A single-node model using stock flax BatchNorm — the conversion
    target, mirroring the reference's "existing Chainer model" input."""

    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(8)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9)(x)
        x = nn.relu(x)
        x = nn.Dense(4)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9)(x)
        return x


def test_create_mnbn_model_params_are_drop_in(comm):
    """Conversion must not move parameters: same tree paths before/after
    (upstream rebuilt the link tree in place; here the scope is shared)."""
    from chainermn_tpu.links import create_mnbn_model

    x = jnp.ones((4, 6))
    plain = _PlainBnNet()
    converted = create_mnbn_model(plain, comm)
    vp = plain.init(jax.random.key(0), x)
    vc = converted.init(jax.random.key(0), x)
    assert jax.tree_util.tree_structure(vp) == jax.tree_util.tree_structure(vc)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        vp,
        vc,
    )


def test_create_mnbn_model_syncs_over_shards(comm):
    """Converted model over N shards == unconverted model on the whole
    batch: the reference's sync-BN invariant, reached via conversion."""
    from chainermn_tpu.links import create_mnbn_model

    rng = np.random.RandomState(3)
    x = rng.randn(N * 4, 6).astype(np.float32) * 2 + 0.5

    plain = _PlainBnNet()
    converted = create_mnbn_model(plain, comm)
    variables = plain.init(jax.random.key(1), x)
    mesh = comm.mesh

    @jax.jit
    def dist(x):
        def body(xl):
            y, _ = converted.apply(variables, xl, mutable=["batch_stats"])
            return y

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        )(x)

    y_dist = np.asarray(dist(x))
    y_ref, _ = plain.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(y_dist, np.asarray(y_ref), rtol=1e-4, atol=1e-5)
    # The override must not leak: the original module is untouched after use.
    y_plain_again, _ = plain.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(y_plain_again), np.asarray(y_ref), rtol=1e-6, atol=1e-7
    )


def test_create_mnbn_model_auxiliary_method(comm):
    """``apply(..., method='encode')`` works on the converted model and BN
    inside the auxiliary method is synchronized (upstream converted the
    whole link tree, so every entry point stayed synchronized)."""
    from chainermn_tpu.links import create_mnbn_model

    class Net(nn.Module):
        def setup(self):
            self.bn = nn.BatchNorm(use_running_average=False, momentum=0.9)

        def __call__(self, x):
            return self.encode(x)

        def encode(self, x):
            return self.bn(x)

    rng = np.random.RandomState(7)
    x = rng.randn(N * 4, 5).astype(np.float32) * 2 + 1

    plain = Net()
    converted = create_mnbn_model(plain, comm)
    variables = plain.init(jax.random.key(0), x)
    mesh = comm.mesh

    @jax.jit
    def dist(x):
        def body(xl):
            y, _ = converted.apply(
                variables, xl, mutable=["batch_stats"], method="encode"
            )
            return y

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        )(x)

    y_ref, _ = plain.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(dist(x)), np.asarray(y_ref), rtol=1e-4, atol=1e-5
    )


def test_create_mnbn_model_runs_outside_mesh(comm):
    """Training-mode forward of a converted model OUTSIDE shard_map (local
    debugging, single-device eval) degrades to plain-BN behavior instead of
    raising an unbound-axis NameError."""
    from chainermn_tpu.links import create_mnbn_model

    rng = np.random.RandomState(11)
    x = rng.randn(8, 6).astype(np.float32)
    plain = _PlainBnNet()
    converted = create_mnbn_model(plain, comm)
    variables = plain.init(jax.random.key(2), x)
    y_conv, _ = converted.apply(variables, x, mutable=["batch_stats"])
    y_ref, _ = plain.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(y_conv), np.asarray(y_ref), rtol=1e-5, atol=1e-6
    )


def test_create_mnbn_model_field_values_pass_through(comm):
    """Config attributes on the converted model are the FIELD VALUES, not
    delegation closures — even when the value is callable (dtype classes,
    initializer functions)."""
    from chainermn_tpu.links import create_mnbn_model
    from chainermn_tpu.models import ResNet50

    m = create_mnbn_model(ResNet50(), axis_name="data")
    assert m.compute_dtype is jnp.bfloat16
    assert m.num_classes == 1000
    inner = _PlainBnNet()
    assert create_mnbn_model(inner, comm).train is True


def test_create_mnbn_model_pickle_and_deepcopy(comm):
    """Converted models survive pickle/deepcopy (stdlib probes dunders on
    field-less instances; __getattr__ must raise AttributeError, not
    recurse)."""
    import copy
    import pickle

    from chainermn_tpu.links import create_mnbn_model

    converted = create_mnbn_model(_PlainBnNet(), axis_name="data")
    clone = pickle.loads(pickle.dumps(converted))
    clone2 = copy.deepcopy(converted)
    x = jnp.ones((4, 6))
    v = converted.init(jax.random.key(0), x)
    for c in (clone, clone2):
        vc = c.init(jax.random.key(0), x)
        assert jax.tree_util.tree_structure(v) == jax.tree_util.tree_structure(vc)


def test_create_mnbn_model_respects_explicit_axis(comm):
    """BN layers that already carry an axis_name are left untouched, and
    exactly one of comm/axis_name must be given."""
    from chainermn_tpu.links import create_mnbn_model

    with pytest.raises(ValueError):
        create_mnbn_model(_PlainBnNet())
    with pytest.raises(ValueError):
        create_mnbn_model(_PlainBnNet(), comm, axis_name="data")

    class Pre(nn.Module):
        @nn.compact
        def __call__(self, x):
            return MultiNodeBatchNormalization(
                use_running_average=False, axis_name="data"
            )(x)

    converted = create_mnbn_model(Pre(), axis_name="other")
    x = jnp.ones((4, 3))
    variables = converted.init(jax.random.key(0), x)
    mesh = comm.mesh

    # Runs under 'data' (not 'other') without error — proof the existing
    # axis_name survived conversion.
    def body(xl):
        y, _ = converted.apply(variables, xl, mutable=["batch_stats"])
        return y

    out = shard_map(
        body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False,
    )(jnp.asarray(np.random.RandomState(0).randn(8, 3), jnp.float32))
    assert out.shape == (8, 3)


def test_create_mnbn_model_full_training_equivalence(comm):
    """Multi-step TRAINING with a converted model over 8 shards equals
    single-device training of the plain model on the full batch — the
    round-trip the unit equality tests don't cover (BN stats feeding back
    into subsequent steps through the optimizer loop)."""
    import optax

    class Net(nn.Module):
        train: bool = True

        @nn.compact
        def __call__(self, x):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not self.train,
                             momentum=0.9)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    from chainermn_tpu.links import create_mnbn_model

    rng = np.random.RandomState(9)
    X = jnp.asarray(rng.randn(32, 6).astype(np.float32))
    Y = jnp.asarray((rng.rand(32) * 4).astype(np.int32))
    plain = Net()
    converted = create_mnbn_model(plain, comm)
    v0 = plain.init(jax.random.key(5), X)
    opt = optax.sgd(0.1)

    def train(model, dist):
        params, bstats = v0["params"], v0["batch_stats"]
        opt_state = opt.init(params)

        def loss_fn(p, bs, xb, yb):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bs}, xb,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()
            return loss, mut["batch_stats"]

        if dist:
            @jax.jit
            def step(p, bs, os_, x, y):
                def local(p, bs, os_, xl, yl):
                    (l, nbs), g = jax.value_and_grad(
                        loss_fn, has_aux=True)(p, bs, xl, yl)
                    g = jax.lax.pmean(g, "data")
                    l = jax.lax.pmean(l, "data")
                    # nbs deliberately NOT pmean-ed: if the conversion's
                    # sync failed, per-shard stats would diverge and the
                    # batch_stats comparison below must catch it.
                    u, os2 = opt.update(g, os_, p)
                    return optax.apply_updates(p, u), nbs, os2, l

                return shard_map(
                    local, mesh=comm.mesh,
                    in_specs=(P(), P(), P(), P("data"), P("data")),
                    out_specs=(P(), P(), P(), P()), check_vma=False,
                )(p, bs, os_, x, y)
        else:
            @jax.jit
            def step(p, bs, os_, x, y):
                (l, nbs), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(p, bs, x, y)
                u, os2 = opt.update(g, os_, p)
                return optax.apply_updates(p, u), nbs, os2, l

        for _ in range(5):
            params, bstats, opt_state, loss = step(
                params, bstats, opt_state, X, Y
            )
        return jax.device_get(params), jax.device_get(bstats), float(loss)

    p_dist, bs_dist, l_dist = train(converted, dist=True)
    p_ref, bs_ref, l_ref = train(plain, dist=False)
    np.testing.assert_allclose(l_dist, l_ref, rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        p_dist, p_ref,
    )
    # Running statistics accumulated over the 5 steps must match too —
    # the conversion's EMA must track GLOBAL batch moments.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        bs_dist, bs_ref,
    )


def test_mnbn_flax_version_guard(monkeypatch):
    """Weak-spot guard (VERDICT r2 #7): the delegation in _MnbnModel leans
    on flax internals — an untested newer flax must produce a loud warning
    at conversion time, and the validated version must stay silent."""
    import warnings

    import flax

    from chainermn_tpu.links import mnbn

    monkeypatch.setattr(flax, "__version__", "0.12.0")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mnbn._warn_if_flax_untested()
    assert not caught, "validated flax version must not warn"

    monkeypatch.setattr(flax, "__version__", "0.99.0")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mnbn._warn_if_flax_untested()
    assert any("mnbn test suite" in str(w.message) for w in caught)
