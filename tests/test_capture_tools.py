"""Regression gates for the chip-pursuit shell tooling.

The watcher/capture scripts gate 30-minute chip stages on
``tools/capture_lib.sh``'s ``fresh_artifact`` predicate; a wrong answer
either silently disables the round's capture (the ``find -exec grep``
zero-match bug caught in review 2026-08-01) or burns scarce chip-up
windows redoing finished stages. Exercised hermetically via a temp
directory shaped like the repo root.
"""

import os
import shutil
import subprocess
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def capture_root(tmp_path):
    (tmp_path / "tools" / "capture_logs").mkdir(parents=True)
    shutil.copy(
        os.path.join(_REPO, "tools", "capture_lib.sh"),
        tmp_path / "tools" / "capture_lib.sh",
    )
    return tmp_path


def _fresh(root, glob, token, marker) -> bool:
    proc = subprocess.run(
        ["bash", "-c",
         f". tools/capture_lib.sh && "
         f"fresh_artifact '{glob}' '{token}' '{marker}'"],
        cwd=root,
    )
    return proc.returncode == 0


def test_zero_matching_files_is_not_fresh(capture_root):
    """A fresh watch with NO artifacts must report nothing fresh —
    `find -exec grep -l {} +` exits 0 on zero files, which read as
    'capture complete' and would have disabled the whole round."""
    marker = capture_root / "tools" / "capture_logs" / ".watch_start"
    marker.touch()
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants",
                      "tools/capture_logs/.watch_start")


def test_fresh_requires_token_and_recency(capture_root):
    logs = capture_root / "tools" / "capture_logs"
    marker = logs / ".watch_start"
    stale = logs / "resnet_sweep_old.log"
    stale.write_text('{"n_variants": 12}\n')
    past = time.time() - 60
    os.utime(stale, (past, past))
    marker.touch()
    m = "tools/capture_logs/.watch_start"

    # older than the marker: belongs to a previous watch/round
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)

    # newer but PARTIAL (step_ms rows, no completion line): not fresh.
    # Explicit future mtime: `find -newer` is a strict comparison, and a
    # same-second write on a coarse-timestamp filesystem would read as
    # not-newer and flake.
    future = time.time() + 60
    partial = logs / "resnet_sweep_new.log"
    partial.write_text('{"step_ms": 52.1}\n')
    os.utime(partial, (future, future))
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)

    # newer with the completion token: fresh
    partial.write_text('{"step_ms": 52.1}\n{"best": {}, "n_variants": 12}\n')
    os.utime(partial, (future, future))
    assert _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)


def test_whitespace_filename_is_handled(capture_root):
    """ADVICE r5: the old `for f in $(find ...)` word-split paths; a log
    name with whitespace must neither break the predicate nor hide a
    fresh artifact."""
    logs = capture_root / "tools" / "capture_logs"
    marker = logs / ".watch_start"
    marker.touch()
    m = "tools/capture_logs/.watch_start"
    spaced = logs / "resnet_sweep_two words.log"
    spaced.write_text('{"n_variants": 12}\n')
    future = time.time() + 60
    os.utime(spaced, (future, future))
    assert _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)


def test_watch_capture_counter_persists_across_restarts():
    """ADVICE r5: the re-fire cap must bound the ROUND, not the watcher
    process — chip_watch.sh persists the attempt count beside
    .watch_start (reset only when a fresh marker starts a new round)
    and counts an attempt BEFORE launching the capture."""
    src = open(os.path.join(_REPO, "tools", "chip_watch.sh")).read()
    assert ".watch_captures" in src
    assert 'captures=$(cat "$counter"' in src
    # counter reset is tied to marker creation (fresh round)
    assert 'touch "$marker"; echo 0 > "$counter"' in src
    # the attempt is persisted before the capture launches
    before = src.index('echo "$captures" > "$counter"')
    assert before < src.index("on_chip_capture.sh")


def _golden_trace_lines():
    """A small fixed trace: meta + auto/explicit collectives + steps +
    dispatch + straggler + one torn line (crashed-writer tail)."""
    import json as _json

    evs = [
        {"schema": 1, "kind": "meta", "t": 1.0, "pid": 1, "rank": 0,
         "started_at": "2026-08-03T00:00:00Z", "sync": False,
         "source": "bench"},
        {"schema": 1, "kind": "collective", "t": 1.1, "pid": 1, "rank": 0,
         "op": "allreduce_grad", "plane": "device", "nbytes": 1000,
         "dur_s": 0.002, "wire_dtype": "bfloat16", "size": 8,
         "device": "cpu",
         "provenance": {"name": "allreduce_wire", "winner": "bf16",
                        "source": "table", "key": "cpu|8|grad"}},
        {"schema": 1, "kind": "collective", "t": 1.2, "pid": 1, "rank": 0,
         "op": "allreduce_grad", "plane": "device", "nbytes": 1000,
         "dur_s": 0.002, "wire_dtype": "bfloat16", "size": 8,
         "device": "cpu"},
        {"schema": 1, "kind": "collective", "t": 1.3, "pid": 1, "rank": 0,
         "op": "bcast_obj", "plane": "host", "nbytes": 64,
         "dur_s": 0.0005, "size": 2},
        {"schema": 1, "kind": "step", "t": 1.4, "pid": 1, "rank": 0,
         "iteration": 1,
         "phases": {"data_wait": 0.001, "compute": 0.01,
                    "logging": 0.0}},
        {"schema": 1, "kind": "step", "t": 1.5, "pid": 1, "rank": 0,
         "iteration": 2,
         "phases": {"data_wait": 0.003, "compute": 0.02,
                    "logging": 0.001}},
        {"schema": 1, "kind": "dispatch", "t": 1.6, "pid": 1, "rank": 0,
         "name": "allreduce_wire", "key": "cpu|8|grad", "winner": "bf16",
         "source": "table"},
        {"schema": 1, "kind": "straggler", "t": 1.7, "pid": 1, "rank": 0,
         "flagged_ranks": [3],
         "phases": {"compute": {"median_s": 0.01, "worst_rank": 3,
                                "worst_rel_dev": 0.8, "flagged": [3]}}},
        # ISSUE 3: overlap configuration + per-bucket wire events — one
        # trace-time layout event (no dur) and two MEASURED eager-
        # reducer events (dur = dispatch->ready, blocked = wait paid at
        # collect; the 4 ms gap on bucket 0 is comm hidden by compute).
        {"schema": 1, "kind": "overlap_config", "t": 1.8, "pid": 1,
         "rank": 0, "double_buffering": True, "staleness": 1,
         "schedule": "two_level", "donate": True},
        {"schema": 1, "kind": "wire", "t": 1.9, "pid": 1, "rank": 0,
         "schedule": "two_level", "bucket": 0, "n_buckets": 1,
         "nbytes": 1000, "wire_dtype": "bfloat16", "overlapped": True},
        {"schema": 1, "kind": "wire", "t": 2.0, "pid": 1, "rank": 0,
         "schedule": "overlap_eager", "bucket": 0, "n_buckets": 2,
         "nbytes": 4096, "dur_s": 0.005, "blocked_s": 0.001,
         "overlapped": True},
        {"schema": 1, "kind": "wire", "t": 2.1, "pid": 1, "rank": 0,
         "schedule": "overlap_eager", "bucket": 1, "n_buckets": 2,
         "nbytes": 4096, "dur_s": 0.003, "blocked_s": 0.003,
         "overlapped": False},
        # ISSUE 12: one composed-schedule bucket — per-STAGE wire
        # events carrying the composition signature (rs -> ar -> ag:
        # the scatter and gather carry the full bucket, the shard
        # allreduce 1/4 of it), grouped by signature in the overlap
        # section's per-stage table. The rs/ag stages additionally
        # carry MEASURED dur_s (ISSUE 13: the eager
        # MeasuredComposedReducer pattern) — the stage rows then gain a
        # dur_ms column; the ar stage stays layout-only (no dur), so
        # the table renders mixed measured/unmeasured rows.
        {"schema": 1, "kind": "wire", "t": 2.12, "pid": 1, "rank": 0,
         "schedule": "two_level", "composition": "rs(a1)>ar(a0)>ag(a1)",
         "stage": "rs(a1)", "stage_index": 0, "stage_op": "reduce-scatter",
         "bucket": 0, "n_buckets": 1, "nbytes": 2048,
         "wire_dtype": "bfloat16", "overlapped": False,
         "dur_s": 0.0015},
        {"schema": 1, "kind": "wire", "t": 2.13, "pid": 1, "rank": 0,
         "schedule": "two_level", "composition": "rs(a1)>ar(a0)>ag(a1)",
         "stage": "ar(a0)", "stage_index": 1, "stage_op": "all-reduce",
         "bucket": 0, "n_buckets": 1, "nbytes": 512,
         "wire_dtype": "bfloat16", "overlapped": False},
        {"schema": 1, "kind": "wire", "t": 2.14, "pid": 1, "rank": 0,
         "schedule": "two_level", "composition": "rs(a1)>ar(a0)>ag(a1)",
         "stage": "ag(a1)", "stage_index": 2, "stage_op": "all-gather",
         "bucket": 0, "n_buckets": 1, "nbytes": 2048,
         "wire_dtype": "bfloat16", "overlapped": False,
         "dur_s": 0.0005},
        # ISSUE 15: a SLICED composition (S=2) — one event per stage
        # per slice in the skewed interleave order, each carrying its
        # slice address. The rs/ag slice rows are MEASURED (dur_s +
        # blocked_s, the eager sliced reducer), the ar rows layout-only
        # — so the per-signature stage table renders mixed
        # sliced/unsliced, measured/unmeasured rows side by side.
        {"schema": 1, "kind": "wire", "t": 2.15, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "rs(a1)", "stage_index": 0, "stage_op": "reduce-scatter",
         "bucket": 0, "n_buckets": 1, "nbytes": 1024, "slice": 0,
         "n_slices": 2, "overlapped": True,
         "dur_s": 0.001, "blocked_s": 0.0002},
        {"schema": 1, "kind": "wire", "t": 2.16, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "rs(a1)", "stage_index": 1, "stage_op": "reduce-scatter",
         "bucket": 0, "n_buckets": 1, "nbytes": 1024, "slice": 1,
         "n_slices": 2, "overlapped": False,
         "dur_s": 0.0008, "blocked_s": 0.0001},
        {"schema": 1, "kind": "wire", "t": 2.17, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "ar(a0)", "stage_index": 2, "stage_op": "all-reduce",
         "bucket": 0, "n_buckets": 1, "nbytes": 256, "slice": 0,
         "n_slices": 2, "overlapped": False},
        {"schema": 1, "kind": "wire", "t": 2.18, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "ar(a0)", "stage_index": 3, "stage_op": "all-reduce",
         "bucket": 0, "n_buckets": 1, "nbytes": 256, "slice": 1,
         "n_slices": 2, "overlapped": False},
        {"schema": 1, "kind": "wire", "t": 2.19, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "ag(a1)", "stage_index": 4, "stage_op": "all-gather",
         "bucket": 0, "n_buckets": 1, "nbytes": 1024, "slice": 0,
         "n_slices": 2, "overlapped": False,
         "dur_s": 0.0004, "blocked_s": 0.0004},
        {"schema": 1, "kind": "wire", "t": 2.195, "pid": 1, "rank": 0,
         "schedule": "composed_eager",
         "composition": "rs(a1)[s0..1]>ar(a0)>ag(a1)",
         "stage": "ag(a1)", "stage_index": 5, "stage_op": "all-gather",
         "bucket": 0, "n_buckets": 1, "nbytes": 1024, "slice": 1,
         "n_slices": 2, "overlapped": True,
         "dur_s": 0.0006, "blocked_s": 0.0},
        # ISSUE 4: one request through the serving scheduler — queue
        # wait, bucketed prefill (its sampled token counts as generated;
        # ttft_s = submit -> first token, ISSUE 5), three decode steps
        # at varying occupancy, finish.
        {"schema": 1, "kind": "serving", "t": 2.2, "pid": 1, "rank": 0,
         "phase": "queue_wait", "request": "r0", "dur_s": 0.002},
        {"schema": 1, "kind": "serving", "t": 2.3, "pid": 1, "rank": 0,
         "phase": "prefill", "request": "r0", "slot": 0, "prompt_len": 5,
         "dur_s": 0.01, "ttft_s": 0.012},
        {"schema": 1, "kind": "serving", "t": 2.4, "pid": 1, "rank": 0,
         "phase": "decode_step", "n_active": 1, "n_slots": 4, "tokens": 1,
         "dur_s": 0.004},
        {"schema": 1, "kind": "serving", "t": 2.5, "pid": 1, "rank": 0,
         "phase": "decode_step", "n_active": 2, "n_slots": 4, "tokens": 2,
         "dur_s": 0.006},
        {"schema": 1, "kind": "serving", "t": 2.6, "pid": 1, "rank": 0,
         "phase": "decode_step", "n_active": 1, "n_slots": 4, "tokens": 1,
         "dur_s": 0.002},
        {"schema": 1, "kind": "serving", "t": 2.7, "pid": 1, "rank": 0,
         "phase": "finish", "request": "r0", "generated": 4,
         "dur_s": 0.03},
        # ISSUE 5: two speculative ticks — per-tick drafted/accepted
        # counts and per-slot accept lengths (8 drafted, 2 accepted ->
        # 25% acceptance; histogram counts PER-SLOT accept lengths).
        {"schema": 1, "kind": "speculate", "t": 2.8, "pid": 1, "rank": 0,
         "drafted": 4, "accepted": 2, "accept_lens": [2], "dur_s": 0.004},
        {"schema": 1, "kind": "speculate", "t": 2.9, "pid": 1, "rank": 0,
         "drafted": 4, "accepted": 0, "accept_lens": [0, 0],
         "dur_s": 0.006},
        # ISSUE 7: two prefix-cache admissions — a miss that prefilled
        # the whole 5-token prompt, then a full-prefix hit that adopted
        # 2 blocks (16 tokens), prefilled only the 1-token tail and
        # copied the boundary block (COW).
        {"schema": 1, "kind": "prefix_cache", "t": 3.0, "pid": 1,
         "rank": 0, "request": "r0", "slot": 0, "prompt_tokens": 5,
         "hit_blocks": 0, "hit_tokens": 0, "prefill_tokens": 5,
         "cow_blocks": 0},
        {"schema": 1, "kind": "prefix_cache", "t": 3.1, "pid": 1,
         "rank": 0, "request": "r1", "slot": 1, "prompt_tokens": 16,
         "hit_blocks": 2, "hit_tokens": 16, "prefill_tokens": 1,
         "cow_blocks": 1},
        # ISSUE 11: chunked prefill + SLO scheduling — one preemption,
        # two mixed-step chunk rows (12 prompt tokens written through
        # the mixed step), and a target-bearing finish whose TPOT
        # verdict failed (explicit tpot_ms preferred over the derived
        # fallback; r0's finish above derives 6.0 ms from dur - ttft).
        {"schema": 1, "kind": "serving", "t": 3.2, "pid": 1, "rank": 0,
         "phase": "preempt", "request": "r1", "generated": 2,
         "dur_s": 0.02},
        {"schema": 1, "kind": "prefill_chunk", "t": 3.3, "pid": 1,
         "rank": 0, "request": "r2", "slot": 2, "chunk": 0,
         "tokens": 8, "dur_s": 0.004},
        {"schema": 1, "kind": "prefill_chunk", "t": 3.35, "pid": 1,
         "rank": 0, "request": "r2", "slot": 2, "chunk": 1,
         "tokens": 4, "dur_s": 0.004},
        # ISSUE 14: r2 carries a tenant tag — the per-tenant rollup
        # buckets it under 'acme' while the pre-tenant r0 events fall
        # back to the 'default' tenant (old traces keep parsing).
        {"schema": 1, "kind": "serving", "t": 3.4, "pid": 1, "rank": 0,
         "phase": "finish", "request": "r2", "generated": 5,
         "dur_s": 0.05, "tpot_ms": 8.0, "slo_ttft_ok": True,
         "slo_tpot_ok": False, "tenant": "acme"},
        # ISSUE 20: two MoE dispatch observations (layers 0/1) — the
        # per-expert load histograms sum across events in the 'moe'
        # section ([10, 6] -> 62.5%/37.5% load fractions), with the
        # dropped/padded token flow and the static capacity beside.
        {"schema": 1, "kind": "moe_dispatch", "t": 3.5, "pid": 1,
         "rank": 0, "layer": 0, "expert_load": [6.0, 2.0],
         "n_experts": 2, "dropped": 1.0, "padded": 0.0,
         "capacity": 4.0},
        {"schema": 1, "kind": "moe_dispatch", "t": 3.6, "pid": 1,
         "rank": 0, "layer": 1, "expert_load": [4.0, 4.0],
         "n_experts": 2, "dropped": 0.0, "padded": 0.0,
         "capacity": 4.0},
    ]
    return [_json.dumps(e) for e in evs] + ['{"torn']


def test_trace_report_contract(tmp_path):
    """Golden JSONL in -> stable summary out (ISSUE 2 satellite): the
    machine-readable contract downstream consumers (capture logs,
    future dashboards) parse. Full-dict equality so a field rename or
    rounding change is a DELIBERATE contract bump, not drift."""
    import json as _json
    import sys

    trace_file = tmp_path / "trace.jsonl"
    trace_file.write_text("\n".join(_golden_trace_lines()) + "\n")
    chrome_file = tmp_path / "chrome.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(trace_file), "--json", "--chrome", str(chrome_file)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    summary = _json.loads(proc.stdout)
    assert summary == {
        "schema_versions": [1],
        "meta": {"started_at": "2026-08-03T00:00:00Z", "sync": False,
                 "source": "bench"},
        "n_events": 37,  # torn tail line skipped, not fatal
        "collectives": [
            {"op": "allreduce_grad", "plane": "device", "n": 2,
             "total_bytes": 2000, "total_s": 0.004, "mean_ms": 2.0,
             "wire_dtypes": ["bfloat16"], "auto_events": 1,
             "gbps": 0.0005},  # 2000 B / 4 ms
            {"op": "bcast_obj", "plane": "host", "n": 1,
             "total_bytes": 64, "total_s": 0.0005, "mean_ms": 0.5,
             "wire_dtypes": [], "auto_events": 0, "gbps": 0.000128},
        ],
        "steps": {"n": 2, "phases": {
            "compute": {"mean_ms": 15.0, "max_ms": 20.0, "n": 2},
            "data_wait": {"mean_ms": 2.0, "max_ms": 3.0, "n": 2},
            "logging": {"mean_ms": 0.5, "max_ms": 1.0, "n": 2},
        }},
        "dispatch": [{"name": "allreduce_wire", "key": "cpu|8|grad",
                      "winner": "bf16", "source": "table"}],
        "packs": [],
        "stragglers": [{"flagged_ranks": [3], "phases": {
            "compute": {"median_s": 0.01, "worst_rank": 3,
                        "worst_rel_dev": 0.8, "flagged": [3]}}}],
        # ISSUE 3: per-step comm vs comm-overlapped-with-compute, from
        # the per-bucket wire events. 8 ms of measured bucket comm, 4 ms
        # of it waited on -> half the wire rode behind compute.
        "overlap": {
            "config": [{"double_buffering": True, "staleness": 1,
                        "schedule": "two_level", "donate": True}],
            "schedules": {"two_level": {"buckets": 1, "nbytes": 1000,
                                        "overlapped": 1}},
            # ISSUE 12: the composed bucket's per-stage table, grouped
            # by composition signature (2048 + 512 + 2048 wire bytes
            # over the three stages of one bucket).
            # ISSUE 13: stage rows carry dur_ms where measured events
            # (dur_s — the eager MeasuredComposedReducer) exist; a
            # layout-only stage row simply has no dur_ms key.
            "compositions": {
                "rs(a1)>ar(a0)>ag(a1)": {
                    "schedule": "two_level", "buckets": 1,
                    "nbytes": 4608, "overlapped": 0,
                    "stages": {
                        "rs(a1)": {"op": "reduce-scatter", "n": 1,
                                   "nbytes": 2048, "dur_ms": 1.5},
                        "ar(a0)": {"op": "all-reduce", "n": 1,
                                   "nbytes": 512},
                        "ag(a1)": {"op": "all-gather", "n": 1,
                                   "nbytes": 2048, "dur_ms": 0.5},
                    },
                },
                # ISSUE 15: the sliced composition's stage rows carry
                # across-slice totals plus the per-slice sub-table
                # (dur_ms/blocked_ms only where the slice was
                # measured — the ar rows are layout-only).
                "rs(a1)[s0..1]>ar(a0)>ag(a1)": {
                    "schedule": "composed_eager", "buckets": 1,
                    "nbytes": 4608, "overlapped": 1,
                    "stages": {
                        "rs(a1)": {
                            "op": "reduce-scatter", "n": 2,
                            "nbytes": 2048, "dur_ms": 1.8,
                            "blocked_ms": 0.3,
                            "slices": {
                                "s0": {"n": 1, "nbytes": 1024,
                                       "dur_ms": 1.0,
                                       "blocked_ms": 0.2},
                                "s1": {"n": 1, "nbytes": 1024,
                                       "dur_ms": 0.8,
                                       "blocked_ms": 0.1},
                            },
                        },
                        "ar(a0)": {
                            "op": "all-reduce", "n": 2, "nbytes": 512,
                            "slices": {
                                "s0": {"n": 1, "nbytes": 256},
                                "s1": {"n": 1, "nbytes": 256},
                            },
                        },
                        "ag(a1)": {
                            "op": "all-gather", "n": 2, "nbytes": 2048,
                            "dur_ms": 1.0, "blocked_ms": 0.4,
                            "slices": {
                                "s0": {"n": 1, "nbytes": 1024,
                                       "dur_ms": 0.4,
                                       "blocked_ms": 0.4},
                                "s1": {"n": 1, "nbytes": 1024,
                                       "dur_ms": 0.6,
                                       "blocked_ms": 0.0},
                            },
                        },
                    },
                },
            },
            "measured": {"n": 2, "comm_ms_total": 8.0,
                         "comm_ms_blocked": 4.0, "comm_ms_hidden": 4.0,
                         "hidden_fraction": 0.5},
        },
        # ISSUE 4/5: the serving rollup — tokens/s over device-busy time
        # (1 prefill token + 4 step tokens over 10 + 12 ms), nearest-rank
        # p50/p99 over the three step durations, TTFT from the prefill's
        # ttft_s, mean occupancy (0.25 + 0.5 + 0.25)/3, and the
        # speculation totals from the two speculate events.
        "serving": {
            "requests": 2,
            "prefills": 1,
            "generated_tokens": 5,
            "decode_steps": 3,
            "queue_wait_ms_mean": 2.0,
            "prefill_ms_mean": 10.0,
            "token_ms_p50": 4.0,
            "token_ms_p99": 6.0,
            "ttft_ms_p50": 12.0,
            "ttft_ms_p99": 12.0,
            # ISSUE 11: per-request TPOT — r0 derives (30 - 12) ms / 3
            # intervals = 6.0; r2 carries an explicit tpot_ms = 8.0.
            "tpot_ms_p50": 6.0,
            "tpot_ms_p99": 8.0,
            "occupancy_mean": 0.3333,
            "tokens_per_sec": 227.27,
            # ISSUE 11: one target-bearing finish, TPOT verdict failed;
            # one preemption; 12 prompt tokens over 2 mixed-step chunks.
            "slo_requests": 1,
            "slo_attainment": 0.0,
            "preemptions": 1,
            "chunked_prefill": {"chunks": 2, "chunk_tokens": 12},
            "speculation": {
                "ticks": 2,
                "drafted": 8,
                "accepted": 2,
                "accept_rate": 0.25,
                "accept_len_hist": {"0": 2, "2": 1},
            },
            # ISSUE 7: the prefix-sharing rollup — 1 of 2 admissions
            # hit; 6 of 21 prompt tokens were actually prefilled (16
            # rode the cache), one boundary-block COW copy.
            "prefix_cache": {
                "lookups": 2,
                "hits": 1,
                "hit_rate": 0.5,
                "prompt_tokens": 21,
                "hit_tokens": 16,
                "prefilled_tokens": 6,
                "hit_token_rate": 0.7619,
                "cow_blocks": 1,
            },
            # ISSUE 14: the per-tenant rollup — r2's tenant-tagged
            # finish lands under 'acme', the pre-tenant r0 events fall
            # back to 'default'; Jain over the [5, 4] token totals =
            # 81/82.
            "tenants": {
                "acme": {"requests": 1, "generated_tokens": 5,
                         "ttft_ms_p50": None, "ttft_ms_p99": None,
                         "tpot_ms_p50": 8.0, "tpot_ms_p99": 8.0,
                         "slo_requests": 1, "slo_attainment": 0.0},
                "default": {"requests": 1, "generated_tokens": 4,
                            "ttft_ms_p50": 12.0, "ttft_ms_p99": 12.0,
                            "tpot_ms_p50": 6.0, "tpot_ms_p99": 6.0},
            },
            "tenant_fairness_jain": 0.9878,
        },
        # ISSUE 20: the MoE dispatch rollup — summed expert-load
        # histogram with load fractions (the router-collapse signal),
        # total dropped/padded token flow, capacity, layers seen.
        "moe": {
            "n_events": 2,
            "dropped_tokens": 1.0,
            "padded_slots": 0.0,
            "capacity": 4.0,
            "expert_load": [10.0, 6.0],
            "load_fractions": [0.625, 0.375],
            "layers": [0, 1],
        },
    }, summary
    # chrome export emitted alongside
    chrome = _json.loads(chrome_file.read_text())
    assert len(chrome["traceEvents"]) == 36  # meta excluded
    # and the human rendering mentions the essentials
    proc2 = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(trace_file)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc2.returncode == 0
    for token in ("allreduce_grad", "STRAGGLER", "allreduce_wire=bf16",
                  "comm/compute overlap", "50.0% hidden",
                  "composed rs(a1)>ar(a0)>ag(a1) [two_level]: "
                  "1 bucket(s), 4.5 KiB wire",
                  "rs(a1) [reduce-scatter]: n=1, 2.0 KiB, 1.500 ms",
                  "ar(a0) [all-reduce]: n=1, 512 B",
                  "ag(a1) [all-gather]: n=1, 2.0 KiB, 0.500 ms",
                  # ISSUE 15: the sliced composition's per-slice rows
                  "composed rs(a1)[s0..1]>ar(a0)>ag(a1) "
                  "[composed_eager]: 1 bucket(s), 4.5 KiB wire",
                  "rs(a1) [reduce-scatter]: n=2, 2.0 KiB, 1.800 ms",
                  "s0: n=1, 1.0 KiB, 1.000 ms (0.200 ms blocked)",
                  "s1: n=1, 1.0 KiB, 0.800 ms (0.100 ms blocked)",
                  "ar(a0) [all-reduce]: n=2, 512 B",
                  "s0: n=1, 256 B",
                  "ag(a1) [all-gather]: n=2, 2.0 KiB, 1.000 ms",
                  "s1: n=1, 1.0 KiB, 0.600 ms (0.000 ms blocked)",
                  "serving (continuous batching)", "tokens/s: 227.27",
                  "p50 4.000 ms, p99 6.000 ms", "33.3% mean",
                  "TTFT: p50 12.000 ms, p99 12.000 ms",
                  "TPOT: p50 6.000 ms, p99 8.000 ms per request",
                  "SLO attainment: 0.0% of 1 target-bearing request(s)",
                  "preemptions: 1",
                  "chunked prefill: 12 prompt token(s) over 2 "
                  "mixed-step chunk(s)",
                  "speculation: 8 drafted, 2 accepted (25.0% acceptance)",
                  "accept-length histogram: 0:2 2:1",
                  "prefix cache: 1/2 admissions hit (50.0%), "
                  "6/21 prompt tokens prefilled (16 served from cache), "
                  "1 COW block copy",
                  "tenants: 2 (Jain fairness 0.9878)",
                  "acme: 1 req, 5 tok, TPOT p50/p99 8.000/8.000 ms, "
                  "SLO 0.0% of 1",
                  "default: 1 req, 4 tok, TTFT p50/p99 12.000/12.000 "
                  "ms, TPOT p50/p99 6.000/6.000 ms",
                  # ISSUE 20: the MoE rollup rendering
                  "moe dispatch: 2 events, capacity 4, dropped 1 "
                  "tokens, padded 0 slots",
                  "layers: [0, 1]",
                  "expert load: e0=62.5% e1=37.5%"):
        assert token in proc2.stdout, (token, proc2.stdout)


def _golden_journey_lines():
    """A two-rank disaggregated journey as two per-rank JSONL files:
    rank 0 routes (hop 0), rank 1 syncs its clock, adopts the KV
    payload and decodes (hops 1-4). Durations are exact binary
    fractions so the pinned decomposition has ZERO float drift."""
    import json as _json

    jid = "r0@b.0"
    rank0 = [
        {"schema": 1, "kind": "meta", "t": 1.0, "pid": 11, "rank": 0,
         "started_at": "2026-08-07T00:00:00Z", "sync": False,
         "source": "cluster"},
        {"schema": 1, "kind": "route", "t": 10.0, "t_mono": 100.0,
         "pid": 11, "rank": 0, "request": "r0", "replica": 1,
         "journey": jid, "span": f"{jid}/0"},
    ]
    rank1 = [
        {"schema": 1, "kind": "clock_sync", "t": 9.5, "t_mono": 200.0,
         "pid": 22, "rank": 1, "peer": 0, "offset_s": -0.5,
         "uncertainty_s": 0.001, "min_rtt_s": 0.002, "n": 8},
        {"schema": 1, "kind": "kv_transfer", "t": 10.5, "t_mono": 200.5,
         "pid": 22, "rank": 1, "request": "r0", "dur_s": 0.25,
         "journey": jid, "span": f"{jid}/1", "parent": f"{jid}/0"},
        {"schema": 1, "kind": "serving", "phase": "queue_wait",
         "t": 10.75, "t_mono": 200.75, "pid": 22, "rank": 1,
         "request": "r0", "dur_s": 0.25, "journey": jid,
         "span": f"{jid}/2", "parent": f"{jid}/1"},
        {"schema": 1, "kind": "serving", "phase": "prefill", "t": 11.0,
         "t_mono": 201.0, "pid": 22, "rank": 1, "request": "r0",
         "slot": 0, "bucket": None, "prompt_len": 4, "dur_s": 0.5,
         "ttft_s": 0.75, "journey": jid, "span": f"{jid}/3",
         "parent": f"{jid}/2"},
        {"schema": 1, "kind": "serving", "phase": "finish", "t": 11.25,
         "t_mono": 201.25, "pid": 22, "rank": 1, "request": "r0",
         "generated": 3, "dur_s": 1.0, "journey": jid,
         "span": f"{jid}/4", "parent": f"{jid}/3"},
    ]
    return ([_json.dumps(e) for e in rank0],
            [_json.dumps(e) for e in rank1])


def test_journey_report_contract(tmp_path):
    """ISSUE 17 golden: multi-file JSONL in -> stable ``--journeys``
    section out (full-dict equality — the causal-merge contract), flow
    events in the Chrome export for the cross-rank hop, and the human
    rendering's essentials."""
    import json as _json
    import sys

    jid = "r0@b.0"
    lines0, lines1 = _golden_journey_lines()
    f0, f1 = tmp_path / "rank0.jsonl", tmp_path / "rank1.jsonl"
    f0.write_text("\n".join(lines0) + "\n")
    f1.write_text("\n".join(lines1) + "\n")
    chrome_file = tmp_path / "chrome.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(f0), str(f1), "--json", "--journeys",
         "--chrome", str(chrome_file)],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    summary = _json.loads(proc.stdout)
    assert summary["n_events"] == 7  # both files concatenated
    assert summary["journeys"] == {
        "n_journeys": 1,
        "n_complete": 1,
        "n_orphan_spans": 0,
        # rank 1 is 500 ms BEHIND rank 0's epoch, known to ±1 ms
        "clock": {
            "offsets": {"1": {"offset_s": -0.5, "uncertainty_s": 0.001,
                              "peer": 0}},
            "max_uncertainty_s": 0.001,
        },
        "slowest": [{
            "journey": jid,
            "request": "r0",
            "n_spans": 5,
            "ranks": [0, 1],
            "pids": [11, 22],
            "complete": True,
            "contiguous": True,
            "orphan_spans": [],
            # 0.25 queue + 0.25 net prefill (0.5 raw minus the 0.25
            # handoff it contains) + 0.25 handoff = the 0.75 TTFT
            "decomposition": {
                "ttft_s": 0.75,
                "queue_wait_s": 0.25,
                "prefill_s": 0.25,
                "handoff_s": 0.25,
                "preempt_gap_s": 0.0,
                "residual_s": 0.0,
                "preempts_before_first_token": 0,
                "total_s": 1.0,
                "decode_s": 0.25,
            },
            # hop order (clock-free); t_adj = t + the traced offset
            "spans": [
                {"hop": 0, "span": f"{jid}/0", "parent": None,
                 "kind": "route", "phase": None, "rank": 0, "pid": 11,
                 "t": 10.0, "t_adj": 10.0, "t_mono": 100.0,
                 "dur_s": None},
                {"hop": 1, "span": f"{jid}/1", "parent": f"{jid}/0",
                 "kind": "kv_transfer", "phase": None, "rank": 1,
                 "pid": 22, "t": 10.5, "t_adj": 10.0, "t_mono": 200.5,
                 "dur_s": 0.25},
                {"hop": 2, "span": f"{jid}/2", "parent": f"{jid}/1",
                 "kind": "serving", "phase": "queue_wait", "rank": 1,
                 "pid": 22, "t": 10.75, "t_adj": 10.25,
                 "t_mono": 200.75, "dur_s": 0.25},
                {"hop": 3, "span": f"{jid}/3", "parent": f"{jid}/2",
                 "kind": "serving", "phase": "prefill", "rank": 1,
                 "pid": 22, "t": 11.0, "t_adj": 10.5, "t_mono": 201.0,
                 "dur_s": 0.5},
                {"hop": 4, "span": f"{jid}/4", "parent": f"{jid}/3",
                 "kind": "serving", "phase": "finish", "rank": 1,
                 "pid": 22, "t": 11.25, "t_adj": 10.75,
                 "t_mono": 201.25, "dur_s": 1.0},
            ],
        }],
    }, summary["journeys"]
    # Chrome export: 6 non-meta base events + ONE s/f flow pair for the
    # single cross-rank hop (route on rank 0 -> kv_transfer on rank 1);
    # the rank-1-internal hops draw no arrows.
    chrome = _json.loads(chrome_file.read_text())
    flows = [e for e in chrome["traceEvents"] if e["ph"] in ("s", "f")]
    assert len(chrome["traceEvents"]) == 8
    assert [e["ph"] for e in flows] == ["s", "f"]
    assert flows[0]["id"] == flows[1]["id"]
    assert flows[0]["pid"] == 0 and flows[1]["pid"] == 1
    assert flows[1]["bp"] == "e"
    assert flows[0]["name"] == jid and flows[0]["cat"] == "journey"
    # t_mono stays a clock, not an arg, on every slice
    assert all("t_mono" not in e.get("args", {})
               for e in chrome["traceEvents"])
    # human rendering: the decomposition line and the clock error bar
    proc2 = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(f0), str(f1), "--journeys"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc2.returncode == 0
    for token in ("journeys: 1 merged, 1 complete, 0 orphan span(s)",
                  "clock: rank 1 offset -500.000 ms to rank 0 "
                  "(± 1.000 ms)",
                  "TTFT 750.000 ms = queue 250.000 + prefill 250.000 "
                  "+ handoff 250.000  (residual +0.0000 ms)",
                  "total 1000.000 ms (decode 250.000 ms)",
                  "hop 1  rank 1 kv_transfer    t_adj 10.0  "
                  "dur 250.000 ms"):
        assert token in proc2.stdout, (token, proc2.stdout)


def test_trace_report_roofline_scoped_to_device_plane(tmp_path):
    """Roofline floors apply only to device-plane ops, against the
    device kinds they actually ran on — a host-plane pickle transfer
    has no HBM roofline, and a mixed cpu+TPU trace (bench's accel child
    + cpu fallback in one file) must not cross-product (code-review
    finding)."""
    import json as _json
    import sys

    evs = [
        {"schema": 1, "kind": "collective", "t": 1.0, "pid": 1,
         "rank": 0, "op": "allreduce", "plane": "device",
         "nbytes": 1 << 30, "dur_s": 0.01, "size": 8,
         "device": "TPU v5 lite"},
        {"schema": 1, "kind": "collective", "t": 1.1, "pid": 1,
         "rank": 0, "op": "bcast", "plane": "device",
         "nbytes": 1 << 20, "dur_s": 0.001, "size": 8, "device": "cpu"},
        {"schema": 1, "kind": "collective", "t": 1.2, "pid": 1,
         "rank": 0, "op": "bcast_obj", "plane": "host", "nbytes": 4096,
         "dur_s": 0.001, "size": 2},
    ]
    trace_file = tmp_path / "trace.jsonl"
    trace_file.write_text("\n".join(_json.dumps(e) for e in evs) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(trace_file), "--json"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    summary = _json.loads(proc.stdout)
    floors = summary.get("roofline", [])
    # only the TPU-device op gets a floor, only under ITS device kind
    assert [(f["op"], f["device"]) for f in floors] == [
        ("allreduce", "TPU v5 lite")
    ], floors
    assert floors[0]["hbm_peak_gbps"] == 819.0  # v5e table via bench
    # no internal bookkeeping leaks into the contract
    assert all("_devices" not in c for c in summary["collectives"])


def _metrics_dump_mod():
    import importlib.util

    path = os.path.join(_REPO, "tools", "metrics_dump.py")
    spec = importlib.util.spec_from_file_location("_md_capture", path)
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)
    return md


_TENANT_PROM = """\
# HELP serving_tenant_tokens_total generated tokens per tenant
# TYPE serving_tenant_tokens_total counter
serving_tenant_tokens_total{tenant="acme"} 5
serving_tenant_tokens_total{tenant="globex"} 3
# HELP serving_queue_depth requests waiting
# TYPE serving_queue_depth gauge
serving_queue_depth 2
"""


def test_metrics_dump_label_filters_offline_table(tmp_path, capsys):
    """ISSUE 14 satellite: ``--label tenant=<id>`` narrows the parsed
    table to one tenant's series — offline (saved scrape) path."""
    prom = tmp_path / "t.prom"
    prom.write_text(_TENANT_PROM)
    md = _metrics_dump_mod()
    assert md.main([str(prom), "--label", "tenant=acme"]) == 0
    out = capsys.readouterr().out
    assert "tenant=acme" in out and "5" in out
    assert "globex" not in out
    assert "serving_queue_depth" not in out  # unlabeled series dropped


def test_metrics_dump_label_no_match_is_loud(tmp_path, capsys):
    """A typoed tenant id must exit 1 with a stderr note, never an
    empty table that reads as 'tenant idle'."""
    prom = tmp_path / "t.prom"
    prom.write_text(_TENANT_PROM)
    md = _metrics_dump_mod()
    assert md.main([str(prom), "--label", "tenant=nope"]) == 1
    err = capsys.readouterr().err
    assert "no series carry" in err and "nope" in err


def test_metrics_dump_label_validation_and_down_endpoint(capsys):
    """Bad --label syntax and --raw/--health combinations are refused;
    a down endpoint under --label keeps the fetch path's exit-1
    contract (the label filter never masks unreachability)."""
    md = _metrics_dump_mod()
    assert md.main(["--label", "tenant", "--port", "1"]) == 1
    assert "key=value" in capsys.readouterr().err
    assert md.main(["--label", "tenant=a", "--raw", "--port", "1"]) == 1
    assert "--raw" in capsys.readouterr().err
    # unreachable endpoint (port 1 is never listening): exit 1 with the
    # unreachable note, not the no-match note
    assert md.main(["--label", "tenant=a", "--port", "1",
                    "--timeout", "0.2"]) == 1
    err = capsys.readouterr().err
    assert "unreachable" in err


def test_missing_marker_is_never_fresh(capture_root):
    logs = capture_root / "tools" / "capture_logs"
    (logs / "bench_2.log").write_text('{"source": "live"}\n')
    assert not _fresh(capture_root, "bench_2*.log", '"source": "live"', "")
    assert not _fresh(capture_root, "bench_2*.log", '"source": "live"',
                      "tools/capture_logs/.no_such_marker")
