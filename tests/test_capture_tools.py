"""Regression gates for the chip-pursuit shell tooling.

The watcher/capture scripts gate 30-minute chip stages on
``tools/capture_lib.sh``'s ``fresh_artifact`` predicate; a wrong answer
either silently disables the round's capture (the ``find -exec grep``
zero-match bug caught in review 2026-08-01) or burns scarce chip-up
windows redoing finished stages. Exercised hermetically via a temp
directory shaped like the repo root.
"""

import os
import shutil
import subprocess
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def capture_root(tmp_path):
    (tmp_path / "tools" / "capture_logs").mkdir(parents=True)
    shutil.copy(
        os.path.join(_REPO, "tools", "capture_lib.sh"),
        tmp_path / "tools" / "capture_lib.sh",
    )
    return tmp_path


def _fresh(root, glob, token, marker) -> bool:
    proc = subprocess.run(
        ["bash", "-c",
         f". tools/capture_lib.sh && "
         f"fresh_artifact '{glob}' '{token}' '{marker}'"],
        cwd=root,
    )
    return proc.returncode == 0


def test_zero_matching_files_is_not_fresh(capture_root):
    """A fresh watch with NO artifacts must report nothing fresh —
    `find -exec grep -l {} +` exits 0 on zero files, which read as
    'capture complete' and would have disabled the whole round."""
    marker = capture_root / "tools" / "capture_logs" / ".watch_start"
    marker.touch()
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants",
                      "tools/capture_logs/.watch_start")


def test_fresh_requires_token_and_recency(capture_root):
    logs = capture_root / "tools" / "capture_logs"
    marker = logs / ".watch_start"
    stale = logs / "resnet_sweep_old.log"
    stale.write_text('{"n_variants": 12}\n')
    past = time.time() - 60
    os.utime(stale, (past, past))
    marker.touch()
    m = "tools/capture_logs/.watch_start"

    # older than the marker: belongs to a previous watch/round
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)

    # newer but PARTIAL (step_ms rows, no completion line): not fresh.
    # Explicit future mtime: `find -newer` is a strict comparison, and a
    # same-second write on a coarse-timestamp filesystem would read as
    # not-newer and flake.
    future = time.time() + 60
    partial = logs / "resnet_sweep_new.log"
    partial.write_text('{"step_ms": 52.1}\n')
    os.utime(partial, (future, future))
    assert not _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)

    # newer with the completion token: fresh
    partial.write_text('{"step_ms": 52.1}\n{"best": {}, "n_variants": 12}\n')
    os.utime(partial, (future, future))
    assert _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)


def test_whitespace_filename_is_handled(capture_root):
    """ADVICE r5: the old `for f in $(find ...)` word-split paths; a log
    name with whitespace must neither break the predicate nor hide a
    fresh artifact."""
    logs = capture_root / "tools" / "capture_logs"
    marker = logs / ".watch_start"
    marker.touch()
    m = "tools/capture_logs/.watch_start"
    spaced = logs / "resnet_sweep_two words.log"
    spaced.write_text('{"n_variants": 12}\n')
    future = time.time() + 60
    os.utime(spaced, (future, future))
    assert _fresh(capture_root, "resnet_sweep_*.log", "n_variants", m)


def test_watch_capture_counter_persists_across_restarts():
    """ADVICE r5: the re-fire cap must bound the ROUND, not the watcher
    process — chip_watch.sh persists the attempt count beside
    .watch_start (reset only when a fresh marker starts a new round)
    and counts an attempt BEFORE launching the capture."""
    src = open(os.path.join(_REPO, "tools", "chip_watch.sh")).read()
    assert ".watch_captures" in src
    assert 'captures=$(cat "$counter"' in src
    # counter reset is tied to marker creation (fresh round)
    assert 'touch "$marker"; echo 0 > "$counter"' in src
    # the attempt is persisted before the capture launches
    before = src.index('echo "$captures" > "$counter"')
    assert before < src.index("on_chip_capture.sh")


def test_missing_marker_is_never_fresh(capture_root):
    logs = capture_root / "tools" / "capture_logs"
    (logs / "bench_2.log").write_text('{"source": "live"}\n')
    assert not _fresh(capture_root, "bench_2*.log", '"source": "live"', "")
    assert not _fresh(capture_root, "bench_2*.log", '"source": "live"',
                      "tools/capture_logs/.no_such_marker")
