"""The public testing helpers must themselves work — they are the
user-facing form of this suite's harness (SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import chainermn_tpu.testing as cmt


def test_ensure_virtual_devices_is_idempotent_when_satisfied():
    # conftest already forced 8 CPU devices; asking for <= that is a no-op
    cmt.ensure_virtual_devices(8)
    cmt.ensure_virtual_devices(4)
    assert len(jax.devices("cpu")) >= 8


def test_ensure_virtual_devices_rejects_late_increase():
    with pytest.raises(RuntimeError, match="before the first jax backend"):
        cmt.ensure_virtual_devices(64)


def test_assert_allclose_tree_reports_path():
    good = {"a": jnp.ones(3), "b": (jnp.zeros(2), jnp.ones(1))}
    cmt.assert_allclose_tree(good, good)
    bad = {"a": jnp.ones(3), "b": (jnp.zeros(2) + 0.5, jnp.ones(1))}
    with pytest.raises(AssertionError, match=r"\['b'\]"):
        cmt.assert_allclose_tree(bad, good)


def test_distributed_equals_single_helper():
    comm = cmt.make_test_communicator()
    x = cmt.seeded_batch((32, 4), seed=3)

    def single(batch):
        return (jnp.asarray(batch) ** 2).mean(axis=0)

    def distributed(comm, batch):
        def local(xl):
            return jax.lax.pmean((xl**2).mean(axis=0), comm.axis_name)

        return shard_map(
            local, mesh=comm.mesh, in_specs=P(comm.axis_name),
            out_specs=P(), check_vma=False,
        )(jnp.asarray(batch))

    cmt.assert_distributed_equals_single(distributed, single, comm, x)

    def broken(comm, batch):
        return distributed(comm, batch) * 1.5

    with pytest.raises(AssertionError):
        cmt.assert_distributed_equals_single(broken, single, comm, x)
