"""Observability utilities and the global except hook — the aux-subsystem
coverage SURVEY.md section 5 calls for (rank-0 gating, divergence checks,
profiling wrappers, whole-job abort)."""

import contextlib
import io
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from chainermn_tpu import create_communicator, global_except_hook
from chainermn_tpu.utils.observability import (
    annotate,
    assert_same_on_all_hosts,
    log0,
    profile,
    rank_zero_only,
)


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def test_log0_gates_on_rank(comm, capsys):
    log0(comm, "hello", 42)
    assert capsys.readouterr().out == "hello 42\n"
    log0(None, "also prints")
    assert "also prints" in capsys.readouterr().out

    class Fake:
        rank = 3

    log0(Fake(), "must not print")
    assert capsys.readouterr().out == ""


def test_rank_zero_only_decorator(comm):
    calls = []

    @rank_zero_only(comm)
    def record(x):
        calls.append(x)
        return x * 2

    assert record(3) == 6  # naive comm is rank 0
    assert calls == [3]

    class Fake:
        rank = 1

    @rank_zero_only(Fake())
    def never(x):
        raise AssertionError("ran on nonzero rank")

    assert never(1) is None


def test_assert_same_on_all_hosts_single_process_noop(comm):
    # single-process: must be a no-op for scalars AND generic objects
    assert_same_on_all_hosts(3, "step")
    assert_same_on_all_hosts({"spec": (8, 224, 224, 3)}, "batch-shape")


def test_annotate_and_profile(tmp_path):
    with annotate("test-span"):
        x = jnp.ones((4,)) * 2
    with profile(str(tmp_path / "trace")):
        y = (x @ x).block_until_ready()
    assert float(y) == 16.0
    # the profiler must have written its trace layout
    written = []
    for root, _, files in os.walk(tmp_path):
        written += files
    assert written, "profile() wrote no trace files"


def test_profile_records_into_event_stream(tmp_path):
    """ISSUE 2 satellite: profile() start/stop land in the structured
    trace so a JSONL shows where the xprof window sat in the timeline."""
    from chainermn_tpu.observability import trace as obs_trace

    rec = obs_trace.enable(None)
    try:
        with profile(str(tmp_path / "trace")):
            jnp.ones((2,)).block_until_ready()
        kinds = [e["kind"] for e in rec.events]
        assert "profile_start" in kinds and "profile_stop" in kinds
        stop = next(e for e in rec.events if e["kind"] == "profile_stop")
        assert stop["dur_s"] >= 0
    finally:
        obs_trace.disable()


def test_profile_stop_failure_does_not_mask_block_exception(monkeypatch):
    """ISSUE 2 satellite: the old bare ``finally: stop_trace()`` masked
    the block's own exception when stop_trace ALSO failed (the usual
    case — a dead backend kills both). The block's error must win."""
    calls = []

    def failing_stop():
        calls.append("stop")
        raise RuntimeError("profiler teardown broke")

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", failing_stop)
    with pytest.raises(ValueError, match="the real failure"):
        with profile("/tmp/nowhere"):
            raise ValueError("the real failure")
    assert calls == ["stop"]  # stop WAS attempted, its failure swallowed


def test_profile_stop_failure_propagates_when_block_succeeds(monkeypatch):
    """No block exception in flight -> a stop_trace failure is the
    caller's signal that the trace was NOT written; it must propagate."""
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def failing_stop():
        raise RuntimeError("no trace written")

    monkeypatch.setattr(jax.profiler, "stop_trace", failing_stop)
    with pytest.raises(RuntimeError, match="no trace written"):
        with profile("/tmp/nowhere"):
            pass


def test_global_except_hook_formats_and_preserves_process(capsys):
    """Single-process: the hook prints the rank-tagged traceback and does
    NOT hard-exit (teardown is only for multi-process worlds)."""
    global_except_hook._add_hook()
    global_except_hook._add_hook()  # idempotent
    assert sys.excepthook is global_except_hook._global_except_hook

    try:
        raise ValueError("boom for the hook")
    except ValueError:
        exctype, value, tb = sys.exc_info()
    sys.excepthook(exctype, value, tb)
    err = capsys.readouterr().err
    assert "uncaught exception on process 0" in err
    assert "boom for the hook" in err


def test_global_except_hook_never_masks_original(capsys, monkeypatch):
    """A failure inside the hook itself falls back to the default
    excepthook — the original traceback must still reach stderr."""
    import traceback as tb_mod

    def explode(*a, **k):
        raise RuntimeError("hook internals broke")

    monkeypatch.setattr(tb_mod, "print_exception", explode)
    try:
        raise KeyError("the real error")
    except KeyError:
        exctype, value, tb = sys.exc_info()
    # must not raise; must delegate to sys.__excepthook__
    global_except_hook._global_except_hook(exctype, value, tb)
    err = capsys.readouterr().err
    assert "the real error" in err
