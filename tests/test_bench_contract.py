"""Driver-contract pins for bench.py: the FINAL stdout line must stay a
single compact JSON object that fits (with margin) inside the driver's
2000-char tail-capture window, whatever rows/notes/carried blobs the run
accumulated (the round-1 artifacts went red precisely because a fat line
got truncated into unparseable JSON)."""

import contextlib
import io
import json

import bench


def test_compact_line_fits_tail_window(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_DETAILS_PATH",
                        str(tmp_path / "details.json"))
    # Worst-case: every compact key present, fat note/error strings, a
    # carried blob with many older-run rows.
    result = {k: 123456.789 for k in bench._COMPACT_KEYS}
    result.update(
        metric="resnet50_images_per_sec",
        unit="images/sec",
        device_kind="TPU v5 lite",
        bench_note="x" * 500,
        error="y" * 500,
        last_good_tpu={
            "value": 2459.12, "mfu": 0.2998, "age_hours": 123.5,
            "stale": True, "measured_at": "2026-07-31T03:31:43Z",
            "carried_keys": {
                "keys": [f"k{i}" for i in range(30)],
                "stamps": {"k0": "2026-07-30T01:00:00Z"},
            },
        },
        # Fat non-compact rows must NOT leak into the line at all.
        allreduce_curve=[{"mib": 512, "busbw_gbps": 1.0}] * 8,
        kernel_sweep=[{"kernel": "causal_fwd", "ok": True}] * 8,
    )
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_final(result)
    line = buf.getvalue().strip().splitlines()[-1]
    assert len(line) < 1900, len(line)
    parsed = json.loads(line)  # a single well-formed object
    assert parsed["metric"] == "resnet50_images_per_sec"
    assert "allreduce_curve" not in parsed
    assert "kernel_sweep" not in parsed
    assert parsed["details"] == "BENCH_DETAILS.json"
    # the full details file holds everything
    full = json.load(open(tmp_path / "details.json"))
    assert "allreduce_curve" in full and "kernel_sweep" in full


def test_purge_retired_methodology_rows():
    """Rows measured under a repudiated method must not be carried
    forward under their (unchanged) names: the long-context attention
    rows moved to the chained-scan harness in r5 (the single-dispatch
    values measured kernel + tunnel dispatch latency), keyed off the
    ``flash_32k_method`` marker — same pattern as the native-input
    rows' ``native_input_method``."""
    old = {
        "flash_32k_fwd_ms": 104.9,
        "flash_32k_window2k_fwd_ms": 72.4,
        "xla_32k_fwd_ms": 1.0,
        "xla_32k_error": "OOM (34.4 GB)",  # method-independent: kept
        "mfu": 0.299,
        "transformer_hw_util": 0.02,  # always-retired key
    }
    bench._purge_retired(old)
    for k in bench._OLD_METHOD_32K_KEYS:
        assert k not in old, k
    assert "transformer_hw_util" not in old
    assert old["xla_32k_error"].startswith("OOM")
    assert old["mfu"] == 0.299

    # marker present -> new-method rows survive the merge untouched
    new = {"flash_32k_fwd_ms": 40.0, "flash_32k_method": "chained-scan"}
    bench._purge_retired(new)
    assert new["flash_32k_fwd_ms"] == 40.0


def test_per_row_provenance_fresh_vs_carried(tmp_path, monkeypatch):
    """Round-5 VERDICT ask #7: every carried-blob row names its own
    measured_at + source (live / carried), and the compact line reports
    fresh_rows/carried_rows so a stale overlay can't read as a fresh
    capture."""
    cache = tmp_path / "last_tpu.json"
    monkeypatch.setattr(bench, "_LAST_TPU_CACHE", str(cache))
    monkeypatch.setattr(bench, "_DETAILS_PATH",
                        str(tmp_path / "details.json"))

    # run 1: a full capture
    bench._save_last_tpu({"device_kind": "TPU v5 lite", "value": 2452.0,
                          "mfu": 0.299, "transformer_mfu": 0.35})
    blob1 = json.load(open(cache))
    assert all(p["source"] == "live"
               for p in blob1["row_provenance"].values())

    # run 2: a partial capture — value re-measured, mfu rows carried
    bench._save_last_tpu({"device_kind": "TPU v5 lite", "value": 2500.0})
    blob2 = json.load(open(cache))
    prov = blob2["row_provenance"]
    assert prov["value"]["source"] == "live"
    assert prov["value"]["measured_at"] == blob2["measured_at"]
    assert prov["mfu"]["source"] == "carried"
    assert prov["mfu"]["measured_at"] == blob1["measured_at"]

    # the compact line rolls the counts up
    result = {"metric": "resnet50_images_per_sec", "value": 1.0,
              "source": "cpu-fallback"}
    bench._attach_last_tpu(result)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_final(result)
    compact = json.loads(buf.getvalue().strip().splitlines()[-1])
    lg = compact["last_good_tpu"]
    assert lg["fresh_rows"] == 2  # value + device_kind re-measured
    assert lg["carried_rows"] == 2  # mfu + transformer_mfu inherited


def test_row_provenance_respects_pre_provenance_carried_stamps(
    tmp_path, monkeypatch
):
    """A pre-provenance blob may ALREADY carry rows from an older run
    (carried_keys.stamps); the new per-row provenance must inherit that
    per-row stamp, not the blob-level measured_at (which would overstate
    freshness — the exact dishonesty the feature prevents)."""
    cache = tmp_path / "last_tpu.json"
    monkeypatch.setattr(bench, "_LAST_TPU_CACHE", str(cache))
    cache.write_text(json.dumps({
        "device_kind": "TPU v5 lite", "value": 2452.0, "mfu": 0.299,
        "measured_at": "2026-07-20T00:00:00Z",
        "carried_keys": {"keys": ["mfu"],
                         "stamps": {"mfu": "2026-07-01T00:00:00Z"}},
    }))
    bench._save_last_tpu({"device_kind": "TPU v5 lite", "value": 2500.0})
    prov = json.load(open(cache))["row_provenance"]
    assert prov["mfu"]["measured_at"] == "2026-07-01T00:00:00Z"
    assert prov["mfu"]["source"] == "carried"


def test_degenerate_tail_skips_accel_child_not_the_reserve(monkeypatch,
                                                           tmp_path):
    """ADVICE r5: when the remaining budget cannot honour the
    CPU-fallback reserve, the accel child is SKIPPED (previously it was
    granted a 60 s floor carved out of the reserve)."""
    calls = []
    monkeypatch.setattr(bench, "_DETAILS_PATH",
                        str(tmp_path / "details.json"))
    monkeypatch.setattr(bench, "_LAST_TPU_CACHE",
                        str(tmp_path / "none.json"))
    # main() truncates the trace artifact — keep that out of the repo
    monkeypatch.setattr(bench, "_TRACE_PATH", str(tmp_path / "t.jsonl"))
    monkeypatch.setattr(bench, "TOTAL_BUDGET",
                        bench.CPU_BENCH_RESERVE + 50)
    monkeypatch.setattr(
        bench, "_probe_with_retries",
        lambda deadline, errors: {"platform": "tpu", "kind": "x", "n": 1},
    )
    monkeypatch.setattr(bench, "_probe_accelerator", lambda t: None)
    monkeypatch.setattr(bench, "_cpu_env", lambda n_devices=8: None)
    monkeypatch.setattr(bench, "_attach_probe_trail", lambda r: None)

    def fake_child(mode, timeout, env=None):
        calls.append(mode)
        return {"metric": "m", "value": 1.0}, None

    monkeypatch.setattr(bench, "_run_child", fake_child)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    assert calls == ["cpu"], calls  # no accel child on the eaten tail
    compact = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert "reserve" in compact.get("error", "")


def test_kernel_sweep_crashed_checker_counts_as_numeric_error():
    """ADVICE r5: a row whose numerics checker RAISED must not read as
    0 numeric failures."""
    rows = [
        {"kernel": "a", "ok": True, "numerics_ok": True},
        {"kernel": "b", "ok": True, "numerics_ok": False},
        {"kernel": "c", "ok": True,
         "numerics_error": "ValueError: boom"},
        {"kernel": "d", "ok": False, "error": "Mosaic"},
    ]
    counts = bench._kernel_sweep_counts(rows)
    assert counts["kernel_sweep_failures"] == 1
    assert counts["kernel_sweep_numeric_failures"] == 1
    assert counts["kernel_sweep_numeric_errors"] == 1
    assert "kernel_sweep_numeric_errors" in bench._COMPACT_KEYS


def test_serving_rows_contract_and_seeding(tmp_path):
    """ISSUE 4 satellite: the ``serving`` phase's headline rows ride the
    compact line (tokens/s + spread gate), and ``tuning seed`` learns
    ``decode_impl``/``kv_block_size`` from the detail rows — spread-gated
    exactly like the in-run adoption, so a noise-band "winner" is never
    resurrected offline."""
    assert "serving_tokens_per_sec" in bench._COMPACT_KEYS
    assert "serving_spread_pct" in bench._COMPACT_KEYS

    from chainermn_tpu.tuning.cache import seed_from_bench_details

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-03T00:00:00Z",
        "serving_model_shape": "D512xH8xL512",
        "serving_decode_impl_ms": {"dense": 4.0, "paged": 2.0},
        "serving_decode_spread_pct": 5.0,
        # 2.9 vs 2.95 inside an 8% spread: indistinguishable from noise
        "serving_kv_block_ms": {"16": 3.0, "32": 2.9, "64": 2.95},
        "serving_kv_block_spread_pct": 8.0,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    # the engine's own key material (serving_decision_key) reproduced
    assert "decode_impl|TPU v5 lite|512x8x512|decode -> paged" in seeded
    assert "kv_block_size" not in seeded  # spread-dominated: refused

    # a decisive sweep seeds the block size too
    doc["serving_kv_block_ms"] = {"16": 4.0, "64": 2.0}
    doc["serving_kv_block_spread_pct"] = 5.0
    details.write_text(json.dumps(doc))
    seeded2 = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "kv_block_size|TPU v5 lite|512x8x512|decode -> 64" in seeded2

    # ABSENT spread key = on-accel single-sample row: the 10% noise
    # floor applies (the live adoption's spreads=None convention) — a
    # 5% margin is refused, a decisive one still seeds.
    doc.pop("serving_decode_spread_pct")
    doc["serving_decode_impl_ms"] = {"dense": 4.0, "paged": 3.9}
    details.write_text(json.dumps(doc))
    assert "decode_impl" not in "\n".join(
        seed_from_bench_details(str(details), str(cache)))
    # ...while a PRESENT 0.0 spread is a real three-tied-medians
    # estimate and adopts verbatim, matching the in-run path.
    doc["serving_decode_spread_pct"] = 0.0
    details.write_text(json.dumps(doc))
    assert "decode_impl|TPU v5 lite|512x8x512|decode -> paged" in "\n".join(
        seed_from_bench_details(str(details), str(cache)))


def test_spec_tokens_rows_contract_and_seeding(tmp_path):
    """ISSUE 5 satellite: the speculative rows ride the compact line
    (selected K, spec-vs-plain speedup, acceptance rate) and ``tuning
    seed`` learns ``spec_tokens`` from ``serving_spec_ms`` (ms per
    GENERATED token: acceptance is priced in) under the same spread
    gate and key material as the other serving decisions — with the
    per-K acceptance rates carried as auditable evidence."""
    for k in ("serving_spec_selected", "serving_spec_speedup",
              "serving_spec_accept_rate"):
        assert k in bench._COMPACT_KEYS, k

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-03T00:00:00Z",
        "serving_model_shape": "D512xH8xL512",
        "serving_spec_ms": {"0": 2.0, "2": 1.4, "4": 1.0, "8": 1.1},
        "serving_spec_spread_pct": 6.0,
        "serving_spec_accept_rates": {"2": 0.8, "4": 0.7, "8": 0.4},
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "spec_tokens|TPU v5 lite|512x8x512|decode -> 4" in seeded
    entry = load_cache(str(cache))["decisions"][
        "spec_tokens|TPU v5 lite|512x8x512|decode"]
    assert entry["accept_rates"] == {"2": 0.8, "4": 0.7, "8": 0.4}
    assert entry["candidates_ms"]["4"] == 1.0

    # spread-dominated spec rows are refused (noise-band "winner")
    doc["serving_spec_ms"] = {"0": 1.0, "2": 0.98, "4": 0.99, "8": 1.01}
    doc["serving_spec_spread_pct"] = 12.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "spec_tokens" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_spec_spread_pct")
    doc["serving_spec_ms"] = {"0": 1.0, "4": 0.95}
    details.write_text(json.dumps(doc))
    assert "spec_tokens" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["serving_spec_ms"] = {"0": 2.0, "4": 0.9}
    details.write_text(json.dumps(doc))
    assert "spec_tokens|TPU v5 lite|512x8x512|decode -> 4" in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))


def test_serving_prefix_rows_contract_and_seeding(tmp_path):
    """ISSUE 7 satellite: the ``serving_prefix`` phase's headline rows
    ride the compact line (TTFT speedup + hit rate + spread gate), and
    ``tuning seed`` learns ``prefix_cache``/``min_shared_blocks`` from
    the TTFT rows under the same spread gate and key material as the
    other serving decisions — with the measured hit rate carried as
    auditable evidence for WHY 'on' won."""
    for k in ("serving_prefix_ttft_speedup", "serving_prefix_hit_rate",
              "serving_prefix_spread_pct"):
        assert k in bench._COMPACT_KEYS, k

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-03T00:00:00Z",
        "serving_model_shape": "D512xH8xL512",
        "serving_prefix_ttft_ms": {"off": 20.0, "on": 6.0},
        "serving_prefix_spread_pct": 8.0,
        "serving_prefix_hit_rate": 0.89,
        "serving_prefix_msb_ttft_ms": {"1": 6.0, "2": 6.8, "4": 9.0},
        "serving_prefix_msb_spread_pct": 7.0,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "prefix_cache|TPU v5 lite|512x8x512|decode -> on" in seeded
    assert "min_shared_blocks|TPU v5 lite|512x8x512|decode -> 1" in seeded
    entry = load_cache(str(cache))["decisions"][
        "prefix_cache|TPU v5 lite|512x8x512|decode"]
    assert entry["hit_rate"] == 0.89
    assert entry["candidates_ms"]["on"] == 6.0

    # spread-dominated rows are refused (noise-band "winner")
    doc["serving_prefix_ttft_ms"] = {"off": 6.1, "on": 6.0}
    doc["serving_prefix_spread_pct"] = 12.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "prefix_cache" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_prefix_spread_pct")
    details.write_text(json.dumps(doc))
    assert "prefix_cache" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["serving_prefix_ttft_ms"] = {"off": 20.0, "on": 6.0}
    details.write_text(json.dumps(doc))
    assert "prefix_cache|TPU v5 lite|512x8x512|decode -> on" in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))


def test_serving_burst_rows_contract_and_seeding(tmp_path):
    """ISSUE 11 satellite: the ``serving_burst`` phase's headline rows
    ride the compact line (per-arm goodput-under-SLO + p99 TTFT +
    spread gate + the adopted decision), and ``tuning seed`` learns
    ``prefill_chunk`` from the ms-per-SLO-good-token rows — spread-
    gated under the phase's OWN shape key, with the measured goodput
    and p99 TTFT carried as evidence."""
    for k in ("serving_burst_goodput", "serving_burst_ttft_p99_ms",
              "serving_burst_spread_pct", "serving_burst_selected"):
        assert k in bench._COMPACT_KEYS, k

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-03T00:00:00Z",
        "serving_burst_model_shape": "D512xH8xL512",
        "serving_burst_chunk_ms": {"0": 2.4, "64": 1.2},
        "serving_burst_spread_pct": 6.0,
        "serving_burst_goodput": {"monolithic": 410.0, "chunked": 830.0,
                                  "chunked_slo": 870.0},
        "serving_burst_ttft_p99_ms": {"monolithic": 90.0,
                                      "chunked": 22.0,
                                      "chunked_slo": 18.0},
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "prefill_chunk|TPU v5 lite|512x8x512|decode -> 64" in seeded
    entry = load_cache(str(cache))["decisions"][
        "prefill_chunk|TPU v5 lite|512x8x512|decode"]
    assert entry["candidates_ms"]["64"] == 1.2
    assert entry["goodput"]["chunked"] == 830.0
    assert entry["ttft_p99_ms"]["monolithic"] == 90.0

    # spread-dominated rows are refused (noise-band "winner") — the
    # table default 0 stands, the honest-refusal precedent
    doc["serving_burst_chunk_ms"] = {"0": 1.25, "64": 1.2}
    doc["serving_burst_spread_pct"] = 15.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "prefill_chunk" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_burst_spread_pct")
    details.write_text(json.dumps(doc))
    assert "prefill_chunk" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))


def test_seq_parallel_rows_contract_and_seeding(tmp_path):
    """ISSUE 13 satellite: the ``seq_parallel`` phase's headline rows
    ride the compact line (selected prefill mode + off/on TTFT + spread
    gate), the phase is wired into the supplementary chain, and
    ``tuning seed`` learns BOTH new decisions — ``seq_attn_impl`` from
    the ring-vs-ulysses step medians (keyed shards x heads x local-T,
    the plan resolver's own key) and ``prefill_seq_parallel`` from the
    long-prompt TTFT rows (the serving decision key) — spread-gated
    exactly like the in-run adoption, with the per-shard TTFT curve
    carried as evidence."""
    for k in ("seq_parallel_selected", "seq_parallel_ttft_ms",
              "seq_parallel_spread_pct"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_seq_parallel)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("seq_parallel", "seq_parallel_error"' in src

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-04T00:00:00Z",
        "seq_parallel_attn_shape": "S4xH8xT512",
        "seq_parallel_attn_ms": {"ring": 2.0, "ulysses": 3.1},
        "seq_parallel_attn_spread_pct": 5.0,
        "seq_parallel_model_shape": "D512xH8xL2048",
        "seq_parallel_ttft_ms": {"off": 40.0, "on": 14.0},
        "seq_parallel_spread_pct": 6.0,
        "seq_parallel_ttft_shards_ms": {"1": 40.0, "2": 22.0, "4": 14.0},
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "seq_attn_impl|TPU v5 lite|4x8x512|seqattn -> ring" in seeded
    assert ("prefill_seq_parallel|TPU v5 lite|512x8x2048|decode -> on"
            in seeded)
    entry = load_cache(str(cache))["decisions"][
        "prefill_seq_parallel|TPU v5 lite|512x8x2048|decode"]
    assert entry["ttft_shards_ms"] == {"1": 40.0, "2": 22.0, "4": 14.0}
    assert entry["candidates_ms"]["on"] == 14.0

    # spread-dominated rows are refused (noise-band "winner") — the
    # table defaults (ring / off) stand, the honest-refusal precedent
    doc["seq_parallel_ttft_ms"] = {"off": 14.2, "on": 14.0}
    doc["seq_parallel_spread_pct"] = 12.0
    doc["seq_parallel_attn_ms"] = {"ring": 2.0, "ulysses": 2.05}
    doc["seq_parallel_attn_spread_pct"] = 11.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    seeded2 = "\n".join(seed_from_bench_details(str(details),
                                                str(cache2)))
    assert "prefill_seq_parallel" not in seeded2
    assert "seq_attn_impl" not in seeded2

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("seq_parallel_spread_pct")
    doc.pop("seq_parallel_attn_spread_pct")
    doc["seq_parallel_ttft_ms"] = {"off": 15.0, "on": 14.0}
    details.write_text(json.dumps(doc))
    assert "prefill_seq_parallel" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["seq_parallel_ttft_ms"] = {"off": 40.0, "on": 14.0}
    details.write_text(json.dumps(doc))
    assert ("prefill_seq_parallel|TPU v5 lite|512x8x2048|decode -> on"
            in "\n".join(seed_from_bench_details(str(details),
                                                 str(cache2))))


def test_serving_tenants_rows_contract_and_seeding(tmp_path):
    """ISSUE 14 satellite: the ``serving_tenants`` phase's headline
    rows ride the compact line (goodput + Jain fairness + spread gate
    + the adopted ``adapter_impl``), the phase is wired into the
    supplementary chain, and ``tuning seed`` learns ``adapter_impl``
    from the gather/merged ms-per-token rows — spread-gated under the
    phase's OWN shape key, with the measured goodput and fairness
    carried as evidence."""
    for k in ("serving_tenants_goodput", "serving_tenants_fairness",
              "serving_tenants_spread_pct", "serving_tenants_selected"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_serving_tenants)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("serving_tenants", "serving_tenants_error"' in src

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-04T00:00:00Z",
        "serving_tenants_model_shape": "D512xH8xL512",
        "serving_tenants_adapter_ms": {"gather": 0.9, "merged": 0.5},
        "serving_tenants_adapter_spread_pct": 5.0,
        "serving_tenants_spread_pct": 40.0,
        "serving_tenants_goodput": 4100.0,
        "serving_tenants_fairness": 0.98,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "adapter_impl|TPU v5 lite|512x8x512|decode -> merged" in seeded
    entry = load_cache(str(cache))["decisions"][
        "adapter_impl|TPU v5 lite|512x8x512|decode"]
    assert entry["candidates_ms"]["merged"] == 0.5
    assert entry["goodput"] == 4100.0
    assert entry["fairness"] == 0.98

    # spread-dominated rows are refused (noise-band "winner") — the
    # table default gather stands, the honest-refusal precedent
    doc["serving_tenants_adapter_ms"] = {"gather": 0.52, "merged": 0.5}
    doc["serving_tenants_adapter_spread_pct"] = 15.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "adapter_impl" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_tenants_adapter_spread_pct")
    details.write_text(json.dumps(doc))
    assert "adapter_impl" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["serving_tenants_adapter_ms"] = {"gather": 0.9, "merged": 0.5}
    details.write_text(json.dumps(doc))
    assert ("adapter_impl|TPU v5 lite|512x8x512|decode -> merged"
            in "\n".join(seed_from_bench_details(str(details),
                                                 str(cache2))))


def test_transformer_knob_env_validation(monkeypatch):
    """The accel transformer knobs reject malformed env values with a
    message naming the variable (a bare ZeroDivisionError from
    CHAINERMN_BENCH_TF_HEADS=0 once leaked through review)."""
    import pytest

    class _Comm:  # knob validation happens before any communicator use
        size = 1

    cases = {
        "CHAINERMN_BENCH_TF_HEADS": ["0", "-8", "7"],
        "CHAINERMN_BENCH_TF_DB": ["yes", "1"],
        "CHAINERMN_BENCH_TF_REMAT": ["conv", "all"],
    }
    for var, bads in cases.items():
        for bad in bads:
            monkeypatch.setenv(var, bad)
            with pytest.raises(ValueError, match=var.rsplit("_", 1)[-1]):
                bench._transformer_setup(_Comm(), on_accel=True)
            monkeypatch.delenv(var)


def test_serving_cluster_rows_contract_and_seeding(tmp_path):
    """ISSUE 8 satellite: the ``serving_cluster`` phase's headline rows
    ride the compact line (goodput at the top replica count, the
    replica-scaling ratio, the disagg-vs-colocated TTFT speedup,
    spread gate), and ``tuning seed`` learns ``cluster_disagg`` from
    the TTFT rows — spread-gated under the phase's OWN shape key, with
    the measured transfer accounting carried as evidence."""
    for k in ("serving_cluster_goodput_tokens_per_sec",
              "serving_cluster_scaling", "serving_cluster_disagg_speedup",
              "serving_cluster_spread_pct"):
        assert k in bench._COMPACT_KEYS, k

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-03T00:00:00Z",
        "serving_cluster_model_shape": "D512xH8xL512",
        "serving_cluster_disagg_ttft_ms": {"colocated": 20.0,
                                           "disaggregated": 8.0},
        "serving_cluster_disagg_spread_pct": 6.0,
        "serving_cluster_transfers": 24,
        "serving_cluster_transfer_bytes": 98304,
        "serving_cluster_scaling": 3.1,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert ("cluster_disagg|TPU v5 lite|512x8x512|decode -> "
            "disaggregated") in seeded
    entry = load_cache(str(cache))["decisions"][
        "cluster_disagg|TPU v5 lite|512x8x512|decode"]
    assert entry["transfer_bytes"] == 98304
    assert entry["scaling"] == 3.1
    assert entry["candidates_ms"]["disaggregated"] == 8.0

    # spread-dominated rows are refused (noise-band "winner")
    doc["serving_cluster_disagg_ttft_ms"] = {"colocated": 8.1,
                                             "disaggregated": 8.0}
    doc["serving_cluster_disagg_spread_pct"] = 12.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "cluster_disagg" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_cluster_disagg_spread_pct")
    details.write_text(json.dumps(doc))
    assert "cluster_disagg" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["serving_cluster_disagg_ttft_ms"] = {"colocated": 20.0,
                                             "disaggregated": 8.0}
    details.write_text(json.dumps(doc))
    assert ("cluster_disagg|TPU v5 lite|512x8x512|decode -> "
            "disaggregated") in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))


def test_compact_overflow_sheds_newest_keys_with_marker(tmp_path,
                                                        monkeypatch):
    """The tail-window guard: a saturated line sheds NEWEST-declared
    compact keys first, marks how many went, and never touches the
    identity/provenance core — the driver sees valid JSON, the details
    file keeps everything."""
    monkeypatch.setattr(bench, "_DETAILS_PATH",
                        str(tmp_path / "details.json"))
    result = {k: 123456.789 for k in bench._COMPACT_KEYS}
    result.update(metric="resnet50_images_per_sec", unit="images/sec",
                  device_kind="TPU v5 lite", bench_note="x" * 500,
                  error="y" * 500)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_final(result)
    parsed = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert parsed.get("compact_keys_shed", 0) >= 1
    # newest-declared keys go first; the core survives
    assert "serving_cluster_spread_pct" not in parsed
    for k in ("metric", "value", "unit", "device_kind", "details"):
        assert k in parsed, k
    # an unsaturated line sheds nothing and carries no marker
    small = {"metric": "m", "value": 1.0,
             "serving_cluster_spread_pct": 2.0}
    buf2 = io.StringIO()
    with contextlib.redirect_stdout(buf2):
        bench._emit_final(small)
    parsed2 = json.loads(buf2.getvalue().strip().splitlines()[-1])
    assert "compact_keys_shed" not in parsed2
    assert parsed2["serving_cluster_spread_pct"] == 2.0


def test_composed_rows_contract_and_seeding(tmp_path, monkeypatch):
    """ISSUE 12 satellite: the ``composed`` phase's headline rows ride
    the compact line (best-vs-two_level ratio + spread gate + selected
    pipeline), the phase is wired into the supplementary chain, and
    ``tuning seed`` learns the 3-level ``reduction_schedule`` decision
    from the signature-keyed ``composed_schedule_ms`` rows — spread-
    gated exactly like the in-run adoption, under its own world-shape
    key so the flat-mesh ``overlap`` entry is untouched."""
    for k in ("composed_best_vs_two_level", "composed_spread_pct",
              "composed_selected"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_composed)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("composed", "composed_error"' in src

    from chainermn_tpu.tuning.cache import seed_from_bench_details

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    ladder = "rs(a2)>rs(a1)>ar(a0)>ag(a1)>ag(a2)"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-04T00:00:00Z",
        "composed_schedule_ms": {
            "ar(a0+a1+a2)": 4.0,
            "rs(a2)>ar(a0+a1)>ag(a2)": 3.5,
            ladder: 2.0,
        },
        "composed_spread_pct": 5.0,
        "composed_world_shape": [2, 2, 2],
        "composed_payload_mb": 3,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    # keyed by the 3-level world shape + payload bucket, winner = the
    # ladder SIGNATURE (a pipeline the old menu could not express)
    assert (f"reduction_schedule|TPU v5 lite|2x2x2x4|sched -> {ladder}"
            in seeded)

    # ...and the seeded entry is exactly what resolve_schedule's
    # derived candidate set resolves for that world shape (conftest
    # pins the registry to 'off' for hermeticity — 'table' still
    # consults the cache, like every non-off mode).
    from chainermn_tpu.parallel.reduction_schedule import resolve_schedule

    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE", str(cache))
    winner, rec = resolve_schedule("TPU v5 lite", 3 << 20, (2, 2, 2))
    assert winner == ladder
    assert rec["source"].startswith("cache")
    assert rec["composition"] == ladder

    # a winner that IS a menu instance adopts by MENU NAME — stored
    # under its signature the candidate list would never match it and
    # choice() would silently fall back to the table default (review
    # finding, pinned here): two_level's derived signature wins ->
    # entry winner 'two_level', and resolve_schedule returns it.
    cache3 = tmp_path / "cache3.json"
    doc["composed_schedule_ms"] = {
        "ar(a0+a1+a2)": 4.0,
        "rs(a2)>ar(a0+a1)>ag(a2)": 2.0,
        ladder: 3.5,
    }
    doc["composed_spread_pct"] = 5.0
    details.write_text(json.dumps(doc))
    seeded3 = "\n".join(seed_from_bench_details(str(details), str(cache3)))
    assert ("reduction_schedule|TPU v5 lite|2x2x2x4|sched -> two_level"
            in seeded3)
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE", str(cache3))
    winner3, rec3 = resolve_schedule("TPU v5 lite", 3 << 20, (2, 2, 2))
    assert winner3 == "two_level"
    assert rec3["composition"] == "rs(a2)>ar(a0+a1)>ag(a2)"

    # a spread-dominated sweep refuses to pin a winner
    doc["composed_schedule_ms"] = {ladder: 2.0, "ar(a0+a1+a2)": 2.05}
    doc["composed_spread_pct"] = 10.0
    details.write_text(json.dumps(doc))
    assert "reduction_schedule" not in "\n".join(
        seed_from_bench_details(str(details), str(cache.with_suffix(".2")))
    )


def test_plan_rows_contract():
    """ISSUE 10 satellite: the ``plan`` bench phase's headline rows ride
    the compact line (hand-wired vs plan-compiled ratio + spread gate),
    and the phase is wired into the supplementary chain so a plan
    regression reaches the driver artifact."""
    for k in ("plan_vs_handwired", "plan_spread_pct"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_plan)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("plan", "plan_error"' in src


def test_composed_sliced_rows_contract_and_seeding(tmp_path, monkeypatch):
    """ISSUE 15 satellite: the ``composed`` phase's sliced-arm rows
    ride the compact line (per-S medians + spread gate + selected
    count), and ``tuning seed`` learns the ``comp_slices`` decision
    from the same rows — spread-gated exactly like the in-run
    ``record_measurement`` adoption, under the world-shape x
    payload-MB key ``resolve_comp_slices`` reads (offline seed and
    live adoption must agree on identical rows — the PR 14
    adapter_impl lesson)."""
    for k in ("composed_sliced_ms", "composed_slices_selected",
              "composed_sliced_spread_pct"):
        assert k in bench._COMPACT_KEYS, k

    from chainermn_tpu.tuning.cache import seed_from_bench_details

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-04T00:00:00Z",
        "composed_sliced_ms": {"1": 4.0, "2": 3.2, "4": 2.0, "8": 2.8},
        "composed_sliced_spread_pct": 5.0,
        "composed_world_shape": [2, 2, 2],
        "composed_payload_mb": 3,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "comp_slices|TPU v5 lite|2x2x2x4|slices -> 4" in seeded

    # the seeded entry is exactly what resolve_comp_slices resolves —
    # and what the 'auto' schedule resolution slices its winner by.
    from chainermn_tpu.parallel.reduction_schedule import (
        resolve_comp_slices,
        resolve_schedule,
    )

    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE", str(cache))
    assert resolve_comp_slices("TPU v5 lite", 3 << 20, (2, 2, 2)) == 4
    winner, rec = resolve_schedule("TPU v5 lite", 3 << 20, (2, 2, 2),
                                   slices="auto")
    assert winner == "ar(a0+a1+a2)[s0..3]"
    assert rec["comp_slices"] == 4

    # live adoption over the SAME rows agrees with the offline seed
    from chainermn_tpu import tuning

    live_cache = tmp_path / "live.json"
    key = tuning.decision_key(
        "TPU v5 lite", shape=(2, 2, 2, 3), dtype="slices")
    live = tuning.record_measurement(
        "comp_slices", key,
        {k: float(v) for k, v in doc["composed_sliced_ms"].items()},
        spreads={k: 5.0 for k in doc["composed_sliced_ms"]},
        cache_path=str(live_cache),
    )
    assert live == "4"

    # a spread-dominated sweep refuses to pin a winner (table default
    # 1 stands — the honest CPU-proxy outcome)
    doc["composed_sliced_ms"] = {"1": 2.0, "2": 1.98, "4": 2.02,
                                 "8": 2.05}
    doc["composed_sliced_spread_pct"] = 10.0
    details.write_text(json.dumps(doc))
    assert "comp_slices" not in "\n".join(
        seed_from_bench_details(str(details),
                                str(cache.with_suffix(".2")))
    )
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_CACHE",
                       str(cache.with_suffix(".2")))
    assert resolve_comp_slices("TPU v5 lite", 3 << 20, (2, 2, 2)) == 1

def test_sched_search_rows_contract_and_seeding(tmp_path):
    """ISSUE 16 satellite: the cost-model schedule search's headline
    rows ride the compact line (``sched_search_selected`` +
    ``cost_model_err_pct``), the composed phase really ranks with
    ``rank_compositions`` and logs the skipped arms with their
    predicted prices (no silent coverage loss), and ``tuning seed``
    learns the ``sched_search`` decision from the model audit —
    error inside the spread keeps top-k, disagreement past the gate
    seeds 'exhaustive' so the next run restores full coverage."""
    for k in ("sched_search_selected", "cost_model_err_pct"):
        assert k in bench._COMPACT_KEYS, k
    import inspect

    src = inspect.getsource(bench._bench_composed)
    # the search contract, pinned structurally: model loaded from the
    # PRIOR capture, ranked top-k measured (k default 3), skipped arms
    # + predicted costs logged, model error recorded as adoption
    # evidence, disagreement falls back to exhaustive loudly.
    for marker in ("load_from_bench_details", "rank_compositions",
                   "k=3", "sched_search_skipped",
                   "sched_search_predicted_ms", "extra_evidence",
                   "exhaustive:model_err"):
        assert marker in src, marker

    from chainermn_tpu.tuning.cache import seed_from_bench_details
    from chainermn_tpu.tuning.cache import lookup_entry

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-05T00:00:00Z",
        "composed_world_shape": [2, 2, 2],
        "composed_payload_mb": 3,
        "composed_spread_pct": 8.0,
        "sched_search_selected": "topk",
        "cost_model_err_pct": 4.5,
        "sched_search_predicted_ms": {"ar(a0+a1+a2)": 3.1},
        "sched_search_skipped": ["rs(a2)>ar(a0+a1)>ag(a2)"],
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "sched_search|TPU v5 lite|2x2x2x4|search -> topk" in seeded
    entry = lookup_entry(
        "sched_search", "TPU v5 lite|2x2x2x4|search", path=str(cache))
    assert entry["cost_model_err_pct"] == 4.5
    assert entry["spread_pct"] == 8.0
    assert entry["skipped"] == ["rs(a2)>ar(a0+a1)>ag(a2)"]
    assert entry["predicted_ms"] == {"ar(a0+a1+a2)": 3.1}

    # model error past the spread gate seeds the exhaustive fallback
    doc["cost_model_err_pct"] = 40.0
    details.write_text(json.dumps(doc))
    seeded2 = "\n".join(seed_from_bench_details(
        str(details), str(cache.with_suffix(".2"))))
    assert ("sched_search|TPU v5 lite|2x2x2x4|search -> exhaustive"
            in seeded2)

    # no audit keys -> no sched_search entry (never seeded blind)
    doc.pop("cost_model_err_pct")
    details.write_text(json.dumps(doc))
    assert "sched_search" not in "\n".join(seed_from_bench_details(
        str(details), str(cache.with_suffix(".3"))))


def test_serving_sampled_rows_contract():
    """ISSUE 18 satellite: the ``serving_sampled`` phase's headline
    rows ride the compact line (per-arm tokens/s + spread + sampled
    spec speedup/acceptance + the spread-gated verdict), the phase is
    wired into the supplementary chain, and its verdict is recorded as
    cache evidence under the NON-decision ``sampled_serving`` name —
    never under spec_tokens/prefill_chunk: the greedy ``serving``/
    ``serving_burst`` phases own those adoption rows, and counter-
    based sampling makes one decision cover both modes
    (docs/serving.md "Sampling")."""
    for k in ("serving_sampled_tokens_per_sec",
              "serving_sampled_spread_pct",
              "serving_sampled_spec_speedup",
              "serving_sampled_spec_accept_rate",
              "serving_sampled_selected"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_serving_sampled)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("serving_sampled", "serving_sampled_error"' in src
    # evidence rides its own cache name; the phase never re-records
    # the greedy phases' knob decisions
    phase_src = inspect.getsource(bench._bench_serving_sampled)
    assert '"sampled_serving"' in phase_src
    for knob in ('"spec_tokens"', '"prefill_chunk"'):
        assert knob not in phase_src

    # the decide rule: decisive sampled win -> stored with evidence;
    # spread-dominated -> None and 'plain' stands (honest refusal)
    from chainermn_tpu import tuning

    winner = tuning.record_measurement(
        "sampled_serving", "unit-test|sampled",
        {"plain": 100.0, "spec": 150.0, "chunked": 90.0},
        spreads={"plain": 5.0, "spec": 5.0, "chunked": 5.0},
        higher_is_better=True,
        extra_evidence={"spec_accept_rate": 0.6},
    )
    assert winner == "spec"
    assert tuning.record_measurement(
        "sampled_serving", "unit-test|sampled",
        {"plain": 100.0, "spec": 104.0},
        spreads={"plain": 12.0, "spec": 12.0},
        higher_is_better=True,
    ) is None


def test_decode_kernel_rows_contract_and_seeding(tmp_path):
    """ISSUE 19 satellite: the fused-kernel adoption rows ride the
    compact line (per-impl ms, spread gate, fused speedup, selected)
    and ``tuning seed`` learns ``decode_attend_impl`` from
    ``serving_decode_kernel_ms`` under the same spread gate — keyed by
    the phase's OWN model shape, with the kernel-vs-gather speedup as
    auditable evidence. The table default is 'xla' (the kernel must
    EARN adoption on a live chip; the CPU proxy times interpret-mode
    emulation, so its honest verdict is refusal-or-xla)."""
    for k in ("serving_decode_kernel_ms",
              "serving_decode_kernel_spread_pct",
              "serving_decode_kernel_fused_speedup",
              "serving_decode_kernel_selected"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_serving_decode_kernel)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert ('supp("serving_decode_kernel", '
            '"serving_decode_kernel_error"') in src

    # the registry's shipped default: the kernel has NOT been adopted
    from chainermn_tpu.tuning.registry import DEFAULT_TABLE

    assert DEFAULT_TABLE["decode_attend_impl"] == {"*": "xla"}

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-06T00:00:00Z",
        # the phase's own shape key, diverging from the main serving
        # shape on purpose: last-writer-wins on a merged key would
        # re-key the other phase's decisions
        "serving_model_shape": "D256xH4xL256",
        "serving_decode_kernel_model_shape": "D512xH8xL512",
        "serving_decode_kernel_ms": {"xla": 3.0, "fused": 1.2},
        "serving_decode_kernel_spread_pct": 6.0,
        "serving_decode_kernel_fused_speedup": 2.5,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert ("decode_attend_impl|TPU v5 lite|512x8x512|decode -> fused"
            in seeded)
    entry = load_cache(str(cache))["decisions"][
        "decode_attend_impl|TPU v5 lite|512x8x512|decode"]
    assert entry["fused_speedup"] == 2.5
    assert entry["candidates_ms"]["fused"] == 1.2

    # spread-dominated rows are refused: the 'xla' default stands
    doc["serving_decode_kernel_ms"] = {"xla": 1.0, "fused": 0.97}
    doc["serving_decode_kernel_spread_pct"] = 9.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "decode_attend_impl" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("serving_decode_kernel_spread_pct")
    doc["serving_decode_kernel_ms"] = {"xla": 1.0, "fused": 0.95}
    details.write_text(json.dumps(doc))
    assert "decode_attend_impl" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["serving_decode_kernel_ms"] = {"xla": 2.0, "fused": 0.9}
    details.write_text(json.dumps(doc))
    assert ("decode_attend_impl|TPU v5 lite|512x8x512|decode -> fused"
            in "\n".join(seed_from_bench_details(str(details),
                                                 str(cache2))))


def test_moe_rows_contract_and_seeding(tmp_path):
    """ISSUE 20 satellite: the ``moe`` phase's headline rows ride the
    compact line (expert-plan step median + selected ``expert_parallel``
    + spread gate + drop accounting), the phase is wired into the
    supplementary chain, and ``tuning seed`` learns ``expert_parallel``
    from the on/off step pair under the SAME key the live adoption uses
    (shape=(T, E, D), float32) — spread-gated exactly like the in-run
    ``record_measurement``."""
    for k in ("moe_step_ms", "moe_selected", "moe_spread_pct",
              "moe_drop_rate"):
        assert k in bench._COMPACT_KEYS, k
    assert callable(bench._bench_moe_plan)
    import inspect

    src = inspect.getsource(bench._run_bench)
    assert 'supp("moe", "moe_error"' in src

    from chainermn_tpu.tuning.cache import (
        load_cache,
        seed_from_bench_details,
    )

    details = tmp_path / "details.json"
    cache = tmp_path / "cache.json"
    doc = {
        "device_kind": "TPU v5 lite", "n_devices": 8,
        "measured_at": "2026-08-07T00:00:00Z",
        "moe_plan_shape": "T16384xE8xD512",
        "moe_step_ms": 3.1, "moe_off_step_ms": 6.0,
        "moe_spread_pct": 4.0, "moe_drop_rate": 0.13,
    }
    details.write_text(json.dumps(doc))
    seeded = "\n".join(seed_from_bench_details(str(details), str(cache)))
    assert "expert_parallel|TPU v5 lite|16384x8x512|float32 -> on" in \
        seeded
    entry = load_cache(str(cache))["decisions"][
        "expert_parallel|TPU v5 lite|16384x8x512|float32"]
    assert entry["candidates_ms"] == {"on": 3.1, "off": 6.0}

    # parity with the live adoption key: decision_key over the same
    # shape lands on the seeded entry
    from chainermn_tpu import tuning

    key = tuning.decision_key("TPU v5 lite", shape=(16384, 8, 512),
                              dtype="float32")
    assert key == "TPU v5 lite|16384x8x512|float32"

    # spread-dominated pair is refused — the table default (off) stands
    doc["moe_step_ms"] = 5.9
    doc["moe_spread_pct"] = 12.0
    details.write_text(json.dumps(doc))
    cache2 = tmp_path / "cache2.json"
    assert "expert_parallel" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))

    # ABSENT spread = on-accel single sample: the 10% floor applies
    doc.pop("moe_spread_pct")
    details.write_text(json.dumps(doc))
    assert "expert_parallel" not in "\n".join(
        seed_from_bench_details(str(details), str(cache2)))
    doc["moe_step_ms"] = 3.1
    details.write_text(json.dumps(doc))
    assert "expert_parallel|TPU v5 lite|16384x8x512|float32 -> on" in \
        "\n".join(seed_from_bench_details(str(details), str(cache2)))
