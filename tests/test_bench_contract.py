"""Driver-contract pins for bench.py: the FINAL stdout line must stay a
single compact JSON object that fits (with margin) inside the driver's
2000-char tail-capture window, whatever rows/notes/carried blobs the run
accumulated (the round-1 artifacts went red precisely because a fat line
got truncated into unparseable JSON)."""

import contextlib
import io
import json

import bench


def test_compact_line_fits_tail_window(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_DETAILS_PATH",
                        str(tmp_path / "details.json"))
    # Worst-case: every compact key present, fat note/error strings, a
    # carried blob with many older-run rows.
    result = {k: 123456.789 for k in bench._COMPACT_KEYS}
    result.update(
        metric="resnet50_images_per_sec",
        unit="images/sec",
        device_kind="TPU v5 lite",
        bench_note="x" * 500,
        error="y" * 500,
        last_good_tpu={
            "value": 2459.12, "mfu": 0.2998, "age_hours": 123.5,
            "stale": True, "measured_at": "2026-07-31T03:31:43Z",
            "carried_keys": {
                "keys": [f"k{i}" for i in range(30)],
                "stamps": {"k0": "2026-07-30T01:00:00Z"},
            },
        },
        # Fat non-compact rows must NOT leak into the line at all.
        allreduce_curve=[{"mib": 512, "busbw_gbps": 1.0}] * 8,
        kernel_sweep=[{"kernel": "causal_fwd", "ok": True}] * 8,
    )
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_final(result)
    line = buf.getvalue().strip().splitlines()[-1]
    assert len(line) < 1900, len(line)
    parsed = json.loads(line)  # a single well-formed object
    assert parsed["metric"] == "resnet50_images_per_sec"
    assert "allreduce_curve" not in parsed
    assert "kernel_sweep" not in parsed
    assert parsed["details"] == "BENCH_DETAILS.json"
    # the full details file holds everything
    full = json.load(open(tmp_path / "details.json"))
    assert "allreduce_curve" in full and "kernel_sweep" in full


def test_purge_retired_methodology_rows():
    """Rows measured under a repudiated method must not be carried
    forward under their (unchanged) names: the long-context attention
    rows moved to the chained-scan harness in r5 (the single-dispatch
    values measured kernel + tunnel dispatch latency), keyed off the
    ``flash_32k_method`` marker — same pattern as the native-input
    rows' ``native_input_method``."""
    old = {
        "flash_32k_fwd_ms": 104.9,
        "flash_32k_window2k_fwd_ms": 72.4,
        "xla_32k_fwd_ms": 1.0,
        "xla_32k_error": "OOM (34.4 GB)",  # method-independent: kept
        "mfu": 0.299,
        "transformer_hw_util": 0.02,  # always-retired key
    }
    bench._purge_retired(old)
    for k in bench._OLD_METHOD_32K_KEYS:
        assert k not in old, k
    assert "transformer_hw_util" not in old
    assert old["xla_32k_error"].startswith("OOM")
    assert old["mfu"] == 0.299

    # marker present -> new-method rows survive the merge untouched
    new = {"flash_32k_fwd_ms": 40.0, "flash_32k_method": "chained-scan"}
    bench._purge_retired(new)
    assert new["flash_32k_fwd_ms"] == 40.0


def test_transformer_knob_env_validation(monkeypatch):
    """The accel transformer knobs reject malformed env values with a
    message naming the variable (a bare ZeroDivisionError from
    CHAINERMN_BENCH_TF_HEADS=0 once leaked through review)."""
    import pytest

    class _Comm:  # knob validation happens before any communicator use
        size = 1

    cases = {
        "CHAINERMN_BENCH_TF_HEADS": ["0", "-8", "7"],
        "CHAINERMN_BENCH_TF_DB": ["yes", "1"],
        "CHAINERMN_BENCH_TF_REMAT": ["conv", "all"],
    }
    for var, bads in cases.items():
        for bad in bads:
            monkeypatch.setenv(var, bad)
            with pytest.raises(ValueError, match=var.rsplit("_", 1)[-1]):
                bench._transformer_setup(_Comm(), on_accel=True)
            monkeypatch.delenv(var)
