"""Data-layer tests — analog of ``tests/dataset_tests/test_scatter_dataset.py``
(dagger) (SURVEY.md section 4): union of shards == original set, balance
within +-1, same shuffle given same seed; empty dataset; iterators.
"""

import numpy as np
import pytest

from chainermn_tpu import (
    create_communicator,
    create_empty_dataset,
    create_multi_node_iterator,
    create_synchronized_iterator,
    scatter_dataset,
)


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


@pytest.mark.parametrize("n", [100, 101, 7, 8])
@pytest.mark.parametrize("size", [8, 3])
def test_scatter_union_and_balance(comm, n, size):
    data = list(range(n))
    shards = [
        scatter_dataset(data, comm, rank=r, size=size) for r in range(size)
    ]
    lengths = [len(s) for s in shards]
    assert max(lengths) - min(lengths) <= 1
    union = sorted(x for s in shards for x in s)
    assert union == data


def test_scatter_shuffle_deterministic(comm):
    data = list(range(50))
    a = scatter_dataset(data, comm, shuffle=True, seed=42, rank=2, size=8)
    b = scatter_dataset(data, comm, shuffle=True, seed=42, rank=2, size=8)
    assert list(a) == list(b)
    c = scatter_dataset(data, comm, shuffle=True, seed=43, rank=2, size=8)
    assert list(a) != list(c)  # overwhelmingly likely


def test_scatter_shuffle_partitions(comm):
    data = list(range(64))
    shards = [
        scatter_dataset(data, comm, shuffle=True, seed=7, rank=r, size=8)
        for r in range(8)
    ]
    union = sorted(x for s in shards for x in s)
    assert union == data


def test_scatter_force_equal_length(comm):
    data = list(range(10))
    shards = [
        scatter_dataset(data, comm, rank=r, size=4, force_equal_length=True)
        for r in range(4)
    ]
    assert all(len(s) == 3 for s in shards)
    # every original element still appears somewhere
    union = set(x for s in shards for x in s)
    assert union == set(data)


def test_scatter_force_equal_length_more_ranks_than_data(comm):
    data = list(range(2))
    shards = [
        scatter_dataset(data, comm, rank=r, size=4, force_equal_length=True)
        for r in range(4)
    ]
    assert [len(s) for s in shards] == [1, 1, 1, 1]  # no empty shard


def test_subdataset_indexing(comm):
    data = [10 * i for i in range(20)]
    s = scatter_dataset(data, comm, rank=0, size=2)
    assert s[0] == 0 and s[1] == 10
    assert s[0:3] == [0, 10, 20]
    assert len(s) == 10


def test_empty_dataset():
    base = list(range(17))
    e = create_empty_dataset(base)
    assert len(e) == 17
    assert e[0] is None and e[16] is None
    assert all(x is None for x in e)
    with pytest.raises(IndexError):
        e[17]
    assert e[2:5] == [None, None, None]


def test_multi_node_iterator_single_process(comm):
    data = list(range(32))
    it = create_multi_node_iterator(data, 8, comm, shuffle=False)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0] == [0, 1, 2, 3, 4, 5, 6, 7]
    # second epoch restarts
    batches2 = list(it)
    assert len(batches2) == 4


def test_synchronized_iterator_same_order(comm):
    data = list(range(40))
    a = list(create_synchronized_iterator(data, 10, comm, seed=5))
    b = list(create_synchronized_iterator(data, 10, comm, seed=5))
    assert a == b
    assert len(a) == 4


def test_iterator_epoch_counting(comm):
    data = list(range(10))
    it = create_multi_node_iterator(data, 4, comm, shuffle=True, seed=1)
    for _ in it:
        pass
    assert it.epoch == 1
    for _ in it:
        pass
    assert it.epoch == 2
