"""The structured trace/metrics subsystem (ISSUE 2):

- recorder mechanics (events, spans, JSONL round-trip, Chrome export,
  env enablement, overhead-off contract);
- collective-wire counters on the communicator surface, with tuning
  provenance on 'auto'-resolved wires;
- the STRUCTURAL guarantee: instrumentation adds ZERO device-plane
  collectives (the repo's ppermute-count convention) and does not
  perturb numerics (dist==single equivalence with the recorder on);
- the Trainer step timeline and the straggler monitor.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu import create_communicator
from chainermn_tpu.observability import StragglerMonitor, trace


@pytest.fixture(autouse=True)
def _isolated_recorder():
    """Every test starts and ends with tracing OFF — the global recorder
    must never leak into the rest of the suite."""
    trace.disable()
    yield
    trace.disable()


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


# ----------------------------------------------------------------------
# Recorder mechanics
# ----------------------------------------------------------------------


def test_disabled_recorder_is_inert(comm):
    assert trace.active() is None
    # instrumented calls run identically with tracing off
    out = comm.allreduce(jnp.ones((comm.size, 2)))
    assert out.shape == (2,)
    assert trace.active() is None


def test_event_schema_and_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = trace.enable(path, meta={"source": "test"})
    rec.event("step", iteration=3, phases={"compute": 0.01})
    rec.collective("allreduce", nbytes=128, dur_s=0.002, wire_dtype="bf16")
    rec.flush()
    events = trace.read_jsonl(path)
    assert [e["kind"] for e in events] == ["meta", "step", "collective"]
    for e in events:
        assert e["schema"] == trace.TRACE_SCHEMA
        assert {"t", "pid", "rank"} <= set(e)
    assert events[0]["source"] == "test"
    assert events[2]["nbytes"] == 128 and events[2]["wire_dtype"] == "bf16"


def test_span_records_duration_and_failure(tmp_path):
    rec = trace.enable(None)
    with trace.span("phase-a") as extra:
        extra["rows"] = 3
    with pytest.raises(ValueError):
        with trace.span("phase-b"):
            raise ValueError("boom")
    spans = [e for e in rec.events if e["kind"] == "span"]
    assert [s["name"] for s in spans] == ["phase-a", "phase-b"]
    assert spans[0]["ok"] is True and spans[0]["rows"] == 3
    assert spans[1]["ok"] is False
    assert all(s["dur_s"] >= 0 for s in spans)


def test_unserialisable_field_degrades_to_repr(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = trace.enable(path)
    rec.event("step", weird=object())
    rec.flush()
    events = trace.read_jsonl(path)
    assert len(events) == 2 and "object object" in events[1]["weird"]


def test_env_var_enables_on_first_use(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("CHAINERMN_TPU_TRACE", path)
    monkeypatch.setattr(trace, "_active", None)
    monkeypatch.setattr(trace, "_env_checked", False)
    rec = trace.active()
    assert rec is not None and rec.path == path
    rec.flush()
    assert trace.read_jsonl(path)[0]["kind"] == "meta"


def test_enable_failure_keeps_prior_recorder_alive(tmp_path):
    """A failing enable() (unwritable path) must raise WITHOUT
    replacing the working recorder with a closed one — otherwise every
    later instrumentation site pays full cost buffering events that are
    never written (code-review finding)."""
    rec = trace.enable(None)
    with pytest.raises(OSError):
        trace.enable("/proc/definitely/not/writable/t.jsonl")
    assert trace.active() is rec
    rec.event("step", still="alive")
    assert rec.events[-1]["still"] == "alive"


def test_chrome_trace_export(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = trace.enable(path)
    rec.collective("allreduce", nbytes=64, dur_s=0.001)
    rec.event("straggler", flagged_ranks=[1])
    rec.flush()
    out = str(tmp_path / "chrome.json")
    n = trace.write_chrome_trace(path, out)
    assert n == 2  # meta excluded
    ct = json.load(open(out))
    phs = {e["ph"] for e in ct["traceEvents"]}
    assert phs == {"X", "i"}  # duration slice + instant
    slice_ = next(e for e in ct["traceEvents"] if e["ph"] == "X")
    assert slice_["dur"] == pytest.approx(1000.0)  # 1 ms in us


# ----------------------------------------------------------------------
# Collective-wire counters
# ----------------------------------------------------------------------


def test_wire_counters_cover_the_collective_surface(comm):
    rec = trace.enable(None)
    n = comm.size
    comm.allreduce(jnp.ones((n, 4)))
    comm.bcast(jnp.ones((3,)))
    comm.allgather(jnp.ones((n, 2)))
    comm.alltoall(jnp.ones((n, n, 2)))
    comm.scatter(jnp.ones((n, 2)))
    comm.bcast_data({"w": jnp.ones((5,))})
    comm.allreduce_grad({"w": jnp.ones((n, 5))})
    comm.bcast_obj({"meta": 1})
    comm.allgather_obj(7)
    comm.barrier()
    ops = [e["op"] for e in rec.events if e["kind"] == "collective"]
    for op in ("allreduce", "bcast", "allgather", "alltoall", "scatter",
               "bcast_data", "allreduce_grad", "bcast_obj",
               "allgather_obj", "barrier"):
        assert op in ops, (op, ops)
    for e in rec.events:
        if e["kind"] != "collective":
            continue
        assert e["dur_s"] >= 0
        assert e["size"] == (comm.host.size if e["plane"] == "host" else n)
    ar = next(e for e in rec.events if e.get("op") == "allreduce")
    assert ar["nbytes"] == n * 4 * 4  # [n, 4] f32
    assert ar["plane"] == "device" and "device" in ar
    # bcast_obj measures the RESULT (the broadcast payload lands on
    # every rank; the argument is None on non-root ranks by convention)
    bo = next(e for e in rec.events if e.get("op") == "bcast_obj")
    import pickle

    assert bo["nbytes"] == len(pickle.dumps({"meta": 1}, protocol=4))


def test_auto_wire_event_carries_tuning_provenance():
    rec = trace.enable(None)
    comm = create_communicator("naive", allreduce_grad_dtype="auto")
    comm.allreduce_grad({"g": jnp.ones((comm.size, 3))})
    ev = [e for e in rec.events if e.get("op") == "allreduce_grad"]
    assert len(ev) == 1
    prov = ev[0]["provenance"]
    # the registry record behind the 'auto' resolution, verbatim
    assert prov["name"] == "allreduce_wire"
    assert prov["winner"] in ("f32", "bf16", "int8")
    assert "source" in prov and "key" in prov
    assert ev[0]["wire_dtype"] in ("float32", "bfloat16", "int8")
    # the registry ALSO logged the resolution as a dispatch event
    disp = [e for e in rec.events if e["kind"] == "dispatch"]
    assert any(d["name"] == "allreduce_wire" for d in disp)


def test_explicit_wire_has_no_provenance(comm):
    rec = trace.enable(None)
    comm2 = create_communicator(
        "naive", allreduce_grad_dtype=jnp.bfloat16
    )
    comm2.allreduce_grad({"g": jnp.ones((comm2.size, 3))})
    ev = [e for e in rec.events if e.get("op") == "allreduce_grad"]
    assert ev and "provenance" not in ev[0]
    assert ev[0]["wire_dtype"] == "bfloat16"


def test_p2p_send_recv_events(comm):
    rec = trace.enable(None)
    comm.send(np.arange(6, dtype=np.float32), dest=0, tag=9)
    got = comm.recv(source=0, tag=9)
    np.testing.assert_array_equal(got, np.arange(6, dtype=np.float32))
    ops = {e["op"]: e for e in rec.events if e["kind"] == "collective"}
    assert ops["send"]["nbytes"] == 24 and ops["send"]["dest"] == 0
    assert ops["recv"]["nbytes"] == 24 and ops["recv"]["source"] == 0


# ----------------------------------------------------------------------
# Structural: zero added device-plane collectives, numerics untouched
# ----------------------------------------------------------------------


def _two_dim_comm():
    from jax.sharding import Mesh

    from chainermn_tpu.communicators.xla_communicator import (
        TwoDimensionalCommunicator,
    )

    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    return TwoDimensionalCommunicator(mesh=Mesh(devs, ("inter", "intra")))


def test_recorder_adds_zero_device_collectives():
    """The ppermute-count certificate (ISSUE 2 acceptance): the traced
    program of an instrumented gradient reduction is IDENTICAL with the
    recorder on and off — instrumentation is host-side timestamps only,
    so no primitive (collective or otherwise) is added or removed."""
    from chainermn_tpu.testing import count_primitives

    comm = _two_dim_comm()
    tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
    env = [("inter", 2), ("intra", 4)]

    def counts():
        return count_primitives(
            lambda t: comm.reduce_gradients_in_jit(
                t, compress_dtype=jnp.bfloat16
            ),
            tree, axis_env=env,
        )

    off = counts()
    trace.enable(None)
    on = counts()
    assert on == off
    # the reduction pipeline really is in there (not vacuous equality)
    assert on.get("reduce_scatter") == 1
    assert on.get("psum") == 1
    assert on.get("all_gather") == 1


def test_pack_event_records_bucket_layout_at_trace_time():
    """The in-jit bucketed reduction can't time itself host-side, but it
    CAN record — once per compilation trace — the pack layout and the
    bucket decision's provenance."""
    comm = _two_dim_comm()
    rec = trace.enable(None)
    from chainermn_tpu.testing import count_primitives

    tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
    count_primitives(
        lambda t: comm.reduce_gradients_in_jit(
            t, compress_dtype=jnp.bfloat16
        ),
        tree, axis_env=[("inter", 2), ("intra", 4)],
    )
    packs = [e for e in rec.events if e["kind"] == "pack"]
    assert len(packs) == 1
    p = packs[0]
    assert p["n_buckets"] == 1
    assert p["wire_dtype"] == "bfloat16"
    assert p["nbytes"] == (64 * 32 + 32) * 2  # bf16 bytes on the wire
    assert p["bucket_bytes"] >= 16 << 20

    # int8 wire: floats PACK in f32 but cross the inter wire at
    # 1 byte/elem — nbytes must describe the named wire, not the pack
    # staging dtype (code-review finding: a 4x overstatement).
    count_primitives(
        lambda t: comm.reduce_gradients_in_jit(t, compress_dtype=jnp.int8),
        tree, axis_env=[("inter", 2), ("intra", 4)],
    )
    p8 = [e for e in rec.events if e["kind"] == "pack"][-1]
    assert p8["wire_dtype"] == "int8"
    assert p8["nbytes"] == 64 * 32 + 32


def test_instrumented_hlo_collective_counts(comm):
    """Compiled-module certificate: a shard_map'd gradient reduction
    compiled WITH the recorder active shows exactly the expected
    collectives — one reduce-scatter, one all-reduce, one all-gather for
    the packed two-level pipeline (same counts the uninstrumented test
    in test_communicator.py pins)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    trace.enable(None)
    comm2 = _two_dim_comm()
    tree = {"w": jnp.ones((8, 16, 8)), "b": jnp.ones((8, 8))}

    def local(t):
        sq = jax.tree.map(lambda l: l[0], t)
        out = comm2.reduce_gradients_in_jit(sq, compress_dtype=jnp.bfloat16)
        return jax.tree.map(lambda l: l[None], out)

    spec = jax.tree.map(
        lambda l: P(("inter", "intra"), *([None] * (l.ndim - 1))), tree
    )
    f = jax.jit(shard_map(
        local, mesh=comm2.mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False,
    ))
    txt = f.lower(tree).compile().as_text()
    counts = {op: txt.count(op) for op in
              ("reduce-scatter(", "all-gather(", "all-reduce(")}
    assert counts == {
        "reduce-scatter(": 1, "all-gather(": 1, "all-reduce(": 1
    }, counts


def test_dist_equals_single_with_recorder_enabled(comm):
    """The suite's core invariant survives instrumentation: values AND
    gradients agree between the distributed step and its single-device
    equivalent while the recorder is running, and a recorder-on run is
    bit-identical to a recorder-off run."""
    import optax

    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.models import MLP
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    # Eager value equivalence: stacked allreduce_grad == numpy mean.
    trace.enable(None)
    rs = np.random.RandomState(3)
    stacked = {"w": jnp.asarray(rs.randn(comm.size, 3, 2), jnp.float32)}
    out = comm.allreduce_grad(stacked)
    np.testing.assert_allclose(
        np.asarray(out["w"]), np.asarray(stacked["w"]).mean(0),
        rtol=1e-6, atol=1e-6,
    )

    # Gradient path: identical training trajectories recorder-on vs
    # recorder-off, and dist == single-slot on the same global batch.
    model = MLP(n_units=8, n_out=3)
    x = jnp.asarray(rs.randn(16, 5), jnp.float32)
    y = jnp.asarray(np.arange(16) % 3, jnp.int32)
    params = model.init(jax.random.key(0), x[:1])["params"]

    def loss_fn(p, batch):
        import optax as _o

        xb, yb = batch
        return _o.softmax_cross_entropy_with_integer_labels(
            model.apply({"params": p}, xb), yb
        ).mean()

    def run(c):
        opt = create_multi_node_optimizer(optax.sgd(0.1), c)
        state = create_train_state(params, opt, c)
        step = make_train_step(loss_fn, opt, c, donate=False)
        for _ in range(2):
            state, m = step(state, (x, y))
        return jax.tree.leaves(jax.device_get(state.params)), float(m["loss"])

    on_leaves, on_loss = run(comm)
    single_leaves, single_loss = run(comm.sub_communicator([0]))
    trace.disable()
    off_leaves, off_loss = run(comm)

    for a, b in zip(on_leaves, off_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert on_loss == off_loss
    for a, b in zip(on_leaves, single_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert abs(on_loss - single_loss) < 1e-6


# ----------------------------------------------------------------------
# Trainer step timeline + straggler monitor
# ----------------------------------------------------------------------


def _tiny_trainer(comm, n_batches=6, log_interval=2):
    from chainermn_tpu.training.trainer import Trainer

    def step_fn(state, batch):
        xb, _ = batch
        return state + 1, {"loss": jnp.mean(xb) + state}

    data = [
        [(np.ones((4,), np.float32), np.int32(0)) for _ in range(8)]
        for _ in range(n_batches)
    ]

    class It:
        def __iter__(self):
            return iter(data)

    return Trainer(step_fn, jnp.float32(0), It(), comm,
                   log_interval=log_interval, out=open(os.devnull, "w"))


def test_trainer_emits_step_timeline(comm):
    rec = trace.enable(None)
    tr = _tiny_trainer(comm)
    tr.run(6)
    steps = [e for e in rec.events if e["kind"] == "step"]
    assert [s["iteration"] for s in steps] == [1, 2, 3, 4, 5, 6]
    for s in steps:
        assert set(s["phases"]) == {
            "data_wait", "h2d", "compute", "logging", "extensions"
        }
        assert all(v >= 0 for v in s["phases"].values())
    # logging fires only on the log interval
    assert steps[0]["phases"]["logging"] == 0.0
    assert steps[1]["phases"]["logging"] > 0.0


def test_trainer_observation_on_every_rank_via_aggregator(comm):
    """ISSUE 2 satellite: ``trainer.observation`` is the aggregated
    host-metrics dict (ObservationAggregator — on a single process the
    aggregate equals the local mean), populated at every log point,
    while rank-0 printing is unchanged."""
    import io

    from chainermn_tpu.training.trainer import Trainer

    def step_fn(state, batch):
        return state + 1, {"loss": jnp.float32(2.5)}

    data = [[(np.zeros((2,), np.float32), np.int32(0))] for _ in range(4)]

    class It:
        def __iter__(self):
            return iter(data)

    buf = io.StringIO()
    tr = Trainer(step_fn, jnp.float32(0), It(), comm, log_interval=2,
                 out=buf)
    tr.run(4)
    assert tr.observation == {"loss": 2.5}
    printed = buf.getvalue()
    assert "loss=2.5000" in printed  # rank-0 pretty print unchanged


def test_trainer_sync_mode_blocks_for_true_compute(comm, monkeypatch):
    rec = trace.enable(None, sync=True)
    assert rec.sync
    tr = _tiny_trainer(comm, n_batches=2)
    tr.run(2)
    steps = [e for e in rec.events if e["kind"] == "step"]
    assert len(steps) == 2  # loop completed under sync mode


def test_consume_phase_window_resets(comm):
    tr = _tiny_trainer(comm, n_batches=3, log_interval=10)
    tr.run(3)
    win = tr.consume_phase_window()
    assert win["compute"] > 0
    assert set(win) == {"data_wait", "h2d", "compute", "logging",
                        "extensions"}
    again = tr.consume_phase_window()
    assert again == {}


def test_observation_aggregator_flush_per_rank(comm):
    from chainermn_tpu.extensions.observation_aggregator import (
        ObservationAggregator,
    )

    agg = ObservationAggregator(comm)
    agg.add({"compute": 1.0})
    agg.add({"compute": 3.0})
    per_rank = agg.flush_per_rank()
    assert per_rank == [{"compute": 2.0}]  # single process: one entry
    assert agg.flush_per_rank() == [{}]  # window cleared


def test_straggler_monitor_flags_divergent_rank(comm, capsys):
    mon = StragglerMonitor(comm, interval=1, threshold=0.3, out=None)
    rec = trace.enable(None)
    report = mon.check([
        {"compute": 0.100, "data_wait": 0.00005},
        {"compute": 0.180, "data_wait": 0.00005},
        {"compute": 0.100, "data_wait": 0.00005},
        {"compute": 0.101, "data_wait": 0.00005},
    ])
    assert report["flagged_ranks"] == [1]
    assert report["phases"]["compute"]["worst_rank"] == 1
    assert report["phases"]["compute"]["flagged"] == [1]
    # data_wait is under min_phase_s -> skipped, not flagged as noise
    assert "data_wait" not in report["phases"]
    # the flag landed in the trace
    assert any(e["kind"] == "straggler" for e in rec.events)
    assert mon.reports and mon.reports[-1] is report


def test_straggler_monitor_fast_rank_not_flagged(comm):
    mon = StragglerMonitor(comm, interval=1, threshold=0.3, out=None)
    report = mon.check([
        {"compute": 0.05},  # faster than the pack: not a straggler
        {"compute": 0.100},
        {"compute": 0.100},
    ])
    assert report["flagged_ranks"] == []


def test_straggler_monitor_as_trainer_extension(comm):
    tr = _tiny_trainer(comm, n_batches=4, log_interval=10)
    mon = StragglerMonitor(comm, interval=2, out=None).attach(tr)
    tr.run(4)
    # single process: exchanges happened (2 windows), nothing flagged
    assert mon.reports == []
    # the window was drained by the extension; only the extension-time
    # accounting that lands AFTER extensions run may remain
    assert set(tr.consume_phase_window()) <= {"extensions"}


def test_straggler_monitor_validates_args(comm):
    with pytest.raises(ValueError):
        StragglerMonitor(comm, interval=0)
    with pytest.raises(ValueError):
        StragglerMonitor(comm, threshold=0.0)
