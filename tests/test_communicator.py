"""Communicator tests — the TPU analog of the reference's big parameterized
matrix (``tests/communicator_tests/test_communicator.py`` (dagger), SURVEY.md
section 4): every communicator x {collectives over arrays and pytrees,
bcast_data, allreduce_grad with mixed dtypes / stacked shapes}, with the core
invariant *distributed result == single-process result*.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu import create_communicator
from chainermn_tpu.communicators import (
    HierarchicalCommunicator,
    NaiveCommunicator,
    XlaCommunicator,
)

N = 8


def _make(name):
    # Pin every communicator to the virtual CPU devices for hermeticity.
    return create_communicator(name, devices=jax.devices("cpu")[:N])


ALL_NAMES = [
    "xla",
    "naive",
    "flat",
    "pure_nccl",
    "hierarchical",
    "two_dimensional",
    "non_cuda_aware",
    "single_node",
]


@pytest.mark.parametrize("name", ALL_NAMES)
def test_factory_and_topology(name):
    comm = _make(name)
    assert comm.size == N
    assert comm.rank == 0
    assert comm.inter_size == 1  # single process, like the reference's CI
    assert comm.intra_size >= 1
    if name in ("hierarchical", "two_dimensional", "non_cuda_aware"):
        assert isinstance(comm, HierarchicalCommunicator)
        assert comm.mesh.shape["inter"] == 1
        assert comm.mesh.shape["intra"] == N


def test_factory_rejects_unknown():
    with pytest.raises(ValueError, match="unknown communicator"):
        create_communicator("mpi")


@pytest.mark.parametrize("name", ["naive", "hierarchical"])
@pytest.mark.parametrize("op", ["sum", "mean", "max", "min"])
def test_allreduce_matches_numpy(name, op):
    comm = _make(name)
    rng = np.random.RandomState(0)
    x = rng.randn(N, 3, 5).astype(np.float32)
    got = np.asarray(comm.allreduce(x, op=op))
    want = {
        "sum": x.sum(0),
        "mean": x.mean(0),
        "max": x.max(0),
        "min": x.min(0),
    }[op]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_bcast_picks_root_when_stacked(comm):
    x = np.arange(N * 4, dtype=np.float32).reshape(N, 4)
    out = np.asarray(comm.bcast(x, root=3, stacked=True))
    np.testing.assert_array_equal(out, x[3])


def test_bcast_plain_array_not_sliced(comm):
    # A batch whose leading dim happens to equal world size must be
    # replicated whole, never silently sliced to one row.
    x = np.arange(N * 4, dtype=np.float32).reshape(N, 4)
    out = comm.bcast(x)
    assert out.shape == (N, 4)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_bcast_stacked_shape_mismatch_raises(comm):
    with pytest.raises(ValueError, match="leading dim"):
        comm.bcast(np.zeros((3, 2), np.float32), stacked=True)


def test_allreduce_grad_preserves_int_leaves():
    comm2 = create_communicator("naive", allreduce_grad_dtype="bfloat16")
    # int leaf must not round-trip through bf16 (1000 would lose bits)
    g = {"count": np.full((N, 1), 1000, np.int32)}
    out = np.asarray(comm2.allreduce_grad(g, op="sum")["count"])
    assert out.dtype == np.int32
    assert int(out[0]) == 8000


def test_allgather_roundtrip(comm):
    x = np.random.RandomState(1).randn(N, 2).astype(np.float32)
    out = np.asarray(comm.allgather(x))
    np.testing.assert_array_equal(out, x)


def test_alltoall_transposes(comm):
    x = np.arange(N * N * 2, dtype=np.float32).reshape(N, N, 2)
    out = np.asarray(comm.alltoall(x))
    np.testing.assert_array_equal(out, np.swapaxes(x, 0, 1))


def test_scatter_shards_leading_dim(comm):
    x = np.arange(N * 3, dtype=np.float32).reshape(N, 3)
    out = comm.scatter(x)
    # each mesh slot owns one row
    assert out.sharding.num_devices == N if hasattr(out.sharding, "num_devices") else True
    np.testing.assert_array_equal(np.asarray(out), x)


def test_allreduce_grad_pytree_mean(comm):
    rng = np.random.RandomState(2)
    grads = {
        "w": rng.randn(N, 4, 3).astype(np.float32),
        "b": rng.randn(N, 3).astype(np.float32),
    }
    out = comm.allreduce_grad(grads)
    np.testing.assert_allclose(np.asarray(out["w"]), grads["w"].mean(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]), grads["b"].mean(0), rtol=1e-5)


def test_allreduce_grad_bf16_compression():
    comm = create_communicator(
        "naive", allreduce_grad_dtype="bfloat16"
    )
    rng = np.random.RandomState(3)
    g = rng.randn(N, 16).astype(np.float32)
    out = np.asarray(comm.allreduce_grad({"g": g})["g"])
    assert out.dtype == np.float32  # restored to master dtype
    np.testing.assert_allclose(out, g.mean(0), rtol=2e-2, atol=2e-2)


def test_bcast_data_replicates(comm):
    params = {"w": np.ones((4, 4), np.float32), "b": np.zeros((4,), np.float32)}
    out = comm.bcast_data(params)
    for leaf in jax.tree.leaves(out):
        assert leaf.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(out["w"]), params["w"])


def test_obj_collectives_single_process(comm):
    assert comm.bcast_obj({"a": 1}) == {"a": 1}
    assert comm.allgather_obj(5) == [5]
    assert comm.gather_obj(7, root=0) == [7]
    assert comm.allreduce_obj({"loss": 2.0}) == {"loss": 2.0}
    assert comm.scatter_obj([42]) == 42
    comm.barrier()


def test_sub_communicator(comm):
    sub = comm.sub_communicator(range(4))
    assert sub.size == 4
    x = np.arange(4 * 2, dtype=np.float32).reshape(4, 2)
    np.testing.assert_allclose(np.asarray(sub.allreduce(x, "mean")), x.mean(0))


def test_split_single_process_returns_self(comm):
    assert comm.split(color=0) is comm


def test_probe_and_any_source_self_mailboxes(comm):
    """MPI_Iprobe / ANY_SOURCE parity on the same-process mailbox plane
    (the cross-process TCP path is covered by the multiprocess suite)."""
    import numpy as np

    from chainermn_tpu import ANY_SOURCE

    assert comm.probe(1, tag=4) is False
    assert comm.probe(ANY_SOURCE, tag=4) is False
    comm.send_obj({"x": 1}, 1, tag=4)
    assert comm.probe(1, tag=4) is True
    assert comm.probe(1, tag=5) is False  # tag-exact on mailboxes
    assert comm.probe(ANY_SOURCE, tag=4) is True
    src, obj = comm.recv_any_obj(tag=4)
    assert src == 1 and obj == {"x": 1}
    assert comm.probe(1, tag=4) is False

    # ndarray form through the same wildcard
    comm.send(np.arange(4.0), 2, tag=7)
    got = comm.recv(ANY_SOURCE, tag=7)
    np.testing.assert_allclose(np.asarray(got), np.arange(4.0))

    # nothing pending and nothing can arrive -> explicit error, not a hang
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="nothing can ever arrive"):
        comm.recv_any_obj(tag=99)


def test_stacked_shape_mismatch_raises(comm):
    with pytest.raises(ValueError, match="leading dim"):
        comm.allreduce(np.zeros((3, 2), np.float32))


def test_grad_axes_names():
    assert _make("xla").grad_axes == ("data",)
    assert _make("hierarchical").grad_axes == ("inter", "intra")
    assert _make("hierarchical").axis_name == "inter"


class TestTwoDimensional:
    """two_dimensional is no longer an alias: its gradient reduction is the
    explicit intra reduce-scatter -> inter allreduce -> intra all-gather
    pipeline, and must equal the fused pmean bit-for-bit-ish."""

    def test_two_level_allreduce_matches_pmean(self):
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from chainermn_tpu.parallel.collectives import two_level_allreduce

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("inter", "intra"))
        # odd leaf sizes exercise the pad/unpad path
        for shape in [(5,), (3, 7), (1,), (16, 16)]:
            x = jnp.asarray(
                np.random.RandomState(0).randn(8, *shape), jnp.float32
            )

            def explicit(xl):
                return two_level_allreduce(xl[0], "intra", "inter")[None]

            def fused(xl):
                return jax.lax.pmean(xl[0], ("inter", "intra"))[None]

            spec = P(("inter", "intra"), *([None] * len(shape)))
            run = lambda f: jax.jit(shard_map(  # noqa: E731
                f, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
            ))(x)
            np.testing.assert_allclose(
                np.asarray(run(explicit)), np.asarray(run(fused)),
                rtol=1e-6, atol=1e-7,
            )

    def test_packed_reduction_mixed_dtypes_matches_base(self):
        """The flat-buffer pack (one collective pipeline per dtype group,
        reference ``_memory_utility.pack_params`` (dagger)) must equal the
        base fused-pmean path on a tree mixing f32/bf16-compressed leaves,
        an int leaf, odd shapes, and a scalar."""
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("inter", "intra"))
        comm = TwoDimensionalCommunicator(mesh=mesh)

        rng = np.random.RandomState(5)
        tree = {
            "w": jnp.asarray(rng.randn(8, 3, 7), jnp.float32),
            "b": jnp.asarray(rng.randn(8, 5), jnp.float32),
            "scalar": jnp.asarray(rng.randn(8), jnp.float32),
            "count": jnp.asarray(np.arange(8 * 4).reshape(8, 4), jnp.int32),
        }

        def run(fn):
            def local(t):
                squeezed = jax.tree.map(lambda l: l[0], t)
                out = fn(squeezed)
                return jax.tree.map(lambda l: l[None], out)

            spec = jax.tree.map(lambda l: P(("inter", "intra"),
                                            *([None] * (l.ndim - 1))), tree)
            return jax.jit(shard_map(
                local, mesh=mesh, in_specs=(spec,), out_specs=spec,
                check_vma=False,
            ))(tree)

        packed = run(lambda t: comm.reduce_gradients_in_jit(
            t, compress_dtype=jnp.bfloat16))
        base = run(lambda t: super(
            TwoDimensionalCommunicator, comm
        ).reduce_gradients_in_jit(t, compress_dtype=jnp.bfloat16))
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(packed[k]), np.asarray(base[k]),
                rtol=1e-2, atol=1e-2,  # bf16 compression noise
                err_msg=k,
            )
            assert packed[k].dtype == base[k].dtype, k

    def test_collective_pipeline_structure(self):
        """Structural certificate (round-4 VERDICT item 6, the
        ppermute-count convention): the two_dimensional reduction must
        trace to EXACTLY one intra psum_scatter -> one inter psum -> one
        intra all_gather per bucket — and to the expected bucket count
        for a given tree (~64 MB buckets, per-dtype groups). Traced
        abstractly, so the >64 MB case costs no memory."""
        from jax.sharding import Mesh

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )
        from chainermn_tpu.testing import count_primitives

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("inter", "intra"))
        comm = TwoDimensionalCommunicator(mesh=mesh)
        env = [("inter", 2), ("intra", 4)]

        def counts_for(tree, compress=None):
            return count_primitives(
                lambda t: comm.reduce_gradients_in_jit(
                    t, compress_dtype=compress
                ),
                tree, axis_env=env,
            )

        # Three small f32 leaves -> ONE bucket -> one pipeline.
        small = {
            "w": jnp.zeros((3, 7)), "b": jnp.zeros((5,)),
            "s": jnp.zeros(()),
        }
        c = counts_for(small)
        # lax.psum_scatter traces to the reduce_scatter primitive.
        assert c.get("reduce_scatter") == 1, c
        assert c.get("psum") == 1, c
        assert c.get("all_gather") == 1, c

        # Two dtype groups (bf16-compressed floats + int pass-through):
        # ints keep their dtype, forming a second group/pipeline.
        mixed = {
            "w": jnp.zeros((3, 7)),
            "n": jnp.zeros((4,), jnp.int32),
        }
        c = counts_for(mixed, compress=jnp.bfloat16)
        assert c.get("reduce_scatter") == 2, c
        assert c.get("psum") == 2, c
        assert c.get("all_gather") == 2, c

        # 3 x 48 MB f32 leaves: greedy ~64 MB packing puts each leaf in
        # its own bucket (48+48 > 64) -> exactly 3 pipelines. Abstract
        # ShapeDtypeStruct args keep the trace allocation-free.
        big = {f"p{i}": jax.ShapeDtypeStruct((12 << 20,), jnp.float32)
               for i in range(3)}
        c = counts_for(big)
        assert c.get("reduce_scatter") == 3, c
        assert c.get("psum") == 3, c
        assert c.get("all_gather") == 3, c

    def test_train_step_matches_xla_communicator(self):
        import optax

        from chainermn_tpu import (
            create_communicator,
            create_multi_node_optimizer,
        )
        from chainermn_tpu.models import MLP
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        model = MLP(n_units=16, n_out=4)
        x = jnp.asarray(np.random.RandomState(1).randn(16, 10), jnp.float32)
        y = jnp.asarray(np.arange(16) % 4, jnp.int32)
        params = model.init(jax.random.key(0), x[:1])["params"]

        def loss_fn(p, batch):
            xb, yb = batch
            logits = model.apply({"params": p}, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        results = {}
        for name in ("xla", "two_dimensional"):
            comm = create_communicator(name, devices=jax.devices("cpu")[:8])
            opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
            state = create_train_state(params, opt, comm)
            step = make_train_step(loss_fn, opt, comm, donate=False)
            for _ in range(3):
                state, m = step(state, (x, y))
            results[name] = (
                jax.tree.leaves(jax.device_get(state.params)),
                float(m["loss"]),
            )
        for a, b in zip(results["xla"][0], results["two_dimensional"][0]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        assert abs(results["xla"][1] - results["two_dimensional"][1]) < 1e-6

    def test_packed_pipeline_hlo_evidence(self):
        """The class claims a PINNED intra reduce-scatter -> inter
        allreduce -> intra all-gather over ONE packed buffer; the compiled
        module must show exactly one of each collective for a multi-leaf
        tree (per-leaf lowering would show one per leaf)."""
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("inter", "intra"))
        comm = TwoDimensionalCommunicator(mesh=mesh)
        tree = {"w": jnp.ones((8, 64, 32)), "b": jnp.ones((8, 32))}

        def local(t):
            sq = jax.tree.map(lambda l: l[0], t)
            out = comm.reduce_gradients_in_jit(
                sq, compress_dtype=jnp.bfloat16
            )
            return jax.tree.map(lambda l: l[None], out)

        spec = jax.tree.map(
            lambda l: P(("inter", "intra"), *([None] * (l.ndim - 1))), tree
        )
        f = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False,
        ))
        txt = f.lower(tree).compile().as_text()
        counts = {op: txt.count(op) for op in
                  ("reduce-scatter(", "all-gather(", "all-reduce(")}
        assert counts == {
            "reduce-scatter(": 1, "all-gather(": 1, "all-reduce(": 1
        }, counts
