"""Train-step/trainer tests: the core reference invariant — distributed
training result == single-process result on the concatenated batch
(SURVEY.md section 4, "Key invariant tested everywhere")."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.training import Trainer, make_eval_step, make_train_step
from chainermn_tpu.training.train_step import create_train_state
from chainermn_tpu.training.trainer import default_collate

N = 8


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def _linreg_loss(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, {"mse": loss}


def _data(n=64, d=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w_true = rng.randn(d).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(n).astype(np.float32)
    return x, y


def test_distributed_step_equals_single_device(comm):
    x, y = _data()
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    # single-device reference on the full batch (computed BEFORE the
    # distributed step: make_train_step donates its state, which may alias
    # these param buffers)
    ref_opt = optax.sgd(0.1)
    (loss, _), grads = jax.value_and_grad(_linreg_loss, has_aux=True)(
        params, (jnp.asarray(x), jnp.asarray(y))
    )
    upd, _ = ref_opt.update(grads, ref_opt.init(params), params)
    ref_params = jax.device_get(optax.apply_updates(params, upd))
    loss = float(loss)

    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = create_train_state(params, opt, comm)
    step = make_train_step(_linreg_loss, opt, comm)

    new_state, metrics = step(state, (x, y))

    np.testing.assert_allclose(
        np.asarray(new_state.params["w"]), np.asarray(ref_params["w"]), rtol=1e-4
    )
    np.testing.assert_allclose(float(metrics["loss"]), float(loss), rtol=1e-4)
    assert int(new_state.step) == 1


def test_accumulated_step_equals_full_batch(comm):
    """accum_steps=K over the same total batch must produce the SAME update
    as the plain step (microbatches see identical params; the mean of
    microbatch gradients of batch-mean losses is the full-batch gradient)."""
    x, y = _data()
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)

    state_plain = create_train_state(params, opt, comm)
    plain = make_train_step(_linreg_loss, opt, comm, donate=False)
    state_plain, m_plain = plain(state_plain, (x, y))

    state_acc = create_train_state(params, opt, comm)
    acc = make_train_step(_linreg_loss, opt, comm, donate=False,
                          accum_steps=4)
    state_acc, m_acc = acc(state_acc, (x, y))

    np.testing.assert_allclose(
        np.asarray(state_acc.params["w"]),
        np.asarray(state_plain.params["w"]), rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        float(m_acc["loss"]), float(m_plain["loss"]), rtol=1e-5
    )

    with pytest.raises(ValueError):
        make_train_step(_linreg_loss, opt, comm, accum_steps=0)
    bad = make_train_step(_linreg_loss, opt, comm, donate=False,
                          accum_steps=3)
    with pytest.raises(ValueError):
        bad(create_train_state(params, opt, comm), (x, y))  # 8 % 3 != 0


def test_multi_step_convergence(comm):
    x, y = _data(n=256)
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.adam(0.05), comm)
    state = create_train_state(params, opt, comm)
    step = make_train_step(_linreg_loss, opt, comm)
    for _ in range(100):
        state, metrics = step(state, (x, y))
    assert float(metrics["loss"]) < 1e-2


def test_eval_step_matches_full_batch(comm):
    x, y = _data()
    params = {"w": jnp.ones(4), "b": jnp.zeros(())}

    def metric_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return {"mse": jnp.mean((pred - y) ** 2)}

    ev = make_eval_step(metric_fn, comm)
    out = ev(params, (x, y), ())
    want = float(np.mean((x @ np.ones(4) - y) ** 2))
    np.testing.assert_allclose(float(out["mse"]), want, rtol=1e-5)


def test_trainer_runs_and_logs(comm):
    x, y = _data(n=128)
    data = [(x[i], y[i]) for i in range(len(x))]
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = create_train_state(params, opt, comm)
    step = make_train_step(_linreg_loss, opt, comm)

    class _Iter:
        def __iter__(self):
            for i in range(0, 128, 32):
                yield data[i : i + 32]

    buf = io.StringIO()
    calls = []
    trainer = Trainer(step, state, _Iter(), comm, log_interval=2, out=buf)
    trainer.extend(lambda tr: calls.append(tr.iteration), interval=3)
    final = trainer.run(6)
    assert int(final.step) == 6
    assert calls == [3, 6]
    logged = buf.getvalue()
    assert "iter 2/6" in logged and "loss=" in logged


def test_trainer_raises_on_empty_epoch(comm):
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = create_train_state(params, opt, comm)
    step = make_train_step(_linreg_loss, opt, comm)

    class _Empty:
        def __iter__(self):
            return iter([])

    trainer = Trainer(step, state, _Empty(), comm, out=io.StringIO())
    with pytest.raises(RuntimeError, match="no batches"):
        trainer.run(5)


def test_optimizer_survives_pickle_roundtrip(comm):
    import pickle

    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    # __getattr__ must not recurse during copy/pickle protocol probing
    import copy

    c = copy.copy(opt)
    assert c.actual_optimizer is opt.actual_optimizer
    with pytest.raises(AttributeError):
        opt.__getstate_nonexistent__


def test_default_collate():
    batch = [(np.zeros(3), np.int32(1)), (np.ones(3), np.int32(2))]
    x, y = default_collate(batch)
    assert x.shape == (2, 3) and y.shape == (2,)
    d = default_collate([{"a": np.zeros(2)}, {"a": np.ones(2)}])
    assert d["a"].shape == (2, 2)
    arr = default_collate([np.zeros(4), np.zeros(4)])
    assert arr.shape == (2, 4)


def test_mnist_model_parallel_example_runs():
    import examples.mnist.train_mnist_model_parallel as ex

    acc = ex.main(["--iterations", "60", "--batchsize", "64", "--n-units", "64"])
    assert acc > 0.9  # synthetic blobs are easy; must actually learn


def test_mnist_example_runs():
    import examples.mnist.train_mnist as ex

    final = ex.main(["--communicator", "naive", "--iterations", "20",
                     "--batchsize", "64"])
    assert "val_acc" in final and final["val_acc"] > 0.3


def test_prefetch_to_device_order_and_count():
    from chainermn_tpu.training import prefetch_to_device

    batches = [{"x": np.full((2,), i, np.float32)} for i in range(7)]
    out = list(prefetch_to_device(iter(batches), size=3))
    assert len(out) == 7
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)  # placed on device
        np.testing.assert_array_equal(np.asarray(b["x"]), np.full((2,), i))

    with pytest.raises(ValueError, match=">= 1"):
        next(prefetch_to_device(iter(batches), size=0))

    # shorter than the buffer: everything still comes out
    out = list(prefetch_to_device(iter(batches[:2]), size=5))
    assert len(out) == 2


def test_trainer_prefetch_matches_unprefetched(comm):
    """prefetch=2 must not change training: same batches in the same
    order -> bit-identical final parameters."""
    x, y = _data()
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    step = make_train_step(_linreg_loss, opt, comm, donate=False)

    class FixedIter:
        def __iter__(self):
            rng = np.random.RandomState(0)
            for _ in range(6):
                idx = rng.permutation(len(x))[:16]
                yield [(x[i], y[i]) for i in idx]

    results = []
    for prefetch in (0, 2):
        state = create_train_state(params, opt, comm)
        tr = Trainer(step, state, FixedIter(), comm, log_interval=100,
                     out=io.StringIO(), prefetch=prefetch)
        state = tr.run(12)  # 2 epochs of 6 batches
        results.append(jax.device_get(state.params))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        results[0], results[1],
    )


def test_trainer_prefetch_accepts_nondivisible_batches(comm):
    """Enabling prefetch must not change which batch sizes are accepted:
    a leading dim not divisible by the mesh falls back to default
    placement instead of crashing in device_put."""
    x, y = _data(n=24)
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    # plain jit step (not mesh-sharded): accepts any batch size
    inner = optax.sgd(0.1)

    @jax.jit
    def step(state, batch):
        (loss, _), grads = jax.value_and_grad(_linreg_loss, has_aux=True)(
            state[0], batch
        )
        upd, opt_state = inner.update(grads, state[1], state[0])
        return (optax.apply_updates(state[0], upd), opt_state), {"loss": loss}

    class _Iter:
        def __iter__(self):
            # 12 examples per batch: 12 % 8 != 0
            yield [(x[i], y[i]) for i in range(12)]
            yield [(x[i], y[i]) for i in range(12, 24)]

    tr = Trainer(step, (params, inner.init(params)), _Iter(), comm,
                 log_interval=100, out=io.StringIO(), prefetch=2)
    state = tr.run(2)
    assert np.isfinite(float(jax.device_get(state[0]["w"])[0]))


def test_train_step_local_sgd_true_local_evolution(comm):
    """THROUGH make_train_step (not opt.update directly): with
    ``create_local_sgd`` the trainer must NOT pre-reduce gradients — the
    inner adam evolves on each member's LOCAL gradients and members only
    meet at the sync. The oracle is a per-member optax simulation over
    the member's own batch shard. This pins the
    ``handles_cross_rank_sync`` protocol: an isinstance-style dispatch
    regression in make_train_step (which once silently kept the
    per-step wire for this wrapper) fails the oracle equality."""
    from chainermn_tpu import create_local_sgd

    x, y = _data(n=N * 4)
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    opt = create_local_sgd(optax.adam(0.1), comm, sync_every=2)
    state = create_train_state(params, opt, comm)
    step = make_train_step(_linreg_loss, opt, comm, donate=False)
    batch = (jnp.asarray(x), jnp.asarray(y))
    for _ in range(2):
        state, _ = step(state, batch)

    # Oracle: each member adams on ITS shard for 2 steps; then average.
    finals = []
    for r in range(N):
        shard = (jnp.asarray(x[r * 4:(r + 1) * 4]),
                 jnp.asarray(y[r * 4:(r + 1) * 4]))
        p = params
        inner = optax.adam(0.1)
        s = inner.init(p)
        for _ in range(2):
            g = jax.grad(lambda pp: _linreg_loss(pp, shard)[0])(p)
            u, s = inner.update(g, s, p)
            p = optax.apply_updates(p, u)
        finals.append(p)
    expect = jax.tree.map(
        lambda *leaves: np.mean([np.asarray(v) for v in leaves], axis=0),
        *finals,
    )
    got = jax.tree.map(np.asarray, state.params)
    for k in ("w", "b"):
        np.testing.assert_allclose(got[k], expect[k], rtol=1e-5, atol=1e-6)
