"""The staged accelerator probe (tools/probe_tpu.py — round-5 VERDICT
ask #1: diagnose probe failures instead of enduring them).

The probe's value is its VERDICT taxonomy: relay_down (tunnel endpoint
refuses — the round-4 wedge), cpu_only (init succeeded but no
accelerator — must NOT count as chip_up, or the watcher burns the
round's budget capturing CPU numbers), chip_up, init_hang. These tests
pin the taxonomy against controlled endpoints; no accelerator needed."""

import json
import os
import socket
import sys
import threading

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
)
import probe_tpu  # noqa: E402


@pytest.fixture
def log_dir(tmp_path, monkeypatch):
    monkeypatch.setattr(probe_tpu, "LOG_DIR", str(tmp_path))
    return tmp_path


def test_relay_down_is_fast_and_diagnosed(log_dir, monkeypatch):
    """Nothing listening on the relay ports: verdict relay_down, no
    backend-init attempt (the probe must stay ~2 s when the tunnel is
    dead), record appended to probes.jsonl."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    # Ports chosen free-by-construction: bind-then-close.
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    monkeypatch.setattr(probe_tpu, "RELAY_PORTS", (free,))
    called = []
    monkeypatch.setattr(probe_tpu, "_init_check",
                        lambda t: called.append(t) or {})
    rec = probe_tpu.probe(5)
    assert rec["verdict"] == "relay_down"
    assert "refuse" in rec["diagnosis"]
    assert not called, "init must not be attempted past a dead relay"
    lines = open(os.path.join(str(log_dir), "probes.jsonl")).readlines()
    assert json.loads(lines[-1])["verdict"] == "relay_down"


def test_relay_up_attempts_init_and_cpu_is_not_a_chip(log_dir, monkeypatch):
    """A live endpoint moves the probe to the init stage; an init that
    reaches only the CPU backend is classified cpu_only (exit 2-vs-0
    taxonomy the chip watcher keys on)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    stop = threading.Event()

    def accept_loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                c.close()
            except OSError:
                pass

    t = threading.Thread(target=accept_loop, daemon=True)
    t.start()
    try:
        monkeypatch.setattr(
            probe_tpu, "RELAY_PORTS", (srv.getsockname()[1],))
        monkeypatch.setattr(
            probe_tpu, "_init_check",
            lambda timeout: {"stage": "backend_init", "ok": True,
                             "platform": "cpu", "kind": "cpu", "n": 8},
        )
        rec = probe_tpu.probe(5)
        assert rec["verdict"] == "cpu_only"

        monkeypatch.setattr(
            probe_tpu, "_init_check",
            lambda timeout: {"stage": "backend_init", "ok": True,
                             "platform": "tpu", "kind": "TPU v5e", "n": 1},
        )
        rec = probe_tpu.probe(5)
        assert rec["verdict"] == "chip_up"

        monkeypatch.setattr(
            probe_tpu, "_init_check",
            lambda timeout: {"stage": "backend_init", "ok": False,
                             "hung": True, "timeout_s": 5},
        )
        rec = probe_tpu.probe(5)
        assert rec["verdict"] == "init_hang"
        assert "past the tunnel" in rec["diagnosis"]
    finally:
        stop.set()
        srv.close()


def test_no_tunnel_env_goes_straight_to_init(log_dir, monkeypatch):
    """Without the tunnel fingerprint (a direct-libtpu TPU VM, a GPU
    box) the TCP short-circuit must NOT gate init — the code-review
    finding that the relay check only applies behind the loopback
    tunnel."""
    monkeypatch.delenv("AXON_LOOPBACK_RELAY", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setattr(
        probe_tpu, "_init_check",
        lambda timeout: {"stage": "backend_init", "ok": True,
                         "platform": "tpu", "kind": "TPU v4", "n": 4},
    )
    rec = probe_tpu.probe(5)
    assert rec["verdict"] == "chip_up"
    assert "relay" not in rec


def test_tail_records_and_latest(log_dir, monkeypatch):
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    monkeypatch.setattr(probe_tpu, "RELAY_PORTS", (free,))
    for _ in range(3):
        probe_tpu.probe(5)
    assert len(probe_tpu.tail_records(2)) == 2
    assert probe_tpu.latest_record()["verdict"] == "relay_down"


def test_probe_records_carry_schema_version(log_dir, monkeypatch):
    """ISSUE 2 satellite: every probes.jsonl record names its schema
    version so future consumers can evolve the format safely (records
    predating the field are implicitly version 0)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    monkeypatch.setattr(probe_tpu, "RELAY_PORTS", (free,))
    rec = probe_tpu.probe(5)
    assert rec["schema"] == probe_tpu.PROBE_SCHEMA == 1
    persisted = json.loads(
        open(os.path.join(str(log_dir), "probes.jsonl")).readlines()[-1]
    )
    assert persisted["schema"] == probe_tpu.PROBE_SCHEMA


def test_log_write_failure_never_vetoes_the_result(monkeypatch):
    """The diagnostic side channel is best-effort: an unwritable log dir
    must not turn a chip_up into an exception (code-review finding)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setattr(probe_tpu, "LOG_DIR", "/proc/definitely/not/writable")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    monkeypatch.setattr(probe_tpu, "RELAY_PORTS", (free,))
    rec = probe_tpu.probe(5)
    assert rec["verdict"] == "relay_down"
