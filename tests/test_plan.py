"""ParallelPlan (ISSUE 10): one global-view mesh program for
DP x TP x ZeRO x pipeline.

Acceptance is structural, per the repo convention:

- dist == single VALUES AND GRADIENTS for every composed plan (gradients
  certified through the first sgd step's delta, values through multi-step
  adam trajectories);
- the compiled plan step carries exactly the hand-wired paths' HLO
  collective counts (the ppermute-count convention);
- buffer donation pinned in XLA's own input_output_alias table — a
  second step re-uploads nothing;
- the jit cache stays pinned at 1 across steps.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel.mesh import best_mesh_shape
from chainermn_tpu.parallel.plan import ParallelPlan, PipelinePlanSpec
from chainermn_tpu.parallel.tensor import stack_tp_params, tp_mlp


def _devices():
    return jax.devices("cpu")[:8]


# ---------------------------------------------------------------------------
# Satellite: best_mesh_shape past the 2-dim wall
# ---------------------------------------------------------------------------


class TestBestMeshShape:
    def test_two_dim_unchanged(self):
        assert best_mesh_shape(8, 2) == (4, 2)
        assert best_mesh_shape(16, 2) == (4, 4)
        assert best_mesh_shape(6, 2) == (3, 2)
        assert best_mesh_shape(7, 2) == (7, 1)
        assert best_mesh_shape(12, 2) == (4, 3)

    def test_n_dim_balanced_larger_first(self):
        assert best_mesh_shape(8, 3) == (2, 2, 2)
        assert best_mesh_shape(16, 3) == (4, 2, 2)
        assert best_mesh_shape(12, 3) == (3, 2, 2)
        assert best_mesh_shape(24, 4) == (3, 2, 2, 2)
        assert best_mesh_shape(64, 3) == (4, 4, 4)
        assert best_mesh_shape(7, 3) == (7, 1, 1)
        assert best_mesh_shape(1, 3) == (1, 1, 1)

    def test_one_dim_and_errors(self):
        assert best_mesh_shape(5, 1) == (5,)
        with pytest.raises(ValueError):
            best_mesh_shape(8, 0)
        with pytest.raises(ValueError):
            best_mesh_shape(0, 2)

    def test_covers_device_count(self):
        import math

        for n in (4, 8, 12, 30, 36):
            for k in (2, 3, 4):
                assert math.prod(best_mesh_shape(n, k)) == n


# ---------------------------------------------------------------------------
# Spec providers
# ---------------------------------------------------------------------------


class TestSpecProviders:
    def test_modules_publish_their_axis(self):
        from chainermn_tpu.parallel.pipeline import pipe_plan_axis
        from chainermn_tpu.parallel.tensor import tp_plan_axis
        from chainermn_tpu.parallel.zero import zero_plan_axis

        assert tp_plan_axis()["collectives"] == ("all-reduce",)
        assert tp_plan_axis()["stacked"] is True
        assert zero_plan_axis()["collectives"] == (
            "reduce-scatter", "all-gather",
        )
        assert zero_plan_axis()["state_stacked"] is True
        assert pipe_plan_axis()["collectives"] == ("collective-permute",)

    def test_describe_aggregates_owed_collectives(self):
        plan = ParallelPlan(("data", "model", "zero"), devices=_devices())
        desc = plan.describe()
        assert desc["mesh"] == {"data": 2, "zero": 2, "model": 2}
        assert desc["collectives"]["zero"] == (
            "reduce-scatter", "all-gather",
        )
        assert desc["collectives"]["model"] == ("all-reduce",)

    def test_auto_factorisation_uses_canonical_order(self):
        # larger factor lands on the first canonical (DCN-most) axis,
        # regardless of the order the names were spelled in
        plan = ParallelPlan(("model", "data"), devices=_devices())
        assert plan.axis_size("data") == 4
        assert plan.axis_size("model") == 2
        assert tuple(plan.mesh.axis_names) == ("data", "model")

    def test_explicit_sizes_and_inference(self):
        plan = ParallelPlan({"data": 2, "zero": -1}, devices=_devices())
        assert plan.axis_size("zero") == 4
        with pytest.raises(ValueError, match="cover"):
            ParallelPlan({"data": 3}, devices=_devices())
        with pytest.raises(ValueError, match="data"):
            ParallelPlan(("data", "data"), devices=_devices())
        with pytest.raises(ValueError, match="subset"):
            ParallelPlan({"tower": 8}, devices=_devices())
        # 'expert' became a first-class axis in ISSUE 20
        assert ParallelPlan(
            {"expert": 8}, devices=_devices()
        ).axis_size("expert") == 8

    def test_param_spec_validation(self):
        plan = ParallelPlan({"data": 4, "model": 2}, devices=_devices())
        params = {"w": jnp.zeros((2, 4, 4)), "b": jnp.zeros((4,))}
        full = plan.param_specs(params, {"w": P("model"), "b": P()})
        assert full["w"] == P("model") and full["b"] == P()
        with pytest.raises(ValueError, match="stacked axes"):
            plan.param_specs(params, {"w": P("data"), "b": P()})
        with pytest.raises(ValueError, match="leading dim"):
            plan.param_specs({"w": jnp.zeros((3, 4)), "b": params["b"]},
                             {"w": P("model"), "b": P()})
        with pytest.raises(ValueError, match="leading-stack"):
            plan.param_specs(params, {"w": P(None, "model"), "b": P()})


# ---------------------------------------------------------------------------
# dist == single, values AND gradients, for every composed plan
# ---------------------------------------------------------------------------


def _mlp_params(key, d=8, d_ff=8):
    ks = jax.random.split(key, 3)
    return (
        jax.random.normal(ks[0], (d, d_ff)) * 0.3,
        jax.random.normal(ks[1], (d_ff, d)) * 0.3,
        jnp.zeros((d,)),
    )


def _ref_loss(w1, w2, b2, x, y):
    return jnp.mean((jax.nn.gelu(x @ w1) @ w2 + b2 - y) ** 2)


def _run_ref(inner, w1, w2, b2, x, y, steps):
    p = {"w1": w1, "w2": w2, "b2": b2}
    st = inner.init(p)
    losses, grads0 = [], None
    for i in range(steps):
        l, g = jax.value_and_grad(
            lambda p: _ref_loss(p["w1"], p["w2"], p["b2"], x, y)
        )(p)
        if i == 0:
            grads0 = g
        u, st = inner.update(g, st, p)
        p = optax.apply_updates(p, u)
        losses.append(float(l))
    return p, losses, grads0


class TestPlanEquivalence:
    def _drive(self, plan, inner, params, specs, loss_fn, x, y, steps):
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        losses = []
        for _ in range(steps):
            state, m = step(state, (x, y))
            losses.append(float(m["loss"]))
        return state, losses, step

    def test_dp_zero_values_and_grads(self):
        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 8))
        params = {"w1": w1, "w2": w2, "b2": b2}

        def loss_fn(p, batch):
            xb, yb = batch
            return _ref_loss(p["w1"], p["w2"], p["b2"], xb, yb)

        plan = ParallelPlan({"data": 2, "zero": 4}, devices=_devices())

        # values: 3 adam steps
        inner = optax.adamw(1e-2)
        state, losses, _ = self._drive(
            plan, inner, params, None, loss_fn, x, y, 3
        )
        _, ref_losses, _ = _run_ref(inner, w1, w2, b2, x, y, 3)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)

        # gradients: one sgd step, delta / lr == grad
        lr = 0.1
        state, _, _ = self._drive(
            plan, optax.sgd(lr), params, None, loss_fn, x, y, 1
        )
        _, _, g0 = _run_ref(optax.sgd(lr), w1, w2, b2, x, y, 1)
        for k in ("w1", "w2", "b2"):
            got = (np.asarray(params[k])
                   - np.asarray(jax.device_get(state.params[k]))) / lr
            np.testing.assert_allclose(got, np.asarray(g0[k]),
                                       rtol=1e-4, atol=1e-6)

    def test_dp_tp_zero_values_and_grads(self):
        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(3))
        x = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(5), (16, 8))
        plan = ParallelPlan(("data", "model", "zero"), devices=_devices())
        m = plan.axis_size("model")
        params = {
            "w1": stack_tp_params(w1, m, 1),
            "w2": stack_tp_params(w2, m, 0),
            "b2": b2,
        }
        specs = {"w1": P("model"), "w2": P("model"), "b2": P()}

        def loss_fn(p, batch):
            xb, yb = batch
            out = tp_mlp(xb, p["w1"], None, p["w2"], p["b2"],
                         axis_name="model")
            return jnp.mean((out - yb) ** 2)

        inner = optax.adamw(1e-2)
        state, losses, step = self._drive(
            plan, inner, params, specs, loss_fn, x, y, 3
        )
        ref_p, ref_losses, _ = _run_ref(inner, w1, w2, b2, x, y, 3)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
        # values: reassemble the TP shards and compare every leaf
        w1_dist = np.concatenate(
            list(np.asarray(jax.device_get(state.params["w1"]))), axis=-1
        )
        w2_dist = np.concatenate(
            list(np.asarray(jax.device_get(state.params["w2"]))), axis=0
        )
        np.testing.assert_allclose(w1_dist, np.asarray(ref_p["w1"]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w2_dist, np.asarray(ref_p["w2"]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(state.params["b2"])),
            np.asarray(ref_p["b2"]), rtol=1e-4, atol=1e-5,
        )
        # the jit cache stayed pinned across the trajectory
        assert step.cache_size() in (None, 1)

        # gradients via the sgd delta
        lr = 0.1
        state, _, _ = self._drive(
            plan, optax.sgd(lr), params, specs, loss_fn, x, y, 1
        )
        _, _, g0 = _run_ref(optax.sgd(lr), w1, w2, b2, x, y, 1)
        w1_after = np.concatenate(
            list(np.asarray(jax.device_get(state.params["w1"]))), axis=-1
        )
        np.testing.assert_allclose(
            (np.asarray(w1) - w1_after) / lr, np.asarray(g0["w1"]),
            rtol=1e-4, atol=1e-6,
        )
        b2_after = np.asarray(jax.device_get(state.params["b2"]))
        np.testing.assert_allclose(
            (np.asarray(b2) - b2_after) / lr, np.asarray(g0["b2"]),
            rtol=1e-4, atol=1e-6,
        )

    def test_dp_pipe_values_and_grads(self):
        d, n_pipe = 8, 4
        plan = ParallelPlan({"data": 2, "pipe": n_pipe},
                            devices=_devices())
        keys = jax.random.split(jax.random.PRNGKey(6), n_pipe)
        stages = jnp.stack(
            [jax.random.normal(k, (d, d)) * 0.4 for k in keys]
        )
        params = {"w": stages}
        x = jax.random.normal(jax.random.PRNGKey(7), (16, d))
        y = jax.random.normal(jax.random.PRNGKey(8), (16, d))

        pipe = PipelinePlanSpec(
            stage_fn=lambda p, mb: jnp.tanh(mb @ p["w"]),
            loss_fn=lambda yh, b: jnp.mean((yh - b[1]) ** 2),
            n_microbatches=n_pipe,
        )
        lr = 0.1
        state = plan.create_train_state(params, optax.sgd(lr),
                                        param_specs={"w": P("pipe")})
        step = plan.compile_train_step(None, optax.sgd(lr), params,
                                       param_specs={"w": P("pipe")},
                                       pipeline=pipe)
        state, m = step(state, (x, y))

        def seq_loss(ws, xb, yb):
            h = xb
            for w in ws:
                h = jnp.tanh(h @ w)
            return jnp.mean((h - yb) ** 2)

        wlist = [stages[i] for i in range(n_pipe)]
        ref_l, ref_g = jax.value_and_grad(seq_loss)(wlist, x, y)
        np.testing.assert_allclose(float(m["loss"]), float(ref_l),
                                   rtol=1e-5)
        new_w = np.asarray(jax.device_get(state.params["w"]))
        for i in range(n_pipe):
            np.testing.assert_allclose(
                (np.asarray(stages[i]) - new_w[i]) / lr,
                np.asarray(ref_g[i]), rtol=1e-4, atol=1e-6,
            )

    def test_pipe_plan_rejects_replicated_trainable_leaves(self):
        """A replicated leaf consumed inside stage_fn would get
        per-stage gradients with no cross-stage sum (and check_vma=False
        would mask the divergence) — the contract is enforced
        structurally, not by docstring."""
        plan = ParallelPlan({"data": 2, "pipe": 4}, devices=_devices())
        params = {"w": jnp.zeros((4, 4, 4)), "b": jnp.zeros((4,))}
        pipe = PipelinePlanSpec(
            stage_fn=lambda p, mb: jnp.tanh(mb @ p["w"] + p["b"]),
            loss_fn=lambda yh, b: jnp.mean(yh ** 2),
            n_microbatches=4,
        )
        with pytest.raises(ValueError, match="pipe-stacked"):
            plan.compile_train_step(
                None, optax.sgd(0.1), params,
                param_specs={"w": P("pipe"), "b": P()}, pipeline=pipe,
            )

    def test_pipe_axis_requires_pipeline_spec(self):
        plan = ParallelPlan({"pipe": 8}, devices=_devices())
        with pytest.raises(ValueError, match="PipelinePlanSpec"):
            plan.compile_train_step(lambda p, b: 0.0, optax.sgd(0.1),
                                    {"w": jnp.zeros((8, 2, 2))})
        plan2 = ParallelPlan({"data": 8}, devices=_devices())
        with pytest.raises(ValueError, match="no 'pipe' axis"):
            plan2.compile_train_step(
                None, optax.sgd(0.1), {"w": jnp.zeros((2, 2))},
                pipeline=PipelinePlanSpec(
                    stage_fn=lambda p, x: x, loss_fn=lambda y, b: 0.0
                ),
            )


# ---------------------------------------------------------------------------
# Structural: HLO collective counts == the hand-wired paths'
# ---------------------------------------------------------------------------


def _collective_counts(txt: str) -> dict:
    return {op: txt.count(op) for op in
            ("all-reduce(", "reduce-scatter(", "all-gather(",
             "collective-permute(")}


class TestPlanStructural:
    def test_dp_tp_zero_counts_match_handwired(self):
        """The acceptance pin: one compiled DP x TP x ZeRO plan step
        carries exactly the collective counts of the same step hand-wired
        from the pre-plan modules (tensor helpers + zero_shard_optimizer
        + call-site pmeans)."""
        from jax import shard_map
        from chainermn_tpu.parallel.zero import zero_shard_optimizer

        devices = _devices()
        plan = ParallelPlan(("data", "model", "zero"), devices=devices)
        m = plan.axis_size("model")
        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(0))
        params = {
            "w1": stack_tp_params(w1, m, 1),
            "w2": stack_tp_params(w2, m, 0),
            "b2": b2,
        }
        specs = {"w1": P("model"), "w2": P("model"), "b2": P()}
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 8))
        lr = 0.1

        def loss_fn(p, batch):
            xb, yb = batch
            out = tp_mlp(xb, p["w1"], None, p["w2"], p["b2"],
                         axis_name="model")
            return jnp.mean((out - yb) ** 2)

        inner = optax.sgd(lr)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        plan_counts = _collective_counts(
            step.lower(state, (x, y)).compile().as_text()
        )

        # hand-wired: the composition a user wrote before the plan
        mesh = plan.mesh

        def hand_local(params, batch):
            p = {
                "w1": params["w1"][0],
                "w2": params["w2"][0],
                "b2": params["b2"],
            }
            loss, g = jax.value_and_grad(loss_fn)(p, batch)
            # TP leaves: grads average over BOTH data-parallel axes
            gtp = jax.lax.pmean({"w1": g["w1"], "w2": g["w2"]},
                                ("data", "zero"))
            # replicated leaves: data-mean, then the zero wrapper's
            # scatter/update/gather over the zero axis
            grep = {"b2": jax.lax.pmean(g["b2"], ("data",))}
            zopt = zero_shard_optimizer(optax.sgd(lr), "zero")
            zstate = zopt.init({"b2": p["b2"]})
            urep, _ = zopt.update(grep, zstate, {"b2": p["b2"]})
            new = {
                "w1": (p["w1"] - lr * gtp["w1"])[None],
                "w2": (p["w2"] - lr * gtp["w2"])[None],
                "b2": p["b2"] + urep["b2"],
            }
            return new, jax.lax.pmean(loss, ("data", "zero"))

        pspec = {"w1": P("model"), "w2": P("model"), "b2": P()}
        hand = jax.jit(shard_map(
            hand_local, mesh=mesh,
            in_specs=(pspec, P(("data", "zero"))),
            out_specs=(pspec, P()),
            check_vma=False,
        ))
        hand_counts = _collective_counts(
            hand.lower(params, (x, y)).compile().as_text()
        )
        assert plan_counts == hand_counts, (plan_counts, hand_counts)
        # and the vocabulary is what the providers owe: TP's psums +
        # zero's scatter/gather are all present, no ppermute
        assert plan_counts["reduce-scatter("] >= 1
        assert plan_counts["all-gather("] >= 1
        assert plan_counts["all-reduce("] >= 2
        assert plan_counts["collective-permute("] == 0

    def test_pipe_counts_match_handwired(self):
        from jax import shard_map
        from chainermn_tpu.parallel.pipeline import pipeline_local

        devices = _devices()
        d, n_pipe = 8, 4
        plan = ParallelPlan({"data": 2, "pipe": n_pipe}, devices=devices)
        stages = jnp.stack([jnp.eye(d) * 0.5 for _ in range(n_pipe)])
        params = {"w": stages}
        x = jax.random.normal(jax.random.PRNGKey(0), (16, d))
        y = jnp.zeros_like(x)
        lr = 0.1
        pipe = PipelinePlanSpec(
            stage_fn=lambda p, mb: jnp.tanh(mb @ p["w"]),
            loss_fn=lambda yh, b: jnp.mean((yh - b[1]) ** 2),
            n_microbatches=n_pipe,
        )
        state = plan.create_train_state(params, optax.sgd(lr),
                                        param_specs={"w": P("pipe")})
        step = plan.compile_train_step(None, optax.sgd(lr), params,
                                       param_specs={"w": P("pipe")},
                                       pipeline=pipe)
        plan_counts = _collective_counts(
            step.lower(state, (x, y)).compile().as_text()
        )

        def hand_local(params, batch):
            xb, yb = batch
            w = {"w": params["w"][0]}

            def loss(w):
                xm = xb.reshape((n_pipe, xb.shape[0] // n_pipe, d))
                ym = pipeline_local(
                    lambda p, mb: jnp.tanh(mb @ p["w"]), w, xm, "pipe"
                )
                yh = ym.reshape(xb.shape)
                return jnp.mean((yh - yb) ** 2)

            l, g = jax.value_and_grad(loss)(w)
            g = jax.lax.pmean(g, ("data",))
            return ({"w": (w["w"] - lr * g["w"])[None]},
                    jax.lax.pmean(l, ("data",)))

        hand = jax.jit(shard_map(
            hand_local, mesh=plan.mesh,
            in_specs=({"w": P("pipe")}, P(("data",))),
            out_specs=({"w": P("pipe")}, P()),
            check_vma=False,
        ))
        hand_counts = _collective_counts(
            hand.lower(params, (x, y)).compile().as_text()
        )
        assert plan_counts["collective-permute("] == \
            hand_counts["collective-permute("] >= 1

    def test_step_donates_every_state_buffer(self):
        """Satellite: compiled plan step donates params/opt-state buffers
        (XLA's own input_output_alias table), and a second step re-uploads
        nothing — the donated first-step buffers are consumed in place."""
        devices = _devices()
        plan = ParallelPlan({"data": 2, "zero": 4}, devices=devices)
        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(0))
        params = {"w1": w1, "w2": w2, "b2": b2}
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 8))

        def loss_fn(p, batch):
            xb, yb = batch
            return _ref_loss(p["w1"], p["w2"], p["b2"], xb, yb)

        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner)
        step = plan.compile_train_step(loss_fn, inner, params)
        txt = step.lower(state, (x, y)).compile().as_text()
        n_state_leaves = len(jax.tree.leaves(state))
        assert "input_output_alias" in txt
        n_alias = txt.count("may-alias") + txt.count("must-alias")
        assert n_alias >= n_state_leaves, (n_alias, n_state_leaves)

        # behavioural pin: after a step, every input state buffer is
        # consumed (donated) — nothing left to re-upload
        old = state
        state, _ = step(state, (x, y))
        assert all(l.is_deleted() for l in jax.tree.leaves(old))
        # and the batch was NOT donated
        assert not x.is_deleted()

        # donate=False: no aliasing, inputs stay live
        step_nd = plan.compile_train_step(loss_fn, inner, params,
                                          donate=False)
        txt_nd = step_nd.lower(state, (x, y)).compile().as_text()
        assert (txt_nd.count("may-alias") + txt_nd.count("must-alias")
                == 0)

    def test_jit_cache_pinned_at_one(self):
        devices = _devices()
        plan = ParallelPlan({"zero": 8}, devices=devices)
        params = {"w": jnp.ones((8, 8)) * 0.1}
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))

        def loss_fn(p, batch):
            return jnp.mean((batch @ p["w"]) ** 2)

        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner)
        step = plan.compile_train_step(loss_fn, inner, params)
        for _ in range(3):
            state, m = step(state, x)
        assert step.cache_size() in (None, 1)
        assert np.isfinite(float(m["loss"]))

    def test_zero_state_is_sharded_and_one_nth(self):
        devices = _devices()
        plan = ParallelPlan({"zero": 8}, devices=devices)
        params = {"w": jnp.ones((64, 8)) * 0.1}
        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner)
        leaves = jax.tree.leaves(state.opt_state["zero"])
        assert leaves, "zero group state missing"
        for leaf in leaves:
            assert leaf.shape[0] == 8  # stacked [n, ...]
            assert "zero" in tuple(leaf.sharding.spec)
            # per-device bytes = 1/n of the stacked whole
            shard = leaf.addressable_shards[0].data
            assert shard.size * 8 == leaf.size


# ---------------------------------------------------------------------------
# ISSUE 13 sweep-ins: TP x ZeRO stacked-group state + pipe x model specs
# ---------------------------------------------------------------------------


class TestZeroStackedGroups:
    """``zero_stacked_groups=True``: the stacked groups' optimizer state
    chunks over the zero axis too (arXiv:2004.13336 applied per TP
    shard) — dist == single values AND grads, state 1/z per shard,
    and the stacked groups' dp reduction becomes the zero composition's
    rs/ag (pinned in the compiled HLO)."""

    def _workload(self):
        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(3))
        x = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(5), (16, 8))

        def loss_fn(p, batch):
            xb, yb = batch
            out = tp_mlp(xb, p["w1"], None, p["w2"], p["b2"],
                         axis_name="model")
            return jnp.mean((out - yb) ** 2)

        return w1, w2, b2, x, y, loss_fn

    def _plan_and_params(self, w1, w2, b2):
        plan = ParallelPlan(("data", "model", "zero"),
                            devices=_devices(), zero_stacked_groups=True)
        m = plan.axis_size("model")
        params = {
            "w1": stack_tp_params(w1, m, 1),
            "w2": stack_tp_params(w2, m, 0),
            "b2": b2,
        }
        specs = {"w1": P("model"), "w2": P("model"), "b2": P()}
        return plan, params, specs

    def test_values_and_grads_match_reference(self):
        w1, w2, b2, x, y, loss_fn = self._workload()
        plan, params, specs = self._plan_and_params(w1, w2, b2)

        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        losses = []
        for _ in range(3):
            state, m = step(state, (x, y))
            losses.append(float(m["loss"]))
        _, ref_losses, _ = _run_ref(inner, w1, w2, b2, x, y, 3)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5,
                                   atol=1e-6)
        assert step.cache_size() in (None, 1)

        lr = 0.1
        state = plan.create_train_state(params, optax.sgd(lr),
                                        param_specs=specs)
        step = plan.compile_train_step(loss_fn, optax.sgd(lr), params,
                                       param_specs=specs)
        state, _ = step(state, (x, y))
        _, _, g0 = _run_ref(optax.sgd(lr), w1, w2, b2, x, y, 1)
        w1_after = np.concatenate(
            list(np.asarray(jax.device_get(state.params["w1"]))), axis=-1
        )
        np.testing.assert_allclose(
            (np.asarray(w1) - w1_after) / lr, np.asarray(g0["w1"]),
            rtol=1e-4, atol=1e-6,
        )

    def test_state_layout_and_hlo(self):
        """Model-group state leaves stack [m, z, ...] with
        P('model', 'zero'), per-device bytes 1/(m*z); the compiled step
        carries one rs + one ag per FLOAT LEAF (TP leaves now included
        — that is the feature) and no ppermute."""
        w1, w2, b2, x, y, loss_fn = self._workload()
        plan, params, specs = self._plan_and_params(w1, w2, b2)
        desc = plan.describe()
        assert desc["zero_stacked_groups"] is True
        inner = optax.adamw(1e-2)
        state = plan.create_train_state(params, inner, param_specs=specs)
        z = plan.axis_size("zero")
        m = plan.axis_size("model")
        for leaf in jax.tree.leaves(state.opt_state["model"]):
            assert leaf.shape[:2] == (m, z), leaf.shape
            assert tuple(leaf.sharding.spec)[:2] == ("model", "zero")
            shard = leaf.addressable_shards[0].data
            assert shard.size * m * z == leaf.size
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        txt = step.lower(state, (x, y)).compile().as_text()
        counts = _collective_counts(txt)
        # per-leaf rs/ag for w1, w2 (model group) AND b2 (zero group):
        # the stacked groups joined the zero pipeline
        assert counts["reduce-scatter("] == 3, counts
        assert counts["all-gather("] == 3, counts
        assert counts["collective-permute("] == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="zero"):
            ParallelPlan({"data": 4, "model": 2},
                         devices=_devices(), zero_stacked_groups=True)
        with pytest.raises(ValueError, match="stacked axis"):
            ParallelPlan({"data": 2, "zero": 4},
                         devices=_devices(), zero_stacked_groups=True)
        with pytest.raises(ValueError, match="mutually exclusive"):
            ParallelPlan({"data": 2, "zero": 2, "model": 2},
                         devices=_devices(), zero_stacked_groups=True,
                         grad_reduction="flat")


class TestPipeModelComposed:
    """``P('pipe', 'model')`` leaves: stage slices that are themselves
    tensor-parallel — the composed plan the PR 9 follow-up named.
    dist == single values AND grads through the one compiled step."""

    def test_values_and_grads(self):
        d, n_pipe, n_tp = 8, 2, 2
        plan = ParallelPlan({"data": 2, "pipe": n_pipe, "model": n_tp},
                            devices=_devices())
        keys = jax.random.split(jax.random.PRNGKey(6), n_pipe)
        stage_w = [jax.random.normal(k, (d, d)) * 0.4 for k in keys]
        pw = jnp.stack([stack_tp_params(w, n_tp, 1) for w in stage_w])
        params = {"w": pw}  # [pipe, model, d, d/n_tp]
        from chainermn_tpu.parallel.tensor import (
            copy_to_tp,
            gather_from_tp,
        )

        def stage_fn(p, mb):
            h = copy_to_tp(mb, "model") @ p["w"]  # column-parallel
            h = gather_from_tp(h, "model", 1)
            return jnp.tanh(h)

        pipe = PipelinePlanSpec(
            stage_fn=stage_fn,
            loss_fn=lambda yh, b: jnp.mean((yh - b[1]) ** 2),
            n_microbatches=n_pipe,
        )
        lr = 0.1
        state = plan.create_train_state(
            params, optax.sgd(lr), param_specs={"w": P("pipe", "model")}
        )
        step = plan.compile_train_step(
            None, optax.sgd(lr), params,
            param_specs={"w": P("pipe", "model")}, pipeline=pipe,
        )
        x = jax.random.normal(jax.random.PRNGKey(7), (8, d))
        y = jax.random.normal(jax.random.PRNGKey(8), (8, d))
        state, m = step(state, (x, y))

        def seq_loss(ws, xb, yb):
            h = xb
            for w in ws:
                h = jnp.tanh(h @ w)
            return jnp.mean((h - yb) ** 2)

        ref_l, ref_g = jax.value_and_grad(seq_loss)(stage_w, x, y)
        np.testing.assert_allclose(float(m["loss"]), float(ref_l),
                                   rtol=1e-5)
        new_w = np.asarray(jax.device_get(state.params["w"]))
        for i in range(n_pipe):
            full_after = np.concatenate(list(new_w[i]), axis=-1)
            np.testing.assert_allclose(
                (np.asarray(stage_w[i]) - full_after) / lr,
                np.asarray(ref_g[i]), rtol=1e-4, atol=1e-6,
            )
        assert step.cache_size() in (None, 1)
        assert "pipe+model" in state.opt_state
        # state mirrors the double stack (adam: non-empty state leaves)
        adam_state = plan.create_train_state(
            params, optax.adamw(1e-2),
            param_specs={"w": P("pipe", "model")},
        )
        leaf = jax.tree.leaves(adam_state.opt_state["pipe+model"])[0]
        assert leaf.shape[:2] == (n_pipe, n_tp)
        assert tuple(leaf.sharding.spec)[:2] == ("pipe", "model")

    def test_spec_validation(self):
        plan = ParallelPlan({"data": 2, "pipe": 2, "model": 2},
                            devices=_devices())
        params = {"w": jnp.zeros((2, 2, 4, 4))}
        full = plan.param_specs(params, {"w": P("pipe", "model")})
        assert full["w"] == P("pipe", "model")
        # non-canonical order rejected
        with pytest.raises(ValueError, match="canonical order"):
            plan.param_specs(params, {"w": P("model", "pipe")})
        # each leading dim checked against its axis
        with pytest.raises(ValueError, match="leading dim"):
            plan.param_specs({"w": jnp.zeros((2, 3, 4))},
                             {"w": P("pipe", "model")})


# ---------------------------------------------------------------------------
# Satellite: checkpoint round-trip over a plan-sharded [n, ...] ZeRO state
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_plan_zero_state(comm, tmp_path):
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )

    devices = _devices()
    plan = ParallelPlan({"data": 2, "zero": 4}, devices=devices)
    w1, w2, b2 = _mlp_params(jax.random.PRNGKey(0))
    params = {"w1": w1, "w2": w2, "b2": b2}
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
    y = jax.random.normal(jax.random.PRNGKey(2), (16, 8))

    def loss_fn(p, batch):
        xb, yb = batch
        return _ref_loss(p["w1"], p["w2"], p["b2"], xb, yb)

    inner = optax.adamw(1e-2)
    state = plan.create_train_state(params, inner)
    step = plan.compile_train_step(loss_fn, inner, params)
    state, _ = step(state, (x, y))

    ckpt = create_multi_node_checkpointer(
        "plan", comm, path=str(tmp_path)
    )
    ckpt.save(state, 1)

    template = plan.create_train_state(params, inner)
    restored, it = ckpt.maybe_load(template)
    assert it == 1
    # restored zero-state leaves keep the stacked [n, ...] layout
    for a, b in zip(jax.tree.leaves(restored.opt_state["zero"]),
                    jax.tree.leaves(state.opt_state["zero"])):
        assert np.shape(a) == np.shape(b)

    # one more step from the restored state == one more from the live one
    s_live, m_live = step(state, (x, y))
    s_rest, m_rest = step(restored, (x, y))
    assert abs(float(m_live["loss"]) - float(m_rest["loss"])) < 1e-6
    for a, b in zip(jax.tree.leaves(jax.device_get(s_live.params)),
                    jax.tree.leaves(jax.device_get(s_rest.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# make_train_step integration + optimizer unwrap
# ---------------------------------------------------------------------------


class TestTrainerIntegration:
    def test_make_train_step_plan_path(self):
        from chainermn_tpu.training.train_step import make_train_step

        plan = ParallelPlan({"data": 2, "zero": 4}, devices=_devices())
        params = {"w": jnp.ones((8, 8)) * 0.1}
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))

        def loss_fn(p, batch):
            return jnp.mean((batch @ p["w"]) ** 2)

        inner = optax.adamw(1e-2)
        step = make_train_step(loss_fn, inner, plan=plan)
        state = plan.create_train_state(params, inner)
        for _ in range(2):
            state, m = step(state, x)
        assert np.isfinite(float(m["loss"]))
        assert step.cache_size() in (None, 1)

    def test_make_train_step_plan_rejects_comm_only_knobs(self):
        from chainermn_tpu.training.train_step import make_train_step

        plan = ParallelPlan({"data": 8}, devices=_devices())
        with pytest.raises(ValueError, match="accum_steps"):
            make_train_step(lambda p, b: 0.0, optax.sgd(0.1), plan=plan,
                            accum_steps=2)
        with pytest.raises(ValueError, match="communicator"):
            make_train_step(lambda p, b: 0.0, optax.sgd(0.1))
        with pytest.raises(ValueError, match="plan"):
            make_train_step(lambda p, b: 0.0, optax.sgd(0.1),
                            comm=None, param_specs={"w": P()})

    def test_make_train_step_pipe_plan_path(self):
        """The trainer delegation can express pipe plans: pipeline=
        threads through to the plan."""
        from chainermn_tpu.training.train_step import make_train_step

        d, n_pipe = 8, 4
        plan = ParallelPlan({"data": 2, "pipe": n_pipe},
                            devices=_devices())
        stages = jnp.stack([jnp.eye(d) * 0.5 for _ in range(n_pipe)])
        params = {"w": stages}
        pipe = PipelinePlanSpec(
            stage_fn=lambda p, mb: jnp.tanh(mb @ p["w"]),
            loss_fn=lambda yh, b: jnp.mean(yh ** 2),
            n_microbatches=n_pipe,
        )
        step = make_train_step(None, optax.sgd(0.1), plan=plan,
                               param_specs={"w": P("pipe")},
                               pipeline=pipe)
        state = plan.create_train_state(params, optax.sgd(0.1),
                                        param_specs={"w": P("pipe")})
        x = jax.random.normal(jax.random.PRNGKey(0), (16, d))
        state, m = step(state, x)
        assert np.isfinite(float(m["loss"]))

    def test_inner_transform_unwraps_and_refuses(self, comm):
        from chainermn_tpu.optimizers import (
            create_local_sgd,
            create_multi_node_optimizer,
            inner_transform,
        )

        sgd = optax.sgd(0.1)
        assert inner_transform(sgd) is sgd
        wrapped = create_multi_node_optimizer(sgd, comm)
        assert inner_transform(wrapped) is sgd
        with pytest.raises(ValueError, match="double_buffering"):
            inner_transform(create_multi_node_optimizer(
                sgd, comm, double_buffering=True))
        with pytest.raises(ValueError, match="LocalSGD"):
            inner_transform(create_local_sgd(sgd, comm, sync_every=4))
        # a configured compressed wire must not be dropped silently
        with pytest.raises(ValueError, match="compress"):
            inner_transform(create_multi_node_optimizer(
                sgd, comm, allreduce_grad_dtype=jnp.bfloat16))

    def test_plan_unwraps_wrapper_consistently(self, comm):
        """The documented migration flow: the user's existing
        MultiNodeOptimizer (even with reduction_schedule='zero') goes to
        BOTH create_train_state and the step — the plan unwraps it at
        every entry point, so the state layout matches the compiled
        step's specs instead of the wrapper's comm-sized chunking."""
        from chainermn_tpu.optimizers import create_multi_node_optimizer

        plan = ParallelPlan({"data": 2, "zero": 4}, devices=_devices())
        params = {"w": jnp.ones((8, 8)) * 0.1}
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))

        def loss_fn(p, batch):
            return jnp.mean((batch @ p["w"]) ** 2)

        wrapped = create_multi_node_optimizer(
            optax.adamw(1e-2), comm, reduction_schedule="zero"
        )
        state = plan.create_train_state(params, wrapped)
        step = plan.compile_train_step(loss_fn, wrapped, params)
        state, m = step(state, x)
        assert np.isfinite(float(m["loss"]))
        # state chunked by the PLAN's zero axis (4), not comm.size (8)
        lead = jax.tree.leaves(state.opt_state["zero"])[0].shape[0]
        assert lead == 4

    def test_make_train_step_plan_matches_comm_path(self, comm):
        """The delegation really is the same math: plan-compiled DP step
        == the communicator-path step on the same workload."""
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )
        from chainermn_tpu.optimizers import create_multi_node_optimizer

        w1, w2, b2 = _mlp_params(jax.random.PRNGKey(0))
        params = {"w1": w1, "w2": w2, "b2": b2}
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 8))

        def loss_fn(p, batch):
            xb, yb = batch
            return _ref_loss(p["w1"], p["w2"], p["b2"], xb, yb)

        inner = optax.adamw(1e-2)
        opt = create_multi_node_optimizer(inner, comm)
        c_state = create_train_state(
            jax.tree.map(lambda p: jnp.array(p, copy=True), params),
            opt, comm,
        )
        c_step = make_train_step(loss_fn, opt, comm, donate=False)

        plan = ParallelPlan({"data": 8}, devices=_devices())
        p_state = plan.create_train_state(params, inner)
        p_step = make_train_step(loss_fn, inner, plan=plan)

        for _ in range(2):
            c_state, cm = c_step(c_state, (x, y))
            p_state, pm = p_step(p_state, (x, y))
        assert abs(float(cm["loss"]) - float(pm["loss"])) < 1e-6
        for k in params:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(p_state.params[k])),
                np.asarray(jax.device_get(c_state.params[k])),
                rtol=1e-5, atol=1e-6,
            )


def test_dryrun_phase_table_wires_plan_phase():
    """Satellite: dryrun phase K is in __graft_entry__'s phase table."""
    src = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "__graft_entry__.py")).read()
    assert "_phase_parallel_plan" in src
    assert '"K:parallel-plan 3-D mesh", _phase_parallel_plan' in src
