"""Cross-rank function tests — analog of
``tests/function_tests/test_point_to_point_communication.py`` (dagger) and
``test_collective_communication.py`` (dagger) (SURVEY.md section 4): forward
values AND numerical gradient checks across ranks (backward of send is recv,
each collective pairs with its transpose).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator
from chainermn_tpu.functions import (
    allgather,
    allreduce,
    alltoall,
    bcast,
    gather,
    pseudo_connect,
    recv,
    scatter,
    send,
    send_recv,
)

N = 8
AX = "data"


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def _smap(comm, fn, *xs, in_spec=None, out_spec=None):
    """Run fn per-shard over stacked inputs [N, ...]."""
    in_spec = in_spec or P(AX)
    out_spec = out_spec or P(AX)

    def body(*locals_):
        squeezed = [l[0] for l in locals_]
        return fn(*squeezed)[None]

    return jax.jit(
        shard_map(
            body,
            mesh=comm.mesh,
            in_specs=tuple(in_spec for _ in xs),
            out_specs=out_spec,
            check_vma=False,
        )
    )(*xs)


def _grad_smap(comm, scalar_fn, x):
    """Gradient of sum-over-shards scalar_fn wrt stacked x."""

    def body(xl):
        def lf(xs):
            return scalar_fn(xs[0])

        val, g = jax.value_and_grad(lf)(xl)
        return jax.lax.psum(val, AX)[None], g

    return jax.jit(
        shard_map(
            body,
            mesh=comm.mesh,
            in_specs=P(AX),
            out_specs=(P(AX), P(AX)),
            check_vma=False,
        )
    )(x)


# ---------------------------------------------------------------------------
# point to point
# ---------------------------------------------------------------------------


def test_send_recv_forward(comm):
    x = np.arange(N, dtype=np.float32).reshape(N, 1) + 1  # shard i holds i+1
    out = np.asarray(_smap(comm, lambda v: send_recv(v, 2, 5, AX), x))
    want = np.zeros((N, 1), np.float32)
    want[5] = 3.0  # shard 5 received shard 2's value
    np.testing.assert_array_equal(out, want)


def test_send_recv_backward_flows_dst_to_src(comm):
    x = jnp.ones((N, 1), jnp.float32)

    def scalar(v):
        y = send_recv(v, 2, 5, AX)
        return jnp.sum(y * 7.0)  # loss lives on shard 5

    _, g = _grad_smap(comm, scalar, x)
    g = np.asarray(g)
    want = np.zeros((N, 1), np.float32)
    want[2] = 7.0  # cotangent returned to the sender
    np.testing.assert_array_equal(g, want)


def test_send_returns_delegate_and_recv_unwraps(comm):
    x = np.arange(N, dtype=np.float32).reshape(N, 1)

    def fn(v):
        received, delegate = send(v, dst=4, axis_name=AX, src=1)
        return recv(received, delegate=delegate)

    out = np.asarray(_smap(comm, fn, x))
    want = np.zeros((N, 1), np.float32)
    want[4] = 1.0
    np.testing.assert_array_equal(out, want)


def test_send_requires_static_src(comm):
    with pytest.raises(ValueError, match="static source"):
        send(jnp.zeros(3), dst=1, axis_name=AX)


def test_pseudo_connect_preserves_value_and_keeps_edge(comm):
    x = jnp.full((N, 2), 3.0)

    def scalar(v):
        transferred = send_recv(v * 2.0, 0, 1, AX)
        delegate = jnp.sum(transferred) * 0.0
        grafted = pseudo_connect(delegate, v)
        return jnp.sum(grafted)

    val, g = _grad_smap(comm, scalar, x)
    # value unchanged by grafting: sum over all shards of v
    assert float(np.asarray(val)[0]) == pytest.approx(3.0 * 2 * N)
    g = np.asarray(g)
    # direct edge: dL/dv = 1 everywhere; delegate edge contributes zero
    np.testing.assert_allclose(g, np.ones((N, 2)), rtol=1e-6)


# ---------------------------------------------------------------------------
# collectives: forward values
# ---------------------------------------------------------------------------


def test_allgather_forward(comm):
    x = np.random.RandomState(0).randn(N, 3).astype(np.float32)
    out = np.asarray(
        _smap(comm, lambda v: allgather(v, AX)[None].squeeze(0), x,
              out_spec=P(AX, None))
    )
    # every shard sees the full stack
    for i in range(N):
        np.testing.assert_allclose(out[i], x, rtol=1e-6)


def test_alltoall_forward(comm):
    x = np.arange(N * N, dtype=np.float32).reshape(N, N, 1)
    out = np.asarray(_smap(comm, lambda v: alltoall(v, AX), x))
    np.testing.assert_array_equal(out.squeeze(-1), x.squeeze(-1).T)


def test_bcast_forward(comm):
    x = np.arange(N, dtype=np.float32).reshape(N, 1) + 10
    out = np.asarray(_smap(comm, lambda v: bcast(v, AX, root=6), x))
    np.testing.assert_array_equal(out, np.full((N, 1), 16.0))


def test_gather_forward_root_only(comm):
    x = np.arange(N, dtype=np.float32).reshape(N, 1)
    out = np.asarray(
        _smap(comm, lambda v: gather(v, AX, root=3), x, out_spec=P(AX, None))
    )
    np.testing.assert_array_equal(out[3], x)  # root has everything
    assert (out[[i for i in range(N) if i != 3]] == 0).all()


def test_scatter_forward(comm):
    x = np.tile(np.arange(N, dtype=np.float32).reshape(1, N, 1), (N, 1, 1))
    out = np.asarray(_smap(comm, lambda v: scatter(v, AX, root=0), x))
    np.testing.assert_array_equal(out.squeeze(-1).squeeze(-1), np.arange(N))


def test_allreduce_forward(comm):
    x = np.ones((N, 4), np.float32)
    out = np.asarray(_smap(comm, lambda v: allreduce(v, AX), x))
    np.testing.assert_array_equal(out, np.full((N, 4), float(N)))


# ---------------------------------------------------------------------------
# collectives: gradients match the dense single-device equivalent
# ---------------------------------------------------------------------------


def test_allgather_gradient_matches_dense(comm):
    rng = np.random.RandomState(1)
    x = rng.randn(N, 3).astype(np.float32)
    w = rng.randn(N, 3).astype(np.float32)
    wj = jnp.asarray(w)

    def scalar(v):
        full = allgather(v, AX)  # [N, 3]
        return jnp.sum(full * wj) / N  # same loss on every shard

    _, g = _grad_smap(comm, scalar, jnp.asarray(x))
    # dense reference: loss = sum(x * w) computed on every of N shards / N
    # summed over shards -> grad = w
    np.testing.assert_allclose(np.asarray(g), w, rtol=1e-5)


def test_alltoall_gradient_is_transpose(comm):
    rng = np.random.RandomState(2)
    x = rng.randn(N, N).astype(np.float32)
    w = rng.randn(N, N).astype(np.float32)
    wj = jnp.asarray(w)

    def scalar_builder(i_mat):
        def scalar(v):
            out = alltoall(v[:, None], AX).squeeze(-1)  # row i of transpose
            idx = jax.lax.axis_index(AX)
            return jnp.sum(out * jax.lax.dynamic_index_in_dim(i_mat, idx, 0, keepdims=False))
        return scalar

    _, g = _grad_smap(comm, scalar_builder(wj), jnp.asarray(x))
    # loss = sum_{ij} xT[i,j] * w[i,j] = sum_{ij} x[j,i] w[i,j] -> dx = wT
    np.testing.assert_allclose(np.asarray(g), w.T, rtol=1e-5)


def test_bcast_gradient_sums_on_root(comm):
    x = jnp.ones((N, 2), jnp.float32)

    def scalar(v):
        y = bcast(v, AX, root=1)
        return jnp.sum(y * 3.0)

    _, g = _grad_smap(comm, scalar, x)
    g = np.asarray(g)
    want = np.zeros((N, 2), np.float32)
    want[1] = 3.0 * N  # cotangents from every shard sum onto the root
    np.testing.assert_allclose(g, want, rtol=1e-6)


def test_gather_scatter_roundtrip_gradient(comm):
    rng = np.random.RandomState(3)
    x = rng.randn(N, 1).astype(np.float32)

    def scalar(v):
        full = gather(v, AX, root=0)          # [N,1] on root, zeros elsewhere
        back = scatter(full, AX, root=0)      # redistribute root's buffer
        return jnp.sum(back * 2.0)

    val, g = _grad_smap(comm, scalar, jnp.asarray(x))
    assert float(np.asarray(val)[0]) == pytest.approx(2.0 * x.sum(), rel=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.full((N, 1), 2.0), rtol=1e-5)


# ---------------------------------------------------------------------------
# collectives: PER-collective backward vs single-device autodiff (round-4
# VERDICT item 10). The reference numerically gradient-checked each
# collective Function's hand-written transpose (SURVEY.md section 4,
# ``test_collective_communication.py`` (dagger)); here the transposes are
# inherited from JAX AD, so each is pinned against the gradient of the
# SAME loss written densely on the stacked array — autodiff vs autodiff,
# no hand-derived expectations.
# ---------------------------------------------------------------------------


def _dist_vs_dense_grad(comm, dist_scalar, dense_loss, x):
    """Gradient of sum-over-shards dist_scalar vs jax.grad of the dense
    single-device formulation of the same loss on the stacked array."""
    val, g = _grad_smap(comm, dist_scalar, jnp.asarray(x))
    dense_val, dense_g = jax.value_and_grad(dense_loss)(jnp.asarray(x))
    assert float(np.asarray(val)[0]) == pytest.approx(
        float(dense_val), rel=1e-5
    )
    np.testing.assert_allclose(np.asarray(g), np.asarray(dense_g),
                               rtol=1e-5, atol=1e-6)


def test_allgather_backward_vs_dense_autodiff(comm):
    rng = np.random.RandomState(10)
    x = rng.randn(N, 3).astype(np.float32)
    W = jnp.asarray(rng.randn(N, N, 3).astype(np.float32))  # per-shard wts

    def dist(v):  # shard s: loss_s = sum(allgather(x) * W[s])
        full = allgather(v, AX)
        idx = jax.lax.axis_index(AX)
        return jnp.sum(full * jax.lax.dynamic_index_in_dim(
            W, idx, 0, keepdims=False))

    def dense(xs):
        return sum(jnp.sum(xs * W[s]) for s in range(N))

    _dist_vs_dense_grad(comm, dist, dense, x)


def test_bcast_backward_vs_dense_autodiff(comm):
    rng = np.random.RandomState(11)
    x = rng.randn(N, 2).astype(np.float32)
    W = jnp.asarray(rng.randn(N, 2).astype(np.float32))
    root = 1

    def dist(v):  # shard s: loss_s = sum(bcast(x) * W[s])
        y = bcast(v, AX, root=root)
        idx = jax.lax.axis_index(AX)
        return jnp.sum(y * jax.lax.dynamic_index_in_dim(
            W, idx, 0, keepdims=False))

    def dense(xs):
        return sum(jnp.sum(xs[root] * W[s]) for s in range(N))

    _dist_vs_dense_grad(comm, dist, dense, x)


def test_gather_backward_vs_dense_autodiff(comm):
    rng = np.random.RandomState(12)
    x = rng.randn(N, 1).astype(np.float32)
    W = jnp.asarray(rng.randn(N, 1).astype(np.float32))
    root = 2

    def dist(v):  # gather -> [N, 1] on root, zeros elsewhere
        full = gather(v, AX, root=root)
        return jnp.sum(full * W)

    def dense(xs):  # only the root's copy carries the loss
        return jnp.sum(xs * W)

    _dist_vs_dense_grad(comm, dist, dense, x)


def test_scatter_backward_vs_dense_autodiff(comm):
    rng = np.random.RandomState(13)
    # Every shard holds an [N, 1] buffer; scatter uses only the root's.
    x = rng.randn(N, N, 1).astype(np.float32)
    W = jnp.asarray(rng.randn(N, 1).astype(np.float32))
    root = 3

    def dist(v):  # shard s receives root's row s
        mine = scatter(v, AX, root=root)
        idx = jax.lax.axis_index(AX)
        return jnp.sum(mine * jax.lax.dynamic_index_in_dim(
            W, idx, 0, keepdims=False))

    def dense(xs):
        return sum(jnp.sum(xs[root, s] * W[s]) for s in range(N))

    _dist_vs_dense_grad(comm, dist, dense, x)


def test_allreduce_backward_vs_dense_autodiff(comm):
    rng = np.random.RandomState(14)
    x = rng.randn(N, 2).astype(np.float32)
    W = jnp.asarray(rng.randn(N, 2).astype(np.float32))

    def dist(v):
        y = allreduce(v, AX)
        idx = jax.lax.axis_index(AX)
        return jnp.sum(y * jax.lax.dynamic_index_in_dim(
            W, idx, 0, keepdims=False))

    def dense(xs):
        total = jnp.sum(xs, axis=0)
        return sum(jnp.sum(total * W[s]) for s in range(N))

    _dist_vs_dense_grad(comm, dist, dense, x)
