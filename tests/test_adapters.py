"""Multi-tenant adapter serving invariants (ISSUE 14).

The load-bearing acceptance pins:

- **Tenant-stream equivalence** — every tenant's engine stream is
  bit-identical to sequential ``generate`` under that tenant's adapter
  (``bank.adapter_arrays`` — the same folded values the program
  gathers) AND to ``generate`` over the offline-merged (base + A@B)
  weights, across dense == paged == TP == single-device, composing
  with speculative decode, the prefix cache, and chunked prefill. A
  zero-adapter tenant is bitwise the base model.
- **Structural pins** — the decode/verify/mixed jit caches stay at ONE
  entry across tenant join/leave/adapter-registration churn, and the
  TP decode HLO with adapters active carries exactly the pre-adapter
  2 all-reduces per layer (nothing new on the wire).
- **Isolation** — the prefix trie is tenant-namespaced: two tenants
  over the identical system prompt share ZERO blocks while
  within-tenant hits are preserved; a session re-submitted under a
  different tenant raises at both front doors.
- **Fairness math in isolation** — deficit-round-robin quota units and
  the Jain index pinned against a literal numpy reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.observability.stats import jain_index
from chainermn_tpu.serving import (
    AdapterBank,
    DeficitRoundRobin,
    LowRankAdapter,
    Request,
    Scheduler,
    ServingEngine,
    random_adapter,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


@pytest.fixture(scope="module")
def bank(lm):
    model, _ = lm
    b = AdapterBank(model, capacity=5, rank=2)
    b.register("t1", random_adapter(model, 2, seed=1, scale=2.0))
    b.register("t2", random_adapter(model, 1, seed=2,
                                    targets=("qkv", "ff_down")))
    b.register("zero")  # zero-adapter tenant: the null row
    return b


def _engine(lm, bank, **kw):
    model, params = lm
    cfg = dict(num_slots=2, max_len=32, decode_impl="paged",
               kv_block_size=8, prefill_buckets=(4, 8),
               spec_tokens=0, prefix_cache="off", prefill_chunk=0,
               prefill_seq_parallel="off", adapter_bank=bank,
               adapter_impl="gather")
    cfg.update(kw)
    return ServingEngine(model, params, **cfg)


def _requests(n, seed=0, tenants=("t1", "t2", "zero", None)):
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        p = rs.randint(1, VOCAB, size=int(rs.randint(2, 7))).tolist()
        out.append((p, int(rs.randint(2, 6)), tenants[i % len(tenants)]))
    return out


def _gen_ref(model, params, prompt, n_new, adapters=None):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new, adapters=adapters,
    ))[0].tolist()


def _run_stream(engine, reqs, policy="prefill_priority", **sched_kw):
    sched = Scheduler(engine, policy=policy, **sched_kw)
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g, tenant_id=t))
           for p, g, t in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids], sched


class TestStreamEquivalence:
    """Engine streams == generate under the tenant's adapter, every
    cache layout and composition."""

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_mixed_tenant_staggered_streams(self, lm, bank, impl):
        model, params = lm
        engine = _engine(lm, bank, decode_impl=impl)
        reqs = _requests(6, seed=0)
        streams, _ = _run_stream(engine, reqs)
        for (p, g, t), got in zip(reqs, streams):
            ad = bank.adapter_arrays(t) if t is not None else None
            assert got == _gen_ref(model, params, p, g, ad), t

    def test_zero_adapter_tenant_is_bitwise_base(self, lm, bank):
        model, params = lm
        engine = _engine(lm, bank)
        p = [3, 5, 7, 11]
        slot, tok, _ = engine.prefill_join(p, tenant_id="zero")
        stream = list(p) + [tok]
        for _ in range(4):
            toks, _ = engine.decode_step()
            stream.append(int(toks[slot]))
        engine.leave(slot)
        assert stream == _gen_ref(model, params, p, 5)

    def test_gather_stream_matches_offline_merged_reference(self, lm,
                                                            bank):
        """The ISSUE 14 anchor: the per-slot gather path reproduces the
        stream of ``generate`` over the offline-merged (base + A@B)
        weights."""
        model, params = lm
        engine = _engine(lm, bank)
        merged = bank.merge_adapter_params(params, "t1")
        reqs = [(p, g, "t1") for p, g, _ in _requests(3, seed=4)]
        streams, _ = _run_stream(engine, reqs)
        for (p, g, _t), got in zip(reqs, streams):
            ref = np.asarray(generate(
                model, merged, jnp.asarray([p], jnp.int32), len(p) + g,
            ))[0].tolist()
            assert got == ref

    def test_merged_engine_serves_offline_merged_stream(self, lm, bank):
        model, params = lm
        engine = _engine(lm, bank, adapter_impl="merged",
                         merged_tenant="t1")
        merged = bank.merge_adapter_params(params, "t1")
        reqs = [(p, g, "t1") for p, g, _ in _requests(3, seed=5)]
        streams, _ = _run_stream(engine, reqs)
        for (p, g, _t), got in zip(reqs, streams):
            ref = np.asarray(generate(
                model, merged, jnp.asarray([p], jnp.int32), len(p) + g,
            ))[0].tolist()
            assert got == ref

    def test_merged_engine_refuses_other_tenants(self, lm, bank):
        engine = _engine(lm, bank, adapter_impl="merged",
                         merged_tenant="t1")
        with pytest.raises(ValueError, match="merged tenant"):
            engine.prefill_join([1, 2, 3], tenant_id="t2")
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="cannot be served"):
            sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                 tenant_id="t2"))

    def test_speculative_decode_composes(self, lm, bank):
        model, params = lm
        engine = _engine(lm, bank, spec_tokens=3, num_slots=3)
        rs = np.random.RandomState(9)
        reqs = []
        for i, t in enumerate(["t1", "t2", "t1", "zero"]):
            base = rs.randint(1, VOCAB, size=3).tolist()
            reqs.append(((base * 3)[: int(rs.randint(4, 9))],
                         int(rs.randint(3, 7)), t))
        streams, _ = _run_stream(engine, reqs)
        for (p, g, t), got in zip(reqs, streams):
            ad = bank.adapter_arrays(t)
            assert got == _gen_ref(model, params, p, g, ad), t
        assert engine.verify_compile_count() in (None, 1)

    def test_prefix_cache_composes_within_tenant(self, lm, bank):
        model, params = lm
        engine = _engine(lm, bank, prefix_cache="on", num_slots=2,
                         max_len=32)
        sys_p = list(range(1, 17))  # two full 8-token blocks
        reqs = [(sys_p + [20 + i], 3, "t1") for i in range(3)]
        streams, sched = _run_stream(engine, reqs)
        for (p, g, t), got in zip(reqs, streams):
            ad = bank.adapter_arrays(t)
            assert got == _gen_ref(model, params, p, g, ad)
        assert engine.prefix_stats["hits"] >= 2  # followers hit

    def test_chunked_prefill_composes(self, lm, bank):
        model, params = lm
        engine = _engine(lm, bank, prefill_chunk=4, num_slots=3)
        reqs = _requests(5, seed=11)
        streams, _ = _run_stream(engine, reqs)
        for (p, g, t), got in zip(reqs, streams):
            ad = bank.adapter_arrays(t) if t is not None else None
            assert got == _gen_ref(model, params, p, g, ad), t
        assert engine.mixed_compile_count() in (None, 1)


class TestTensorParallel:
    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices("cpu")[:2]), ("model",))

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_tp_streams_match_single_device_and_generate(self, lm, bank,
                                                         mesh, impl):
        model, params = lm
        reqs = _requests(5, seed=13)
        single = _engine(lm, bank, decode_impl=impl, num_slots=3)
        tp = _engine(lm, bank, decode_impl=impl, num_slots=3, mesh=mesh)
        s_streams, _ = _run_stream(single, reqs)
        t_streams, _ = _run_stream(tp, reqs)
        assert t_streams == s_streams
        for (p, g, t), got in zip(reqs, t_streams):
            ad = bank.adapter_arrays(t) if t is not None else None
            assert got == _gen_ref(model, params, p, g, ad), t

    def test_tp_decode_collective_counts_with_adapters(self, lm, bank,
                                                       mesh):
        """The ISSUE 14 wire pin: adapters active, the compiled decode
        step carries EXACTLY the pre-adapter 2 all-reduces per layer —
        the deltas ride the existing column/row split, nothing new."""
        model, _params = lm
        engine = _engine(lm, bank, num_slots=3, mesh=mesh)
        args = (
            engine._cache, engine._vars, engine._adapter_device(),
            jnp.zeros((3,), jnp.int32), jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.zeros((3,), jnp.int32), jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        n_ar = txt.count("all-reduce(")
        assert n_ar == 2 * model.num_layers, n_ar
        for op in ("all-gather(", "collective-permute(", "all-to-all(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op}"


class TestNoRecompile:
    def test_jit_cache_pinned_across_tenant_and_registration_churn(
            self, lm):
        """The tentpole structural pin: tenant join/leave churn AND
        adapter registration/eviction churn mutate host metadata (+ one
        H2D) only — the decode step compiles exactly once."""
        model, params = lm
        bank = AdapterBank(model, capacity=4, rank=2)
        bank.register("a", random_adapter(model, 2, seed=1))
        bank.register("b", random_adapter(model, 2, seed=2))
        engine = _engine(lm, bank, num_slots=2)
        for i, t in enumerate(["a", "b", "a", None]):
            p = [1 + i, 2 + i, 3 + i]
            slot, _tok, _ = engine.prefill_join(p, tenant_id=t)
            engine.decode_step()
            engine.leave(slot)
        # registration churn mid-life: new tenant, evicted tenant,
        # re-registered weights — same compiled step serves them all
        engine.evict_adapter("b")
        engine.register_adapter("c", random_adapter(model, 1, seed=3))
        slot, _tok, _ = engine.prefill_join([5, 6, 7], tenant_id="c")
        engine.decode_step()
        engine.leave(slot)
        assert engine.decode_compile_count() in (None, 1)

    def test_registration_reaches_next_step_without_recompile(self, lm):
        model, params = lm
        bank = AdapterBank(model, capacity=3, rank=2)
        bank.register("a", random_adapter(model, 2, seed=1))
        engine = _engine(lm, bank, num_slots=2)
        p = [2, 3, 4, 5]
        slot, tok, _ = engine.prefill_join(p, tenant_id="a")
        stream = [*p, tok]
        toks, _ = engine.decode_step()
        stream.append(int(toks[slot]))
        engine.leave(slot)
        # swap a's weights (drained) — streams now follow the NEW rows
        bank.register("a", random_adapter(model, 2, seed=42))
        slot, tok, _ = engine.prefill_join(p, tenant_id="a")
        stream2 = [*p, tok]
        for _ in range(3):
            toks, _ = engine.decode_step()
            stream2.append(int(toks[slot]))
        engine.leave(slot)
        assert stream2 == _gen_ref(model, params, p, 4,
                                   bank.adapter_arrays("a"))
        assert engine.decode_compile_count() in (None, 1)


class TestAdapterBank:
    def test_register_evict_refcounts(self, lm):
        model, _ = lm
        bank = AdapterBank(model, capacity=3, rank=2)
        r1 = bank.register("a", random_adapter(model, 2, seed=1))
        assert r1 != 0 and bank.resident("a")
        bank.pin("a")
        with pytest.raises(RuntimeError, match="pinned"):
            bank.evict("a")
        with pytest.raises(RuntimeError, match="pinned"):
            bank.register("a", random_adapter(model, 2, seed=2))
        bank.unpin("a")
        bank.evict("a")
        assert not bank.resident("a")
        with pytest.raises(KeyError):
            bank.row_of("a")

    def test_capacity_and_rank_budget(self, lm):
        model, _ = lm
        bank = AdapterBank(model, capacity=2, rank=1)
        bank.register("a", random_adapter(model, 1, seed=1))
        with pytest.raises(RuntimeError, match="bank full"):
            bank.register("b", random_adapter(model, 1, seed=2))
        bank.evict("a")
        with pytest.raises(ValueError, match="rank"):
            bank.register("b", random_adapter(model, 2, seed=2))

    def test_zero_adapter_rides_null_row_and_row_reuse(self, lm):
        model, _ = lm
        bank = AdapterBank(model, capacity=3, rank=2)
        assert bank.register("z") == 0
        assert bank.row_of("z") == 0 and bank.row_of(None) == 0
        r = bank.register("a", random_adapter(model, 2, seed=1))
        bank.evict("a")
        assert bank.register("b", random_adapter(model, 2, seed=2)) == r

    def test_smaller_rank_zero_pads_exactly(self, lm):
        """A rank-1 adapter in a rank-2 bank gathers identical values:
        the padded columns are exact zeros."""
        model, params = lm
        ad = random_adapter(model, 1, seed=3)
        bank = AdapterBank(model, capacity=2, rank=4)
        bank.register("a", ad)
        arrays = bank.adapter_arrays("a")
        for li, layer in enumerate(ad.layers):
            for tgt, (A, B) in layer.items():
                As, Bs = arrays[li][tgt]
                np.testing.assert_array_equal(As[:, :1], A)
                assert not As[:, 1:].any() and not Bs[1:, :].any()

    def test_shape_validation(self, lm):
        model, _ = lm
        bank = AdapterBank(model, capacity=2, rank=2)
        bad = LowRankAdapter(
            [{"qkv": (np.zeros((7, 2), np.float32),
                      np.zeros((2, 5), np.float32))}
             for _ in range(model.num_layers)]
        )
        with pytest.raises(ValueError, match="do not match"):
            bank.register("a", bad)
        with pytest.raises(ValueError, match="layers"):
            bank.register("a", LowRankAdapter([{}]))

    def test_engine_requires_registered_tenant(self, lm, bank):
        engine = _engine(lm, bank)
        with pytest.raises(KeyError, match="no registered adapter"):
            engine.prefill_join([1, 2, 3], tenant_id="ghost")
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="cannot be served"):
            sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                 tenant_id="ghost"))

    def test_adapter_impl_validation(self, lm, bank):
        with pytest.raises(ValueError, match="adapter_impl"):
            _engine(lm, None, adapter_bank=None, adapter_impl="gather")
        with pytest.raises(ValueError, match="merged_tenant"):
            _engine(lm, bank, adapter_impl="merged")
        with pytest.raises(ValueError, match="adapter_impl"):
            _engine(lm, bank, adapter_impl="bogus")


class TestFairnessMath:
    """ISSUE 14 satellite: the DRR quota units and the Jain index in
    isolation."""

    def test_weighted_shares_under_saturation(self):
        drr = DeficitRoundRobin()
        drr.set_weight("a", 3.0)
        drr.set_weight("b", 1.0)
        served = {"a": 0, "b": 0}
        for _ in range(400):
            t = drr.select({"a": 5, "b": 5})
            drr.charge(t, 5)
            served[t] += 1
        assert abs(served["a"] / served["b"] - 3.0) < 0.15

    def test_weighted_shares_with_uneven_costs(self):
        """Shares are WORK-proportional, not request-proportional: a
        tenant whose requests cost 2x gets half the admissions at
        equal weight."""
        drr = DeficitRoundRobin()
        work = {"big": 0.0, "small": 0.0}
        for _ in range(600):
            t = drr.select({"big": 8, "small": 4})
            drr.charge(t, 8 if t == "big" else 4)
            work[t] += 8 if t == "big" else 4
        assert abs(work["big"] / work["small"] - 1.0) < 0.1

    def test_idle_tenant_deficit_resets(self):
        """A tenant that went idle must NOT hoard credit and
        burst-starve the others on return."""
        drr = DeficitRoundRobin()
        for _ in range(50):  # b backlogged alone: would bank credit
            t = drr.select({"a": 1})
            drr.charge(t, 1)
        assert drr.deficit("b") == 0.0
        served = {"a": 0, "b": 0}
        for _ in range(100):  # b returns: even split, no catch-up burst
            t = drr.select({"a": 1, "b": 1})
            drr.charge(t, 1)
            served[t] += 1
        assert abs(served["a"] - served["b"]) <= 2

    def test_quota_churn_mid_run(self):
        drr = DeficitRoundRobin()
        drr.set_weight("a", 1.0)
        drr.set_weight("b", 1.0)
        for _ in range(100):
            drr.charge(drr.select({"a": 1, "b": 1}), 1)
        drr.set_weight("a", 4.0)  # quota raised mid-run
        served = {"a": 0, "b": 0}
        for _ in range(500):
            t = drr.select({"a": 1, "b": 1})
            drr.charge(t, 1)
            served[t] += 1
        assert abs(served["a"] / served["b"] - 4.0) < 0.25

    def test_validation(self):
        drr = DeficitRoundRobin()
        with pytest.raises(ValueError):
            drr.set_weight("a", 0.0)
        with pytest.raises(ValueError):
            DeficitRoundRobin(quantum=0)
        assert drr.select({}) is None

    def test_jain_index_against_numpy_reference(self):
        rs = np.random.RandomState(5)
        for _ in range(10):
            xs = rs.uniform(0.0, 10.0, size=int(rs.randint(1, 9)))
            ref = float(
                np.sum(xs) ** 2 / (xs.size * np.sum(np.square(xs))))
            assert abs(jain_index(xs.tolist()) - ref) < 1e-12
        assert jain_index([]) is None
        assert jain_index([0.0, 0.0]) == 1.0
        assert abs(jain_index([1.0, 0.0, 0.0, 0.0]) - 0.25) < 1e-12

    def test_scheduler_fair_share_order_under_saturation(self, lm, bank):
        """End-to-end: a 1-slot engine + weighted tenants — admission
        ORDER follows the weights even though every request finishes."""
        engine = _engine(lm, bank, num_slots=1)
        sched = Scheduler(engine, policy="fcfs",
                          tenant_weights={"t1": 2.0, "t2": 1.0})
        rs = np.random.RandomState(3)
        order = []
        orig = engine.prefill_join

        def spy(prompt, tenant_id=None):
            res = orig(prompt, tenant_id=tenant_id)
            if res is not None:
                order.append(tenant_id)
            return res

        engine.prefill_join = spy
        for i in range(9):
            t = "t1" if i < 4 else "t2"  # t2 queued behind t1's block
            p = rs.randint(1, VOCAB, size=3).tolist()
            sched.submit(Request(prompt=p, max_new_tokens=2,
                                 tenant_id=t))
        sched.run()
        engine.prefill_join = orig
        # weight 2:1 with equal costs: t1 admits ~2 per t2 while both
        # are backlogged (first 6 admissions carry both tenants).
        assert order.count("t1") == 4 and order.count("t2") == 5
        assert "t2" in order[:3]  # t2 was not starved behind t1's block


class TestPrefixIsolation:
    """ISSUE 14 satellite: cross-tenant adoption is structurally
    impossible; within-tenant hits are preserved."""

    def test_identical_prompt_two_tenants_zero_shared_blocks(self, lm,
                                                             bank):
        model, params = lm
        engine = _engine(lm, bank, prefix_cache="on", num_slots=2)
        sys_p = list(range(1, 17))  # two full blocks
        # tenant t1 warms ITS namespace
        for tail in (20, 21):
            streams, sched = _run_stream(
                engine, [(sys_p + [tail], 3, "t1")])
        info_t1 = engine.last_prefix_info
        assert info_t1["hit_blocks"] == 2  # within-tenant hit preserved
        # t2 over the IDENTICAL prompt: must MISS (namespace isolation)
        streams, sched = _run_stream(engine, [(sys_p + [20], 3, "t2")])
        info_t2 = engine.last_prefix_info
        assert info_t2["hit_blocks"] == 0
        assert info_t2["prefill_tokens"] == len(sys_p) + 1
        # and the streams are each tenant's own, not each other's
        assert streams[0] == _gen_ref(model, params, sys_p + [20], 3,
                                      bank.adapter_arrays("t2"))
        # structural: the two namespaces cache DISJOINT physical blocks
        trie = engine._prefix
        assert trie.namespace_blocks("t1") >= 2
        assert trie.namespace_blocks("t2") >= 2

    def test_match_depth_is_namespaced(self, lm, bank):
        engine = _engine(lm, bank, prefix_cache="on", num_slots=2)
        sys_p = list(range(1, 17))
        _run_stream(engine, [(sys_p + [20], 3, "t1")])
        assert engine.prefix_match_depth(sys_p, tenant_id="t1") == 2
        assert engine.prefix_match_depth(sys_p, tenant_id="t2") == 0
        assert engine.prefix_match_depth(sys_p) == 0  # default ns


class TestSessionTenantGuard:
    def test_scheduler_refuses_tenant_swap(self, lm, bank):
        engine = _engine(lm, bank)
        sched = Scheduler(engine)
        sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                             tenant_id="t1", session_id="s"))
        with pytest.raises(ValueError, match="never change tenants"):
            sched.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                 tenant_id="t2", session_id="s"))
        # same tenant: fine (the run drains both turns)
        sched.submit(Request(prompt=[3, 4], max_new_tokens=2,
                             tenant_id="t1", session_id="s"))
        sched.run()

    def test_router_refuses_tenant_swap(self, lm, bank):
        from chainermn_tpu.serving.cluster import Replica, Router

        engine = _engine(lm, bank)
        rep = Replica(engine, Scheduler(engine, "prefill_priority"), 0)
        router = Router([rep], mode="colocated")
        router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                              tenant_id="t1", session_id="s"))
        with pytest.raises(ValueError, match="never change tenants"):
            router.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                  tenant_id="t2", session_id="s"))
        router.run()


class TestRouterResidency:
    def test_placement_follows_adapter_residency(self, lm):
        from chainermn_tpu.serving.cluster import Replica, Router

        model, params = lm
        bank_a = AdapterBank(model, capacity=3, rank=2)
        bank_a.register("acme", random_adapter(model, 2, seed=1))
        bank_b = AdapterBank(model, capacity=3, rank=2)
        bank_b.register("globex", random_adapter(model, 2, seed=2))
        reps = []
        for i, b in enumerate((bank_a, bank_b)):
            eng = _engine(lm, b, num_slots=2)
            reps.append(Replica(eng, Scheduler(eng, "prefill_priority"),
                                i))
        router = Router(reps, policy="least_loaded", mode="colocated")
        rs = np.random.RandomState(5)
        reqs = []
        for i in range(6):
            t = "acme" if i % 2 == 0 else "globex"
            p = rs.randint(1, VOCAB, size=4).tolist()
            reqs.append((router.submit(Request(
                prompt=p, max_new_tokens=3, tenant_id=t)), p, t))
        results = router.run()
        # every stream decoded under ITS tenant's adapter
        for rid, p, t in reqs:
            b = bank_a if t == "acme" else bank_b
            assert results[rid]["tokens"] == _gen_ref(
                model, params, p, 3, b.adapter_arrays(t)), t
        # routes: acme only ever landed on replica 0, globex on 1
        routes = {e["request"]: e["replica"]
                  for e in router._events if e["kind"] == "route"}
        for rid, _p, t in reqs:
            assert routes[rid] == (0 if t == "acme" else 1)

    def test_unplaceable_tenant_raises_at_front_door(self, lm, bank):
        from chainermn_tpu.serving.cluster import Replica, Router

        engine = _engine(lm, bank)
        rep = Replica(engine, Scheduler(engine, "prefill_priority"), 0)
        router = Router([rep], mode="colocated")
        with pytest.raises(ValueError, match="no resident adapter"):
            router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                  tenant_id="ghost"))


class TestKvTransferTenant:
    def test_export_import_carries_tenant(self, lm, bank):
        model, params = lm
        src = _engine(lm, bank, num_slots=2)
        dst = _engine(lm, bank, num_slots=2)
        p = [2, 3, 5, 7, 11]
        slot, tok, _ = src.prefill_join(p, tenant_id="t1")
        payload = src.export_kv(slot)
        src.leave(slot)
        assert payload["tenant"] == "t1"
        dslot, last = dst.import_kv(payload)
        assert dst.tenant_of_slot(dslot) == "t1"
        stream = list(p) + [int(last)]
        for _ in range(3):
            toks, _ = dst.decode_step()
            stream.append(int(toks[dslot]))
        dst.leave(dslot)
        assert stream == _gen_ref(model, params, p, 4,
                                  bank.adapter_arrays("t1"))

    def test_import_refuses_unresident_tenant(self, lm, bank):
        model, params = lm
        src = _engine(lm, bank, num_slots=2)
        other = AdapterBank(model, capacity=2, rank=2)
        dst = _engine(lm, other, num_slots=2)
        slot, _tok, _ = src.prefill_join([1, 2, 3], tenant_id="t1")
        payload = src.export_kv(slot)
        src.leave(slot)
        with pytest.raises(ValueError, match="no resident adapter"):
            dst.import_kv(payload)


class TestTenantRollup:
    def test_summary_tenants_and_fairness(self, lm, bank):
        engine = _engine(lm, bank, num_slots=2)
        reqs = [(p, g, t) for (p, g, _), t in zip(
            _requests(6, seed=21), ["t1", "t1", "t2", "t2", "zero",
                                    "t1"])]
        _streams, sched = _run_stream(engine, reqs)
        s = sched.summary()
        assert set(s["tenants"]) == {"t1", "t2", "zero"}
        assert s["tenants"]["t1"]["requests"] == 3
        assert s["tenants"]["t2"]["requests"] == 2
        for row in s["tenants"].values():
            assert row["ttft_ms_p50"] is not None
            assert row["generated_tokens"] >= 1
        tok = [s["tenants"][t]["generated_tokens"]
               for t in s["tenants"]]
        assert s["tenant_fairness_jain"] == round(jain_index(tok), 4)

    def test_pre_tenant_events_roll_up_as_default(self, lm):
        """Satellite: traces without tenant fields keep parsing — one
        'default' tenant carries everything."""
        from chainermn_tpu.observability.trace import summarize_serving

        events = [
            {"kind": "serving", "phase": "prefill", "request": "r0",
             "slot": 0, "prompt_len": 3, "dur_s": 0.01, "ttft_s": 0.012},
            {"kind": "serving", "phase": "decode_step", "n_active": 1,
             "n_slots": 2, "tokens": 1, "dur_s": 0.004},
            {"kind": "serving", "phase": "finish", "request": "r0",
             "generated": 2, "dur_s": 0.03},
        ]
        s = summarize_serving(events)
        assert list(s["tenants"]) == ["default"]
        assert s["tenants"]["default"]["requests"] == 1
        assert s["tenant_fairness_jain"] == 1.0

    def test_tenant_gauges_publish(self, lm, bank):
        from chainermn_tpu.observability import metrics

        metrics.reset()
        try:
            reg = metrics.registry()
            engine = _engine(lm, bank, num_slots=2)
            slot, _tok, _ = engine.prefill_join([1, 2, 3],
                                                tenant_id="t1")
            snap = reg.snapshot()
            assert "adapter_bank_residents" in snap
            assert "adapter_bank_free_rows" in snap
            vals = {
                row["labels"].get("tenant"): row["value"]
                for row in snap["serving_tenant_active_slots"]["values"]
            }
            assert vals["t1"] == 1
            assert vals.get("t2", 0) == 0
            engine.leave(slot)
        finally:
            metrics.reset()


class TestAdapterChurnInvalidation:
    """Review finding: re-registering a tenant changes the weights
    behind its cached KV — the engine must drop the tenant's trie
    namespace on ANY bank content change (overwrite, zero-downgrade,
    evict), or a later join adopts stale-adapter blocks and the stream
    silently diverges from ``generate`` under the new weights."""

    def test_reregister_drops_stale_prefix_blocks(self, lm):
        model, params = lm
        b = AdapterBank(model, capacity=3, rank=2)
        b.register("acme", random_adapter(model, 2, seed=11))
        engine = _engine(lm, b, prefix_cache="on", num_slots=2)
        sys_p = list(range(1, 17))  # two full blocks
        _run_stream(engine, [(sys_p + [20], 3, "acme")])
        assert engine._prefix.namespace_blocks("acme") >= 2
        b.register("acme", random_adapter(model, 2, seed=12))
        assert engine._prefix.namespace_blocks("acme") == 0
        streams, _ = _run_stream(engine, [(sys_p + [20], 3, "acme")])
        info = engine.last_prefix_info
        assert info["hit_blocks"] == 0  # re-prefilled, never adopted
        assert streams[0] == _gen_ref(model, params, sys_p + [20], 3,
                                      b.adapter_arrays("acme"))

    def test_zero_downgrade_and_evict_drop_namespace(self, lm):
        model, params = lm
        b = AdapterBank(model, capacity=3, rank=2)
        b.register("acme", random_adapter(model, 2, seed=13))
        engine = _engine(lm, b, prefix_cache="on", num_slots=2)
        sys_p = list(range(1, 17))
        _run_stream(engine, [(sys_p + [20], 3, "acme")])
        b.register("acme")  # downgrade to the zero adapter
        assert engine._prefix.namespace_blocks("acme") == 0
        streams, _ = _run_stream(engine, [(sys_p + [21], 3, "acme")])
        assert streams[0] == _gen_ref(model, params, sys_p + [21], 3)
        _run_stream(engine, [(sys_p + [20], 3, "acme")])
        assert engine._prefix.namespace_blocks("acme") >= 2
        b.evict("acme")
        assert engine._prefix.namespace_blocks("acme") == 0

    def test_drop_namespace_respects_live_refcounts(self):
        from chainermn_tpu.serving.kv_blocks import (
            BlockAllocator,
            PrefixCache,
        )

        alloc = BlockAllocator(num_blocks=8, block_size=4, num_slots=2,
                               max_len=16)
        trie = PrefixCache(alloc)
        assert alloc.ensure(0, 8)
        blocks = alloc.owned_blocks(0)
        assert trie.insert(list(range(8)), blocks,
                           namespace="acme") == 2
        free_before = alloc.free_blocks
        assert trie.drop_namespace("acme") == 2
        assert trie.lookup(list(range(8)), namespace="acme") == []
        # still referenced by slot 0: uncached, NOT freed
        assert alloc.free_blocks == free_before
        alloc.release(0)
        assert alloc.free_blocks == free_before + len(blocks)
        # the default namespace is recreated after a drop
        trie.drop_namespace(None)
        assert alloc.ensure(1, 4)
        trie.insert(list(range(4)), alloc.owned_blocks(1))
        assert trie.drop_namespace("ghost") == 0


class TestDisaggResidency:
    """Review finding: 'resident somewhere' let a tenant whose adapter
    lived only on the wrong plane past the front door — the prefill
    pump then crashed the run loop with a KeyError."""

    def _disagg(self, lm, bank_p, bank_d):
        from chainermn_tpu.serving.cluster import Replica, Router

        eng_p = _engine(lm, bank_p, num_slots=2)
        eng_d = _engine(lm, bank_d, num_slots=2)
        reps = [Replica(eng_p, Scheduler(eng_p, "prefill_priority"), 0),
                Replica(eng_d, Scheduler(eng_d, "prefill_priority"), 1)]
        return Router(reps, policy="least_loaded", mode="disaggregated",
                      prefill_replicas=[0])

    def test_decode_only_residency_refused(self, lm):
        model, _ = lm
        bank_p = AdapterBank(model, capacity=3, rank=2)
        bank_d = AdapterBank(model, capacity=3, rank=2)
        bank_d.register("acme", random_adapter(model, 2, seed=3))
        router = self._disagg(lm, bank_p, bank_d)
        with pytest.raises(ValueError, match="alive prefill replica"):
            router.submit(Request(prompt=[1, 2, 3], max_new_tokens=2,
                                  tenant_id="acme"))

    def test_prefill_only_residency_refused(self, lm):
        model, _ = lm
        bank_p = AdapterBank(model, capacity=3, rank=2)
        bank_p.register("acme", random_adapter(model, 2, seed=3))
        bank_d = AdapterBank(model, capacity=3, rank=2)
        router = self._disagg(lm, bank_p, bank_d)
        with pytest.raises(ValueError, match="alive decode replica"):
            router.submit(Request(prompt=[1, 2, 3], max_new_tokens=2,
                                  tenant_id="acme"))

    def test_both_planes_resident_serves(self, lm):
        model, params = lm
        bank_p = AdapterBank(model, capacity=3, rank=2)
        bank_d = AdapterBank(model, capacity=3, rank=2)
        # identical weights on both planes (same seed): the handoff's
        # stream must match the single-engine reference bitwise
        bank_p.register("acme", random_adapter(model, 2, seed=3))
        bank_d.register("acme", random_adapter(model, 2, seed=3))
        router = self._disagg(lm, bank_p, bank_d)
        rid = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=3,
                                    tenant_id="acme"))
        results = router.run()
        assert results[rid]["tokens"] == _gen_ref(
            model, params, [1, 2, 3], 3, bank_d.adapter_arrays("acme"))


class TestRequeueFairShareCost:
    """Review finding: a preempted-and-requeued stream was re-charged
    its full decode budget on re-admission, dragging the tenant's
    admitted share below its weight."""

    def test_resume_and_requeue_cost_zero(self):
        r = Request(prompt=[1], max_new_tokens=8, tenant_id="t")
        assert Scheduler._drr_cost(r) == 8.0
        r._requeued = True
        assert Scheduler._drr_cost(r) == 0.0
        r2 = Request(prompt=[1], max_new_tokens=8, tenant_id="t")
        r2._resume = {"stream": [1, 2]}
        assert Scheduler._drr_cost(r2) == 0.0

    def test_zero_cost_head_admits_without_new_credit(self):
        """A requeued head must not wait for its tenant's deficit to
        re-cover the full budget it already paid."""
        drr = DeficitRoundRobin()
        t = drr.select({"a": 8.0, "b": 8.0})
        drr.charge(t, 8.0)  # first admission: full price
        # the preempted request returns at cost 0 — served immediately,
        # no fresh credit rounds needed for THIS head
        assert drr.select({t: 0.0, "b" if t == "a" else "a": 8.0}) is not None
        before = drr.deficit(t)
        drr.charge(t, 0.0)
        assert drr.deficit(t) == before


class TestMergedEngineFrontDoors:
    """Review finding: the residency guards exempted tenant_id=None —
    a BASE-model request on a merged engine/replica crashed mid-run
    instead of being refused at the front door."""

    def _merged(self, lm, bank, **kw):
        return _engine(lm, bank, adapter_impl="merged",
                       merged_tenant="t1", **kw)

    def test_scheduler_refuses_tenantless_on_merged(self, lm, bank):
        sched = Scheduler(self._merged(lm, bank))
        with pytest.raises(ValueError, match="base-model"):
            sched.submit(Request(prompt=[1, 2], max_new_tokens=2))

    def test_router_refuses_tenantless_on_merged_only_cluster(
            self, lm, bank):
        from chainermn_tpu.serving.cluster import Replica, Router

        eng = self._merged(lm, bank)
        rep = Replica(eng, Scheduler(eng, "prefill_priority"), 0)
        router = Router([rep], mode="colocated")
        with pytest.raises(ValueError, match="base-model"):
            router.submit(Request(prompt=[1, 2], max_new_tokens=2))

    def test_router_places_tenantless_on_gather_replica(self, lm, bank):
        from chainermn_tpu.serving.cluster import Replica, Router

        model, params = lm
        eng_m = self._merged(lm, bank)
        eng_g = _engine(lm, bank)
        reps = [Replica(eng_m, Scheduler(eng_m, "prefill_priority"), 0),
                Replica(eng_g, Scheduler(eng_g, "prefill_priority"), 1)]
        router = Router(reps, policy="least_loaded", mode="colocated")
        rid = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=3))
        results = router.run()
        # placed on the gather replica, served as the base model
        routes = {e["request"]: e["replica"]
                  for e in router._events if e["kind"] == "route"}
        assert routes[rid] == 1
        assert results[rid]["tokens"] == _gen_ref(model, params,
                                                  [1, 2, 3], 3)


class TestMigrateResidency:
    def test_migrate_refuses_before_preempting(self, lm):
        """Review finding: migrate scored residency instead of
        filtering — a non-resident destination stranded the
        just-preempted request. Now it raises BEFORE preempting and
        the stream keeps running in place."""
        from chainermn_tpu.serving.cluster import Replica, Router

        model, params = lm
        bank_a = AdapterBank(model, capacity=3, rank=2)
        bank_a.register("acme", random_adapter(model, 2, seed=7))
        bank_b = AdapterBank(model, capacity=3, rank=2)  # not resident
        reps = []
        for i, b in enumerate((bank_a, bank_b)):
            eng = _engine(lm, b, num_slots=2)
            reps.append(Replica(eng, Scheduler(eng, "prefill_priority"),
                                i))
        router = Router(reps, policy="least_loaded", mode="colocated")
        rid = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=4,
                                    tenant_id="acme"))
        # admit it into flight on replica 0
        reps[0].scheduler.tick()
        assert reps[0].scheduler.slot_of(rid) is not None
        with pytest.raises(RuntimeError, match="acme"):
            router.preempt_request(rid, exclude_replica=True)
        # NOT stranded: still in flight on 0, and the run completes
        assert reps[0].scheduler.slot_of(rid) is not None
        results = router.run()
        assert results[rid]["tokens"] == _gen_ref(
            model, params, [1, 2, 3], 4, bank_a.adapter_arrays("acme"))


class TestSloPreemptGatesOnDrrPick:
    def test_blocked_drr_candidate_can_preempt(self, lm, bank):
        """Review finding: _maybe_preempt gated on the arrival head —
        a targetless head masked the DRR-picked candidate's at-risk
        TTFT and the winnable SLO was lost."""
        import time as _time

        engine = _engine(lm, bank, num_slots=1)
        sched = Scheduler(engine, policy="slo",
                          tenant_weights={"t1": 1.0, "t2": 1.0})
        x = Request(prompt=[1, 2, 3], max_new_tokens=8, tenant_id="t1",
                    tpot_target_ms=1e-4)  # will blow its TPOT budget
        sched.submit(x)
        assert sched._admit_round()  # x owns the only slot
        sched.step()  # generated >= 2: TPOT is measurable (and over)
        h = Request(prompt=[2, 3], max_new_tokens=2, tenant_id="t1")
        b = Request(prompt=[3, 4], max_new_tokens=2, tenant_id="t2",
                    ttft_target_ms=1.0)
        sched.submit(h)
        sched.submit(b)
        b._arrival -= 10.0  # far past half its TTFT budget
        sched._drr.charge("t1", 1000.0)  # t1 in debt: DRR names t2
        assert sched._next_candidate() is b
        _time.sleep(0.002)
        assert sched._maybe_preempt() is True  # head-gating returned False here
        assert sched.preemptions == 1
        results = sched.run()  # everything (incl. the resume) drains
        assert len(results) == 3


def test_adapter_impls_single_definition():
    """Review finding: ADAPTER_IMPLS was defined in both engine.py and
    adapters.py — the ctor validation and the tuning candidate set
    must read the SAME tuple."""
    from chainermn_tpu.serving import adapters as a_mod
    from chainermn_tpu.serving import engine as e_mod

    assert e_mod.ADAPTER_IMPLS is a_mod.ADAPTER_IMPLS


class TestSessionPinAfterValidation:
    """Review finding: both front doors pinned session->tenant BEFORE
    validation — a refused submission permanently poisoned the session
    id under the wrong tenant."""

    def test_refused_router_submit_does_not_pin_session(self, lm, bank):
        from chainermn_tpu.serving.cluster import Replica, Router

        eng = _engine(lm, bank, adapter_impl="merged",
                      merged_tenant="t1")
        rep = Replica(eng, Scheduler(eng, "prefill_priority"), 0)
        router = Router([rep], mode="colocated")
        with pytest.raises(ValueError, match="base-model"):
            router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                  session_id="s1"))
        # the refusal did NOT bind s1 to tenant None: the session's
        # real first turn (the merged tenant) is accepted
        rid = router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                    tenant_id="t1", session_id="s1"))
        results = router.run()
        assert rid in results

    def test_refused_scheduler_submit_does_not_pin_session(self, lm,
                                                           bank):
        engine = _engine(lm, bank)
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="cannot be served"):
            sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                 tenant_id="ghost", session_id="s1"))
        sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                             tenant_id="t1", session_id="s1"))
        sched.run()


def test_dead_decode_pool_reads_as_outage_not_residency(lm_request=None):
    """Review finding: _choose_decode filtered residency before the
    alive check, so a dead decode pool was misdiagnosed as a missing
    adapter."""
    from chainermn_tpu.serving.cluster.router import Router

    class _Rep:
        def __init__(self, rid):
            self.replica_id, self.alive, self.role = rid, True, None
            self.engine = type("E", (), {"max_len": 64})()
            self.scheduler = None

    r = Router.__new__(Router)
    r.replicas = {0: _Rep(0), 1: _Rep(1)}
    r._decode_ids = [1]
    r.replicas[1].alive = False
    with pytest.raises(RuntimeError, match="no alive decode replica"):
        r._choose_decode("acme")


def test_gather_with_merged_tenant_raises(lm=None):
    """Review finding: an explicit gather engine silently ignored
    merged_tenant instead of refusing like every other invalid
    combination."""
    import jax
    import jax.numpy as jnp

    model = tiny_lm()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32), train=False)
    b = AdapterBank(model, capacity=3, rank=2)
    with pytest.raises(ValueError, match="only meaningful"):
        ServingEngine(model, params, num_slots=2, max_len=32,
                      decode_impl="paged", kv_block_size=8,
                      prefill_buckets=(4, 8), adapter_bank=b,
                      adapter_impl="gather", merged_tenant="acme")
