"""ZeRO optimizer-state sharding tests: sharded-state training must equal
full-state single-device training (SURVEY.md section 4 invariant), and the
per-shard state really must be 1/n-sized."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel.zero import (
    zero_shard_optimizer,
    zero_state_specs,
)


def _params():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 3)
    return {
        "w1": jax.random.normal(ks[0], (17, 9)),  # deliberately odd shapes
        "b1": jax.random.normal(ks[1], (9,)),
        "w2": jax.random.normal(ks[2], (9, 5)),
    }


def _loss(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = h @ params["w2"]
    return ((pred - y) ** 2).mean()


class TestZeroSharding:
    def test_matches_unsharded_adam(self, comm):
        params = _params()
        n = comm.size
        ax = comm.axis_name
        batch = 4 * n
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, 17))
        y = jax.random.normal(jax.random.PRNGKey(2), (batch, 5))

        inner = optax.adamw(1e-2)

        # --- reference: plain adam on the full batch, full state
        ref_params = params
        ref_state = inner.init(ref_params)
        for _ in range(3):
            grads = jax.grad(_loss)(ref_params, x, y)
            updates, ref_state = inner.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)

        # --- ZeRO: sharded state inside shard_map
        zopt = zero_shard_optimizer(inner, ax)
        st_spec = zero_state_specs(inner, params, n, ax)

        zstate = jax.jit(
            shard_map(
                zopt.init, mesh=comm.mesh, in_specs=P(),
                out_specs=st_spec, check_vma=False,
            )
        )(params)

        def local_step(params, zstate, xb, yb):
            loss, grads = jax.value_and_grad(_loss)(params, xb, yb)
            grads = jax.lax.pmean(grads, ax)  # DP grad averaging first
            updates, zstate = zopt.update(grads, zstate, params)
            params = optax.apply_updates(params, updates)
            return params, zstate

        step = jax.jit(
            shard_map(
                local_step,
                mesh=comm.mesh,
                in_specs=(P(), st_spec, P(ax), P(ax)),
                out_specs=(P(), st_spec),
                check_vma=False,
            )
        )
        zparams = params
        for _ in range(3):
            zparams, zstate = step(zparams, zstate, x, y)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            zparams,
            ref_params,
        )

    def test_state_is_sharded(self, comm):
        """The global adam moment leaves hold n chunks of ceil(size/n) —
        1/n of the state per shard."""
        params = _params()
        n = comm.size
        ax = comm.axis_name
        inner = optax.adam(1e-3)
        zopt = zero_shard_optimizer(inner, ax)
        st_spec = zero_state_specs(inner, params, n, ax)

        zstate = jax.jit(
            shard_map(
                zopt.init, mesh=comm.mesh, in_specs=P(),
                out_specs=st_spec, check_vma=False,
            )
        )(params)
        mu = zstate[0].mu  # first moment, chunks concatenated over ax
        for name, leaf in params.items():
            chunk = -(-leaf.size // n)
            assert mu[name].shape == (n * chunk,), (name, mu[name].shape)
            # the sharding really spreads it over the mesh axis
            assert ax in str(mu[name].sharding.spec)