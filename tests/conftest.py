"""Test harness configuration.

The reference simulated "multi-node" with N MPI processes on one host
(``mpiexec -n 2 pytest ...``, SURVEY.md section 4). The TPU-native analog is
a single process with N virtual host-platform devices: set
``--xla_force_host_platform_device_count=8`` *before* JAX initialises, and
build meshes from ``jax.devices('cpu')`` (NaiveCommunicator does this) so
tests are hermetic on any machine, TPU present or not.
"""

import os
import tempfile

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402
import pytest  # noqa: E402

# Older baked-in jax (0.4.x) has no top-level ``jax.shard_map``; install
# the one-place compatibility gate BEFORE any test module's
# ``from jax import shard_map`` runs (conftest imports first).
from chainermn_tpu import _jax_compat  # noqa: E402,F401

# Hermeticity for the autotune registry (chainermn_tpu.tuning): the
# repo-root .autotune_cache.json is a bench-mutated artifact — a prior
# `python bench.py` on this machine could flip which code path the
# "hermetic" suite exercises. Pin the suite to pure-table resolution
# (deterministic) and point the cache at an untracked temp path so no
# test write touches the repo file. tests/test_tuning.py overrides both
# per-test via monkeypatch to exercise cache/measurement behaviour.
os.environ["CHAINERMN_TPU_AUTOTUNE"] = "off"
os.environ.setdefault(
    "CHAINERMN_TPU_AUTOTUNE_CACHE",
    os.path.join(tempfile.gettempdir(), f"autotune_test_{os.getpid()}.json"),
)

# Same hermeticity rule for the observability recorder: a developer
# shell (or a capture-script run) exporting CHAINERMN_TPU_TRACE must not
# make the suite write trace files — tests that need a recorder enable
# one explicitly (tests/test_trace.py).
os.environ.pop("CHAINERMN_TPU_TRACE", None)
os.environ.pop("CHAINERMN_TPU_TRACE_SYNC", None)
# ...and for the live telemetry plane (ISSUE 6): an exported metrics
# port would make every Trainer.run/Scheduler construction in the suite
# spawn an HTTP listener, and a hang-dump threshold would arm watchdog
# threads that write hang_dump_*.json into the repo — tests that need
# them start exporter/watchdog explicitly (tests/test_metrics.py).
os.environ.pop("CHAINERMN_TPU_METRICS_PORT", None)
os.environ.pop("CHAINERMN_TPU_HANG_DUMP_S", None)
os.environ.pop("CHAINERMN_TPU_HANG_DUMP_DIR", None)

# The suite is CPU-mesh-only by design, but an externally injected
# accelerator-plugin shim (sitecustomize on PYTHONPATH) can HANG jax
# backend discovery outright when its tunnel is dead — observed live in
# round 2, and the cause of round 1's red driver artifacts. The shim also
# overrides the JAX_PLATFORMS env var at interpreter startup, so the pin
# must happen at the config level, after `import jax` (which runs after
# sitecustomize) and before the first backend init: with platforms pinned
# to cpu, the plugin's backend factory is simply never invoked.
jax.config.update("jax_platforms", "cpu")

# Default eager/jit computations to the CPU backend: reference values in
# tests must use the same arithmetic as the CPU-mesh distributed versions
# (the real TPU's default bf16 matmul precision would otherwise skew
# eager-computed expectations by ~1e-3).
jax.config.update("jax_default_device", jax.devices("cpu")[0])


def load_example(*rel):
    """Load an example module by FILE PATH. A site-packages regular
    package named ``examples`` shadows the repo's namespace portions for
    any subdirectory both define (observed: ``examples.transformer``),
    so package imports are unreliable for examples — use this instead."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", *rel,
    )
    spec = importlib.util.spec_from_file_location(rel[-1][:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, "conftest must set device count before jax import"
    return devs[:8]


@pytest.fixture(scope="session")
def comm():
    """The canonical 8-slot test communicator (CPU mesh)."""
    from chainermn_tpu import create_communicator

    return create_communicator("naive")
