"""Detection stress workload tests (BASELINE.json 'Faster-RCNN stress'
config): odd-channel grads through the fused allreduce, masked ragged
ground truth, and the shape-bucket compile discipline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models.detection import (
    TinyDetector,
    detection_loss,
    iou_matrix,
    make_anchors,
)


def _batch(rng, b, hw, n_boxes=3):
    H, W = hw
    images = rng.randn(b, H, W, 3).astype(np.float32)
    boxes = np.zeros((b, 4, 4), np.float32)
    mask = np.zeros((b, 4), np.float32)
    for i in range(b):
        for j in range(n_boxes):
            boxes[i, j] = (10 + 20 * j, 10 + 20 * j, 90 + 20 * j, 90 + 20 * j)
            mask[i, j] = 1.0
    return jnp.asarray(images), jnp.asarray(boxes), jnp.asarray(mask)


def test_iou_matrix_known_values():
    a = jnp.asarray([[0.0, 0.0, 10.0, 10.0]])
    g = jnp.asarray([[0.0, 0.0, 10.0, 10.0], [5.0, 5.0, 15.0, 15.0],
                     [20.0, 20.0, 30.0, 30.0]])
    iou = np.asarray(iou_matrix(a, g))[0]
    np.testing.assert_allclose(iou[0], 1.0, atol=1e-6)
    np.testing.assert_allclose(iou[1], 25.0 / 175.0, atol=1e-6)
    np.testing.assert_allclose(iou[2], 0.0, atol=1e-6)


def test_anchors_cover_feature_map():
    anchors = make_anchors(4, 6)
    assert anchors.shape == (4 * 6 * 9, 4)
    # centers stay within the image extent implied by the stride
    cy = (anchors[:, 0] + anchors[:, 2]) / 2
    assert float(cy.min()) > 0 and float(cy.max()) < 4 * 16


def test_loss_finite_and_odd_grads(comm):
    """Odd channel counts (13/27/54) produce odd-shaped grads; they must
    flow through the distributed pmean unchanged and stay finite."""
    model = TinyDetector()
    rng = np.random.RandomState(0)
    images, boxes, mask = _batch(rng, comm.size, (128, 160))
    params = model.init(jax.random.key(0), images[:1])
    # Check the odd shapes really are odd.
    shapes = [x.shape for x in jax.tree.leaves(params)]
    assert any(13 in s for s in shapes) and any(27 in s for s in shapes)

    def local(params, batch):
        im, bx, mk = batch

        def loss_fn(p):
            obj, deltas = model.apply(p, im)
            return detection_loss(obj, deltas, bx, mk)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.lax.pmean(loss, "data"), jax.lax.pmean(grads, "data")

    loss, grads = jax.jit(
        shard_map(local, mesh=comm.mesh, in_specs=(P(), P("data")),
                  out_specs=(P(), P()), check_vma=False)
    )(params, (images, boxes, mask))
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))


def test_padded_boxes_do_not_affect_loss():
    model = TinyDetector()
    rng = np.random.RandomState(1)
    images, boxes, mask = _batch(rng, 2, (128, 128))
    params = model.init(jax.random.key(0), images[:1])
    obj, deltas = model.apply(params, images)
    l1 = detection_loss(obj, deltas, boxes, mask)
    garbage = boxes.at[:, 3].set(jnp.asarray([64.0, 64.0, 640.0, 640.0]))
    l2 = detection_loss(obj, deltas, garbage, mask)  # row 3 is masked out
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_no_gt_image_trains():
    """All-padding (no real boxes): loss reduces to pure background BCE and
    must stay finite (the any_gt guard)."""
    model = TinyDetector()
    rng = np.random.RandomState(2)
    images, boxes, mask = _batch(rng, 2, (128, 128))
    mask = jnp.zeros_like(mask)
    params = model.init(jax.random.key(0), images[:1])
    obj, deltas = model.apply(params, images)
    loss = detection_loss(obj, deltas, boxes, mask)
    assert np.isfinite(float(loss))
    g = jax.grad(
        lambda p: detection_loss(*model.apply(p, images), boxes, mask)
    )(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))


@pytest.mark.parametrize("hw", [(128, 128), (128, 160), (160, 128)])
def test_shape_buckets_each_compile_once(comm, hw):
    """Each (H, W) bucket is one static shape — the example's per-bucket
    compile discipline holds by construction; smoke the step per bucket."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "train_detection",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "examples", "detection", "train_detection.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rng = np.random.RandomState(3)
    images, boxes, mask = mod.synthetic_batch(rng, comm.size, hw)
    model = TinyDetector()
    params = model.init(jax.random.key(0), jnp.asarray(images[:1]))
    obj, deltas = model.apply(params, jnp.asarray(images))
    loss = detection_loss(obj, deltas, jnp.asarray(boxes), jnp.asarray(mask))
    assert np.isfinite(float(loss))


class TestTwoStage:
    """Faster-RCNN-style second stage (round-4 VERDICT item 5): static
    top-K proposals, bilinear RoI-align, per-RoI class+box head — with
    the suite's core invariant (dist == single, values AND grads)."""

    def _batch(self, rng, b, hw=(128, 128)):
        images, boxes, mask = _batch(rng, b, hw)
        labels = jnp.asarray(rng.randint(0, 7, size=mask.shape), jnp.int32)
        return images, boxes, mask, labels

    def test_forward_shapes_and_static_topk(self):
        from chainermn_tpu.models.detection import TwoStageDetector

        model = TwoStageDetector(num_rois=16, roi_size=5)
        rng = np.random.RandomState(0)
        images, *_ = self._batch(rng, 2)
        params = model.init(jax.random.key(0), images[:1])
        out = model.apply(params, images)
        assert out["proposals"].shape == (2, 16, 4)
        assert out["cls"].shape == (2, 16, 8)  # 7 classes + background
        assert out["refine"].shape == (2, 16, 4)
        # proposals stay inside the image and are non-degenerate
        p = np.asarray(out["proposals"])
        assert (p[..., 2] > p[..., 0]).all() and (p[..., 3] > p[..., 1]).all()
        assert p.min() >= 0.0 and p.max() <= 128.0
        # the head's odd widths show up in the grads-to-come
        shapes = [x.shape for x in jax.tree.leaves(params)]
        assert any(93 in s for s in shapes)

    def test_roi_align_constant_and_linear_fields(self):
        """Bilinear sampling must reproduce a constant feature exactly and
        a linear-in-y field at the analytic cell-center values."""
        from chainermn_tpu.models.detection import roi_align

        S = 4
        const = jnp.full((8, 8, 3), 2.5)
        box = jnp.asarray([[1.0, 1.0, 7.0, 7.0]])
        out = np.asarray(roi_align(const, box, S))
        np.testing.assert_allclose(out, 2.5, atol=1e-6)

        lin = jnp.broadcast_to(
            jnp.arange(8.0)[:, None, None], (8, 8, 1)
        )
        out = np.asarray(roi_align(lin, box, S))[0, :, 0, 0]
        # cell centers at y = 1 + (i+.5)*6/4, sampled at y-0.5 in index
        # space -> value = y - 0.5
        want = 1.0 + (np.arange(S) + 0.5) * 6.0 / S - 0.5
        np.testing.assert_allclose(out, want, atol=1e-5)

    def test_padded_gt_inert_and_no_gt_finite(self):
        from chainermn_tpu.models.detection import (
            TwoStageDetector,
            two_stage_loss,
        )

        model = TwoStageDetector(num_rois=16)
        rng = np.random.RandomState(1)
        images, boxes, mask, labels = self._batch(rng, 2)
        params = model.init(jax.random.key(0), images[:1])
        out = model.apply(params, images)
        l1 = two_stage_loss(out, boxes, mask, labels)
        garbage_boxes = boxes.at[:, 3].set(
            jnp.asarray([64.0, 64.0, 640.0, 640.0])
        )
        garbage_labels = labels.at[:, 3].set(6)
        l2 = two_stage_loss(out, garbage_boxes, mask, garbage_labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)

        loss0 = two_stage_loss(out, boxes, jnp.zeros_like(mask), labels)
        assert np.isfinite(float(loss0))

    def test_dist_equals_single_values_and_grads(self, comm):
        """The core invariant for the two-stage model: distributed loss
        and gradients over the 8-way mesh == single-device on the full
        batch."""
        from chainermn_tpu.models.detection import (
            TwoStageDetector,
            two_stage_loss,
        )

        model = TwoStageDetector(num_rois=16)
        rng = np.random.RandomState(2)
        images, boxes, mask, labels = self._batch(rng, comm.size)
        params = model.init(jax.random.key(0), images[:1])

        def loss_of(p, im, bx, mk, lb):
            return two_stage_loss(model.apply(p, im), bx, mk, lb)

        def local(params, batch):
            im, bx, mk, lb = batch
            loss, grads = jax.value_and_grad(loss_of)(
                params, im, bx, mk, lb
            )
            return (jax.lax.pmean(loss, "data"),
                    jax.lax.pmean(grads, "data"))

        dist_loss, dist_grads = jax.jit(
            shard_map(local, mesh=comm.mesh, in_specs=(P(), P("data")),
                      out_specs=(P(), P()), check_vma=False)
        )(params, (images, boxes, mask, labels))

        # Single device: per-image losses averaged == pmean of shards
        # (each shard holds exactly one image here).
        single_loss, single_grads = jax.value_and_grad(loss_of)(
            params, images, boxes, mask, labels
        )
        np.testing.assert_allclose(
            float(dist_loss), float(single_loss), rtol=1e-5, atol=1e-6
        )
        for a, b in zip(jax.tree.leaves(dist_grads),
                        jax.tree.leaves(single_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )
