"""MultiNodeOptimizer tests — the TPU analog of
``tests/optimizer_tests/test_multi_node_optimizer.py`` (dagger) (SURVEY.md
section 4): applied grads equal the mean of per-rank grads; double-buffering
applies grads with exactly one step of staleness; compressed allreduce stays
close to f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.optimizers import allreduce_gradients, allreduce_grads_transform

N = 8


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def _per_rank_grads(comm):
    """A jitted step where every mesh slot contributes a different gradient;
    returns what the optimizer applied, for comparison with the numpy mean."""
    rng = np.random.RandomState(0)
    return rng.randn(N, 4).astype(np.float32)


def _run_sharded_update(comm, opt, grads_stacked, params, n_steps=1,
                        state=None):
    """Run `opt.update` inside shard_map over the comm's mesh: the production
    usage pattern (gradient reduction happens in-program). ``state``
    threads a prior run's optimizer state (default: fresh init)."""
    mesh = comm.mesh
    axes = comm.grad_axes

    if state is None:
        state = opt.init(params)

    @jax.jit
    def step(params, state, gstack):
        def body(gstack_local):
            g = gstack_local[0]
            updates, new_state = opt.update(g, state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_state

        return shard_map(
            body,
            mesh=mesh,
            in_specs=P(axes),
            out_specs=P(),
            check_vma=False,
        )(gstack)

    out_params, out_state = params, state
    for _ in range(n_steps):
        out_params, out_state = step(out_params, out_state, grads_stacked)
        state = out_state
        params = out_params
    return out_params, out_state


def test_update_applies_mean_gradient(comm):
    grads = _per_rank_grads(comm)
    params = jnp.zeros((4,), jnp.float32)
    opt = create_multi_node_optimizer(optax.sgd(1.0), comm)
    new_params, _ = _run_sharded_update(comm, opt, grads, params)
    np.testing.assert_allclose(
        np.asarray(new_params), -grads.mean(0), rtol=1e-5, atol=1e-6
    )


def test_outside_axis_context_is_identity_reduction(comm):
    # pjit auto-parallel mode: no named axis => reduction is a no-op and XLA
    # handles averaging via sharding propagation. Single-device: exact.
    opt = create_multi_node_optimizer(optax.sgd(1.0), comm)
    params = jnp.zeros((4,), jnp.float32)
    g = jnp.ones((4,), jnp.float32)
    state = opt.init(params)
    updates, _ = jax.jit(opt.update)(g, state, params)
    np.testing.assert_allclose(np.asarray(updates), -np.ones(4), rtol=1e-6)


def test_double_buffering_staleness_semantics(comm):
    """Step t applies grads reduced at step t-1 (reference
    ``_DoubleBufferingOptimizer`` semantics); step 0 applies zeros."""
    grads = _per_rank_grads(comm)
    params = jnp.zeros((4,), jnp.float32)
    opt = create_multi_node_optimizer(optax.sgd(1.0), comm, double_buffering=True)

    # one step: nothing applied yet
    p1, s1 = _run_sharded_update(comm, opt, grads, params, n_steps=1)
    np.testing.assert_allclose(np.asarray(p1), np.zeros(4), atol=1e-7)
    assert int(jax.device_get(s1.step)) == 1

    # two steps with the same grads: exactly one application
    p2, s2 = _run_sharded_update(comm, opt, grads, params, n_steps=2)
    np.testing.assert_allclose(np.asarray(p2), -grads.mean(0), rtol=1e-5, atol=1e-6)


def test_double_buffer_update_independent_of_same_step_collective(comm):
    """Structural certificate of the overlap PRECONDITION (round-4 VERDICT
    item 3), measured on the traced program: with double buffering, the
    parameter update consumed at step t must NOT data-depend on step t's
    psum — only the banked ``communicated_grads`` state may. That
    independence is exactly what lets an async scheduler run the
    collective concurrently with the update (and, across a scan, with
    step t+1's compute); without it (plain mode) the collective sits on
    the critical path by construction."""
    from chainermn_tpu.testing import collective_taint

    params = jnp.zeros((4,), jnp.float32)
    g = jnp.ones((4,), jnp.float32)

    def updates_of(double_buffering):
        opt = create_multi_node_optimizer(
            optax.sgd(1.0, momentum=0.9), comm,
            double_buffering=double_buffering,
        )
        state = opt.init(params)

        def fn(g, params):
            updates, new_state = opt.update(g, state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_state

        return collective_taint(
            fn, g, params, targets={"psum"},
            axis_env=[(ax, n) for ax, n in
                      zip(comm.mesh.axis_names, comm.mesh.devices.shape)],
        )

    buf_params, buf_state = updates_of(True)
    # The new params are psum-free; the banked grads are psum-derived.
    assert not any(jax.tree.leaves(buf_params))
    assert all(jax.tree.leaves(buf_state.communicated_grads))

    # Sanity check of the analysis itself: plain mode's params DO depend
    # on the same step's psum.
    plain_params, _ = updates_of(False)
    assert all(jax.tree.leaves(plain_params))


def test_double_buffer_scan_next_step_compute_is_collective_free(comm):
    """The scan-level corollary: in a 2-step scanned loop, step t+1's
    forward/backward depends only on params updated with BANKED grads —
    trace one scanned double-buffered step pair and certify the final
    params never acquire a same-step psum dependency."""
    from chainermn_tpu.testing import collective_taint

    opt = create_multi_node_optimizer(
        optax.sgd(1.0), comm, double_buffering=True
    )
    params = jnp.zeros((4,), jnp.float32)
    state = opt.init(params)

    def two_steps(params, state, x):
        def one(carry, _):
            params, state = carry
            loss, g = jax.value_and_grad(
                lambda p: jnp.sum((p * x) ** 2)
            )(params)
            updates, state = opt.update(g, state, params)
            return (optax.apply_updates(params, updates), state), loss

        (params, state), losses = jax.lax.scan(
            one, (params, state), None, length=2
        )
        return params, losses

    taint_params, taint_losses = collective_taint(
        two_steps, params, state, jnp.ones((4,)), targets={"psum"},
        axis_env=[(ax, n) for ax, n in
                  zip(comm.mesh.axis_names, comm.mesh.devices.shape)],
    )
    # After 2 steps the params HAVE absorbed step 0's psum (via the bank)
    # — that is the staleness-1 semantic, not a scheduling hazard. The
    # losses, computed BEFORE each step's update applies, stay psum-free
    # in step 0 and absorb the bank only one step later; the live
    # property certified here is that the scan carry keeps compute and
    # collective decoupled within a step, which the single-step test
    # pins. This scan-level trace guards the carry plumbing: the psum
    # must flow ONLY through communicated_grads.
    assert bool(jax.tree.leaves(taint_params)[0]) is True  # via the bank
    # Step-0 loss precedes any update: must be psum-free.
    # (losses is a stacked [2] array — taint is per-leaf, so assert via a
    # per-step trace instead.)

    def one_step_loss(params, state, x):
        loss, g = jax.value_and_grad(
            lambda p: jnp.sum((p * x) ** 2)
        )(params)
        updates, state = opt.update(g, state, params)
        return optax.apply_updates(params, updates), loss

    t_params, t_loss = collective_taint(
        one_step_loss, params, state, jnp.ones((4,)), targets={"psum"},
        axis_env=[(ax, n) for ax, n in
                  zip(comm.mesh.axis_names, comm.mesh.devices.shape)],
    )
    assert not t_loss      # loss of step t: no same-step collective
    assert not t_params    # update of step t: no same-step collective


def test_collective_taint_tracks_control_dependencies(comm):
    """The analysis must not certify collective-independence for values
    SELECTED by a collective-derived predicate (cond) or loop condition
    (while) — the code-review counterexample for the naive data-only
    propagation."""
    from chainermn_tpu.testing import collective_taint

    ax = comm.axis_name
    env = [(ax, N)]

    def via_cond(g):
        pred = jax.lax.psum(g, ax).sum() > 0
        return jax.lax.cond(pred, lambda: 1.0, lambda: 2.0)

    assert collective_taint(
        via_cond, jnp.ones((4,)), targets={"psum"}, axis_env=env
    )

    def via_while(g):
        s = jax.lax.psum(g, ax).sum()

        def cond(c):
            return c[1] < s

        def body(c):
            return (c[0] + 1.0, c[1] + 1.0)

        return jax.lax.while_loop(cond, body, (0.0, 0.0))[0]

    assert collective_taint(
        via_while, jnp.ones((4,)), targets={"psum"}, axis_env=env
    )

    # And the negative: a cond whose predicate is local stays clean.
    def clean_cond(g):
        return jax.lax.cond(g.sum() > 0, lambda: 1.0, lambda: 2.0)

    assert not collective_taint(
        clean_cond, jnp.ones((4,)), targets={"psum"}, axis_env=env
    )


def test_double_buffer_state_carries_reduced_grads(comm):
    grads = _per_rank_grads(comm)
    params = jnp.zeros((4,), jnp.float32)
    opt = create_multi_node_optimizer(optax.sgd(1.0), comm, double_buffering=True)
    _, state = _run_sharded_update(comm, opt, grads, params, n_steps=1)
    np.testing.assert_allclose(
        np.asarray(state.communicated_grads), grads.mean(0), rtol=1e-5, atol=1e-6
    )


def test_bf16_compressed_allreduce_close(comm):
    grads = _per_rank_grads(comm)
    params = jnp.zeros((4,), jnp.float32)
    opt = create_multi_node_optimizer(
        optax.sgd(1.0), comm, allreduce_grad_dtype=jnp.bfloat16
    )
    new_params, _ = _run_sharded_update(comm, opt, grads, params)
    np.testing.assert_allclose(
        np.asarray(new_params), -grads.mean(0), rtol=2e-2, atol=2e-2
    )


def test_transform_composes_with_chain(comm):
    grads = _per_rank_grads(comm)
    params = jnp.zeros((4,), jnp.float32)
    opt = optax.chain(allreduce_grads_transform(comm), optax.sgd(1.0))

    mesh = comm.mesh
    state = opt.init(params)

    @jax.jit
    def step(gstack):
        def body(g):
            updates, _ = opt.update(g[0], state, params)
            return optax.apply_updates(params, updates)

        return shard_map(
            body, mesh=mesh, in_specs=P(comm.grad_axes), out_specs=P(),
            check_vma=False,
        )(gstack)

    np.testing.assert_allclose(
        np.asarray(step(grads)), -grads.mean(0), rtol=1e-5, atol=1e-6
    )


def test_adam_end_to_end_matches_single_process(comm):
    """Distributed Adam on mean grads == single-process Adam on the big
    batch's mean gradient — the reference's core invariant."""
    grads = _per_rank_grads(comm)
    params = jnp.ones((4,), jnp.float32)
    opt = create_multi_node_optimizer(optax.adam(1e-2), comm)
    dist_params, _ = _run_sharded_update(comm, opt, grads, params, n_steps=3)

    ref_opt = optax.adam(1e-2)
    ref_state = ref_opt.init(params)
    ref_params = params
    for _ in range(3):
        upd, ref_state = ref_opt.update(jnp.asarray(grads.mean(0)), ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)
    np.testing.assert_allclose(
        np.asarray(dist_params), np.asarray(ref_params), rtol=1e-5, atol=1e-6
    )


def test_broadcast_replicates_params(comm):
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    params = {"w": np.ones((3, 3), np.float32)}
    out = opt.broadcast(params)
    assert out["w"].sharding.is_fully_replicated


def test_allreduce_gradients_function_requires_args():
    with pytest.raises(ValueError):
        allreduce_gradients({"g": jnp.zeros(2)})


class TestInt8CompressedAllreduce:
    """Quantized int8-wire gradient allreduce (beyond the reference's
    fp16 compression): accuracy against the exact mean, the structural
    int8-wire certificate, multi-axis meshes, and the optimizer path."""

    def _exact_and_quant(self, comm, x, axes=None):
        from chainermn_tpu.parallel.collectives import int8_allreduce_mean

        axes = axes or comm.grad_axes
        mesh = comm.mesh

        def run(fn):
            def body(xl):
                return fn(xl[0])[None]

            return jax.jit(shard_map(
                body, mesh=mesh,
                in_specs=P(axes), out_specs=P(axes), check_vma=False,
            ))(x)

        quant = run(lambda v: int8_allreduce_mean(v, axes))
        exact = run(lambda v: jax.lax.pmean(v, axes))
        return np.asarray(quant), np.asarray(exact)

    def test_matches_exact_mean_within_quantization_noise(self, comm):
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(N, 1000).astype(np.float32))
        quant, exact = self._exact_and_quant(comm, x)
        # two rounding stages, each <= amax/254 absolute
        amax = np.abs(np.asarray(x)).max()
        np.testing.assert_allclose(quant[0], exact[0], atol=2 * amax / 100)
        # identical on every shard (it IS an allreduce)
        for r in range(1, N):
            np.testing.assert_array_equal(quant[r], quant[0])

    def test_odd_sizes_and_zero_grads(self, comm):
        rng = np.random.RandomState(8)
        # size not divisible by 8 exercises the pad/unpad path
        x = jnp.asarray(rng.randn(N, 37).astype(np.float32))
        quant, exact = self._exact_and_quant(comm, x)
        amax = np.abs(np.asarray(x)).max()
        np.testing.assert_allclose(quant[0], exact[0], atol=2 * amax / 100)
        # all-zero gradients survive the scale floor exactly
        z = jnp.zeros((N, 16), jnp.float32)
        quant, _ = self._exact_and_quant(comm, z)
        np.testing.assert_array_equal(quant, np.zeros((N, 16)))

    def test_wire_is_int8_structurally(self, comm):
        """The compression claim, measured on the program: the bulk
        collectives (all_to_all chunks + the phase-2 all_gather) carry
        int8; only the two scalar scale gathers are f32."""
        from jax.extend import core as jex_core

        from chainermn_tpu.parallel.collectives import int8_allreduce_mean
        from chainermn_tpu.testing import _subjaxprs

        closed = jax.make_jaxpr(
            lambda g: int8_allreduce_mean(g, "data"),
            axis_env=[("data", N)],
        )(jnp.zeros((1024,), jnp.float32))

        found = {"all_to_all": [], "all_gather": []}

        def walk(jaxpr):
            for eqn in jaxpr.eqns:
                if eqn.primitive.name in found:
                    found[eqn.primitive.name].append(
                        eqn.invars[0].aval.dtype
                        if not isinstance(eqn.invars[0], jex_core.Literal)
                        else eqn.invars[0].val.dtype
                    )
                for _, sub in _subjaxprs(eqn.params):
                    walk(sub)

        walk(closed.jaxpr)
        assert [str(d) for d in found["all_to_all"]] == ["int8"], found
        gather_dtypes = sorted(str(d) for d in found["all_gather"])
        # one int8 payload gather + three f32/int8... exactly: scales
        # (f32), phase-2 shards (int8), phase-2 scales (f32)
        assert gather_dtypes.count("int8") == 1, found
        assert all(d in ("int8", "float32") for d in gather_dtypes), found

    def test_two_axis_mesh(self):
        comm = create_communicator(
            "hierarchical", devices=jax.devices("cpu")[:N]
        )
        rng = np.random.RandomState(9)
        x = jnp.asarray(rng.randn(N, 65).astype(np.float32))
        quant, exact = self._exact_and_quant(
            comm, x, axes=("inter", "intra")
        )
        amax = np.abs(np.asarray(x)).max()
        np.testing.assert_allclose(quant[0], exact[0], atol=2 * amax / 100)

    @pytest.mark.parametrize("name", ["naive", "two_dimensional"])
    def test_optimizer_path_applies_quantized_mean(self, name):
        comm = create_communicator(
            name, devices=jax.devices("cpu")[:N],
            allreduce_grad_dtype=jnp.int8,
        )
        grads = _per_rank_grads(comm)
        params = jnp.zeros((4,), jnp.float32)
        opt = create_multi_node_optimizer(optax.sgd(1.0), comm)
        new_params, _ = _run_sharded_update(comm, opt, grads, params)
        amax = np.abs(grads).max()
        np.testing.assert_allclose(
            np.asarray(new_params), -grads.mean(0), atol=2 * amax / 100
        )

    def test_identity_outside_axis_context(self):
        from chainermn_tpu.optimizers import allreduce_gradients

        g = jnp.asarray(np.random.RandomState(10).randn(16), jnp.float32)
        out = allreduce_gradients(
            {"g": g}, axis_names=("data",), compress_dtype=jnp.int8
        )
        np.testing.assert_array_equal(np.asarray(out["g"]), np.asarray(g))

    def test_gradient_is_straight_through(self, comm):
        """CLAUDE.md gradient invariant: jax.grad through the quantized
        allreduce equals jax.grad through the exact pmean (the custom
        VJP is the exact mean's transpose — straight-through)."""
        from chainermn_tpu.parallel.collectives import int8_allreduce_mean

        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(N, 24).astype(np.float32))
        W = jnp.asarray(rng.randn(N, 24).astype(np.float32))

        def grad_of(red):
            def body(xl):
                def lf(v):
                    y = red(v[0])
                    idx = jax.lax.axis_index("data")
                    return jnp.sum(y * jax.lax.dynamic_index_in_dim(
                        W, idx, 0, keepdims=False))

                return jax.grad(lf)(xl)

            return np.asarray(jax.jit(shard_map(
                body, mesh=comm.mesh,
                in_specs=P("data"), out_specs=P("data"), check_vma=False,
            ))(x))

        g_quant = grad_of(lambda v: int8_allreduce_mean(v, "data"))
        g_exact = grad_of(lambda v: jax.lax.pmean(v, "data"))
        np.testing.assert_allclose(g_quant, g_exact, rtol=1e-6)

    def test_eager_allreduce_grad_not_truncated(self):
        """The eager debugging path must quantize-dequantize, never raw
        astype(int8) (which truncates sub-1.0 gradients to zero)."""
        comm = create_communicator(
            "naive", devices=jax.devices("cpu")[:N],
            allreduce_grad_dtype=jnp.int8,
        )
        rng = np.random.RandomState(12)
        g = (rng.randn(N, 32) * 0.01).astype(np.float32)  # all |g| << 1
        out = np.asarray(comm.allreduce_grad({"g": g})["g"])
        exact = g.mean(0)
        assert np.abs(out).max() > 0  # not zeroed
        amax = np.abs(g).max()
        np.testing.assert_allclose(out, exact, atol=2 * amax / 100)

    def test_two_dimensional_int8_stays_bucketed(self):
        """The flat-buffer discipline survives the int8 wire: MANY small
        float leaves ride ONE quantized pipeline (1 all_to_all), not one
        per leaf."""
        from jax.sharding import Mesh

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )
        from chainermn_tpu.testing import count_primitives

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("inter", "intra"))
        comm2d = TwoDimensionalCommunicator(mesh=mesh)
        tree = {f"p{i}": jnp.zeros((5, 3)) for i in range(12)}
        c = count_primitives(
            lambda t: comm2d.reduce_gradients_in_jit(
                t, compress_dtype=jnp.int8
            ),
            tree, axis_env=[("inter", 2), ("intra", 4)],
        )
        assert c.get("all_to_all") == 1, c  # one bucket -> one pipeline


class TestErrorFeedback:
    """EF-SGD over the int8 wire: the stage-1 quantization error is
    carried in optimizer state and fed into the next message, so the
    CUMULATIVE applied gradient tracks the exact mean to one-step noise
    — where plain deterministic rounding drifts linearly.

    The residual is PER-RANK state: these tests thread it across steps
    explicitly stacked [N, ...] under a P(axes) spec (make_train_step
    refuses EF optimizers for exactly this reason — replicated state
    specs cannot carry per-rank values)."""

    def _run_ef_update(self, comm, opt, grads_stacked, params,
                       n_steps=1):
        from chainermn_tpu.optimizers import _ErrorFeedbackState

        mesh, axes = comm.mesh, comm.grad_axes
        state0 = opt.init(params)
        res = jax.tree.map(
            lambda r: jnp.broadcast_to(r[None], (N,) + r.shape),
            state0.residual,
        )
        inner = state0.inner

        @jax.jit
        def step(params, inner, res, gstack):
            def body(gl, rl):
                st = _ErrorFeedbackState(
                    inner=inner,
                    residual=jax.tree.map(lambda x: x[0], rl),
                )
                updates, new_state = opt.update(gl[0], st, params)
                new_params = optax.apply_updates(params, updates)
                return (
                    new_params,
                    new_state.inner,
                    jax.tree.map(lambda x: x[None], new_state.residual),
                )

            return shard_map(
                body, mesh=mesh,
                in_specs=(P(axes), P(axes)),
                out_specs=(P(), P(), P(axes)), check_vma=False,
            )(gstack, res)

        for _ in range(n_steps):
            params, inner, res = step(params, inner, res, grads_stacked)
        return params, inner, res

    def _cumulative_error(self, error_feedback, steps=30):
        comm = create_communicator("naive")
        rng = np.random.RandomState(21)
        # small values with a deliberate sub-quantum spread: one int8
        # quantum is amax/127, so per-rank rounding bias is material
        grads = (rng.randn(N, 6) * 0.01).astype(np.float32)
        grads[0, :] = 0.9  # sets amax; makes tiny entries sub-quantum
        params = jnp.zeros((6,), jnp.float32)
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8,
            error_feedback=error_feedback,
        )
        if error_feedback:
            new_params, _, _ = self._run_ef_update(
                comm, opt, jnp.asarray(grads), params, n_steps=steps
            )
        else:
            new_params, _ = _run_sharded_update(
                comm, opt, jnp.asarray(grads), params, n_steps=steps
            )
        # params = -sum(applied grads); exact would be -steps * mean
        exact = -steps * grads.mean(0)
        return np.abs(np.asarray(new_params) - exact).max(), grads

    def test_cumulative_bias_removed(self):
        err_plain, grads = self._cumulative_error(False)
        err_ef, _ = self._cumulative_error(True)
        quantum = np.abs(grads).max() / 127.0
        # EF keeps the total error bounded by ~a couple of quanta
        assert err_ef < 4 * quantum, (err_ef, quantum)
        # and beats plain rounding (which accumulates its per-step bias)
        assert err_ef < err_plain / 3, (err_ef, err_plain)

    def test_residuals_are_per_rank_distinct(self):
        """The reason the residual needs a per-rank spec: after one step
        with distinct per-rank grads, the residuals differ by rank."""
        comm = create_communicator("naive")
        grads = _per_rank_grads(comm)
        params = jnp.zeros((4,), jnp.float32)
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        _, _, res = self._run_ef_update(
            comm, opt, jnp.asarray(grads), params, n_steps=1
        )
        stacked = np.asarray(jax.tree.leaves(res)[0])  # [N, 4]
        assert not all(
            np.allclose(stacked[r], stacked[0]) for r in range(1, N)
        ), "per-rank residuals should differ for distinct grads"

    def test_non_float_leaves_still_reduced(self):
        """EF must not skip integer leaves: they take the exact pmean
        (reference parity), keeping all ranks' state in sync."""
        from chainermn_tpu.optimizers import _ErrorFeedbackState

        comm = create_communicator("naive")
        mesh, axes = comm.mesh, comm.grad_axes
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        g = {
            "w": jnp.asarray(
                np.random.RandomState(3).randn(N, 4), jnp.float32),
            "count": jnp.asarray(
                np.arange(N, dtype=np.int32)[:, None] * np.ones(
                    (1, 2), np.int32)),
        }
        params = {"w": jnp.zeros((4,)),
                  "count": jnp.zeros((2,), jnp.int32)}
        state = opt.init(params)

        def body(gl, rl):
            st = _ErrorFeedbackState(
                inner=state.inner,
                residual=jax.tree.map(lambda x: x[0], rl),
            )
            updates, _ = opt.update(
                jax.tree.map(lambda x: x[0], gl), st, params
            )
            return updates["count"][None]

        res = jax.tree.map(
            lambda r: jnp.broadcast_to(r[None], (N,) + r.shape),
            state.residual,
        )
        out = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(axes), P(axes)),
            out_specs=P(axes), check_vma=False,
        ))(g, res)
        stacked = np.asarray(out)  # [N, 2]
        # every rank got the same (mean) value for the int leaf
        for r in range(1, N):
            np.testing.assert_array_equal(stacked[r], stacked[0])

    def test_requires_int8_wire(self):
        comm = create_communicator("naive")
        with pytest.raises(ValueError, match="error_feedback requires"):
            create_multi_node_optimizer(
                optax.sgd(1.0), comm,
                allreduce_grad_dtype=jnp.bfloat16, error_feedback=True,
            )

    def test_train_step_carries_residual_per_rank(self):
        """EF through the STANDARD trainer path: make_train_step carries
        the residual sharded over the grad axes (stacked [n, ...]), the
        cumulative applied gradient tracks the exact mean (EF working),
        and the residual array is genuinely per-rank-sharded."""
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        comm = create_communicator("naive")
        rng = np.random.RandomState(22)
        grads_np = (rng.randn(N, 6) * 0.01).astype(np.float32)
        grads_np[0, :] = 0.9  # amax row: makes tiny entries sub-quantum
        params = {"w": jnp.zeros((6,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        state = create_train_state(params, opt, comm)
        res0 = jax.tree.leaves(state.opt_state.residual)[0]
        assert res0.shape == (N, 6)
        assert not res0.sharding.is_fully_replicated

        # loss = sum(params * batch-row): grad per shard = its batch row
        def loss_fn(p, batch):
            return jnp.sum(p["w"] * batch[0])

        step = make_train_step(loss_fn, opt, comm, donate=False)
        batch = jnp.asarray(grads_np)
        steps = 30
        for _ in range(steps):
            state, _ = step(state, batch)
        exact = -steps * grads_np.mean(0)
        err = np.abs(np.asarray(state.params["w"]) - exact).max()
        quantum = np.abs(grads_np).max() / 127.0
        assert err < 4 * quantum, (err, quantum)
        # residuals differ per rank (per-rank state survived the loop)
        stacked = np.asarray(
            jax.tree.leaves(state.opt_state.residual)[0]
        )
        assert not all(
            np.allclose(stacked[r], stacked[0]) for r in range(1, N)
        )

    def test_train_step_rejects_unstacked_residual(self):
        """A bare optimizer.init() state (unstacked residual) must fail
        LOUDLY at trace time, naming create_train_state as the fix."""
        from chainermn_tpu.training.train_step import (
            TrainState,
            make_train_step,
        )

        comm = create_communicator("naive")
        params = {"w": jnp.zeros((8,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        bad_state = TrainState(
            params=params, opt_state=opt.init(params),
            step=jnp.zeros((), jnp.int32), model_state=(),
        )
        step = make_train_step(
            lambda p, b: jnp.sum(p["w"] * b[0]), opt, comm, donate=False
        )
        with pytest.raises(ValueError, match="create_train_state"):
            step(bad_state, jnp.ones((N, 8)))
        # Non-divisible / scalar-leaf shapes must hit the SAME message,
        # not a generic shard_map divisibility error.
        params6 = {"w": jnp.zeros((6,), jnp.float32)}
        bad6 = TrainState(
            params=params6, opt_state=opt.init(params6),
            step=jnp.zeros((), jnp.int32), model_state=(),
        )
        with pytest.raises(ValueError, match="create_train_state"):
            step(bad6, jnp.ones((N, 6)))

    def test_composes_with_double_buffering(self):
        """EF + double buffering: staleness-1 semantics intact (step 0
        applies zeros; two steps apply exactly one reduced grad) and
        both state layers are present."""
        comm = create_communicator("naive")
        grads = _per_rank_grads(comm)
        params = jnp.zeros((4,), jnp.float32)
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8,
            double_buffering=True, error_feedback=True,
        )
        state = opt.init(params)
        from chainermn_tpu.optimizers import (
            _DoubleBufferState,
            _ErrorFeedbackState,
        )

        assert isinstance(state, _ErrorFeedbackState)
        assert isinstance(state.inner, _DoubleBufferState)

        p1, _, _ = self._run_ef_update(comm, opt, grads, params,
                                       n_steps=1)
        np.testing.assert_allclose(np.asarray(p1), np.zeros(4), atol=1e-7)
        p2, _, _ = self._run_ef_update(comm, opt, grads, params,
                                       n_steps=2)
        amax = np.abs(grads).max()
        np.testing.assert_allclose(
            np.asarray(p2), -grads.mean(0), atol=2 * amax / 100
        )

    def test_identity_outside_axis_context_keeps_residual(self):
        comm = create_communicator("naive")
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        params = jnp.zeros((4,), jnp.float32)
        g = jnp.full((4,), 0.25, jnp.float32)
        state = opt.init(params)
        updates, new_state = jax.jit(opt.update)(g, state, params)
        np.testing.assert_allclose(np.asarray(updates), -0.25 * np.ones(4),
                                   rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(new_state.residual)[0]),
            np.zeros(4),
        )

    def test_train_step_ef_on_hierarchical_mesh(self):
        """EF through the trainer on a TWO-axis ('inter','intra') mesh:
        the residual shards over the flattened axes tuple and the
        quantized mean still tracks the exact mean."""
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        comm = create_communicator(
            "hierarchical", devices=jax.devices("cpu")[:N],
            allreduce_grad_dtype=jnp.int8,
        )
        rng = np.random.RandomState(23)
        grads_np = (rng.randn(N, 4) * 0.01).astype(np.float32)
        grads_np[0, :] = 0.9
        params = {"w": jnp.zeros((4,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        state = create_train_state(params, opt, comm)
        assert jax.tree.leaves(state.opt_state.residual)[0].shape == (N, 4)

        def loss_fn(p, batch):
            return jnp.sum(p["w"] * batch[0])

        step = make_train_step(loss_fn, opt, comm, donate=False)
        batch = jnp.asarray(grads_np)
        steps = 20
        for _ in range(steps):
            state, _ = step(state, batch)
        exact = -steps * grads_np.mean(0)
        err = np.abs(np.asarray(state.params["w"]) - exact).max()
        quantum = np.abs(grads_np).max() / 127.0
        assert err < 4 * quantum, (err, quantum)


class TestInt8TwoLevel:
    """Topology-aware quantized reduction (round-4): exact psum_scatter
    over intra (ICI), int8 two-phase ONLY over inter (DCN), exact
    all_gather back — the quantized rendering of the reference's
    TwoDimensionalCommunicator algorithm."""

    def _mesh_comm(self):
        from jax.sharding import Mesh

        devs = np.array(jax.devices("cpu")[:N]).reshape(2, 4)
        return Mesh(devs, ("inter", "intra"))

    def test_matches_exact_mean_within_single_stage_noise(self):
        from chainermn_tpu.parallel.collectives import (
            int8_two_level_allreduce_mean,
        )

        mesh = self._mesh_comm()
        rng = np.random.RandomState(31)
        x = jnp.asarray(rng.randn(N, 501).astype(np.float32))  # odd size
        spec = P(("inter", "intra"))

        def run(fn):
            def body(xl):
                return fn(xl[0])[None]

            return np.asarray(jax.jit(shard_map(
                body, mesh=mesh, in_specs=spec, out_specs=spec,
                check_vma=False,
            ))(x))

        quant = run(lambda v: int8_two_level_allreduce_mean(
            v, "intra", "inter"))
        exact = run(lambda v: jax.lax.pmean(v, ("inter", "intra")))
        amax = np.abs(np.asarray(x)).max()
        # intra stays exact; only the inter stage quantizes (2 roundings
        # of the int8 scheme over the intra-summed shard)
        np.testing.assert_allclose(quant[0], exact[0],
                                   atol=2 * N * amax / 100)
        for r in range(1, N):
            np.testing.assert_array_equal(quant[r], quant[0])

    def test_topology_structure(self):
        """Structural certificate: exact reduce_scatter + all_gather ride
        INTRA; the int8 all_to_all + payload gather ride INTER only."""
        from chainermn_tpu.parallel.collectives import (
            int8_two_level_allreduce_mean,
        )
        from chainermn_tpu.testing import collect_collectives

        seen = collect_collectives(
            lambda g: int8_two_level_allreduce_mean(g, "intra", "inter"),
            jnp.zeros((1024,), jnp.float32),
            axis_env=[("inter", 2), ("intra", 4)],
        )
        _assert_int8_rides_inter_only(seen)

    def test_gradient_is_straight_through(self):
        """CLAUDE.md values-AND-gradients invariant: jax.grad through
        the topology-aware quantized reduction equals jax.grad through
        the exact two-axis pmean (straight-through custom VJP)."""
        from chainermn_tpu.parallel.collectives import (
            int8_two_level_allreduce_mean,
        )

        mesh = self._mesh_comm()
        rng = np.random.RandomState(32)
        x = jnp.asarray(rng.randn(N, 16).astype(np.float32))
        W = jnp.asarray(rng.randn(N, 16).astype(np.float32))
        spec = P(("inter", "intra"))

        def grad_of(red):
            def body(xl):
                def lf(v):
                    y = red(v[0])
                    ii = jax.lax.axis_index("inter")
                    jj = jax.lax.axis_index("intra")
                    idx = ii * 4 + jj
                    return jnp.sum(y * jax.lax.dynamic_index_in_dim(
                        W, idx, 0, keepdims=False))

                return jax.grad(lf)(xl)

            return np.asarray(jax.jit(shard_map(
                body, mesh=mesh, in_specs=spec, out_specs=spec,
                check_vma=False,
            ))(x))

        g_quant = grad_of(lambda v: int8_two_level_allreduce_mean(
            v, "intra", "inter"))
        g_exact = grad_of(lambda v: jax.lax.pmean(v, ("inter", "intra")))
        np.testing.assert_allclose(g_quant, g_exact, rtol=1e-6)


class TestShardLevelEF:
    """Round-5 shard-level error feedback for the TOPOLOGY-AWARE wire
    (``int8_two_level_allreduce_mean_with_feedback``): the intra stage
    is exact, so the residual lives at the int8 inter stage's shard
    shape. Same invariants as the flat-wire ``TestErrorFeedback``,
    applied at the stage where the error actually arises."""

    def _mesh_comm(self, shape=(2, 4)):
        from jax.sharding import Mesh

        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:N]).reshape(shape)
        return TwoDimensionalCommunicator(
            mesh=Mesh(devs, ("inter", "intra"))
        )

    def test_zero_residual_matches_bare_two_level(self):
        """With a zero residual the feedback form must equal the bare
        topology-aware wire EXACTLY (same frame, same rounding), and
        return a shard-shaped residual."""
        from chainermn_tpu.parallel.collectives import (
            int8_two_level_allreduce_mean,
            int8_two_level_allreduce_mean_with_feedback,
            two_level_shard_len,
        )

        comm = self._mesh_comm()
        L = 33  # deliberately not divisible by intra=4
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(N, L).astype(np.float32))
        shard_len = two_level_shard_len(L, 4)
        spec = P(("inter", "intra"))

        def body(xl):
            v = xl[0]
            bare = int8_two_level_allreduce_mean(v, "intra", "inter")
            mean, res = int8_two_level_allreduce_mean_with_feedback(
                v, jnp.zeros((shard_len,), jnp.float32),
                "intra", "inter",
            )
            return bare[None], mean[None], res[None]

        bare, mean, res = jax.jit(shard_map(
            body, mesh=comm.mesh, in_specs=spec,
            out_specs=(spec, spec, spec), check_vma=False,
        ))(x)
        np.testing.assert_array_equal(np.asarray(bare), np.asarray(mean))
        assert res.shape == (N, shard_len)

    def _grads(self):
        """Per-member grads whose INTER-stage message is
        quantization-hostile: coordinate 0 carries an adversarial
        component (sign flipping between the two inter groups, exactly
        cancelling in the mean) that pins the j=0 shard message's amax;
        coordinate 1 (same shard slice) carries a persistent
        sub-half-quantum signal that plain deterministic rounding kills
        every step."""
        g = np.zeros((N, 6), np.float32)
        g[:4, 0], g[4:, 0] = 0.225, -0.225  # intra sums +-0.9, mean 0
        g[:, 1] = 0.003 / 4                 # inter msg 0.003 < q/2
        g[:, 2:] = 0.05                     # healthy super-quantum coords
        return g

    def _trainer(self, **opt_kwargs):
        """Shared trainer setup over the (2,4) mesh with the
        quantization-hostile grads: returns (state, step, batch,
        grads_np, opt)."""
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        comm = self._mesh_comm()
        grads_np = self._grads()
        params = {"w": jnp.zeros((6,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, **opt_kwargs,
        )

        def loss_fn(p, batch):
            return jnp.sum(p["w"] * batch[0])

        state = create_train_state(params, opt, comm)
        step = make_train_step(loss_fn, opt, comm, donate=False)
        return state, step, jnp.asarray(grads_np), grads_np, opt

    def _cumulative(self, error_feedback, steps=30):
        state, step, batch, grads_np, _ = self._trainer(
            error_feedback=error_feedback)
        for _ in range(steps):
            state, _ = step(state, batch)
        exact = -steps * grads_np.mean(0)
        return (np.abs(np.asarray(state.params["w"]) - exact).max(),
                state, grads_np)

    def test_cumulative_bias_removed_at_the_inter_stage(self):
        err_plain, _, grads_np = self._cumulative(False)
        err_ef, state, _ = self._cumulative(True)
        # message-level quantum at the pinned shard: intra-sum amax 0.9
        msg_quantum = 0.9 / 127.0
        # output-level: /(n_inter * n_intra)... but the telescoping
        # bound is at message level divided by the inter mean only.
        assert err_ef < 4 * msg_quantum, (err_ef, msg_quantum)
        assert err_ef < err_plain / 3, (err_ef, err_plain)
        # the per-member shard residuals are genuinely distinct state
        stacked = np.asarray(
            jax.tree.leaves(state.opt_state.residual)[0]
        )
        assert stacked.shape[0] == N
        assert not all(
            np.allclose(stacked[r], stacked[0]) for r in range(1, N)
        )

    def test_plain_two_level_kills_the_subquantum_coordinate(self):
        """The mechanism the EF exists for, asserted directly: without
        feedback the persistent sub-half-quantum coordinate never
        trains."""
        err_plain, state, grads_np = self._cumulative(False)
        w = np.asarray(state.params["w"])
        # coordinate 1's exact target moved; plain int8 left it at ~0
        assert abs(w[1]) < 1e-6, w[1]
        assert abs(30 * grads_np[:, 1].mean()) > 0.02

    def test_topology_structure_with_feedback(self):
        """Structural certificate for the EF form (CLAUDE.md: measured,
        not asserted in prose): adding the residual must not move any
        collective — the exact reduce_scatter and the f32 payload
        all_gather ride INTRA; every int8 collective (all_to_all +
        payload gathers) rides INTER only. A refactor routing f32
        across inter (or int8 across intra) fails here even if every
        numeric test still passes."""
        from chainermn_tpu.parallel.collectives import (
            int8_two_level_allreduce_mean_with_feedback,
            two_level_shard_len,
        )
        from chainermn_tpu.testing import collect_collectives

        L = 1024
        seen = collect_collectives(
            lambda g, e: int8_two_level_allreduce_mean_with_feedback(
                g, e, "intra", "inter"),
            jnp.zeros((L,), jnp.float32),
            jnp.zeros((two_level_shard_len(L, 4),), jnp.float32),
            axis_env=[("inter", 2), ("intra", 4)],
        )
        _assert_int8_rides_inter_only(seen)
        # the residual path adds NO intra-axis traffic beyond the f32
        # scatter/gather pair of the exact frame
        intra_ops = [e for e in seen if "intra" in e[1]]
        assert all(e[2] == "float32" for e in intra_ops), seen

    def test_composes_with_double_buffering_on_two_level_mesh(self):
        """Shard-level EF + double buffering on the (2,4) mesh through
        the standard trainer: staleness-1 intact (step 0 applies
        zeros; two steps apply one reduced grad) with the shard-shaped
        residual carried alongside the banked grads."""
        from chainermn_tpu.optimizers import (
            _DoubleBufferState,
            _ErrorFeedbackState,
        )

        state, step, batch, grads_np, opt = self._trainer(
            double_buffering=True, error_feedback=True)
        assert isinstance(state.opt_state, _ErrorFeedbackState)
        assert isinstance(state.opt_state.inner, _DoubleBufferState)
        state, _ = step(state, batch)
        np.testing.assert_allclose(
            np.asarray(state.params["w"]), np.zeros(6), atol=1e-7)
        state, _ = step(state, batch)
        # exactly one (quantized) mean applied; the healthy coords are
        # super-quantum so they land within one message quantum
        msg_quantum = 0.9 / 127.0
        np.testing.assert_allclose(
            np.asarray(state.params["w"])[2:], -grads_np.mean(0)[2:],
            atol=msg_quantum,
        )

    def test_multi_bucket_layout_and_invariant(self, monkeypatch):
        """The >64 MB path, exercised at test scale by shrinking the
        bucket budget: several float leaves split across MULTIPLE
        buckets, each with its own shard residual. init's layout must
        match the reduction's (the shared _float_bucket_partition), and
        the cumulative-bias invariant must hold across every bucket."""
        import chainermn_tpu.optimizers as opt_mod
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        monkeypatch.setattr(opt_mod, "_EF_BUCKET_BYTES", 64)  # ~16 floats
        comm = self._mesh_comm()
        rng = np.random.RandomState(9)
        # three leaves of 12/8/6 floats -> 64-byte buckets: [12], [8, 6]
        params = {"a": jnp.zeros((12,), jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32),
                  "c": jnp.zeros((6,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        st = opt.init(params)
        from chainermn_tpu.parallel.collectives import two_level_shard_len
        assert [r.shape for r in st.residual] == [
            (two_level_shard_len(12, 4),),
            (two_level_shard_len(14, 4),),
        ]

        grads_np = rng.randn(N, 26).astype(np.float32) * 0.01
        grads_np[0, :] = 0.9  # amax rows: sub-quantum spread elsewhere

        def loss_fn(p, batch):
            flat = jnp.concatenate([p["a"], p["b"], p["c"]])
            return jnp.sum(flat * batch[0])

        state = create_train_state(params, opt, comm)
        step = make_train_step(loss_fn, opt, comm, donate=False)
        batch = jnp.asarray(grads_np)
        steps = 30
        for _ in range(steps):
            state, _ = step(state, batch)
        got = np.concatenate([
            np.asarray(state.params[k]) for k in ("a", "b", "c")
        ])
        exact = -steps * grads_np.mean(0)
        # intra sums can reach 4 * 0.9; EF keeps the cumulative error
        # bounded by a few message-level quanta in EVERY bucket
        msg_quantum = 4 * 0.9 / 127.0
        assert np.abs(got - exact).max() < 4 * msg_quantum

    @pytest.mark.parametrize("shape", [(1, 8), (8, 1), (4, 2)])
    def test_degenerate_and_alternate_factorisations(self, shape):
        """Shard-EF across mesh factorisations: (1,8) has a degenerate
        inter axis — the wire quantizes NOTHING, the mean is exact and
        the residual stays zero; (8,1) has a degenerate intra axis —
        the full buffer is the 'shard' and everything is quantized
        (flat-wire-equivalent); (4,2) is the transposed split. One
        trainer step each, mean within one message quantum, residual
        shaped by two_level_shard_len."""
        from chainermn_tpu.parallel.collectives import two_level_shard_len
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        comm = self._mesh_comm(shape)
        params = {"w": jnp.zeros((10,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        state = create_train_state(params, opt, comm, model_state={})
        g = np.random.RandomState(1).randn(N, 10).astype(np.float32)

        def loss_fn(p, b, ms):
            return jnp.sum(p["w"] * b[0]), ({}, ms)

        step = make_train_step(loss_fn, opt, comm, donate=False)
        state, _ = step(state, (jnp.asarray(g), jnp.zeros(N)))
        w = np.asarray(state.params["w"])
        res = np.asarray(jax.tree.leaves(state.opt_state.residual)[0])
        n_intra = shape[1]
        assert res.shape == (N, two_level_shard_len(10, n_intra))
        err = np.abs(w + g.mean(0)).max()
        if shape[0] == 1:
            # degenerate inter: nothing was quantized
            assert err == 0.0 and np.abs(res).max() == 0.0
        else:
            # quantized inter leg: within ~one message quantum, and the
            # dropped error was captured in the residual
            intra_amax = np.abs(
                g.reshape(shape[0], shape[1], 10).sum(1)).max()
            assert err < 2 * intra_amax / 127.0, (err, intra_amax)
            assert np.abs(res).max() > 0.0


def _assert_int8_rides_inter_only(seen):
    """Shared assertions of the topology-aware wire's structural
    certificates (bare and EF forms): int8 all_to_all + int8 payload
    gathers on INTER only; the exact f32 reduce_scatter on INTRA only.
    ``seen`` is ``chainermn_tpu.testing.collect_collectives`` output."""
    a2a = [e for e in seen if e[0] == "all_to_all"]
    assert a2a and all(e[1] == ("inter",) and e[2] == "int8"
                       for e in a2a), seen
    rs = [e for e in seen if e[0] == "reduce_scatter"]
    assert rs and all(e[1] == ("intra",) and e[2] == "float32"
                      for e in rs), seen
    int8_gathers = [e for e in seen
                    if e[0] == "all_gather" and e[2] == "int8"]
    assert int8_gathers and all(e[1] == ("inter",)
                                for e in int8_gathers), seen


def test_nonfinite_skip_via_optax_composition(comm):
    """``optax.apply_if_finite`` composes with the multi-node wrapper out
    of the box: the finiteness check runs on the REDUCED gradients, so
    every rank sees the same verdict and skips in lockstep (no parameter
    divergence across the mesh). One poisoned rank therefore poisons —
    and skips — the whole step, and the next clean step applies
    normally. Documented in docs/fault_tolerance.md."""
    inner = optax.apply_if_finite(optax.sgd(1.0), max_consecutive_errors=3)
    opt = create_multi_node_optimizer(inner, comm)
    params = jnp.zeros((4,), jnp.float32)

    grads = _per_rank_grads(comm).copy()
    grads[3, 2] = np.nan  # ONE rank contributes a NaN
    poisoned, state = _run_sharded_update(comm, opt, grads, params)
    # allreduce-mean spreads the NaN to every rank; apply_if_finite skips
    # the whole update — params unchanged everywhere.
    np.testing.assert_array_equal(np.asarray(poisoned), np.asarray(params))

    # Recovery is tested THROUGH the post-skip state (a fresh init would
    # only re-test the clean path): notfinite bookkeeping must reset and
    # the inner state must still be valid.
    clean = _per_rank_grads(comm)
    recovered, _ = _run_sharded_update(
        comm, opt, clean, params, state=state
    )
    np.testing.assert_allclose(
        np.asarray(recovered), -clean.mean(0), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------------------------------
# Local SGD / DiLoCo periodic averaging (beyond the reference)
# ---------------------------------------------------------------------------


def test_local_sgd_sync_every_1_equals_per_step_dp(comm):
    """With sync_every=1 and a LINEAR inner (sgd), averaging the locally
    updated candidates equals averaging the gradients: local SGD must
    reproduce the per-step data-parallel wrapper exactly."""
    from chainermn_tpu import create_local_sgd

    grads = _per_rank_grads(comm)
    params = jnp.ones((4,), jnp.float32)
    local = create_local_sgd(optax.sgd(0.5), comm, sync_every=1)
    dp = create_multi_node_optimizer(optax.sgd(0.5), comm)
    p_local, _ = _run_sharded_update(comm, local, grads, params, n_steps=3)
    p_dp, _ = _run_sharded_update(comm, dp, grads, params, n_steps=3)
    np.testing.assert_allclose(
        np.asarray(p_local), np.asarray(p_dp), rtol=1e-5, atol=1e-6
    )


def test_local_sgd_matches_per_worker_simulation(comm):
    """sync_every=3 with a NONLINEAR inner (adam): each member must
    evolve on its own gradients for 3 steps and only then average — the
    oracle is a literal per-worker optax simulation. A linear inner
    cannot distinguish local from per-step averaging; adam's
    second-moment normalisation can, so this pins the actual local-SGD
    semantics (and that NO averaging happened in between)."""
    from chainermn_tpu import create_local_sgd

    grads = _per_rank_grads(comm)
    params = jnp.full((4,), 0.25, jnp.float32)
    local = create_local_sgd(optax.adam(0.1), comm, sync_every=3)
    p_got, state = _run_sharded_update(
        comm, local, grads, params, n_steps=3
    )

    # Oracle: run adam per worker, then average the candidates.
    finals = []
    for r in range(N):
        p = params
        inner = optax.adam(0.1)
        s = inner.init(p)
        for _ in range(3):
            u, s = inner.update(jnp.asarray(grads[r]), s, p)
            p = optax.apply_updates(p, u)
        finals.append(np.asarray(p))
    expect = np.stack(finals).mean(0)
    np.testing.assert_allclose(np.asarray(p_got), expect, rtol=1e-5,
                               atol=1e-6)
    # mid-window steps must NOT have synced: step 2's params diverge per
    # worker, which the oracle equality above only certifies indirectly —
    # the anchor must equal the step-3 target, proving exactly one sync.
    np.testing.assert_allclose(
        np.asarray(state.anchor), expect, rtol=1e-5, atol=1e-6
    )


def test_local_sgd_outer_momentum_closed_form(comm):
    """DiLoCo outer momentum at sync_every=1 with sgd inner: the outer
    recursion is heavy ball on the mean gradient scaled by the inner lr:
    v_t = m v_{t-1} + lr*mean(g); p_t = p_{t-1} - outer_lr * v_t."""
    from chainermn_tpu import create_local_sgd

    lr, m, olr = 0.5, 0.9, 0.7
    grads = _per_rank_grads(comm)
    gbar = grads.mean(0)
    params = jnp.zeros((4,), jnp.float32)
    opt = create_local_sgd(
        optax.sgd(lr), comm, sync_every=1, outer_lr=olr, outer_momentum=m
    )
    p_got, _ = _run_sharded_update(comm, opt, grads, params, n_steps=3)

    p = np.zeros(4, np.float32)
    v = np.zeros(4, np.float32)
    for _ in range(3):
        v = m * v + lr * gbar
        p = p - olr * v
    np.testing.assert_allclose(np.asarray(p_got), p, rtol=1e-5, atol=1e-6)


def test_local_sgd_single_device_degrades_to_inner():
    """Outside any named-axis context the mean is the identity: local
    SGD is exactly the inner chain (dist==single invariant)."""
    from chainermn_tpu import create_communicator, create_local_sgd

    comm = create_communicator("single_node")
    params = jnp.ones((3,), jnp.float32)
    g = jnp.asarray([0.1, -0.2, 0.3], jnp.float32)

    opt = create_local_sgd(optax.adam(0.05), comm, sync_every=4)
    inner = optax.adam(0.05)
    s_l, s_i = opt.init(params), inner.init(params)
    p_l = p_i = params
    for _ in range(5):
        u_l, s_l = jax.jit(opt.update)(g, s_l, p_l)
        p_l = optax.apply_updates(p_l, u_l)
        u_i, s_i = jax.jit(inner.update)(g, s_i, p_i)
        p_i = optax.apply_updates(p_i, u_i)
    np.testing.assert_allclose(np.asarray(p_l), np.asarray(p_i),
                               rtol=1e-6, atol=1e-7)


def test_local_sgd_rejects_bad_cadence(comm):
    from chainermn_tpu import create_local_sgd

    with pytest.raises(ValueError, match="sync_every"):
        create_local_sgd(optax.sgd(0.1), comm, sync_every=0)
