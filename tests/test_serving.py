"""Continuous-batching serving engine invariants (ISSUE 4).

The two load-bearing acceptance pins, asserted structurally:

- **Stream equivalence** — N requests through the engine (staggered
  joins/leaves forced by a slot count smaller than the request count)
  produce token streams identical to N sequential ``generate`` calls,
  and tensor-parallel decode == single-device for the same stream (the
  repo's distributed == single-device values convention extended to
  serving).
- **No recompile** — the steady-state decode step compiles exactly once
  across occupancy churn (jit cache size pinned: a second compile is a
  FAILURE, not a slowdown), and prefill compiles are bounded by the
  bucket ladder.

Plus the TP efficiency contract (one psum per column→row pair, zero
collectives in the paged-cache bookkeeping), allocator/scheduler units,
and the serving trace-event rollup.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.serving import (
    BlockAllocator,
    Request,
    Scheduler,
    ServingEngine,
    default_num_blocks,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _requests(n, seed=0, max_prompt=7, max_new=6):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        p_len = int(rs.randint(1, max_prompt))
        out.append((rs.randint(1, VOCAB, size=p_len).tolist(),
                    int(rs.randint(1, max_new))))
    return out

def _generate_ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _run_stream(engine, reqs, policy="fcfs"):
    sched = Scheduler(engine, policy=policy)
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g))
           for p, g in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids], sched


class TestStreamEquivalence:
    """The serving acceptance invariant: engine streams == sequential
    ``generate`` streams, join/leave churn and cache layout
    notwithstanding."""

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_staggered_stream_matches_sequential_generate(self, lm, impl):
        model, params = lm
        # 2 slots x 6 requests: the scheduler is FORCED to stagger
        # joins/leaves mid-decode of other requests.
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8, 16),
        )
        reqs = _requests(6, seed=0)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_rope_positions_stream_matches(self, lm):
        model = tiny_lm(pos_encoding="rope")
        params = model.init(
            jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8),
        )
        reqs = _requests(4, seed=3)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_windowed_model_stream_matches(self):
        # window is not a parameter: init through the windowless twin
        # (the training path demands a window-honouring attention_fn the
        # decode-only serving engine never calls).
        model = tiny_lm(window=6)
        params = tiny_lm().init(
            jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="dense",
            prefill_buckets=(4, 8, 16),
        )
        reqs = _requests(3, seed=5, max_prompt=10, max_new=8)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_gqa_model_stream_matches(self):
        model = tiny_lm(num_kv_heads=2)
        params = model.init(
            jax.random.PRNGKey(6), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=16, prefill_buckets=(4, 8),
        )
        reqs = _requests(5, seed=7)
        streams, _ = _run_stream(engine, reqs, policy="prefill_priority")
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_sampling_reproducible_across_engines(self, lm):
        model, params = lm
        def stream(seed):
            engine = ServingEngine(
                model, params, num_slots=2, max_len=32,
                decode_impl="dense", prefill_buckets=(4, 8),
                temperature=0.8, top_k=8, rng=jax.random.PRNGKey(seed),
            )
            streams, _ = _run_stream(engine, _requests(3, seed=9))
            return streams
        assert stream(42) == stream(42)
        assert stream(42) != stream(43)  # rng actually reaches sampling


class TestTensorParallel:
    """dist == single for the same stream + the structural collective
    pins (HLO-count convention)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices("cpu")[:2]), ("model",))

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_tp_stream_matches_single_device(self, lm, mesh, impl):
        model, params = lm
        reqs = _requests(5, seed=11)
        single = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8),
        )
        tp = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8), mesh=mesh,
        )
        s_streams, _ = _run_stream(single, reqs)
        t_streams, _ = _run_stream(tp, reqs)
        assert t_streams == s_streams
        # ...and both equal the sequential generate reference.
        for (prompt, n_new), got in zip(reqs, t_streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_tp_decode_collective_counts(self, lm, mesh):
        """One all-reduce per column→row pair — 2 per layer (attention
        proj + FFN down), nothing else on the wire: zero collectives in
        the paged-cache bookkeeping (scatter/gather are slot-local by
        construction)."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4,), mesh=mesh,
        )
        args = (
            engine._cache, engine._vars,
            jnp.zeros((3,), jnp.int32), jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        n_ar = txt.count("all-reduce(")
        assert n_ar == 2 * model.num_layers, (
            f"expected {2 * model.num_layers} all-reduces "
            f"(2 per layer), got {n_ar}"
        )
        for op in ("all-gather(", "collective-permute(", "all-to-all(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op} in decode step"


class TestSeqParallelPrefill:
    """ISSUE 13: sequence-parallel prefill over the mesh's 'model'
    partition — streams must stay bit-identical to sequential
    ``generate`` across dense/paged x prefix-cache on/off, the trie-hit
    path must compose (hit -> monolithic tail, miss -> wide), and the
    explicit-'on' capability gates must reject loudly."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices("cpu")[:2]), ("model",))

    def _long_requests(self, n, seed=0):
        rs = np.random.RandomState(seed)
        out = []
        for _ in range(n):
            p_len = int(rs.randint(8, 20))
            out.append((rs.randint(1, VOCAB, size=p_len).tolist(),
                        int(rs.randint(1, 6))))
        return out

    @pytest.mark.parametrize("impl,prefix", [
        ("dense", "off"), ("paged", "off"), ("paged", "on"),
    ])
    def test_streams_bit_identical_to_generate(self, lm, mesh, impl,
                                               prefix):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(8, 16), mesh=mesh,
            prefix_cache=prefix, prefill_seq_parallel="on",
        )
        reqs = self._long_requests(4)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        # the wide path actually ran, and its compiles stay bounded by
        # the shard-rounded bucket ladder
        assert engine.last_prefill_seq_parallel is True
        assert engine.seq_prefill_compile_count() <= 2
        assert engine.decode_compile_count() == 1
        assert engine._seq_attn_impl == "ring"  # the table default

    def test_gqa_streams_match(self, mesh):
        model = tiny_lm(num_kv_heads=2)
        params = model.init(
            jax.random.PRNGKey(6), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(8, 16), mesh=mesh,
            prefill_seq_parallel="on",
        )
        reqs = self._long_requests(3, seed=21)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_prefix_hit_takes_monolithic_tail_and_streams_match(
        self, lm, mesh
    ):
        """Composition with the prefix cache: the MISS goes wide, a
        trie HIT (its context lives in adopted blocks the sharded
        forward cannot see) takes the monolithic tail — both streams
        equal to sequential generate."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(8, 16), mesh=mesh,
            prefix_cache="on", min_shared_blocks=1,
            prefill_seq_parallel="on",
        )
        prompt = np.random.RandomState(7).randint(
            1, VOCAB, size=18
        ).tolist()
        want = _generate_ref(model, params, prompt, 4)
        streams, _ = _run_stream(engine, [(prompt, 4)])
        assert streams[0] == want
        assert engine.last_prefill_seq_parallel is True  # miss: wide
        streams2, _ = _run_stream(engine, [(prompt, 4)])
        assert streams2[0] == want
        assert engine.prefix_stats["hits"] >= 1
        assert engine.last_prefill_seq_parallel is False  # hit: tail

    def test_scheduler_prefill_event_carries_seq_parallel(self, lm,
                                                          mesh):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(8, 16), mesh=mesh,
            prefill_seq_parallel="on",
        )
        _, sched = _run_stream(engine, self._long_requests(2, seed=5))
        evs = [e for e in sched.event_window
               if e.get("phase") == "prefill"]
        assert evs and all(e.get("seq_parallel") for e in evs)

    def test_unshard_roundtrip(self, lm):
        from chainermn_tpu.serving.engine import (
            shard_lm_params,
            unshard_lm_params,
        )

        model, params = lm
        stacked = shard_lm_params(model, {"params": params["params"]}, 2)
        full = unshard_lm_params(model, stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-7, atol=1e-7
            ),
            full, {"params": params["params"]},
        )

    def test_explicit_on_capability_gates(self, lm, mesh):
        model, params = lm
        with pytest.raises(ValueError, match="mesh"):
            ServingEngine(model, params, num_slots=2, max_len=32,
                          prefill_seq_parallel="on")
        # ISSUE 18: sampling no longer gates the wide prefill — the
        # counter-keyed sample over the psum-selected logits keeps the
        # bit-identical-stream guarantee (pinned in test_sampling.py).
        ServingEngine(model, params, num_slots=2, max_len=32,
                      mesh=mesh, temperature=0.7,
                      prefill_seq_parallel="on")
        with pytest.raises(ValueError, match="chunked"):
            ServingEngine(model, params, num_slots=2, max_len=32,
                          mesh=mesh, prefill_chunk=8,
                          prefill_seq_parallel="on")
        with pytest.raises(ValueError, match="prefill_seq_parallel"):
            ServingEngine(model, params, num_slots=2, max_len=32,
                          prefill_seq_parallel="sideways")
        # 'auto' resolves through the registry: table default off, with
        # the decision recorded
        engine = ServingEngine(model, params, num_slots=2, max_len=32)
        recs = [d for d in engine.decisions
                if d["name"] == "prefill_seq_parallel"]
        assert recs and recs[-1]["winner"] == "off"
        assert engine.prefill_seq_parallel is False


class TestNoRecompile:
    def test_decode_step_compiles_exactly_once_across_churn(self, lm):
        """The tentpole's shape discipline, pinned: joins/leaves/ragged
        prompts churn the slot array through a full stream, and the
        steady-state step still shows ONE jit cache entry — a second
        compile is a failure, not a slowdown."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8, 16),
        )
        streams, _ = _run_stream(engine, _requests(6, seed=13))
        assert len(streams) == 6
        assert engine.decode_compile_count() == 1

    def test_prefill_compiles_bounded_by_buckets(self, lm):
        model, params = lm
        buckets = (4, 8, 16)
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="dense",
            prefill_buckets=buckets,
        )
        # prompt lengths spanning every bucket, several per bucket
        reqs = [([1 + i] * p, 2) for i, p in enumerate(
            (1, 3, 4, 5, 7, 8, 9, 15, 16, 2)
        )]
        _run_stream(engine, reqs)
        assert engine.prefill_compile_count() <= len(buckets)


class TestBlockAllocator:
    def test_alloc_grow_release_cycle(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.free_blocks == 8 and a.max_blocks == 4
        assert a.ensure(0, 5)  # 2 blocks
        assert a.blocks_in_use == 2
        assert a.ensure(0, 5)  # idempotent
        assert a.blocks_in_use == 2
        assert (a.tables[0][:2] > 0).all()  # scratch never handed out
        assert (a.tables[1] == 0).all()
        a.release(0)
        assert a.blocks_in_use == 0
        assert (a.tables[0] == 0).all()  # row points back at scratch

    def test_exhaustion_is_all_or_nothing(self):
        a = BlockAllocator(num_blocks=4, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 12)  # 3 blocks: pool drained
        assert not a.ensure(1, 5)  # needs 2, has 0
        assert (a.tables[1] == 0).all()  # nothing half-granted
        a.release(0)
        assert a.ensure(1, 5)

    def test_horizon_and_ctor_validation(self):
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=1,
                           max_len=16)
        with pytest.raises(ValueError, match="horizon"):
            a.ensure(0, 17)
        with pytest.raises(ValueError, match="scratch"):
            BlockAllocator(num_blocks=1, block_size=4, num_slots=1,
                           max_len=16)

    def test_default_num_blocks_covers_worst_case(self):
        assert default_num_blocks(4, 8, 32) == 4 * 4 + 1

    def test_trim_returns_tail_blocks_and_repoints_scratch(self):
        """ensure's inverse (the speculative per-tick lease): the tail
        shrinks back to the pool, trimmed table entries repoint at
        scratch, kept entries are untouched, and trimming at or above
        current coverage is a no-op (no version churn)."""
        a = BlockAllocator(num_blocks=9, block_size=4, num_slots=2,
                           max_len=16)
        assert a.ensure(0, 13)  # 4 blocks
        kept = a.tables[0][:1].copy()
        v = a.version
        a.trim(0, 16)  # above coverage: no-op
        a.trim(0, 13)  # exactly coverage: no-op
        assert a.version == v and a.blocks_in_use == 4
        a.trim(0, 3)  # back to 1 block
        assert a.blocks_in_use == 1
        assert a.version > v
        assert (a.tables[0][:1] == kept).all()
        assert (a.tables[0][1:] == 0).all()
        # freed blocks are immediately reusable by another slot
        assert a.ensure(1, 16)


class TestSchedulerAndAccounting:
    def test_oversubscribed_pool_defers_admission(self, lm):
        """A pool that fits ~one request at a time still serves the
        whole queue (admission defers instead of failing) — the paged
        oversubscription contract."""
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, num_blocks=3, prefill_buckets=(4, 8),
        )
        reqs = _requests(4, seed=17, max_prompt=6, max_new=4)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_prefill_reserves_real_tokens_not_the_padded_bucket(self, lm):
        """A prompt that falls back to the max_len bucket must reserve
        blocks for its REAL tokens only — pad writes ride the scratch
        block and decode grows incrementally, so bucket-width
        reservation would defeat oversubscription (review finding)."""
        model, params = lm
        # ladder (4,) + appended max_len=32: a 6-token prompt buckets
        # to 32, but with block_size=8 it must claim only ONE block.
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, num_blocks=3,  # 2 allocatable << bucket 32
            prefill_buckets=(4,),
        )
        prompt = [3, 1, 4, 1, 5, 9]
        res = engine.prefill_join(prompt)
        assert res is not None and res[2] == 32  # admitted at bucket 32
        assert engine._alloc.blocks_in_use == 1
        # ...and the stream still matches generate (pad writes landed in
        # scratch, decode grew the second block on demand).
        slot, tok, _ = res
        stream = list(prompt) + [tok]
        for _ in range(9):
            toks, _dur = engine.decode_step()
            stream.append(int(toks[slot]))
        assert stream == _generate_ref(model, params, prompt, 10)
        assert engine._alloc.blocks_in_use == 2

    def test_impossible_request_raises_not_hangs(self, lm):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=1, max_len=32, decode_impl="paged",
            kv_block_size=4, num_blocks=2,  # 1 allocatable block: 4 slots
            prefill_buckets=(8,),
        )
        sched = Scheduler(engine)
        # 5 real tokens need 2 blocks — more than the pool will EVER have
        sched.submit(Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=2))
        with pytest.raises(RuntimeError, match="cannot be admitted"):
            sched.run()

    def test_eos_finishes_early_and_frees_the_slot(self, lm):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=1, max_len=32, decode_impl="dense",
            prefill_buckets=(4,),
        )
        prompt = [3, 5, 7]
        full = _generate_ref(model, params, prompt, 8)
        eos = full[len(prompt) + 2]  # third generated token
        sched = Scheduler(engine)
        rid = sched.submit(Request(prompt=prompt, max_new_tokens=8,
                                   eos_id=eos))
        results = sched.run()
        gen = results[rid]["generated"]
        assert gen == full[len(prompt):len(prompt) + 3]  # stops AT eos
        assert gen[-1] == eos
        assert engine.n_active == 0 and engine.free_slot_count == 1

    def test_serving_trace_events_and_rollup(self, lm):
        from chainermn_tpu.observability import trace as obs_trace

        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8),
        )
        rec = obs_trace.enable(None)  # in-memory recorder
        try:
            reqs = _requests(4, seed=19)
            streams, sched = _run_stream(engine, reqs)
            events = list(rec.events)
        finally:
            obs_trace.disable()
        serving = [e for e in events if e.get("kind") == "serving"]
        assert serving, "scheduler emitted no serving events"
        assert all(e["schema"] == obs_trace.TRACE_SCHEMA for e in serving)
        phases = {e["phase"] for e in serving}
        assert phases == {"queue_wait", "prefill", "decode_step", "finish"}
        n_fin = sum(1 for e in serving if e["phase"] == "finish")
        assert n_fin == len(reqs)
        # rollup (the trace_report serving-section owner) agrees with
        # the scheduler's own accounting
        roll = obs_trace.summarize_serving(events)
        summ = sched.summary()
        assert roll["requests"] == len(reqs)
        assert roll["generated_tokens"] == summ["generated_tokens"]
        assert roll["generated_tokens"] == sum(
            len(s) for s in streams
        ) - sum(len(p) for p, _ in reqs)
        assert roll["decode_steps"] == summ["decode_steps"]
        assert roll["occupancy_mean"] == summ["occupancy_mean"]
        assert roll["tokens_per_sec"] is not None
        assert roll["token_ms_p50"] is not None
        assert roll["token_ms_p99"] >= roll["token_ms_p50"]
        # TTFT (ISSUE 5 satellite): submit -> first token percentiles
        # ride the same rollup; queue wait + prefill bound it below.
        assert roll["ttft_ms_p50"] is not None
        assert roll["ttft_ms_p99"] >= roll["ttft_ms_p50"] >= 0.0
        assert "speculation" not in roll  # plain engine: no spec keys
        # no serving-family events -> section omitted, not empty
        # (prefix_cache/speculate are serving-family too, ISSUE 5/7)
        assert obs_trace.summarize_serving(
            [e for e in events if e.get("kind") not in
             ("serving", "speculate", "prefix_cache")]
        ) is None
        # ...and the paged default engine's prefix events roll up
        px = roll.get("prefix_cache")
        assert px is not None and px["lookups"] == len(reqs)

    def test_fcfs_preserves_arrival_order_of_admission(self, lm):
        from chainermn_tpu.observability import trace as obs_trace

        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=1, max_len=32, decode_impl="dense",
            prefill_buckets=(4,),
        )
        rec = obs_trace.enable(None)
        try:
            sched = Scheduler(engine, policy="fcfs")
            ids = [sched.submit(Request(prompt=[i + 1, i + 2],
                                        max_new_tokens=3))
                   for i in range(3)]
            sched.run()
            order = [e["request"] for e in rec.events
                     if e.get("kind") == "serving"
                     and e.get("phase") == "prefill"]
        finally:
            obs_trace.disable()
        assert order == ids


class TestValidation:
    def test_engine_rejects_bad_configs(self, lm):
        model, params = lm
        with pytest.raises(ValueError, match="num_slots"):
            ServingEngine(model, params, num_slots=0)
        with pytest.raises(ValueError, match="max_len"):
            ServingEngine(model, params, num_slots=1, max_len=64)
        with pytest.raises(ValueError, match="decode_impl"):
            ServingEngine(model, params, num_slots=1, decode_impl="magic")
        with pytest.raises(ValueError, match="top_k/top_p"):
            ServingEngine(model, params, num_slots=1, top_k=4)
        with pytest.raises(ValueError, match="return_hidden"):
            ServingEngine(tiny_lm(return_hidden=True), params, num_slots=1)

    def test_submit_rejects_over_horizon_request_up_front(self, lm):
        """prompt + max_new_tokens beyond the engine horizon is refused
        AT SUBMIT — caught mid-stream it would abort every other
        in-flight request (review finding)."""
        model, params = lm
        engine = ServingEngine(model, params, num_slots=2, max_len=32,
                               decode_impl="dense", prefill_buckets=(4,))
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="horizon"):
            sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=30))
        # a legal request still serves normally afterwards
        rid = sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=29))
        results = sched.run()
        assert len(results[rid]["generated"]) == 29

    def test_submit_rejects_duplicate_requests_and_ids(self, lm):
        """Requests are mutable (submit writes the id onto them): the
        same object twice, or a stale id colliding with another
        scheduler's sequence, must raise instead of silently merging
        results (review finding)."""
        model, params = lm
        engine = ServingEngine(model, params, num_slots=2, max_len=32,
                               decode_impl="dense", prefill_buckets=(4,))
        sched = Scheduler(engine)
        req = Request(prompt=[1, 2], max_new_tokens=2)
        sched.submit(req)
        with pytest.raises(ValueError, match="already queued"):
            sched.submit(req)
        sched.run()
        # carried over to a SECOND scheduler, the stale 'r0' collides
        # with its own sequence either way round
        engine2 = ServingEngine(model, params, num_slots=2, max_len=32,
                                decode_impl="dense", prefill_buckets=(4,))
        sched2 = Scheduler(engine2)
        sched2.submit(req)  # stale id 'r0' rides along
        with pytest.raises(ValueError, match="duplicate request_id"):
            sched2.submit(Request(prompt=[3, 4], max_new_tokens=2))

    def test_prompt_bounds(self, lm):
        model, params = lm
        engine = ServingEngine(model, params, num_slots=1, max_len=32,
                               decode_impl="dense", prefill_buckets=(4,))
        with pytest.raises(ValueError, match="empty"):
            engine.prefill_join([])
        with pytest.raises(ValueError, match="no room"):
            engine.prefill_join(list(range(1, 33)))

    def test_tp_divisibility_checked(self, lm):
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:3]), ("model",))
        with pytest.raises(ValueError, match="divide"):
            ServingEngine(model, params, num_slots=1, mesh=mesh)

    def test_slot_decode_guards(self, lm):
        model, params = lm
        with pytest.raises(ValueError, match="decode=True"):
            model.apply(params, jnp.zeros((1, 1), jnp.int32), train=False,
                        decode_positions=jnp.zeros((1,), jnp.int32))
        paged = tiny_lm(kv_layout="paged", kv_num_blocks=4)
        with pytest.raises(ValueError, match="block_tables"):
            paged.apply(params, jnp.zeros((1, 1), jnp.int32), train=False,
                        decode=True,
                        decode_positions=jnp.zeros((1,), jnp.int32),
                        mutable=["cache"])


class TestAdmissionDeques:
    """ISSUE 15 satellite: per-tenant admission deques — fair-share
    selection off per-tenant heads instead of an O(backlog) scan of
    the one FIFO per admission. Two pins: (1) admission order is
    UNCHANGED vs the scan implementation on a 1k-request backlog, and
    (2) the admission path never walks the backlog (no queue
    iteration between run start and drain — O(1) amortized per
    admit)."""

    class _FakeEngine:
        """Host-only engine: every admission samples its first token
        immediately (max_new_tokens=1 requests finish at prefill), so
        a drain is admission-dominated — exactly the quadratic-drain
        regime the deques fix."""

        num_slots = 4
        max_len = 64
        spec_tokens = 0

        def __init__(self):
            self._active = {}

        @property
        def n_active(self):
            return len(self._active)

        @property
        def free_slot_count(self):
            return self.num_slots - len(self._active)

        def prefill_join(self, prompt, tenant_id=None):
            if len(self._active) >= self.num_slots:
                return None
            slot = min(s for s in range(self.num_slots)
                       if s not in self._active)
            self._active[slot] = True
            return slot, 1, 8

        def decode_step(self):
            return [2] * self.num_slots, 0.0001

        def leave(self, slot):
            del self._active[slot]

    @staticmethod
    def _backlog(n=1000, seed=7):
        """A deterministic 1k-request mixed-tenant backlog (skewed
        tenant draw, varying decode budgets so DRR costs differ)."""
        rs = np.random.RandomState(seed)
        tenants = ["t0", "t1", "t2", "t3", None]
        probs = [0.4, 0.25, 0.15, 0.15, 0.05]
        return [
            (f"q{i}", tenants[rs.choice(len(tenants), p=probs)],
             int(rs.randint(1, 4)))
            for i in range(n)
        ]

    def _drain(self, sched_cls, weights):
        sched = sched_cls(self._FakeEngine(), policy="prefill_priority",
                          tenant_weights=weights)
        for rid, tenant, cost in self._backlog():
            sched.submit(Request(prompt=[1, 2], max_new_tokens=1,
                                 request_id=rid, tenant_id=tenant))
        order = []
        orig = sched._dequeue

        def spy(req):
            order.append(req.request_id)
            orig(req)

        sched._dequeue = spy
        sched.run()
        assert len(order) == 1000 and sched.drained
        return order

    def test_admission_order_unchanged_vs_scan_on_1k_backlog(self):
        """The regression pin: the deque-backed scheduler admits the
        1k backlog in EXACTLY the order the scan implementation (the
        pre-ISSUE-15 _next_candidate, reconstructed verbatim over the
        arrival-ordered queue view) would."""

        class ScanScheduler(Scheduler):
            def _next_candidate(self):
                queue = list(self._queue)  # arrival order
                if not queue:
                    return None
                if not self._fair_share:
                    return queue[0]
                heads = {}
                for r in queue:
                    if r.tenant_id not in heads:
                        heads[r.tenant_id] = r
                tenant = self._drr.select(
                    {t: self._drr_cost(r) for t, r in heads.items()})
                return heads[tenant]

        weights = {"t0": 1.0, "t1": 2.0, "t2": 4.0, None: 1.0}
        got = self._drain(Scheduler, weights)
        ref = self._drain(ScanScheduler, weights)
        assert got == ref
        # arrival order within each tenant is preserved
        by_tenant = {}
        backlog = {rid: t for rid, t, _ in self._backlog()}
        for rid in got:
            by_tenant.setdefault(backlog[rid], []).append(
                int(rid[1:]))
        for t, seq in by_tenant.items():
            assert seq == sorted(seq), t

        # FCFS (no fair share) is the strict arrival head
        got_fcfs = self._drain(Scheduler, None)
        assert got_fcfs == [rid for rid, _, _ in self._backlog()]

    def test_admission_never_walks_the_backlog(self, monkeypatch):
        """The O(1)-amortized pin, structural: draining 1k queued
        requests never ITERATES the admission queue (iteration is the
        scan marker; submit-time duplicate checks run before the
        drain)."""
        from chainermn_tpu.serving import scheduler as sched_mod

        sched = Scheduler(self._FakeEngine(), policy="prefill_priority",
                          tenant_weights={"t0": 2.0})
        for rid, tenant, _ in self._backlog(n=1000):
            sched.submit(Request(prompt=[1, 2], max_new_tokens=1,
                                 request_id=rid, tenant_id=tenant))
        walks = []
        orig_iter = sched_mod._AdmissionQueue.__iter__
        monkeypatch.setattr(
            sched_mod._AdmissionQueue, "__iter__",
            lambda self: (walks.append(1), orig_iter(self))[1],
        )
        sched.run()
        assert sched.drained
        assert not walks, f"admission walked the backlog {len(walks)}x"

    def test_identity_dequeue_semantics_kept(self):
        """_dequeue stays by-identity: a request equal to (but not
        identical with) a queued one is refused, and removal of a
        non-head entry (the defensive path) still works."""
        from chainermn_tpu.serving.scheduler import _AdmissionQueue

        q = _AdmissionQueue()
        a = Request(prompt=[1], max_new_tokens=1, request_id="a",
                    tenant_id="t")
        b = Request(prompt=[1], max_new_tokens=1, request_id="b",
                    tenant_id="t")
        q.append(a)
        q.append(b)
        twin = Request(prompt=[1], max_new_tokens=1, request_id="a",
                       tenant_id="t")
        with pytest.raises(ValueError, match="not queued"):
            q.remove(twin)
        q.remove(b)  # non-head: scans only t's own deque
        assert list(q) == [a]
        q.remove(a)
        assert not q and len(q) == 0
        with pytest.raises(IndexError):
            q[0]


class TestMoEServing:
    """ISSUE 20 serving half: a mixture-of-experts FFN decodes through
    the SAME jitted decode/verify/mixed programs as the dense model —
    streams bit-identical to sequential ``generate`` across dense ==
    paged == TP == spec == chunked arms, jit cache still pinned at 1,
    and the TP wire pinned at 2 all-reduces per layer PLUS 2
    all-to-alls per MoE layer (the ownership-split dispatch)."""

    @pytest.fixture(scope="class")
    def moe_lm(self):
        model = tiny_lm(n_experts=4)
        params = model.init(
            jax.random.PRNGKey(20), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        return model, params

    @pytest.fixture(scope="class")
    def mesh(self):
        return Mesh(np.array(jax.devices("cpu")[:2]), ("model",))

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_moe_stream_matches_generate(self, moe_lm, impl):
        model, params = moe_lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8, 16),
        )
        reqs = _requests(6, seed=21)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        assert engine.decode_compile_count() == 1
        # the dispatch decision resolved through the registry
        recs = [d for d in engine.decisions if d["name"] == "moe_dispatch"]
        assert recs and recs[-1]["winner"] in ("sort", "einsum")

    @pytest.mark.parametrize("spec,chunk", [(2, 0), (0, 3), (2, 3)])
    def test_moe_spec_and_chunked_streams_match(self, moe_lm, spec,
                                                chunk):
        model, params = moe_lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4, 8, 16),
            spec_tokens=spec, prefill_chunk=chunk,
        )
        reqs = _requests(5, seed=23)
        streams, _ = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        if spec:
            assert engine.verify_compile_count() == 1
        if chunk:
            assert engine.mixed_compile_count() in (None, 1)

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_moe_tp_stream_matches_single_device(self, moe_lm, mesh,
                                                 impl):
        model, params = moe_lm
        reqs = _requests(5, seed=25)
        single = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8),
        )
        tp = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl=impl,
            kv_block_size=8, prefill_buckets=(4, 8), mesh=mesh,
        )
        s_streams, _ = _run_stream(single, reqs)
        t_streams, _ = _run_stream(tp, reqs)
        assert t_streams == s_streams
        for (prompt, n_new), got in zip(reqs, t_streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        assert tp.decode_compile_count() == 1

    def test_moe_tp_decode_collective_counts(self, moe_lm, mesh):
        """The ISSUE 20 wire pin: the dense 2-AR-per-layer contract is
        PRESERVED (attention proj psum + the MoE combine psum replacing
        the ff_down reduce), and expert dispatch adds exactly 2
        all-to-alls per MoE layer — nothing else appears."""
        model, params = moe_lm
        engine = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4,), mesh=mesh,
        )
        args = (
            engine._cache, engine._vars,
            jnp.zeros((3,), jnp.int32), jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        n_ar = txt.count("all-reduce(")
        n_a2a = txt.count("all-to-all(")
        assert n_ar == 2 * model.num_layers, (
            f"expected {2 * model.num_layers} all-reduces "
            f"(2 per layer), got {n_ar}"
        )
        assert n_a2a == 2 * model.num_layers, (
            f"expected {2 * model.num_layers} all-to-alls "
            f"(2 per MoE layer), got {n_a2a}"
        )
        for op in ("all-gather(", "collective-permute(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op} in decode step"

    def test_moe_shard_unshard_roundtrip(self, moe_lm):
        """Expert leaves slice along their leading ``n_experts`` dim
        (router stays replicated) and the inverse reassembles the exact
        global tree."""
        from chainermn_tpu.serving.engine import (
            shard_lm_params,
            unshard_lm_params,
        )

        model, params = moe_lm
        stacked = shard_lm_params(model, {"params": params["params"]}, 2)
        blk = stacked["params"]["block_0"]
        assert blk["moe_w_up"].shape[:2] == (2, 2)  # [tp, E_local, ...]
        assert blk["moe_router"].shape[0] == 2      # replicated tiles
        full = unshard_lm_params(model, stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-7, atol=1e-7
            ),
            full, {"params": params["params"]},
        )

    def test_moe_expert_divisibility_rejected(self, mesh):
        model = tiny_lm(n_experts=3)
        params = model.init(
            jax.random.PRNGKey(27), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        with pytest.raises(ValueError, match="must divide"):
            ServingEngine(model, params, num_slots=2, max_len=32,
                          mesh=mesh)

    def test_moe_rejects_ff_adapter_hooks(self, moe_lm):
        """MoE blocks have no dense ff_up/ff_down projections — an
        adapter targeting them must fail loudly, not silently no-op."""
        model, params = moe_lm
        A = jnp.zeros((16, 2), jnp.float32)
        B = jnp.zeros((2, 16), jnp.float32)
        hooks = [{"ff_up": (A, B)} for _ in range(model.num_layers)]
        with pytest.raises(ValueError, match="ff_up/ff_down"):
            model.apply(params, jnp.zeros((1, 4), jnp.int32),
                        train=False, adapters=hooks)

    def test_moe_expert_signature(self, moe_lm, mesh):
        model, params = moe_lm
        dense_model, dense_params = tiny_lm(), None
        dense_params = dense_model.init(
            jax.random.PRNGKey(28), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        dense = ServingEngine(dense_model, dense_params, num_slots=2,
                              max_len=32)
        assert dense.expert_signature() is None
        local = ServingEngine(model, params, num_slots=2, max_len=32)
        assert local.expert_signature() == (4, 4)
        tp = ServingEngine(model, params, num_slots=2, max_len=32,
                           mesh=mesh)
        assert tp.expert_signature() == (4, 2)
