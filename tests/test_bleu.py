"""BLEU + greedy decode (the reference seq2seq example's eval story,
SURVEY.md §2.8): corpus BLEU from summable statistics, decode under jit,
and multi-rank aggregation == single-corpus computation."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.extensions import create_multi_node_evaluator
from chainermn_tpu.models import Seq2Seq, seq2seq_loss
from chainermn_tpu.models.seq2seq import greedy_decode
from chainermn_tpu.utils import bleu


def test_bleu_identical_is_one():
    seqs = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10]]
    assert bleu.corpus_bleu(seqs, seqs) == pytest.approx(1.0)


def test_bleu_no_match_is_zero():
    assert bleu.corpus_bleu([[1, 2, 3, 4, 5]], [[6, 7, 8, 9, 10]]) == 0.0


def test_bleu_hand_computed():
    # hyp: 6 tokens, ref: 7 tokens. Unigrams: 5/6 match; bigrams 4/5;
    # trigrams 3/4; 4-grams 2/3. BP = exp(1 - 7/6).
    hyp = [1, 2, 3, 4, 5, 9]
    ref = [1, 2, 3, 4, 5, 6, 7]
    expected = math.exp(1 - 7 / 6) * (
        (5 / 6) * (4 / 5) * (3 / 4) * (2 / 3)
    ) ** 0.25
    assert bleu.corpus_bleu([hyp], [ref]) == pytest.approx(expected)


def test_bleu_clipping():
    # "the the the": hyp unigram 'the' appears 3x but ref only 1x -> clip.
    stats = bleu.bleu_stats([5, 5, 5], [5, 6, 7], max_n=1)
    assert stats["match_1"] == 1 and stats["total_1"] == 3


def test_stats_shards_sum_to_corpus():
    rng = np.random.RandomState(0)
    hyps = [list(rng.randint(1, 20, size=rng.randint(3, 12))) for _ in range(10)]
    refs = [list(rng.randint(1, 20, size=rng.randint(3, 12))) for _ in range(10)]
    whole = bleu.sum_stats(bleu.bleu_stats(h, r) for h, r in zip(hyps, refs))
    shard_a = bleu.sum_stats(
        bleu.bleu_stats(h, r) for h, r in zip(hyps[:4], refs[:4])
    )
    shard_b = bleu.sum_stats(
        bleu.bleu_stats(h, r) for h, r in zip(hyps[4:], refs[4:])
    )
    assert bleu.sum_stats([shard_a, shard_b]) == whole
    assert bleu.bleu_from_stats(whole) == pytest.approx(
        bleu.corpus_bleu(hyps, refs)
    )


def test_truncate_at_eos():
    assert bleu.truncate_at_eos([4, 5, 2, 9, 2], eos=2) == [4, 5]
    assert bleu.truncate_at_eos([4, 5], eos=2) == [4, 5]


def test_evaluator_sum_reduce_finalize(comm):
    ev = create_multi_node_evaluator(
        lambda: {"match_1": 3, "total_1": 4, "hyp_len": 4, "ref_len": 4},
        comm,
        reduce="sum",
        finalize=lambda t: {"bleu": bleu.bleu_from_stats(t, max_n=1)},
    )
    # single process: sum == local values
    assert ev()["bleu"] == pytest.approx(0.75)


def test_greedy_decode_learns_copy_task():
    """End-to-end proof of the decode path: a tiny seq2seq learns the copy
    task and greedy decode reaches high BLEU on held-out samples."""
    VOCAB, BOS, EOS, T = 12, 1, 2, 6
    rng = np.random.RandomState(3)

    def make(n):
        src = rng.randint(3, VOCAB, size=(n, T)).astype(np.int32)
        tgt = np.concatenate(
            [src, np.full((n, 1), EOS, np.int32)], axis=1
        )
        return src, tgt

    model = Seq2Seq(src_vocab=VOCAB, tgt_vocab=VOCAB, embed=32, hidden=64,
                    num_layers=1)
    src, tgt = make(256)
    sm = jnp.ones(src.shape, jnp.float32)
    tm = jnp.ones(tgt.shape, jnp.float32)
    tgt_in = np.concatenate(
        [np.full((tgt.shape[0], 1), BOS, np.int32), tgt[:, :-1]], axis=1
    )
    params = model.init(
        jax.random.key(0), jnp.asarray(src), jnp.asarray(tgt_in), sm, tm
    )
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, src, tgt_in, tgt, sm, tm):
        def loss_fn(p):
            logits = model.apply(p, src, tgt_in, sm, tm)
            return seq2seq_loss(logits, tgt, tm)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(600):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(src), jnp.asarray(tgt_in),
            jnp.asarray(tgt), sm, tm,
        )
    assert float(loss) < 0.05, f"copy task failed to train: loss={float(loss)}"

    hsrc, htgt = make(16)
    hyp = np.asarray(
        jax.jit(
            lambda s, m: greedy_decode(model, params, s, m, T + 3,
                                       bos=BOS, eos=EOS)
        )(jnp.asarray(hsrc), jnp.ones(hsrc.shape, jnp.float32))
    )
    hyps = [bleu.truncate_at_eos(r, EOS) for r in hyp]
    refs = [bleu.truncate_at_eos(r, EOS) for r in htgt]
    score = bleu.corpus_bleu(hyps, refs)
    assert score > 0.5, f"greedy decode BLEU too low: {score}"


def test_greedy_decode_eos_padding():
    """Rows finish with EOS fill after the first EOS (static-shape decode)."""
    model = Seq2Seq(src_vocab=8, tgt_vocab=8, embed=4, hidden=8, num_layers=1)
    src = jnp.asarray(np.random.RandomState(0).randint(3, 8, (2, 5)))
    sm = jnp.ones((2, 5), jnp.float32)
    tgt_in = jnp.asarray(np.random.RandomState(1).randint(3, 8, (2, 5)))
    params = model.init(jax.random.key(0), src, tgt_in, sm, sm)
    out = np.asarray(greedy_decode(model, params, src, sm, 10, eos=2))
    assert out.shape == (2, 10)
    for row in out:
        seen = False
        for t in row:
            if seen:
                assert t == 2  # everything after first EOS is EOS
            seen = seen or t == 2
