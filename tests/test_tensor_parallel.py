"""Tensor-parallel layer invariants: sharded column/row/MLP/attention ==
the unsharded computation, in values AND gradients (the reference's
universal distributed==single-device test style, SURVEY.md section 4,
applied to the TP library that generalises its channel-parallel-conv
example, ``examples/parallel_convolution`` (dagger))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.parallel.tensor import (
    column_parallel_dense,
    row_parallel_dense,
    stack_tp_params,
    tp_attention,
    tp_mlp,
    tp_slice,
)

N = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices("cpu")[:N]), ("model",))


def _rand(key, shape, scale=0.3):
    return jax.random.normal(jax.random.key(key), shape) * scale


def test_tp_mlp_matches_dense_values_and_grads(mesh):
    d, d_ff, b = 6, 16, 4
    x = _rand(0, (b, d))
    w1, b1 = _rand(1, (d, d_ff)), _rand(2, (d_ff,), 0.1)
    w2, b2 = _rand(3, (d_ff, d)), _rand(4, (d,), 0.1)

    def ref_loss(w1, b1, w2, b2, x):
        h = jax.nn.gelu(x @ w1 + b1)
        return jnp.sum((h @ w2 + b2) ** 2)

    # Stacked per-shard weights: [n, ...] over the model axis.
    w1s, b1s = stack_tp_params(w1, N, 1), stack_tp_params(b1, N, 0)
    w2s = stack_tp_params(w2, N, 0)

    # Grads are taken INSIDE shard_map (the framework's train-step pattern:
    # the f/g adjoint ops make shard-local autodiff globally correct;
    # differentiating through the shard_map boundary with check_vma=False
    # is not the supported path).
    def local_step(w1l, b1l, w2l, b2, x):
        def loss(w1l, b1l, w2l, b2, x):
            y = tp_mlp(x, w1l, b1l, w2l, b2, axis_name="model")
            return jnp.sum(y**2)

        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4))(
            w1l[0], b1l[0], w2l[0], b2, x
        )
        return l, (g[0][None], g[1][None], g[2][None], g[3], g[4])

    dist = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P("model"), P("model"), P("model"), P(), P()),
            out_specs=(
                P(),
                (P("model"), P("model"), P("model"), P(), P()),
            ),
            check_vma=False,
        )
    )
    loss_dist, g_dist = dist(w1s, b1s, w2s, b2, x)

    np.testing.assert_allclose(
        float(loss_dist), float(ref_loss(w1, b1, w2, b2, x)), rtol=1e-5
    )

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2, 3, 4))(w1, b1, w2, b2, x)

    # Shard-local weight grads reassemble into the full-weight grads.
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g_dist[0])), axis=1),
        np.asarray(g_ref[0]), rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g_dist[1])), axis=0),
        np.asarray(g_ref[1]), rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g_dist[2])), axis=0),
        np.asarray(g_ref[2]), rtol=1e-4, atol=1e-5,
    )
    # Replicated-weight and input grads come out exact.
    np.testing.assert_allclose(
        np.asarray(g_dist[3]), np.asarray(g_ref[3]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(g_dist[4]), np.asarray(g_ref[4]), rtol=1e-4, atol=1e-5
    )


def test_column_gather_output_matches_dense(mesh):
    d, d_out, b = 4, 16, 3
    x = _rand(5, (b, d))
    w, bias = _rand(6, (d, d_out)), _rand(7, (d_out,), 0.1)
    ws, bs = stack_tp_params(w, N, 1), stack_tp_params(bias, N, 0)

    def local(x, wl, bl):
        return column_parallel_dense(
            x, wl[0], bl[0], axis_name="model", gather_output=True
        )

    y = jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(), P("model"), P("model")), out_specs=P(),
            check_vma=False,
        )
    )(x, ws, bs)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x @ w + bias), rtol=1e-5, atol=1e-6
    )


def test_column_gather_output_grads_exact(mesh):
    """gather_output=True must not scale gradients: all_gather's default
    transpose SUMS the replicated cotangents (N-times-too-large grads);
    gather_from_tp's slice adjoint restores exactness."""
    d, d_out, b = 4, 16, 3
    x = _rand(30, (b, d))
    w = _rand(31, (d, d_out))
    ws = stack_tp_params(w, N, 1)

    def ref_loss(w, x):
        return jnp.sum((x @ w) ** 2)

    def local_step(wl, x):
        def loss(wl, x):
            y = column_parallel_dense(
                x, wl, axis_name="model", gather_output=True
            )
            return jnp.sum(y**2)

        l, g = jax.value_and_grad(loss, argnums=(0, 1))(wl[0], x)
        return l, g[0][None], g[1]

    loss_d, gw_d, gx_d = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=(P("model"), P()),
            out_specs=(P(), P("model"), P()),
            check_vma=False,
        )
    )(ws, x)

    gw_ref, gx_ref = jax.grad(ref_loss, argnums=(0, 1))(w, x)
    np.testing.assert_allclose(float(loss_d), float(ref_loss(w, x)), rtol=1e-5)
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(gw_d)), axis=1),
        np.asarray(gw_ref), rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(gx_d), np.asarray(gx_ref), rtol=1e-4, atol=1e-5
    )


def test_tp_slice_inside_shard_map(mesh):
    """tp_slice + row_parallel over a replicated full weight equals the
    full matmul (the from-single-node-checkpoint path)."""
    d_in, d_out, b = 16, 5, 3
    x = _rand(8, (b, d_in))
    w = _rand(9, (d_in, d_out))

    def local(x, w):
        xl = tp_slice(x, "model", 1)  # shard the input features
        wl = tp_slice(w, "model", 0)
        return row_parallel_dense(xl, wl, axis_name="model")

    y = jax.jit(
        shard_map(
            local, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
    )(x, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=1e-5, atol=1e-6)


def test_tp_attention_matches_single_device(mesh):
    b, t, d_model, n_heads = 2, 6, 16, 8
    head_dim = d_model // n_heads
    x = _rand(10, (b, t, d_model))
    wq, wk, wv, wo = (_rand(11 + i, (d_model, d_model)) for i in range(4))

    def ref_attn(x):
        q = (x @ wq).reshape(b, t, n_heads, head_dim)
        k = (x @ wk).reshape(b, t, n_heads, head_dim)
        v = (x @ wv).reshape(b, t, n_heads, head_dim)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(head_dim, x.dtype)
        )
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, t, d_model) @ wo

    # Head-sharded: q/k/v columns split over heads, wo rows likewise.
    wqs, wks, wvs = (stack_tp_params(w, N, 1) for w in (wq, wk, wv))
    wos = stack_tp_params(wo, N, 0)

    def local(x, wql, wkl, wvl, wol):
        return tp_attention(
            x, wql[0], wkl[0], wvl[0], wol[0],
            axis_name="model", n_heads=n_heads, causal=True,
        )

    y = jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(),) + (P("model"),) * 4, out_specs=P(),
            check_vma=False,
        )
    )(x, wqs, wks, wvs, wos)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref_attn(x)), rtol=1e-4, atol=1e-5
    )


def test_tp_mlp_single_psum_in_forward(mesh):
    """The efficiency contract: one column→row MLP forward lowers to
    EXACTLY one all-reduce (Megatron's invariant; more would mean the
    activation was gathered)."""
    d, d_ff = 8, 32
    x = _rand(20, (2, d))
    w1s = stack_tp_params(_rand(21, (d, d_ff)), N, 1)
    w2s = stack_tp_params(_rand(22, (d_ff, d)), N, 0)

    fwd = jax.jit(
        shard_map(
            lambda x, w1l, w2l: tp_mlp(
                x, w1l[0], None, w2l[0], None, axis_name="model"
            ),
            mesh=mesh,
            in_specs=(P(), P("model"), P("model")),
            out_specs=P(),
            check_vma=False,
        )
    )
    txt = fwd.lower(x, w1s, w2s).compile().as_text()
    n_ar = txt.count("all-reduce(")
    assert n_ar == 1, f"expected exactly 1 all-reduce in TP MLP forward, got {n_ar}"


def test_tp_attention_head_divisibility(mesh):
    with pytest.raises(ValueError):
        # traced eagerly enough: call inside shard_map with bad head count
        def local(x, w):
            return tp_attention(
                x, w, w, w, w.T, axis_name="model", n_heads=4
            )  # 4 heads over 8 shards

        x = jnp.ones((1, 2, 8))
        w = jnp.ones((8, 1))
        shard_map(
            local, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )(x, w)


def test_tp_composes_with_data_parallelism():
    """dp(2) x tp(4): batch sharded over 'data', hidden sharded over
    'model'; grads (pmean over data inside the step, per the framework's
    train-step pattern) equal the sequential full-batch computation."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices("cpu")[:N]).reshape(2, 4)
    mesh2 = Mesh(devs, ("data", "model"))
    d, d_ff, batch = 6, 16, 8

    x = _rand(50, (batch, d))
    w1, w2 = _rand(51, (d, d_ff)), _rand(52, (d_ff, d))
    w1s, w2s = stack_tp_params(w1, 4, 1), stack_tp_params(w2, 4, 0)

    def ref_loss(w1, w2, x):
        return jnp.mean((jax.nn.gelu(x @ w1) @ w2) ** 2)

    def local_step(w1l, w2l, xl):
        def loss(w1l, w2l):
            y = tp_mlp(xl, w1l, None, w2l, None, axis_name="model")
            return jnp.mean(y**2)

        l, g = jax.value_and_grad(loss, argnums=(0, 1))(w1l[0], w2l[0])
        # data-parallel reduction, as in every train step
        l = jax.lax.pmean(l, "data")
        g = jax.lax.pmean(g, "data")
        return l, g[0][None], g[1][None]

    loss_d, g1, g2 = jax.jit(
        shard_map(
            local_step, mesh=mesh2,
            in_specs=(P("model"), P("model"), P("data")),
            out_specs=(P(), P("model"), P("model")),
            check_vma=False,
        )
    )(w1s, w2s, x)

    ref_l, (g1_ref, g2_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1)
    )(w1, w2, x)
    np.testing.assert_allclose(float(loss_d), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g1)), axis=1), np.asarray(g1_ref),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g2)), axis=0), np.asarray(g2_ref),
        rtol=1e-4, atol=1e-5,
    )


def test_tp_example_learns():
    """The TP example CLI (dp x tp transformer block, teacher regression)
    reduces loss substantially — attention AND MLP gradients flow through
    the sharded layers (deterministic seeds: measured 0.25 at these
    settings)."""
    import examples.tensor_parallel.train_tp_transformer as ex

    loss = ex.main(["--iterations", "200", "--lr", "3e-3"])
    assert loss < 0.35, f"tp example did not learn: loss={loss}"
