"""Worker for ``test_mp_disaggregated_handoff_over_tcp`` (ISSUE 8):
rank 0 is a prefill replica, rank 1 a decode replica, KV payloads
cross REAL process boundaries over the native TCP plane — the
multi-process form of the handoff the in-process loopback tests
rehearse. Both ranks init identical params (same seed, CPU backend),
so rank 1 can check every adopted stream against its own sequential
``generate`` reference.

With ``CHAINERMN_TPU_JOURNEY_DIR`` set (ISSUE 17:
``test_mp_journey_merge_over_tcp``) each rank additionally records a
per-rank JSONL trace there, the ranks run a real clock-sync exchange
over the same TCP plane, and the journey context rides the KV payloads
— afterwards the test merges the two files and checks every request
reconstructs to one complete cross-PROCESS causal chain."""

import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from chainermn_tpu import _jax_compat  # noqa: E402,F401
from chainermn_tpu.models.transformer import (  # noqa: E402
    TransformerLM,
    generate,
)
from chainermn_tpu.native.tcp_comm import TcpHostComm  # noqa: E402
from chainermn_tpu.serving import (  # noqa: E402
    Request,
    Scheduler,
    ServingEngine,
)
from chainermn_tpu.serving.cluster import recv_kv, send_kv  # noqa: E402

VOCAB = 32
N_REQUESTS = 4


def build():
    model = TransformerLM(
        vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
        d_ff=32, max_len=64, compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), train=False
    )
    engine = ServingEngine(
        model, params, num_slots=N_REQUESTS, max_len=64,
        decode_impl="paged", kv_block_size=8, prefill_buckets=(4, 8, 16),
    )
    rs = np.random.RandomState(21)
    shared = rs.randint(1, VOCAB, size=10).tolist()
    reqs = [
        (shared + rs.randint(1, VOCAB, size=int(rs.randint(2, 5))
                             ).tolist(), int(rs.randint(2, 5)))
        for _ in range(N_REQUESTS)
    ]
    return model, params, engine, reqs


def main():
    rank, size, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert size == 2
    journey_dir = os.environ.get("CHAINERMN_TPU_JOURNEY_DIR")
    if journey_dir:
        # Per-rank trace file + the rank stamp the recorder reads —
        # BEFORE the recorder exists.
        os.environ["CHAINERMN_TPU_RANK"] = str(rank)
        from chainermn_tpu.observability import clocksync, journey, trace
        rec = trace.enable(os.path.join(journey_dir,
                                        f"rank{rank}.jsonl"))
    comm = TcpHostComm(rank, size, coord)
    model, params, engine, reqs = build()

    if journey_dir:
        # Real two-process clock sync over the same TCP plane the KV
        # payloads ride: rank 1's trace gains the clock_sync event the
        # merge uses to align rank-0 stamps.
        if rank == 0:
            clocksync.sync_server(comm, 1)
        else:
            clocksync.sync_client(comm, 0)

    if rank == 0:
        for i, (prompt, _gen) in enumerate(reqs):
            slot, _tok, _bucket = engine.prefill_join(prompt)
            payload = engine.export_kv(slot)
            engine.leave(slot)
            if journey_dir:
                # Hop 0 on the prefill rank; the ADVANCED snapshot
                # rides the payload so rank 1 parents onto this span.
                ctx = journey.new(f"mp{i}")
                rec.event("route", request=f"mp{i}", replica=1,
                          **ctx.begin_hop())
                payload[journey.WIRE_KEY] = ctx.to_wire()
            send_kv(comm, payload, 1)
        assert comm.recv_obj(1) == "adopted"
    else:
        sched = Scheduler(engine)
        sched.start_window()
        for i, (prompt, gen) in enumerate(reqs):
            req = Request(prompt=prompt, max_new_tokens=gen,
                          request_id=f"mp{i}")
            # Arrival stamps BEFORE the receive so the wire+adoption
            # time sits inside TTFT (the router stamps at submit the
            # same way).
            req._arrival = time.perf_counter()
            payload = recv_kv(comm, 0)
            res = engine.import_kv(payload)
            assert res is not None, "pool sized for the full burst"
            slot, tok = res
            handoff_s = None
            if journey_dir:
                journey.adopt_payload(req, payload)
                handoff_s = round(time.perf_counter() - req._arrival, 9)
                rec.event("kv_transfer", request=f"mp{i}", src=0,
                          nbytes=payload.get("nbytes"),
                          dur_s=handoff_s, **journey.fields(req))
            sched.admit_prefilled(req, slot, tok, dur_s=handoff_s)
        comm.send_obj("adopted", 0)
        while not sched.drained:
            sched.tick()
        sched.close_window()
        for i, (prompt, gen) in enumerate(reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray([prompt], jnp.int32),
                len(prompt) + gen,
            ))[0].tolist()
            got = sched.results[f"mp{i}"]["tokens"]
            assert got == ref, (i, got, ref)

    comm.barrier()
    comm.finalize()
    if journey_dir:
        trace.disable()  # flush + close the per-rank JSONL
    print(f"CLUSTER_WORKER_OK {rank}")


if __name__ == "__main__":
    main()
