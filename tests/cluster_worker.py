"""Worker for ``test_mp_disaggregated_handoff_over_tcp`` (ISSUE 8):
rank 0 is a prefill replica, rank 1 a decode replica, KV payloads
cross REAL process boundaries over the native TCP plane — the
multi-process form of the handoff the in-process loopback tests
rehearse. Both ranks init identical params (same seed, CPU backend),
so rank 1 can check every adopted stream against its own sequential
``generate`` reference."""

import os
import sys

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from chainermn_tpu import _jax_compat  # noqa: E402,F401
from chainermn_tpu.models.transformer import (  # noqa: E402
    TransformerLM,
    generate,
)
from chainermn_tpu.native.tcp_comm import TcpHostComm  # noqa: E402
from chainermn_tpu.serving import (  # noqa: E402
    Request,
    Scheduler,
    ServingEngine,
)
from chainermn_tpu.serving.cluster import recv_kv, send_kv  # noqa: E402

VOCAB = 32
N_REQUESTS = 4


def build():
    model = TransformerLM(
        vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
        d_ff=32, max_len=64, compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32), train=False
    )
    engine = ServingEngine(
        model, params, num_slots=N_REQUESTS, max_len=64,
        decode_impl="paged", kv_block_size=8, prefill_buckets=(4, 8, 16),
    )
    rs = np.random.RandomState(21)
    shared = rs.randint(1, VOCAB, size=10).tolist()
    reqs = [
        (shared + rs.randint(1, VOCAB, size=int(rs.randint(2, 5))
                             ).tolist(), int(rs.randint(2, 5)))
        for _ in range(N_REQUESTS)
    ]
    return model, params, engine, reqs


def main():
    rank, size, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert size == 2
    comm = TcpHostComm(rank, size, coord)
    model, params, engine, reqs = build()

    if rank == 0:
        for prompt, _gen in reqs:
            slot, _tok, _bucket = engine.prefill_join(prompt)
            payload = engine.export_kv(slot)
            engine.leave(slot)
            send_kv(comm, payload, 1)
        assert comm.recv_obj(1) == "adopted"
    else:
        sched = Scheduler(engine)
        sched.start_window()
        for i, (prompt, gen) in enumerate(reqs):
            payload = recv_kv(comm, 0)
            res = engine.import_kv(payload)
            assert res is not None, "pool sized for the full burst"
            slot, tok = res
            sched.admit_prefilled(
                Request(prompt=prompt, max_new_tokens=gen,
                        request_id=f"mp{i}"),
                slot, tok,
            )
        comm.send_obj("adopted", 0)
        while not sched.drained:
            sched.tick()
        sched.close_window()
        for i, (prompt, gen) in enumerate(reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray([prompt], jnp.int32),
                len(prompt) + gen,
            ))[0].tolist()
            got = sched.results[f"mp{i}"]["tokens"]
            assert got == ref, (i, got, ref)

    comm.barrier()
    comm.finalize()
    print(f"CLUSTER_WORKER_OK {rank}")


if __name__ == "__main__":
    main()
