"""ISSUE 12 — topology-composed collective schedules.

Covers, per the repo's conventions (dist==single equivalence for every
distributed feature; structural/HLO-level assertions for communication
claims — measured, not asserted in prose):

- the VALIDATOR rejection suite: double-reduce, missing axis,
  non-conjugate scatter/gather, empty stage list, misplaced
  sharded_update — each a loud :class:`CompositionError` naming the
  broken invariant;
- the DERIVER property sweep: every derived composition for 1-, 2- and
  3-axis meshes passes the validator, parses back from its signature,
  and reduces EXACTLY like ``flat`` (bitwise, on dyadic inputs whose
  partial sums are exact in f32 — so any reduction order must agree to
  the last bit);
- per-composition structural pins: the compiled HLO's collective
  counts equal :func:`predicted_collectives` for every derived
  composition (the menu's ``flat``/``two_level``/``zero`` pins live in
  test_reduction_schedule.py and must not move — they now route
  through the same executor);
- dist == single equivalence (values AND gradients) for every derived
  composition on the 2x2x2 mesh, through the real train step;
- a composition driving the ParallelPlan-compiled step: the
  single-stage ``ar(all)`` composition compiles to the hand-wired
  plan's exact collective counts AND trajectory, a ladder compiles to
  its predicted per-leaf counts, and ZeRO is expressed as the
  composition ``rs > [ar] > su > ag`` with zero behavior change;
- the satellite error-path fix: ``reduce_tree``'s schedule-name errors
  enumerate valid choices dynamically from ``SCHEDULES``, and
  ``resolve_schedule`` provenance names the composition signature.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.communicators.xla_communicator import XlaCommunicator
from chainermn_tpu.parallel.composition import (
    Composition,
    CompositionError,
    Stage,
    bind_composition,
    canonical_axis_names,
    compile_schedule,
    derive_compositions,
    flat_composition,
    parse_signature,
    predicted_collectives,
    reduce_composed,
    schedule_candidates,
    signature_for,
    stage_wire_layout,
    two_level_composition,
    validate_composition,
    zero_composition,
)
from chainermn_tpu.parallel.reduction_schedule import (
    SCHEDULES,
    reduce_tree,
    resolve_schedule,
)

N = 8
AXES3 = ("a0", "a1", "a2")


def _comm(shape, names):
    devs = np.array(jax.devices("cpu")[:N]).reshape(shape)
    return XlaCommunicator(mesh=Mesh(devs, names))


@pytest.fixture(scope="module")
def comm3():
    return _comm((2, 2, 2), AXES3)


# ----------------------------------------------------------------------
# Validator rejection suite (each invariant named in the error)
# ----------------------------------------------------------------------


class TestValidator:
    def test_empty_stage_list(self):
        with pytest.raises(CompositionError, match="empty stage list"):
            validate_composition(Composition(()), AXES3)

    def test_double_reduce(self):
        comp = parse_signature("ar(a0+a1+a2)>ar(a0)")
        with pytest.raises(CompositionError,
                           match="reduced more than once"):
            validate_composition(comp, AXES3)

    def test_missing_axis(self):
        comp = parse_signature("rs(a2)>ag(a2)")
        with pytest.raises(CompositionError, match="never reduced"):
            validate_composition(comp, AXES3)

    def test_non_conjugate_gather_axes(self):
        comp = parse_signature("rs(a2)>ar(a0+a1)>ag(a1)")
        with pytest.raises(CompositionError,
                           match="does not conjugate"):
            validate_composition(comp, AXES3)

    def test_non_conjugate_gather_order(self):
        # LIFO violation: inner scatter must close first
        comp = parse_signature("rs(a2)>rs(a1)>ar(a0)>ag(a2)>ag(a1)")
        with pytest.raises(CompositionError,
                           match="does not conjugate"):
            validate_composition(comp, AXES3)

    def test_gather_without_scatter(self):
        comp = parse_signature("ar(a0+a1+a2)>ag(a2)")
        with pytest.raises(CompositionError,
                           match="no open reduce_scatter"):
            validate_composition(comp, AXES3)

    def test_unclosed_scatter(self):
        comp = parse_signature("rs(a2)>ar(a0+a1)")
        with pytest.raises(CompositionError, match="never gathered back"):
            validate_composition(comp, AXES3)

    def test_update_before_reduction_complete(self):
        comp = parse_signature("rs(a2)>su>ar(a0+a1)>ag(a2)")
        with pytest.raises(CompositionError,
                           match="before every axis is reduced"):
            validate_composition(comp, AXES3)

    def test_update_needs_open_scatter(self):
        comp = parse_signature("ar(a0+a1+a2)>su")
        with pytest.raises(CompositionError,
                           match="no open reduce_scatter"):
            validate_composition(comp, AXES3)

    def test_double_update(self):
        comp = parse_signature("rs(a0+a1+a2)>su>su>ag(a0+a1+a2)")
        with pytest.raises(CompositionError,
                           match="more than one sharded_update"):
            validate_composition(comp, AXES3)

    def test_unknown_axis(self):
        comp = parse_signature("ar(bogus)")
        with pytest.raises(CompositionError, match="not on the mesh"):
            validate_composition(comp, AXES3)

    def test_unknown_primitive_and_empty_axes(self):
        with pytest.raises(CompositionError, match="unknown primitive"):
            validate_composition(
                Composition((Stage("alltoall", ("a0",)),)), AXES3
            )
        with pytest.raises(CompositionError, match="empty axis group"):
            validate_composition(
                Composition((Stage("allreduce", ()),)), AXES3
            )

    def test_duplicate_axis_within_stage(self):
        with pytest.raises(CompositionError, match="duplicate axis"):
            validate_composition(
                Composition((Stage("allreduce", ("a0", "a0", "a1", "a2")),)),
                AXES3,
            )

    def test_parse_rejects_garbage(self):
        with pytest.raises(CompositionError, match="unparseable"):
            parse_signature("rs(a0)>frobnicate")
        with pytest.raises(CompositionError, match="carries no axes"):
            parse_signature("rs(a0+a1+a2)>su(a0)>ag(a0+a1+a2)")

    def test_bind_rejects_foreign_axes(self):
        comp = parse_signature("ar(x0+x1)")
        with pytest.raises(CompositionError, match="neither on the mesh"):
            bind_composition(comp, ("data", "model"))


# ----------------------------------------------------------------------
# Deriver property sweep: validate + parse roundtrip + bitwise vs flat
# ----------------------------------------------------------------------


MESHES = {
    1: ((8,), ("a0",)),
    2: ((2, 4), ("a0", "a1")),
    3: ((2, 2, 2), AXES3),
}


class TestDerivation:
    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_derived_set_validates_and_roundtrips(self, k):
        names = canonical_axis_names(k)
        comps = derive_compositions(names)
        # 2^k: every contiguous partition of the reversed axes x the
        # innermost primitive choice, deduped
        assert len(comps) == 2 ** k
        sigs = set()
        for comp in comps:
            validate_composition(comp, names)  # must not raise
            sig = comp.signature()
            assert sig not in sigs
            sigs.add(sig)
            assert parse_signature(sig).signature() == sig
        # the menu's entries are derived instances
        assert flat_composition(names).signature() in sigs
        assert two_level_composition(names).signature() in sigs

    def test_schedule_candidates_menu_plus_novel(self):
        cands = schedule_candidates(3)
        assert cands[:3] == SCHEDULES
        novel = cands[3:]
        assert len(novel) == 2 ** 3 - 2  # minus the two menu signatures
        for sig in novel:
            comp = parse_signature(sig)
            validate_composition(comp, canonical_axis_names(3))

    def test_zero_composition_shapes(self):
        assert zero_composition(("d",)).signature() == "rs(d)>su>ag(d)"
        assert (zero_composition(("data", "zero")).signature()
                == "rs(zero)>ar(data)>su>ag(zero)")
        # the menu labels compile to their derived signatures
        assert signature_for("flat", 3) == "ar(a0+a1+a2)"
        assert signature_for("two_level", 3) == "rs(a2)>ar(a0+a1)>ag(a2)"
        assert signature_for("zero", 3) == "rs(a2)>ar(a0+a1)>su>ag(a2)"

    def test_stage_wire_layout_conjugate_sizes(self):
        comp = parse_signature("rs(a2)>rs(a1)>ar(a0)>ag(a1)>ag(a2)")
        rows = stage_wire_layout(
            comp, {"a0": 2, "a1": 2, "a2": 2}, 4, 100
        )
        assert [r["op"] for r in rows] == [
            "reduce-scatter", "reduce-scatter", "all-reduce",
            "all-gather", "all-gather",
        ]
        # scatter frame: 100 -> 50 -> 25 elements; gathers mirror it
        assert [r["nbytes"] for r in rows] == [400, 200, 100, 200, 400]


# ----------------------------------------------------------------------
# Structural + bitwise: every derived composition vs flat
# ----------------------------------------------------------------------


def _dyadic_tree(rs, shape_map):
    """f32 trees of small integers / 8: every partial sum and the /8
    mean are exact in f32, so ANY reduction order is bitwise equal."""
    return {
        k: jnp.asarray(rs.randint(-16, 16, shape), jnp.float32) / 8.0
        for k, shape in shape_map.items()
    }


def _reduce_counts_and_out(comm, sched, tree):
    axes = comm.grad_axes

    def local(t):
        sq = jax.tree.map(lambda l: l[0], t)
        out = reduce_tree(sq, schedule=sched, axes=axes)
        return jax.tree.map(lambda l: l[None], out)

    spec = jax.tree.map(
        lambda l: P(axes, *([None] * (l.ndim - 1))), tree
    )
    f = jax.jit(shard_map(local, mesh=comm.mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False))
    txt = f.lower(tree).compile().as_text()
    counts = {
        "reduce-scatter": txt.count("reduce-scatter("),
        "all-reduce": txt.count("all-reduce("),
        "all-gather": txt.count("all-gather("),
    }
    return counts, jax.device_get(f(tree))


class TestDerivedStructuralAndBitwise:
    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_every_derived_composition_counts_and_bitwise_vs_flat(self, k):
        shape, names = MESHES[k]
        comm = _comm(shape, names)
        rs = np.random.RandomState(k)
        tree = _dyadic_tree(rs, {"w": (N, 40, 8), "b": (N, 9)})
        _, ref = _reduce_counts_and_out(comm, "flat", tree)
        for comp in derive_compositions(names):
            counts, out = _reduce_counts_and_out(
                comm, comp.signature(), tree
            )
            assert counts == predicted_collectives(comp), (
                comp.signature(), counts,
            )
            for key in tree:
                np.testing.assert_array_equal(
                    out[key], ref[key],
                    err_msg=f"{comp.signature()} != flat bitwise ({key})",
                )

    def test_menu_names_route_through_the_executor_unchanged(self, comm3):
        """flat/two_level spelled as names and as their signatures are
        the SAME program (signature-spelled pins can't drift from the
        menu pins in test_reduction_schedule.py)."""
        rs = np.random.RandomState(7)
        tree = _dyadic_tree(rs, {"w": (N, 33, 5)})
        for name in ("flat", "two_level"):
            sig = signature_for(name, 3)
            c_name, o_name = _reduce_counts_and_out(comm3, name, tree)
            c_sig, o_sig = _reduce_counts_and_out(comm3, sig, tree)
            assert c_name == c_sig, (name, c_name, c_sig)
            np.testing.assert_array_equal(o_name["w"], o_sig["w"])

    def test_int8_wire_refuses_beyond_menu_compositions(self, comm3):
        ladder = derive_compositions(comm3.grad_axes)[0]
        with pytest.raises(ValueError, match="int8 two-phase wire"):
            reduce_tree(
                {"w": jnp.ones((4,))}, schedule=ladder.signature(),
                axes=comm3.grad_axes, compress_dtype=jnp.int8,
            )


# ----------------------------------------------------------------------
# Satellite: dynamic error path + provenance names the composition
# ----------------------------------------------------------------------


class TestErrorPathAndProvenance:
    def test_reduce_tree_zero_error_enumerates_dynamically(self, comm3):
        valid = tuple(s for s in SCHEDULES if s != "zero")
        with pytest.raises(ValueError) as e:
            reduce_tree({"w": jnp.ones((4,))}, schedule="zero",
                        axes=comm3.grad_axes)
        assert str(valid) in str(e.value)  # derived from SCHEDULES
        assert "MultiNodeOptimizer" in str(e.value)

    def test_reduce_tree_unknown_schedule_names_the_menu(self, comm3):
        with pytest.raises(ValueError, match="unknown schedule"):
            reduce_tree({"w": jnp.ones((4,))}, schedule="ring",
                        axes=comm3.grad_axes)

    def test_resolve_schedule_provenance_names_composition(
        self, monkeypatch
    ):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
        winner, rec = resolve_schedule("cpu", 3 << 20, (2, 2, 2))
        assert winner == "flat"  # table default, still a candidate
        assert rec["composition"] == "ar(a0+a1+a2)"
        # candidates include the derived beyond-menu pipelines
        winner2, rec2 = resolve_schedule(
            "cpu", 3 << 20, (2, 2, 2),
            candidates=("rs(a2)>rs(a1)>ar(a0)>ag(a1)>ag(a2)",),
        )
        assert winner2 == "rs(a2)>rs(a1)>ar(a0)>ag(a1)>ag(a2)"
        assert rec2["composition"] == winner2

    def test_optimizer_rejects_update_composition_and_bad_signature(
        self, comm3
    ):
        from chainermn_tpu import create_multi_node_optimizer

        with pytest.raises(ValueError, match="sharded_update"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm3,
                reduction_schedule="rs(a0+a1+a2)>su>ag(a0+a1+a2)",
            )
        with pytest.raises(ValueError, match="reduction_schedule"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm3,
                reduction_schedule="rs(a2)>ag(a2)",  # a0/a1 never reduced
            )


# ----------------------------------------------------------------------
# dist == single equivalence for every derived 2x2x2 composition
# ----------------------------------------------------------------------


def _loss_fn(p, batch):
    xb, yb = batch
    logits = xb @ p["w"] + p["b"]
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, yb
    ).mean()


def _train(c, params, batch, *, steps=2, **opt_kwargs):
    from chainermn_tpu import create_multi_node_optimizer
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    opt = create_multi_node_optimizer(optax.adam(1e-2), c, **opt_kwargs)
    state = create_train_state(params, opt, c)
    step = make_train_step(_loss_fn, opt, c, donate=False)
    for _ in range(steps):
        state, m = step(state, batch)
    return jax.device_get(state.params), float(m["loss"])


class TestTrainerEquivalence:
    @pytest.fixture(scope="class")
    def problem(self):
        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(5, 3), jnp.float32),
                  "b": jnp.asarray(rs.randn(3), jnp.float32)}
        x = jnp.asarray(rs.randn(16, 5), jnp.float32)
        y = jnp.asarray(np.arange(16) % 3, np.int32)
        return params, (x, y)

    def test_every_derived_composition_dist_equals_single(
        self, comm3, problem
    ):
        """The suite's core invariant per DERIVED composition: the
        2x2x2 distributed trajectory (values AND gradients — two adam
        steps exercise both) equals the single-device one. The
        single-device reference runs the default reduction (a 1-device
        mean is the identity; a 3-axis signature cannot bind there)."""
        params, batch = problem
        single_p, single_l = _train(
            comm3.sub_communicator([0]), params, batch
        )
        for comp in derive_compositions(comm3.grad_axes):
            dist_p, dist_l = _train(
                comm3, params, batch,
                reduction_schedule=comp.signature(),
            )
            for k in params:
                np.testing.assert_allclose(
                    dist_p[k], single_p[k], rtol=1e-5, atol=1e-6,
                    err_msg=comp.signature(),
                )
            assert abs(dist_l - single_l) < 1e-6, comp.signature()


# ----------------------------------------------------------------------
# A composition drives the ParallelPlan-compiled step
# ----------------------------------------------------------------------


def _plan_loss(p, batch):
    xb, yb = batch
    return jnp.mean((xb @ p["w"] - yb) ** 2)


class TestPlanComposition:
    def _mk(self, grad_reduction=None, axes=("data", "zero")):
        from chainermn_tpu.parallel.plan import ParallelPlan

        return ParallelPlan(
            dict.fromkeys(axes, 2) if len(axes) == 3
            else {a: (2 if i == 0 else 4) for i, a in enumerate(axes)},
            devices=jax.devices("cpu")[:N],
            grad_reduction=grad_reduction,
        )

    def _counts(self, plan):
        d = 8
        rs = np.random.RandomState(3)
        params = {"w": jnp.asarray(rs.randn(d, d), jnp.float32)}
        x = jnp.asarray(rs.randn(16, d), jnp.float32)
        y = jnp.asarray(rs.randn(16, d), jnp.float32)
        inner = optax.adam(1e-2)
        step = plan.compile_train_step(_plan_loss, inner, params,
                                       donate=False)
        state = plan.create_train_state(params, inner)
        txt = step.lower(state, (x, y)).compile().as_text()
        counts = {op: txt.count(op + "(") for op in
                  ("all-reduce", "reduce-scatter", "all-gather")}
        for _ in range(2):
            state, m = step(state, (x, y))
        return counts, jax.device_get(state.params), float(m["loss"])

    def test_flat_composition_matches_handwired_dp_plan_exactly(self):
        """Acceptance: a composition drives the plan-compiled step with
        the SAME collective counts as the hand-wired path — on a pure
        dp plan (the rep group actually carries the leaves) the
        ar(data) composition IS the hand-wired fused pmean: identical
        counts AND bitwise-equal trajectory."""
        from chainermn_tpu.parallel.plan import ParallelPlan

        def run(grad_reduction):
            plan = ParallelPlan({"data": 8},
                                devices=jax.devices("cpu")[:N],
                                grad_reduction=grad_reduction)
            return self._counts(plan)

        base, base_p, base_l = run(None)
        comp, comp_p, comp_l = run("flat")
        assert base == comp, (base, comp)
        np.testing.assert_array_equal(base_p["w"], comp_p["w"])
        assert base_l == comp_l

    def test_ladder_on_zero_plan_is_provenance_only(self):
        """On a data x zero plan every replicated leaf is in the ZERO
        group (its own composition), so a grad_reduction ladder must
        change NOTHING in the compiled program — it only re-describes
        the data axis's owed collectives. Counts and trajectory pinned
        equal to the hand-wired base."""
        base, base_p, base_l = self._counts(self._mk(None))
        ladder = "rs(a1)>rs(a0)>ag(a0)>ag(a1)"  # a0=data, a1=zero
        plan = self._mk(ladder)
        assert plan.describe()["grad_reduction"] == \
            "rs(zero)>rs(data)>ag(data)>ag(zero)"
        # the composition is the data axis's spec provider now
        assert plan.describe()["collectives"]["data"] == (
            "reduce-scatter", "all-gather",
        )
        # the zero axis keeps its own provider entry
        assert plan.describe()["collectives"]["zero"] == (
            "reduce-scatter", "all-gather",
        )
        counts, comp_p, l = self._counts(plan)
        assert counts == base, (counts, base)
        np.testing.assert_array_equal(base_p["w"], comp_p["w"])
        assert l == base_l

    def test_composition_drives_tp_plan_with_predicted_stages(self):
        """dp x model plan (no zero): the rep group's gradients ride
        the composed pipeline; compiled counts move EXACTLY by the
        composition's extra stages vs the hand-wired pmean, and the
        trajectory is bitwise-unchanged (dyadic inputs)."""
        from chainermn_tpu.parallel.plan import ParallelPlan

        d = 8
        rs = np.random.RandomState(5)
        params = {"w": (jnp.asarray(
            rs.randint(-8, 8, (d, d)), jnp.float32) / 8.0)}
        x = jnp.asarray(rs.randint(-8, 8, (16, d)), jnp.float32) / 8.0
        y = jnp.asarray(rs.randint(-8, 8, (16, d)), jnp.float32) / 8.0
        inner = optax.sgd(0.5)

        def run(grad_reduction):
            plan = ParallelPlan({"data": 8}, devices=jax.devices("cpu")[:N],
                                grad_reduction=grad_reduction)
            step = plan.compile_train_step(_plan_loss, inner, params,
                                           donate=False)
            state = plan.create_train_state(params, inner)
            txt = step.lower(state, (x, y)).compile().as_text()
            counts = {op: txt.count(op + "(") for op in
                      ("all-reduce", "reduce-scatter", "all-gather")}
            state, m = step(state, (x, y))
            return counts, jax.device_get(state.params)["w"]

        base_counts, base_w = run(None)
        sig = "rs(a0)>ag(a0)"  # the decomposed pipeline over 'data'
        comp_counts, comp_w = run(sig)
        comp = compile_schedule(sig, ("data",))
        pred = predicted_collectives(comp)
        # one param leaf: the composed step carries the base counts
        # minus the grad all-reduce plus the composition's stages
        assert comp_counts["reduce-scatter"] == (
            base_counts["reduce-scatter"] + pred["reduce-scatter"]
        )
        assert comp_counts["all-gather"] == (
            base_counts["all-gather"] + pred["all-gather"]
        )
        assert comp_counts["all-reduce"] == base_counts["all-reduce"] - 1
        np.testing.assert_array_equal(base_w, comp_w)

    def test_zero_is_a_composition_with_zero_behavior_change(self):
        """The acceptance's ZeRO clause, stated structurally: the plan's
        zero group runs rs(zero)>ar(data)>su>ag(zero) (the derived
        instance) and the existing hand-wired count pins in
        test_plan.py keep passing — here we assert the composition the
        group compiles from and that the optimizer's structural 'zero'
        equals it."""
        assert (zero_composition(("data", "zero")).signature()
                == "rs(zero)>ar(data)>su>ag(zero)")
        # the optimizer's 'zero' schedule compiles to the same shape
        assert signature_for("zero", 1) == "rs(a0)>su>ag(a0)"

    def test_grad_reduction_validation(self):
        from chainermn_tpu.parallel.plan import ParallelPlan

        with pytest.raises(ValueError, match="sharded_update"):
            ParallelPlan({"data": 8}, devices=jax.devices("cpu")[:N],
                         grad_reduction="zero")
        with pytest.raises(ValueError, match="needs a data-parallel"):
            ParallelPlan({"model": 8}, devices=jax.devices("cpu")[:N],
                         grad_reduction="flat")
        with pytest.raises(CompositionError, match="never reduced"):
            ParallelPlan({"data": 2, "zero": 4},
                         devices=jax.devices("cpu")[:N],
                         grad_reduction="rs(zero)>ag(zero)")


# ----------------------------------------------------------------------
# ISSUE 15: bucket-sliced composed reduction
# ----------------------------------------------------------------------


class TestSlicedComposition:
    """The sliced-stage DSL: grammar roundtrip, validator invariants,
    the slice_bounds zero-leaf contract, and the structural pin — a
    sliced composition's compiled HLO carries exactly S× the per-stage
    collective count at 1/S payload (total wire bytes unchanged) and
    is BITWISE == flat on exact-dyadic inputs."""

    def test_signature_roundtrip_compact_and_expanded(self):
        from chainermn_tpu.parallel.composition import (
            expand_slices,
            sliced_composition,
        )

        comp = sliced_composition(two_level_composition(AXES3), 4)
        sig = comp.signature()
        assert sig == "rs(a2)[s0..3]>ar(a0+a1)>ag(a2)"
        assert parse_signature(sig) == comp
        validate_composition(comp, AXES3)
        # expanded spelling: per-stage [sI:S] addresses, skewed order,
        # parseable and valid (per-slice conjugacy)
        ex = expand_slices(comp, 64)
        assert len(ex) == 12 and ex[0].signature() == "rs(a2)[s0:4]"
        ex_sig = ">".join(s.signature() for s in ex)
        ex_comp = parse_signature(ex_sig)
        validate_composition(ex_comp, AXES3)
        assert ex_comp.signature() == ex_sig
        # the skew: slice 1's rs is issued before slice 0's ar
        order = [s.signature() for s in ex]
        assert order.index("rs(a2)[s1:4]") < order.index(
            "ar(a0+a1)[s0:4]")
        # the ONE front door reconstitutes the expanded spelling to
        # the compact executable form (review finding: an expanded
        # composition validated but would have executed as a flat
        # double-reduction) — and a heterogeneous expansion, where
        # slices run different pipelines, is refused loudly.
        from chainermn_tpu.parallel.composition import compact_slices

        assert compile_schedule(ex_sig, AXES3) == comp
        assert compact_slices(ex_comp) == comp
        het = parse_signature(
            "rs(a2)[s0:2]>ar(a0+a1)[s0:2]>ag(a2)[s0:2]"
            ">ar(a0+a1+a2)[s1:2]")
        validate_composition(het, AXES3)  # mathematically fine...
        with pytest.raises(CompositionError,
                           match="different pipeline"):
            compact_slices(het)  # ...but not executable

    def test_slice_bounds_contract(self):
        from chainermn_tpu.parallel.composition import (
            effective_slices,
            slice_bounds,
        )

        # degrade: S > elements -> min(S, elements); S == elements ok
        assert effective_slices(8, 3) == 3
        assert effective_slices(4, 4) == 4
        assert effective_slices(4, 0) == 1  # zero-leaf floor
        with pytest.raises(CompositionError, match=">= 1"):
            effective_slices(0, 10)
        for n, s in ((10, 4), (8, 8), (7, 3), (1, 1)):
            bounds = slice_bounds(n, s)
            assert len(bounds) == s
            assert bounds[0][0] == 0 and bounds[-1][1] == n
            for (a0, b0), (a1, b1) in zip(bounds, bounds[1:]):
                assert b0 == a1  # disjoint, covering
            assert all(hi > lo for lo, hi in bounds)  # never empty

    def test_validator_rejections(self):
        from chainermn_tpu.parallel.composition import sliced_composition

        with pytest.raises(CompositionError, match="unsliceable"):
            validate_composition(
                Composition(zero_composition(AXES3).stages, slices=2),
                AXES3,
            )
        with pytest.raises(CompositionError, match="cannot be sliced"):
            sliced_composition(zero_composition(AXES3), 2)
        with pytest.raises(CompositionError, match="slices must be"):
            validate_composition(
                Composition(flat_composition(AXES3).stages, slices=0),
                AXES3,
            )
        # expanded form: a slice whose pipeline is incomplete
        with pytest.raises(CompositionError, match="slice s1:2"):
            validate_composition(
                parse_signature("rs(a2)[s0:2]>rs(a2)[s1:2]"
                                ">ar(a0+a1)[s0:2]>ag(a2)[s0:2]"),
                AXES3,
            )
        # expanded form: mixed addressed/unaddressed stages
        with pytest.raises(CompositionError, match="no slice address"):
            validate_composition(
                parse_signature("ar(a0+a1+a2)[s0:2]>ar(a0+a1+a2)"),
                ("a0", "a1", "a2"),
            )
        # conflicting totals
        with pytest.raises(CompositionError, match="slice totals"):
            validate_composition(
                parse_signature("ar(a0+a1+a2)[s0:2]>ar(a0+a1+a2)[s1:3]"),
                AXES3,
            )
        with pytest.raises(CompositionError, match="must start at s0"):
            parse_signature("rs(a2)[s1..3]>ar(a0+a1)>ag(a2)")

    def test_sliced_wire_layout_bytes_conserved(self):
        """Per-slice rows at 1/S payload each; summed over slices the
        per-stage wire bytes equal the unsliced rendering's (divisible
        size, so no padding slack)."""
        from chainermn_tpu.parallel.composition import sliced_composition

        sizes = {"a0": 2, "a1": 2, "a2": 2}
        base = parse_signature("rs(a2)>rs(a1)>ar(a0)>ag(a1)>ag(a2)")
        flat_rows = stage_wire_layout(base, sizes, 4, 128)
        for S in (2, 4, 8):
            rows = stage_wire_layout(
                sliced_composition(base, S), sizes, 4, 128)
            assert len(rows) == S * len(flat_rows)
            per_stage: dict = {}
            for r in rows:
                assert r["n_slices"] == S and 0 <= r["slice"] < S
                per_stage[r["stage"]] = (
                    per_stage.get(r["stage"], 0) + r["nbytes"])
            assert per_stage == {
                r["stage"]: r["nbytes"] for r in flat_rows
            }, S

    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_sliced_counts_and_bitwise_vs_flat(self, k):
        """The acceptance pin, per mesh depth: every slice count of
        the two_level instance compiles to EXACTLY S× the per-stage
        collectives and reduces bitwise == flat through the real
        bucketed reduction (dyadic inputs)."""
        from chainermn_tpu.parallel.composition import sliced_composition

        shape, names = MESHES[k]
        comm = _comm(shape, names)
        rs = np.random.RandomState(k + 40)
        tree = _dyadic_tree(rs, {"w": (N, 40, 8), "b": (N, 16)})
        _, ref = _reduce_counts_and_out(comm, "flat", tree)
        base = two_level_composition(names)
        for S in (2, 4):
            comp = sliced_composition(base, S)
            counts, out = _reduce_counts_and_out(
                comm, comp.signature(), tree
            )
            pred = predicted_collectives(comp, size=40 * 8 + 16)
            assert counts == pred, (comp.signature(), counts, pred)
            for key in tree:
                np.testing.assert_array_equal(
                    out[key], ref[key],
                    err_msg=f"{comp.signature()} != flat ({key})",
                )

    def test_degrade_below_slice_count(self, comm3):
        """A bucket smaller than S runs min(S, elements) slices —
        never an empty stage or zero-size collective (the PR 3
        zero-leaf contract): a 3-element bucket under S=8 compiles
        exactly 3 of each stage."""
        from chainermn_tpu.parallel.composition import sliced_composition

        comp = sliced_composition(two_level_composition(AXES3), 8)
        rs = np.random.RandomState(9)
        tree = {"b": jnp.asarray(
            rs.randint(-8, 8, (N, 3)), jnp.float32) / 8.0}
        _, ref = _reduce_counts_and_out(comm3, "flat", tree)
        counts, out = _reduce_counts_and_out(
            comm3, comp.signature(), tree)
        assert counts == predicted_collectives(comp, size=3)
        assert counts["all-reduce"] == 3  # min(8, 3), not 8, never 0
        np.testing.assert_array_equal(out["b"], ref["b"])

    def test_sliced_dist_equals_single_through_trainer(self, comm3):
        """The suite's core invariant for the sliced rendering: the
        2x2x2 trajectory (values AND gradients, two adam steps) under
        a sliced schedule equals the single-device one."""
        from chainermn_tpu.parallel.composition import sliced_composition

        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(5, 3), jnp.float32),
                  "b": jnp.asarray(rs.randn(3), jnp.float32)}
        x = jnp.asarray(rs.randn(16, 5), jnp.float32)
        y = jnp.asarray(np.arange(16) % 3, np.int32)
        single_p, single_l = _train(
            comm3.sub_communicator([0]), params, (x, y)
        )
        sig = sliced_composition(
            two_level_composition(comm3.grad_axes), 4).signature()
        dist_p, dist_l = _train(
            comm3, params, (x, y), reduction_schedule=sig
        )
        for key in params:
            np.testing.assert_allclose(
                dist_p[key], single_p[key], rtol=1e-5, atol=1e-6,
                err_msg=sig,
            )
        assert abs(dist_l - single_l) < 1e-6

    def _int8_counts_and_out(self, comm, sched, tree):
        axes = comm.grad_axes

        def local(t):
            sq = jax.tree.map(lambda m: m[0], t)
            out = reduce_tree(sq, schedule=sched, axes=axes,
                              compress_dtype=jnp.int8)
            return jax.tree.map(lambda m: m[None], out)

        spec = jax.tree.map(
            lambda m: P(axes, *([None] * (m.ndim - 1))), tree
        )
        f = jax.jit(shard_map(local, mesh=comm.mesh, in_specs=(spec,),
                              out_specs=spec, check_vma=False))
        txt = f.lower(tree).compile().as_text()
        return txt.count("all-to-all("), jax.device_get(f(tree))

    def test_int8_wire_sliced_renders_per_slice(self, comm3):
        """ISSUE 16 satellite: sliced spellings of the two int8
        renderings are ACCEPTED (the PR 15 refusal is lifted) and
        render the two-phase wire per bucket slice — S× the
        all_to_all phases in HLO, equivalent to the unsliced int8
        wire within quantization tolerance (per-slice max-abs scales,
        so not bitwise) and to the exact mean within the wire's
        stated error."""
        from chainermn_tpu.parallel.composition import sliced_composition

        S = 4
        rs = np.random.RandomState(5)
        tree = {"w": jnp.asarray(rs.randn(N, 67), jnp.float32)}
        exact = np.mean(np.asarray(tree["w"]), axis=0)
        tol = 4.0 * float(np.abs(tree["w"]).max()) / 127.0
        for base_name in ("flat", "two_level"):
            base = compile_schedule(base_name, AXES3)
            a2a_1, out_1 = self._int8_counts_and_out(
                comm3, base_name, tree)
            sig = sliced_composition(base, S).signature()
            a2a_s, out_s = self._int8_counts_and_out(comm3, sig, tree)
            assert a2a_s == S * a2a_1, (sig, a2a_s, a2a_1)
            np.testing.assert_allclose(
                out_s["w"][0], exact, atol=tol, err_msg=sig)
            np.testing.assert_allclose(
                out_s["w"][0], out_1["w"][0], atol=tol, err_msg=sig)

    def test_int8_wire_sliced_zigzag_layout(self, comm3):
        """The zigzag cut rides the sliced int8 wire too: same HLO
        phase count as contiguous, equivalent within quantization
        tolerance (slice membership differs, so scales differ)."""
        from chainermn_tpu.parallel.composition import sliced_composition

        rs = np.random.RandomState(6)
        tree = {"w": jnp.asarray(rs.randn(N, 53), jnp.float32)}
        exact = np.mean(np.asarray(tree["w"]), axis=0)
        tol = 4.0 * float(np.abs(tree["w"]).max()) / 127.0
        base = two_level_composition(AXES3)
        sig_s = sliced_composition(base, 4).signature()
        sig_z = sliced_composition(base, 4, layout="zigzag").signature()
        a2a_s, out_s = self._int8_counts_and_out(comm3, sig_s, tree)
        a2a_z, out_z = self._int8_counts_and_out(comm3, sig_z, tree)
        assert a2a_z == a2a_s
        np.testing.assert_allclose(out_z["w"][0], exact, atol=tol)
        np.testing.assert_allclose(
            out_z["w"][0], out_s["w"][0], atol=tol)

    def test_int8_wire_still_refuses_beyond_menu_sliced(self, comm3):
        """Slicing does not widen the int8 gate: a sliced spelling of
        a composition whose UNSLICED base is not flat/two_level is
        still refused."""
        from chainermn_tpu.parallel.composition import sliced_composition

        ladder = derive_compositions(comm3.grad_axes)[0]
        sig = sliced_composition(ladder, 2).signature()
        with pytest.raises(ValueError, match="int8 two-phase wire"):
            reduce_tree(
                {"w": jnp.ones((16,))}, schedule=sig,
                axes=comm3.grad_axes, compress_dtype=jnp.int8,
            )

    def test_plan_grad_reduction_accepts_sliced_signature(self):
        """ParallelPlan grad_reduction= accepts a sliced spelling and
        reports it in describe() — the end-to-end plumbing pin (the
        compiled-step equivalence rides dryrun phase M)."""
        from chainermn_tpu.parallel.plan import ParallelPlan

        plan = ParallelPlan(
            {"data": 2, "zero": 4}, devices=jax.devices("cpu")[:N],
            grad_reduction="rs(a1)[s0..1]>rs(a0)>ag(a0)>ag(a1)",
        )
        assert plan.describe()["grad_reduction"] == \
            "rs(zero)[s0..1]>rs(data)>ag(data)>ag(zero)"


# ----------------------------------------------------------------------
# ISSUE 16: broadcast/multicast tree stages + zigzag slice layout
# ----------------------------------------------------------------------


def _bc_counts_and_out(comm, comp, x):
    """Compile a broadcast composition through the one executor and
    return (HLO collective counts incl. collective-permute, output)."""
    axes = comm.grad_axes

    def local(v):
        return reduce_composed(v, comp, op="sum")

    f = jax.jit(shard_map(local, mesh=comm.mesh, in_specs=P(axes),
                          out_specs=P(axes)))
    txt = f.lower(x).compile().as_text()
    import re as _re

    counts = {
        "reduce-scatter": txt.count("reduce-scatter("),
        "all-reduce": txt.count("all-reduce("),
        "all-gather": txt.count("all-gather("),
        "collective-permute": len(
            _re.findall(r"collective-permute(?:-start)?\(", txt)),
    }
    return counts, jax.device_get(f(x))


class TestBroadcastStages:
    """The bc multicast-tree stage family: grammar, validator family
    separation, tree_depth/tree_sends arithmetic, and the structural
    pin — a bc composition's compiled HLO carries exactly
    tree_sends(n, radix) collective-permutes per stage and delivers
    the root's buffer to every member."""

    def test_signature_roundtrip_and_radix_spelling(self):
        from chainermn_tpu.parallel.composition import (
            broadcast_composition,
        )

        comp = parse_signature("bc(a0+a1)@4>bc(a2)")
        assert comp.signature() == "bc(a0+a1)@4>bc(a2)"
        assert parse_signature(comp.signature()) == comp
        validate_composition(comp, AXES3)
        # default radix (@2) is never printed
        one = broadcast_composition(AXES3)
        assert one.signature() == "bc(a0+a1+a2)"
        assert parse_signature("bc(a0+a1+a2)@2") == one
        # compile_schedule front door accepts the spelling
        assert compile_schedule("bc(a0+a1)@4>bc(a2)", AXES3) == comp

    def test_tree_depth_and_sends(self):
        from chainermn_tpu.parallel.composition import (
            tree_depth,
            tree_sends,
        )

        assert tree_depth(8, 2) == 3 and tree_sends(8, 2) == 3
        assert tree_depth(8, 4) == 2 and tree_sends(8, 4) == 4
        assert tree_depth(4, 4) == 1 and tree_sends(4, 4) == 3
        assert tree_depth(1, 2) == 0 and tree_sends(1, 2) == 0
        with pytest.raises(CompositionError, match="radix must be >= 2"):
            tree_depth(8, 1)

    def test_validator_family_separation(self):
        # bc mixed into a reduction pipeline
        with pytest.raises(CompositionError, match="never compose"):
            validate_composition(
                parse_signature("bc(a0)>ar(a1+a2)"), AXES3)
        # missing axis in a broadcast family
        with pytest.raises(CompositionError, match="never broadcast"):
            validate_composition(parse_signature("bc(a0+a1)"), AXES3)
        # doubled axis across stages
        with pytest.raises(CompositionError, match="more than once"):
            validate_composition(
                parse_signature("bc(a0+a1+a2)>bc(a0)"), AXES3)
        # radix on a reduction stage: refused at parse AND validate
        with pytest.raises(CompositionError, match="radix"):
            parse_signature("rs(a2)@4>ar(a0+a1)>ag(a2)")
        with pytest.raises(CompositionError, match="radix"):
            validate_composition(Composition((
                Stage("reduce_scatter", ("a2",), radix=4),
                Stage("allreduce", ("a0", "a1")),
                Stage("allgather", ("a2",)),
            )), AXES3)

    def test_predicted_collectives_contract(self):
        sizes = {"a0": 2, "a1": 2, "a2": 2}
        comp = parse_signature("bc(a0+a1+a2)")
        pred = predicted_collectives(comp, axis_sizes=sizes)
        assert pred == {"reduce-scatter": 0, "all-reduce": 0,
                        "all-gather": 0, "collective-permute": 3}
        # a bc composition without axis_sizes degrades loudly
        with pytest.raises(CompositionError, match="axis_sizes"):
            predicted_collectives(comp)
        # reduction-only counts keep the exact three-key dict
        assert set(predicted_collectives(
            parse_signature("ar(a0+a1+a2)"), axis_sizes=sizes)) == {
                "reduce-scatter", "all-reduce", "all-gather"}

    @pytest.mark.parametrize("sig,cp", [
        ("bc(a0+a1+a2)", 3),       # radix 2: ceil(log2 8) rounds
        ("bc(a0+a1+a2)@4", 4),     # radix 4: 2 rounds x up to 3 sends
        ("bc(a0+a1)@4>bc(a2)", 4),  # 3 sends over n=4 + 1 over n=2
    ])
    def test_hlo_counts_and_root_delivery(self, comm3, sig, cp):
        comp = compile_schedule(sig, comm3.grad_axes)
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(64), jnp.float32)
        counts, out = _bc_counts_and_out(comm3, comp, x)
        sizes = {a: 2 for a in comm3.grad_axes}
        assert counts == predicted_collectives(comp, axis_sizes=sizes), (
            sig, counts)
        assert counts["collective-permute"] == cp, (sig, counts)
        # every member returns the root shard's buffer
        np.testing.assert_array_equal(out, np.tile(np.asarray(x[:8]), 8))


class TestZigzagLayout:
    """ISSUE 16 satellite: the zigzag (strided) slice layout — same
    per-slice element counts as contiguous, so wire layout and HLO
    counts do not move; only the cut/reassembly indexing does, and
    both layouts reduce bitwise-equal."""

    def test_signature_roundtrip_and_rejections(self):
        from chainermn_tpu.parallel.composition import sliced_composition

        comp = sliced_composition(
            two_level_composition(AXES3), 4, layout="zigzag")
        assert comp.signature() == "rs(a2)[z0..3]>ar(a0+a1)>ag(a2)"
        assert parse_signature(comp.signature()) == comp
        validate_composition(comp, AXES3)
        with pytest.raises(CompositionError, match="composition-level"):
            parse_signature("rs(a2)[z1:4]>ar(a0+a1)>ag(a2)")
        with pytest.raises(CompositionError, match="layout"):
            sliced_composition(two_level_composition(AXES3), 4,
                               layout="diagonal")
        with pytest.raises(CompositionError, match="layout"):
            validate_composition(
                Composition(two_level_composition(AXES3).stages,
                            slices=2, slice_layout="diagonal"),
                AXES3)

    def test_wire_layout_identical_to_contiguous(self):
        from chainermn_tpu.parallel.composition import sliced_composition

        sizes = {"a0": 2, "a1": 2, "a2": 2}
        base = two_level_composition(AXES3)
        for n_elems in (128, 103):  # divisible and ragged
            cont = stage_wire_layout(
                sliced_composition(base, 4), sizes, 4, n_elems)
            zig = stage_wire_layout(
                sliced_composition(base, 4, layout="zigzag"),
                sizes, 4, n_elems)
            assert cont == zig, n_elems

    @pytest.mark.parametrize("k", [2, 3])
    def test_bitwise_vs_contiguous_and_flat(self, k):
        shape, names = MESHES[k]
        comm = _comm(shape, names)
        from chainermn_tpu.parallel.composition import sliced_composition

        rs = np.random.RandomState(k + 60)
        # ragged size: the gather tails are where the layouts differ
        tree = _dyadic_tree(rs, {"w": (N, 13, 5), "b": (N, 9)})
        _, ref = _reduce_counts_and_out(comm, "flat", tree)
        base = two_level_composition(names)
        for S in (2, 4):
            zig = sliced_composition(base, S, layout="zigzag")
            counts, out = _reduce_counts_and_out(
                comm, zig.signature(), tree)
            assert counts == predicted_collectives(zig, size=9), (
                zig.signature(), counts)
            for key in tree:
                np.testing.assert_array_equal(
                    out[key], ref[key],
                    err_msg=f"{zig.signature()} != flat ({key})",
                )
