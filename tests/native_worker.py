"""Worker script for tests/test_native_comm.py: exercises every TcpHostComm
operation across real OS processes (the true multi-process analogue of the
reference's ``mpiexec -n N pytest`` harness, SURVEY.md section 4)."""

import sys

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from chainermn_tpu.native.tcp_comm import TcpHostComm


def main():
    rank, size, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    c = TcpHostComm(rank, size, coord)

    assert c.bcast_obj({"x": 42} if rank == 0 else None, 0) == {"x": 42}

    g = c.gather_obj(rank * 10, 0)
    if rank == 0:
        assert g == [i * 10 for i in range(size)], g
    else:
        assert g is None

    assert c.allgather_obj(("r", rank)) == [("r", i) for i in range(size)]

    got = c.scatter_obj(
        [f"item{i}" for i in range(size)] if rank == 0 else None, 0
    )
    assert got == f"item{rank}"

    out = c.alltoall_obj([(rank, j) for j in range(size)])
    assert out == [(i, rank) for i in range(size)], out

    s = c.allreduce_obj({"v": rank})
    assert s == {"v": sum(range(size))}

    # split(): independent subgroup collectives (reference: MPI_Comm_split).
    # Two color groups (low/high halves); key=-rank REVERSES in-group order.
    if size >= 2:
        half = size // 2
        color = 0 if rank < half else 1
        g = c.split(color, key=-rank)
        lo, hi = (0, half) if color == 0 else (half, size)
        assert g.members == list(reversed(range(lo, hi))), g.members
        assert g.size == hi - lo and g.members[g.rank] == rank
        # Group root (group rank 0) is the HIGHEST world rank in the group.
        got = g.bcast_obj(("grp", color, rank) if g.rank == 0 else None, 0)
        assert got == ("grp", color, hi - 1), got
        assert g.allgather_obj(rank) == list(reversed(range(lo, hi)))
        assert g.allreduce_obj(1) == hi - lo
        g.barrier()  # p2p group barrier, not the world-wide native one
        # Nested split: singleton groups, trivially consistent.
        gg = g.split(g.rank, key=0)
        assert gg.size == 1 and gg.allreduce_obj(rank) == rank

        # Group-level probe on a peer that never sends (ISSUE 8: the
        # router's health checks lean on this being BOUNDED): after a
        # group barrier drains the pair channels, group-rank 1 blocks
        # in recv and sends NOTHING until released — group-rank 0's
        # probes must return False instantly, every time, and the
        # translated reply must land on the right world-rank channel.
        if g.size >= 2:
            import time as _time

            g.barrier()
            if g.rank == 0:
                for _ in range(5):
                    assert g.probe(1) is False  # silent peer: no hang
                g.send_obj("grp-go", 1)
                deadline = _time.time() + 30
                while not g.probe(1):
                    assert _time.time() < deadline
                    _time.sleep(0.002)
                assert g.recv_obj(1) == "grp-reply"
            elif g.rank == 1:
                assert g.recv_obj(0) == "grp-go"
                g.send_obj("grp-reply", 0)
            g.barrier()

    # p2p ring with a large payload (exercises framing/chunked recv)
    big = bytes(range(256)) * 4096  # 1 MiB
    c.send_obj((rank, big), (rank + 1) % size)
    src, payload = c.recv_obj((rank - 1) % size)
    assert src == (rank - 1) % size and payload == big

    # probe (MPI_Iprobe analog), raced-free by construction: rank 1 sends
    # NOTHING until rank 0's "go" arrives, so rank 0's empty-probe is
    # deterministic (the preceding barrier consumed its own tokens).
    import time

    c.barrier()
    if rank == 0:
        assert c.probe(1) is False
        c.send_obj("go", 1)
        deadline = time.time() + 30
        while not c.probe(1):
            assert time.time() < deadline, "probe never saw the message"
            time.sleep(0.002)
        assert c.probe(1) is True  # non-consuming
        assert c.recv_obj(1) == "probe-reply"
    elif rank == 1:
        assert c.recv_obj(0) == "go"
        c.send_obj("probe-reply", 0)

    c.barrier()
    c.finalize()
    print(f"WORKER_OK {rank}")


if __name__ == "__main__":
    main()
