"""Expert-parallel MoE tests: distributed all_to_all dispatch must equal a
single-device dense evaluation of the same routing (SURVEY.md section 4
invariant, applied to the new EP layer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel.moe import (
    make_expert_params,
    moe_layer_local,
    top1_route,
)

D = 16


def expert_fn(params, x):
    w1, w2 = params
    return jnp.tanh(x @ w1) @ w2


def _expert_init(rng):
    k1, k2 = jax.random.split(rng)
    return (
        jax.random.normal(k1, (D, 32)) / 4.0,
        jax.random.normal(k2, (32, D)) / 4.0,
    )


class TestRouting:
    def test_capacity_bounds_queue(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
        dispatch, combine = top1_route(logits, capacity=8)
        # each expert receives at most `capacity` tokens
        per_expert = dispatch.sum(axis=(0, 2))
        assert (np.asarray(per_expert) <= 8).all()
        # each kept token occupies exactly one (expert, slot)
        per_token = dispatch.sum(axis=(1, 2))
        assert set(np.asarray(per_token).tolist()) <= {0.0, 1.0}

    def test_combine_carries_gate(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
        probs = jax.nn.softmax(logits, -1)
        dispatch, combine = top1_route(logits, capacity=16)
        gates = np.asarray(combine.sum(axis=(1, 2)))
        top = np.asarray(probs.max(axis=-1))
        kept = np.asarray(dispatch.sum(axis=(1, 2))) > 0
        np.testing.assert_allclose(gates[kept], top[kept], rtol=1e-6)


class TestMoELayer:
    def test_matches_dense_single_device(self, comm):
        """EP dispatch over the 8-way mesh == dense per-token expert eval
        with the same router decisions (no drops: generous capacity)."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(0), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(1), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(2), n)

        # --- dense reference: every token through its argmax expert
        logits = x @ router_w
        probs = jax.nn.softmax(logits, -1)
        choice = np.asarray(jnp.argmax(logits, -1))
        ref = np.zeros((tokens, D), np.float32)
        for t in range(tokens):
            e = int(choice[t])
            params_e = jax.tree.map(lambda l: l[e], stacked)
            ref[t] = np.asarray(
                expert_fn(params_e, x[t : t + 1])[0] * probs[t, e]
            )

        # --- distributed: one expert per shard, capacity = all tokens
        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)  # my expert
            return moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=float(n),  # no drops
            )

        out = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )(x, router_w, stacked)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_gradients_flow_to_router_and_experts(self, comm):
        n = comm.size
        ax = comm.axis_name
        tokens = 4 * n
        x = jax.random.normal(jax.random.PRNGKey(3), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(4), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(5), n)

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            out = moe_layer_local(
                x, router_w, expert_fn, params, ax, capacity_factor=float(n)
            )
            return jax.lax.pmean((out**2).mean(), ax)

        loss_fn = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )
        grads = jax.grad(
            lambda rw, st: loss_fn(x, rw, st), argnums=(0, 1)
        )(router_w, stacked)
        g_router, g_experts = grads
        assert float(jnp.abs(g_router).sum()) > 0
        assert all(
            float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(g_experts)
        )

class TestSortDispatch:
    """dispatch_impl='sort' (index scatter/gather, no [T,E,C] tensor) must
    reproduce the dense einsum dispatch exactly — values AND gradients,
    top-1 and top-2, WITH drops (tight capacity) — VERDICT r2 item 8."""

    def _layer(self, comm, impl, k, capacity_factor):
        ax = comm.axis_name

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            out = moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=capacity_factor, k=k, dispatch_impl=impl,
            )
            return out

        return jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)), out_specs=P(),
                check_vma=False,
            )
        )

    @pytest.mark.parametrize("k", [1, 2])
    @pytest.mark.parametrize("capacity_factor", [0.5, 4.0])
    def test_matches_einsum(self, comm, k, capacity_factor):
        n = comm.size
        tokens = 16 * n
        x = jax.random.normal(jax.random.PRNGKey(20), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(21), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(22), n)

        out_e = self._layer(comm, "einsum", k, capacity_factor)(
            x, router_w, stacked
        )
        out_s = self._layer(comm, "sort", k, capacity_factor)(
            x, router_w, stacked
        )
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_e),
                                   rtol=1e-5, atol=1e-6)

    def test_mixed_precision_dtype_parity(self, comm):
        """bf16 activations + f32 router (the normal mixed-precision
        setup): sort dispatch must return the same dtype AND values as the
        einsum path's promotion semantics."""
        n = comm.size
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(30), (tokens, D),
                              jnp.bfloat16)
        router_w = jax.random.normal(jax.random.PRNGKey(31), (D, n),
                                     jnp.float32) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(32), n)

        def run(impl):
            ax = comm.axis_name

            def local(x, router_w, stacked):
                params = jax.tree.map(lambda l: l[0], stacked)
                return moe_layer_local(
                    x, router_w.astype(jnp.float32), expert_fn, params, ax,
                    capacity_factor=2.0, k=2, dispatch_impl=impl,
                )

            return jax.jit(
                shard_map(
                    local, mesh=comm.mesh,
                    in_specs=(P(), P(), P(ax)), out_specs=P(),
                    check_vma=False,
                )
            )(x, router_w, stacked)

        out_e, out_s = run("einsum"), run("sort")
        assert out_s.dtype == out_e.dtype
        np.testing.assert_allclose(
            np.asarray(out_s, np.float32), np.asarray(out_e, np.float32),
            rtol=2e-2, atol=2e-2,
        )

    def test_grads_match_einsum(self, comm):
        n = comm.size
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(23), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(24), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(25), n)

        def loss(impl):
            layer = self._layer(comm, impl, 2, 1.0)

            def f(x, rw, st):
                return (layer(x, rw, st) ** 2).mean()

            return jax.grad(f, argnums=(0, 1, 2))(x, router_w, stacked)

        g_e = loss("einsum")
        g_s = loss("sort")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g_s, g_e,
        )


class TestTopK:
    def test_topk_capacity_and_slots(self):
        from chainermn_tpu.parallel.moe import topk_route

        logits = jax.random.normal(jax.random.PRNGKey(6), (64, 4))
        dispatch, combine = topk_route(logits, capacity=16, k=2)
        per_expert = dispatch.sum(axis=(0, 2))
        assert (np.asarray(per_expert) <= 16).all()
        # each token occupies at most k (expert, slot) cells
        per_token = dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 2.0 + 1e-6).all()
        # no two tokens share a queue slot
        per_slot = dispatch.sum(axis=0)
        assert (np.asarray(per_slot) <= 1.0 + 1e-6).all()

    def test_topk_gates_normalised(self):
        from chainermn_tpu.parallel.moe import topk_route

        logits = jax.random.normal(jax.random.PRNGKey(7), (32, 4))
        dispatch, combine = topk_route(logits, capacity=32, k=2)  # no drops
        # with both choices kept, the two normalised gates sum to 1
        gates = np.asarray(combine.sum(axis=(1, 2)))
        np.testing.assert_allclose(gates, np.ones_like(gates), rtol=1e-5)

    def test_top2_layer_matches_dense(self, comm):
        """k=2 EP dispatch == dense weighted two-expert evaluation."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(8), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(9), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(10), n)

        logits = x @ router_w
        probs = np.asarray(jax.nn.softmax(logits, -1))
        order = np.argsort(-probs, axis=-1)
        ref = np.zeros((tokens, D), np.float32)
        for t in range(tokens):
            e1, e2 = int(order[t, 0]), int(order[t, 1])
            g1, g2 = probs[t, e1], probs[t, e2]
            zsum = g1 + g2
            for e, g in ((e1, g1), (e2, g2)):
                pe = jax.tree.map(lambda l: l[e], stacked)
                ref[t] += np.asarray(expert_fn(pe, x[t : t + 1])[0]) * (g / zsum)

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            return moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=float(n), k=2,
            )

        out = jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)), out_specs=P(),
                check_vma=False,
            )
        )(x, router_w, stacked)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)

    def test_load_balancing_loss_signal(self):
        from chainermn_tpu.parallel.moe import load_balancing_loss

        n = 8
        # perfectly balanced: uniform logits -> loss ~ 1
        uniform = jnp.zeros((128, n))
        assert abs(float(load_balancing_loss(uniform)) - 1.0) < 1e-5
        # collapsed: all tokens to expert 0 -> loss ~ n
        collapsed = jnp.zeros((128, n)).at[:, 0].set(20.0)
        assert float(load_balancing_loss(collapsed)) > n - 0.1


def test_moe_example_converges():
    """The example CLI trains router + experts to high accuracy (top-1)."""
    import examples.moe.train_moe_mlp as ex

    acc = ex.main(["--iterations", "150", "--batchsize", "128",
                   "--width", "32"])
    assert acc > 0.9, f"moe example did not converge: acc={acc}"


def test_topk_bf16_logits_no_slot_collisions():
    """Queue slot indices must be exact in int32 even when router logits
    are bf16 (bf16 cumsum cannot represent integers past 256, which
    collided slots and dropped tokens despite ample capacity)."""
    from chainermn_tpu.parallel.moe import topk_route

    tokens = 1024
    logits = jnp.zeros((tokens, 4), jnp.bfloat16).at[:, 0].set(5.0)
    dispatch, combine = topk_route(logits, capacity=tokens, k=2)
    d = np.asarray(dispatch, np.float32)
    # no two tokens share a queue slot
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # nothing dropped: every token occupies exactly k slots
    np.testing.assert_allclose(d.sum(axis=(1, 2)), np.full(tokens, 2.0),
                               rtol=0, atol=1e-6)


def test_topk_no_duplicate_expert_on_underflow():
    """A diverged router (softmax mass underflows to 0 outside the top
    choice) must still pick k DISTINCT experts — logit-space masking; and
    k > n_experts is rejected."""
    from chainermn_tpu.parallel.moe import topk_route

    logits = jnp.zeros((16, 4), jnp.float32).at[:, 2].set(200.0)
    dispatch, _ = topk_route(logits, capacity=16, k=2)
    d = np.asarray(dispatch)
    per_token_expert = d.sum(axis=2)  # [tokens, experts]
    assert (per_token_expert <= 1.0 + 1e-6).all(), "expert chosen twice"
    assert (d.sum(axis=(1, 2)) == 2.0).all()

    import pytest as _pytest
    with _pytest.raises(ValueError, match="exceeds"):
        topk_route(logits, capacity=4, k=5)


# ---------------------------------------------------------------------------
# ISSUE 20: 'expert' as a first-class ParallelPlan axis
# ---------------------------------------------------------------------------


def _devices():
    return jax.devices("cpu")[:8]


def _collective_counts(txt: str) -> dict:
    return {op: txt.count(op) for op in
            ("all-to-all(", "all-reduce(", "collective-permute(")}


def _moe_loss_fn(moe_fn, aux_weight=0.01, with_stats=False):
    def loss_fn(p, batch):
        x, y = batch
        out, aux = moe_fn(x, p["router"], expert_fn, p["experts"])
        out = x + out
        loss = jnp.mean((out - y) ** 2) + aux_weight * aux["load_balance"]
        metrics = {}
        if with_stats:
            metrics = {"dropped": aux["dropped"],
                       "expert_load": aux["expert_load"]}
        return loss, (metrics, ())
    return loss_fn


def _ref_moe_dense(x, router_w, stacked, k=1):
    """No-drop dense reference: every token through its top-k experts
    (layout-independent — what any no-drop sharding must reproduce)."""
    from chainermn_tpu.parallel.moe import dispatch_einsum

    logits = x @ router_w
    queues, combine_fn = dispatch_einsum(x, logits, x.shape[0], k)
    out = jax.vmap(expert_fn)(stacked, queues)
    return combine_fn(out)


def _ref_moe_loss(p, batch, aux_weight=0.01, k=1):
    from chainermn_tpu.parallel.moe import load_balancing_loss

    x, y = batch
    out = x + _ref_moe_dense(x, p["router"], p["experts"], k)
    return (jnp.mean((out - y) ** 2)
            + aux_weight * load_balancing_loss(x @ p["router"]))


class TestExpertPlanAxis:
    """'expert' beside data x zero x pipe x seq x model (ISSUE 20): the
    spec-provider contract, dist == single values AND grads roped through
    the real compiled train step, and the compiled HLO pinned at exactly
    2 all_to_alls per MoE layer per pass."""

    def _params(self, n_experts, rng=2):
        import optax  # noqa: F401

        experts = make_expert_params(
            _expert_init, jax.random.PRNGKey(rng), n_experts
        )
        router = jax.random.normal(
            jax.random.PRNGKey(rng + 1), (D, n_experts)) / 4.0
        return {"experts": experts, "router": router}

    def test_moe_plan_axis_provider(self):
        from chainermn_tpu.parallel.plan_specs import (
            CANONICAL_AXES, moe_plan_axis,
        )

        d = moe_plan_axis()
        assert d["name"] == "expert"
        assert d["stacked"] is True
        assert d["state_stacked"] is False
        assert d["collectives"] == ("all-to-all", "all-reduce")
        # canonical slot: between seq and model (ICI-hungry, but model
        # keeps the fastest axis)
        assert CANONICAL_AXES.index("expert") == \
            CANONICAL_AXES.index("model") - 1

    def test_expert_plan_dist_eq_single(self):
        """expert-only plan: one real compiled+donated train step ==
        the single-device dense evaluation — values AND grads (certified
        through the sgd delta), jit cache pinned at 1."""
        import optax
        from chainermn_tpu.parallel.plan import ParallelPlan

        plan = ParallelPlan({"expert": 8}, devices=_devices())
        params = self._params(8)
        specs = {"experts": P("expert"), "router": P()}
        moe_fn, rec = plan.moe_layer(
            tokens_local=4, d_model=D, capacity_factor=None
        )
        assert rec["winner"] in ("sort", "einsum")
        assert plan.describe()["moe_dispatch_impl"] == rec["winner"]
        assert plan.describe()["collectives"]["expert"] == (
            "all-to-all", "all-reduce",
        )

        x = jax.random.normal(jax.random.PRNGKey(5), (32, D))
        y = jax.random.normal(jax.random.PRNGKey(6), (32, D))
        inner = optax.sgd(0.1)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(
            _moe_loss_fn(moe_fn, with_stats=True), inner, params,
            param_specs=specs,
        )
        state, metrics = step(state, (x, y))
        state, metrics = step(state, (x, y))
        assert step.cache_size() in (1, None)

        # reference: two plain steps on one device
        ref = jax.device_get(params)
        for _ in range(2):
            l, g = jax.value_and_grad(_ref_moe_loss)(ref, (x, y))
            ref = jax.tree.map(lambda p, gi: p - 0.1 * gi, ref, g)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            ),
            jax.device_get(state.params), ref,
        )
        np.testing.assert_allclose(float(metrics["loss"]), float(l),
                                   rtol=1e-4)
        # stats rode the metric pmean: loads sum to kept assignments
        assert float(metrics["dropped"]) == 0.0
        np.testing.assert_allclose(
            np.asarray(metrics["expert_load"]).sum(), 32.0, rtol=1e-6)

    @pytest.mark.parametrize("axes", [{"expert": 4, "data": 2},
                                      {"expert": 4, "model": 2}])
    def test_composed_plans_dist_eq_single(self, axes):
        """expert x data and expert x model: dist == single values AND
        grads through the real train step; the composition adds ZERO
        extra all_to_alls (still exactly 2 per MoE layer per pass)."""
        import optax
        from chainermn_tpu.parallel.plan import ParallelPlan
        from chainermn_tpu.parallel.tensor import stack_tp_params, tp_mlp

        plan = ParallelPlan(axes, devices=_devices())
        has_tp = "model" in axes
        m = plan.axis_size("model")
        params = self._params(4)
        specs = {"experts": P("expert"), "router": P()}
        d_ff = 32
        if has_tp:
            w1 = jax.random.normal(jax.random.PRNGKey(7), (D, d_ff)) / 4.0
            w2 = jax.random.normal(jax.random.PRNGKey(8), (d_ff, D)) / 4.0
            b2 = jnp.zeros((D,))
            params.update({
                "w1": stack_tp_params(w1, m, 1),
                "w2": stack_tp_params(w2, m, 0),
                "b2": b2,
            })
            specs.update({"w1": P("model"), "w2": P("model"), "b2": P()})
        moe_fn, _ = plan.moe_layer(
            tokens_local=8, d_model=D, capacity_factor=None
        )

        def loss_fn(p, batch):
            x, y = batch
            h = x
            if has_tp:
                h = tp_mlp(x, p["w1"], None, p["w2"], p["b2"],
                           axis_name="model")
            out, aux = moe_fn(h, p["router"], expert_fn, p["experts"])
            out = h + out
            return (jnp.mean((out - y) ** 2)
                    + 0.01 * aux["load_balance"])

        x = jax.random.normal(jax.random.PRNGKey(9), (32, D))
        y = jax.random.normal(jax.random.PRNGKey(10), (32, D))
        inner = optax.sgd(0.1)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(loss_fn, inner, params,
                                       param_specs=specs)
        counts = _collective_counts(
            step.lower(state, (x, y)).compile().as_text()
        )
        # dispatch + combine forward, their exact transposes backward —
        # nothing else (XLA may merge the back-to-back transposes)
        assert 2 <= counts["all-to-all("] <= 4
        assert counts["collective-permute("] == 0
        state, metrics = step(state, (x, y))

        def ref_loss(p, batch):
            from chainermn_tpu.parallel.moe import load_balancing_loss

            xb, yb = batch
            h = xb
            if has_tp:
                h = jax.nn.gelu(xb @ w1) @ w2 + b2
            out = h + _ref_moe_dense(h, p["router"], p["experts"])
            return (jnp.mean((out - yb) ** 2)
                    + 0.01 * load_balancing_loss(h @ p["router"]))

        ref = {"experts": jax.device_get(params["experts"]),
               "router": jax.device_get(params["router"])}
        l, g = jax.value_and_grad(ref_loss)(ref, (x, y))
        ref_new = jax.tree.map(lambda p, gi: p - 0.1 * gi, ref, g)
        got = jax.device_get(state.params)
        np.testing.assert_allclose(float(metrics["loss"]), float(l),
                                   rtol=1e-4)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
            ),
            {"experts": got["experts"], "router": got["router"]}, ref_new,
        )
        if has_tp:
            # TP leaves see the expert axis as extra data parallelism:
            # the sharded update must match the dense w1 gradient exactly
            def w1_loss(w1g):
                from chainermn_tpu.parallel.moe import load_balancing_loss

                h = jax.nn.gelu(x @ w1g) @ w2 + b2
                out = h + _ref_moe_dense(h, ref["router"], ref["experts"])
                return (jnp.mean((out - y) ** 2)
                        + 0.01 * load_balancing_loss(h @ ref["router"]))

            gw1 = jax.grad(w1_loss)(w1)
            new_w1 = np.concatenate(
                [np.asarray(got["w1"][i]) for i in range(m)], axis=1)
            np.testing.assert_allclose(
                new_w1, np.asarray(w1 - 0.1 * gw1), rtol=2e-4, atol=1e-5)

    def test_expert_plan_hlo_counts_match_handwired(self):
        """The ppermute-count convention for the expert axis: one
        compiled expert-plan step carries exactly the collective counts
        of the same step hand-wired from moe_layer_local + call-site
        pmeans, and the FORWARD program carries exactly 2 all_to_alls
        per MoE layer (dispatch + combine, nothing else)."""
        import optax
        from jax import shard_map
        from chainermn_tpu.parallel.moe import moe_layer_local
        from chainermn_tpu.parallel.plan import ParallelPlan

        plan = ParallelPlan({"expert": 8}, devices=_devices())
        n = 8
        params = self._params(n)
        specs = {"experts": P("expert"), "router": P()}
        moe_fn, _ = plan.moe_layer(
            tokens_local=4, d_model=D, capacity_factor=None, impl="sort"
        )
        x = jax.random.normal(jax.random.PRNGKey(5), (32, D))
        y = jax.random.normal(jax.random.PRNGKey(6), (32, D))
        lr = 0.1
        inner = optax.sgd(lr)
        state = plan.create_train_state(params, inner, param_specs=specs)
        step = plan.compile_train_step(_moe_loss_fn(moe_fn), inner,
                                       params, param_specs=specs)
        plan_counts = _collective_counts(
            step.lower(state, (x, y)).compile().as_text()
        )

        def local_loss(p, xb, yb):
            out, aux = moe_layer_local(
                xb, p["router"], expert_fn, p["experts"], "expert",
                capacity_factor=None, dispatch_impl="sort",
                return_stats=True,
            )
            out = xb + out
            return jnp.mean((out - yb) ** 2) + 0.01 * aux["load_balance"]

        def hand_local(params, batch):
            xb, yb = batch
            p = {"experts": jax.tree.map(lambda l: l[0],
                                         params["experts"]),
                 "router": params["router"]}
            loss, g = jax.value_and_grad(local_loss)(p, xb, yb)
            # expert leaves arrive fully accumulated via the a2a
            # transpose: rescale; the router takes the fused pmean
            g_experts = jax.tree.map(lambda l: l / n, g["experts"])
            g_router = jax.lax.pmean(g["router"], "expert")
            new = {
                "experts": jax.tree.map(
                    lambda pl, gl: (pl - lr * gl)[None],
                    p["experts"], g_experts),
                "router": p["router"] - lr * g_router,
            }
            return new, jax.lax.pmean(loss, "expert")

        pspec = {"experts": jax.tree.map(lambda _: P("expert"),
                                         params["experts"]),
                 "router": P()}
        hand = jax.jit(shard_map(
            hand_local, mesh=plan.mesh,
            in_specs=(pspec, P("expert")),
            out_specs=(pspec, P()),
            check_vma=False,
        ))
        hand_counts = _collective_counts(
            hand.lower(params, (x, y)).compile().as_text()
        )
        assert plan_counts == hand_counts, (plan_counts, hand_counts)
        assert 2 <= plan_counts["all-to-all("] <= 4
        assert plan_counts["collective-permute("] == 0

        # the forward program: EXACTLY 2 all_to_alls per MoE layer
        for n_layers in (1, 2):
            def fwd_local(params, xb, n_layers=n_layers):
                p = {"experts": jax.tree.map(lambda l: l[0],
                                             params["experts"]),
                     "router": params["router"]}
                h = xb
                for _ in range(n_layers):
                    h = h + moe_layer_local(
                        h, p["router"], expert_fn, p["experts"],
                        "expert", capacity_factor=None,
                        dispatch_impl="sort",
                    )
                return h

            fwd = jax.jit(shard_map(
                fwd_local, mesh=plan.mesh,
                in_specs=(pspec, P("expert")), out_specs=P("expert"),
                check_vma=False,
            ))
            txt = fwd.lower(params, x).compile().as_text()
            assert txt.count("all-to-all(") == 2 * n_layers, n_layers


class TestRoutingEdges:
    """ISSUE 20 satellite: capacity-factor 0 / one-expert overflow,
    loud k rejection, load-balancing-loss layout invariance."""

    def test_capacity_zero_overflow_residual_counted(self, comm):
        """capacity_factor=0 (one slot per expert) with every token
        choosing the same expert: dropped tokens pass through the
        residual unchanged and are COUNTED — never NaN, never silently
        corrupted."""
        n = comm.size
        ax = comm.axis_name
        t_local = 6
        tokens = t_local * n
        x = jax.random.normal(jax.random.PRNGKey(40), (tokens, D))

        def local(x, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            # zero router => identical logits => argmax breaks every tie
            # to expert 0: the all-tokens-one-expert overflow case
            out, aux = moe_layer_local(
                x, jnp.zeros((D, n)), expert_fn, params, ax,
                capacity_factor=0.0, return_stats=True,
            )
            return x + out, aux

        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(41),
                                     n)
        out, aux = jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(ax), P(ax)),
                out_specs=(P(ax), P()),
                check_vma=False,
            )
        )(x, stacked)
        out = np.asarray(out)
        assert np.isfinite(out).all(), "dropped tokens corrupted the batch"
        # capacity_factor=0 floors at ONE slot per expert per shard:
        # each shard keeps exactly 1 of its 6 tokens (zero logits break
        # ties to expert 0), the rest ride the residual unchanged
        assert float(aux["capacity"]) == 1.0
        assert float(aux["dropped"]) == tokens - n
        np.testing.assert_allclose(
            float(np.asarray(aux["expert_load"]).sum()), n)
        # the dropped rows ARE the residual: out == x wherever moe == 0
        moe_part = out - np.asarray(x)
        dropped_rows = np.abs(moe_part).sum(-1) == 0.0
        assert dropped_rows.sum() == tokens - n

    def test_k_exceeding_experts_rejected_loudly(self):
        from chainermn_tpu.parallel.moe import route_slots
        from chainermn_tpu.parallel.plan import ParallelPlan

        logits = jnp.zeros((8, 4))
        with pytest.raises(ValueError, match="exceeds"):
            route_slots(logits, capacity=4, k=5)
        plan = ParallelPlan({"expert": 8}, devices=_devices())
        with pytest.raises(ValueError, match="exceeds"):
            plan.moe_layer(tokens_local=4, d_model=D, k=9)

    def test_load_balancing_loss_layout_invariant(self, comm):
        """The aux loss computed over the expert axis (token-sharded
        logits + pmean'd statistics) equals the loss computed locally
        over the gathered logits — the value is a property of the
        GLOBAL batch, not the shard layout."""
        from chainermn_tpu.parallel.moe import load_balancing_loss

        n = comm.size
        ax = comm.axis_name
        logits = jax.random.normal(jax.random.PRNGKey(50), (16 * n, n))
        local_val = float(load_balancing_loss(logits))

        def sharded(lg):
            return load_balancing_loss(lg, ax)

        dist_val = float(jax.jit(
            shard_map(
                sharded, mesh=comm.mesh,
                in_specs=P(ax), out_specs=P(),
                check_vma=False,
            )
        )(logits))
        np.testing.assert_allclose(dist_val, local_val, rtol=1e-6)

    def test_capacity_factor_negative_rejected(self):
        from chainermn_tpu.parallel.moe import moe_capacity

        with pytest.raises(ValueError, match="capacity_factor"):
            moe_capacity(16, 4, 1, -1.0)
        assert moe_capacity(16, 4, 1, None) == 16  # no-drop
        assert moe_capacity(16, 4, 1, 0.0) == 1   # minimal, drops


def test_topk_respects_caller_neg_inf_padding():
    """Callers mask disallowed experts with -inf; even when k exceeds the
    remaining finite experts, a taken expert must never be picked twice
    (the duplicate slot is dropped instead)."""
    from chainermn_tpu.parallel.moe import topk_route

    neg = float("-inf")
    logits = jnp.array([[5.0, 1.0, neg, 0.5]] * 8, jnp.float32)
    dispatch, combine = topk_route(logits, capacity=8, k=4)
    d = np.asarray(dispatch)
    per_token_expert = d.sum(axis=2)
    assert (per_token_expert <= 1.0 + 1e-6).all(), "expert double-booked"
    # the three finite experts each picked once; the -inf expert may absorb
    # one pick with zero gate, never a duplicate of a finite one
    c = np.asarray(combine)
    assert np.isfinite(c).all()
