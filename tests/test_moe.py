"""Expert-parallel MoE tests: distributed all_to_all dispatch must equal a
single-device dense evaluation of the same routing (SURVEY.md section 4
invariant, applied to the new EP layer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel.moe import (
    make_expert_params,
    moe_layer_local,
    top1_route,
)

D = 16


def expert_fn(params, x):
    w1, w2 = params
    return jnp.tanh(x @ w1) @ w2


def _expert_init(rng):
    k1, k2 = jax.random.split(rng)
    return (
        jax.random.normal(k1, (D, 32)) / 4.0,
        jax.random.normal(k2, (32, D)) / 4.0,
    )


class TestRouting:
    def test_capacity_bounds_queue(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
        dispatch, combine = top1_route(logits, capacity=8)
        # each expert receives at most `capacity` tokens
        per_expert = dispatch.sum(axis=(0, 2))
        assert (np.asarray(per_expert) <= 8).all()
        # each kept token occupies exactly one (expert, slot)
        per_token = dispatch.sum(axis=(1, 2))
        assert set(np.asarray(per_token).tolist()) <= {0.0, 1.0}

    def test_combine_carries_gate(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
        probs = jax.nn.softmax(logits, -1)
        dispatch, combine = top1_route(logits, capacity=16)
        gates = np.asarray(combine.sum(axis=(1, 2)))
        top = np.asarray(probs.max(axis=-1))
        kept = np.asarray(dispatch.sum(axis=(1, 2))) > 0
        np.testing.assert_allclose(gates[kept], top[kept], rtol=1e-6)


class TestMoELayer:
    def test_matches_dense_single_device(self, comm):
        """EP dispatch over the 8-way mesh == dense per-token expert eval
        with the same router decisions (no drops: generous capacity)."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(0), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(1), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(2), n)

        # --- dense reference: every token through its argmax expert
        logits = x @ router_w
        probs = jax.nn.softmax(logits, -1)
        choice = np.asarray(jnp.argmax(logits, -1))
        ref = np.zeros((tokens, D), np.float32)
        for t in range(tokens):
            e = int(choice[t])
            params_e = jax.tree.map(lambda l: l[e], stacked)
            ref[t] = np.asarray(
                expert_fn(params_e, x[t : t + 1])[0] * probs[t, e]
            )

        # --- distributed: one expert per shard, capacity = all tokens
        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)  # my expert
            return moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=float(n),  # no drops
            )

        out = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )(x, router_w, stacked)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_gradients_flow_to_router_and_experts(self, comm):
        n = comm.size
        ax = comm.axis_name
        tokens = 4 * n
        x = jax.random.normal(jax.random.PRNGKey(3), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(4), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(5), n)

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            out = moe_layer_local(
                x, router_w, expert_fn, params, ax, capacity_factor=float(n)
            )
            return jax.lax.pmean((out**2).mean(), ax)

        loss_fn = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )
        grads = jax.grad(
            lambda rw, st: loss_fn(x, rw, st), argnums=(0, 1)
        )(router_w, stacked)
        g_router, g_experts = grads
        assert float(jnp.abs(g_router).sum()) > 0
        assert all(
            float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(g_experts)
        )

class TestSortDispatch:
    """dispatch_impl='sort' (index scatter/gather, no [T,E,C] tensor) must
    reproduce the dense einsum dispatch exactly — values AND gradients,
    top-1 and top-2, WITH drops (tight capacity) — VERDICT r2 item 8."""

    def _layer(self, comm, impl, k, capacity_factor):
        ax = comm.axis_name

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            out = moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=capacity_factor, k=k, dispatch_impl=impl,
            )
            return out

        return jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)), out_specs=P(),
                check_vma=False,
            )
        )

    @pytest.mark.parametrize("k", [1, 2])
    @pytest.mark.parametrize("capacity_factor", [0.5, 4.0])
    def test_matches_einsum(self, comm, k, capacity_factor):
        n = comm.size
        tokens = 16 * n
        x = jax.random.normal(jax.random.PRNGKey(20), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(21), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(22), n)

        out_e = self._layer(comm, "einsum", k, capacity_factor)(
            x, router_w, stacked
        )
        out_s = self._layer(comm, "sort", k, capacity_factor)(
            x, router_w, stacked
        )
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_e),
                                   rtol=1e-5, atol=1e-6)

    def test_mixed_precision_dtype_parity(self, comm):
        """bf16 activations + f32 router (the normal mixed-precision
        setup): sort dispatch must return the same dtype AND values as the
        einsum path's promotion semantics."""
        n = comm.size
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(30), (tokens, D),
                              jnp.bfloat16)
        router_w = jax.random.normal(jax.random.PRNGKey(31), (D, n),
                                     jnp.float32) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(32), n)

        def run(impl):
            ax = comm.axis_name

            def local(x, router_w, stacked):
                params = jax.tree.map(lambda l: l[0], stacked)
                return moe_layer_local(
                    x, router_w.astype(jnp.float32), expert_fn, params, ax,
                    capacity_factor=2.0, k=2, dispatch_impl=impl,
                )

            return jax.jit(
                shard_map(
                    local, mesh=comm.mesh,
                    in_specs=(P(), P(), P(ax)), out_specs=P(),
                    check_vma=False,
                )
            )(x, router_w, stacked)

        out_e, out_s = run("einsum"), run("sort")
        assert out_s.dtype == out_e.dtype
        np.testing.assert_allclose(
            np.asarray(out_s, np.float32), np.asarray(out_e, np.float32),
            rtol=2e-2, atol=2e-2,
        )

    def test_grads_match_einsum(self, comm):
        n = comm.size
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(23), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(24), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(25), n)

        def loss(impl):
            layer = self._layer(comm, impl, 2, 1.0)

            def f(x, rw, st):
                return (layer(x, rw, st) ** 2).mean()

            return jax.grad(f, argnums=(0, 1, 2))(x, router_w, stacked)

        g_e = loss("einsum")
        g_s = loss("sort")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g_s, g_e,
        )


class TestTopK:
    def test_topk_capacity_and_slots(self):
        from chainermn_tpu.parallel.moe import topk_route

        logits = jax.random.normal(jax.random.PRNGKey(6), (64, 4))
        dispatch, combine = topk_route(logits, capacity=16, k=2)
        per_expert = dispatch.sum(axis=(0, 2))
        assert (np.asarray(per_expert) <= 16).all()
        # each token occupies at most k (expert, slot) cells
        per_token = dispatch.sum(axis=(1, 2))
        assert (np.asarray(per_token) <= 2.0 + 1e-6).all()
        # no two tokens share a queue slot
        per_slot = dispatch.sum(axis=0)
        assert (np.asarray(per_slot) <= 1.0 + 1e-6).all()

    def test_topk_gates_normalised(self):
        from chainermn_tpu.parallel.moe import topk_route

        logits = jax.random.normal(jax.random.PRNGKey(7), (32, 4))
        dispatch, combine = topk_route(logits, capacity=32, k=2)  # no drops
        # with both choices kept, the two normalised gates sum to 1
        gates = np.asarray(combine.sum(axis=(1, 2)))
        np.testing.assert_allclose(gates, np.ones_like(gates), rtol=1e-5)

    def test_top2_layer_matches_dense(self, comm):
        """k=2 EP dispatch == dense weighted two-expert evaluation."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(8), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(9), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(10), n)

        logits = x @ router_w
        probs = np.asarray(jax.nn.softmax(logits, -1))
        order = np.argsort(-probs, axis=-1)
        ref = np.zeros((tokens, D), np.float32)
        for t in range(tokens):
            e1, e2 = int(order[t, 0]), int(order[t, 1])
            g1, g2 = probs[t, e1], probs[t, e2]
            zsum = g1 + g2
            for e, g in ((e1, g1), (e2, g2)):
                pe = jax.tree.map(lambda l: l[e], stacked)
                ref[t] += np.asarray(expert_fn(pe, x[t : t + 1])[0]) * (g / zsum)

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            return moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=float(n), k=2,
            )

        out = jax.jit(
            shard_map(
                local, mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)), out_specs=P(),
                check_vma=False,
            )
        )(x, router_w, stacked)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)

    def test_load_balancing_loss_signal(self):
        from chainermn_tpu.parallel.moe import load_balancing_loss

        n = 8
        # perfectly balanced: uniform logits -> loss ~ 1
        uniform = jnp.zeros((128, n))
        assert abs(float(load_balancing_loss(uniform)) - 1.0) < 1e-5
        # collapsed: all tokens to expert 0 -> loss ~ n
        collapsed = jnp.zeros((128, n)).at[:, 0].set(20.0)
        assert float(load_balancing_loss(collapsed)) > n - 0.1


def test_moe_example_converges():
    """The example CLI trains router + experts to high accuracy (top-1)."""
    import examples.moe.train_moe_mlp as ex

    acc = ex.main(["--iterations", "150", "--batchsize", "128",
                   "--width", "32"])
    assert acc > 0.9, f"moe example did not converge: acc={acc}"


def test_topk_bf16_logits_no_slot_collisions():
    """Queue slot indices must be exact in int32 even when router logits
    are bf16 (bf16 cumsum cannot represent integers past 256, which
    collided slots and dropped tokens despite ample capacity)."""
    from chainermn_tpu.parallel.moe import topk_route

    tokens = 1024
    logits = jnp.zeros((tokens, 4), jnp.bfloat16).at[:, 0].set(5.0)
    dispatch, combine = topk_route(logits, capacity=tokens, k=2)
    d = np.asarray(dispatch, np.float32)
    # no two tokens share a queue slot
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # nothing dropped: every token occupies exactly k slots
    np.testing.assert_allclose(d.sum(axis=(1, 2)), np.full(tokens, 2.0),
                               rtol=0, atol=1e-6)


def test_topk_no_duplicate_expert_on_underflow():
    """A diverged router (softmax mass underflows to 0 outside the top
    choice) must still pick k DISTINCT experts — logit-space masking; and
    k > n_experts is rejected."""
    from chainermn_tpu.parallel.moe import topk_route

    logits = jnp.zeros((16, 4), jnp.float32).at[:, 2].set(200.0)
    dispatch, _ = topk_route(logits, capacity=16, k=2)
    d = np.asarray(dispatch)
    per_token_expert = d.sum(axis=2)  # [tokens, experts]
    assert (per_token_expert <= 1.0 + 1e-6).all(), "expert chosen twice"
    assert (d.sum(axis=(1, 2)) == 2.0).all()

    import pytest as _pytest
    with _pytest.raises(ValueError, match="exceeds"):
        topk_route(logits, capacity=4, k=5)


def test_topk_respects_caller_neg_inf_padding():
    """Callers mask disallowed experts with -inf; even when k exceeds the
    remaining finite experts, a taken expert must never be picked twice
    (the duplicate slot is dropped instead)."""
    from chainermn_tpu.parallel.moe import topk_route

    neg = float("-inf")
    logits = jnp.array([[5.0, 1.0, neg, 0.5]] * 8, jnp.float32)
    dispatch, combine = topk_route(logits, capacity=8, k=4)
    d = np.asarray(dispatch)
    per_token_expert = d.sum(axis=2)
    assert (per_token_expert <= 1.0 + 1e-6).all(), "expert double-booked"
    # the three finite experts each picked once; the -inf expert may absorb
    # one pick with zero gate, never a duplicate of a finite one
    c = np.asarray(combine)
    assert np.isfinite(c).all()
