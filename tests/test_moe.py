"""Expert-parallel MoE tests: distributed all_to_all dispatch must equal a
single-device dense evaluation of the same routing (SURVEY.md section 4
invariant, applied to the new EP layer)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel.moe import (
    make_expert_params,
    moe_layer_local,
    top1_route,
)

D = 16


def expert_fn(params, x):
    w1, w2 = params
    return jnp.tanh(x @ w1) @ w2


def _expert_init(rng):
    k1, k2 = jax.random.split(rng)
    return (
        jax.random.normal(k1, (D, 32)) / 4.0,
        jax.random.normal(k2, (32, D)) / 4.0,
    )


class TestRouting:
    def test_capacity_bounds_queue(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
        dispatch, combine = top1_route(logits, capacity=8)
        # each expert receives at most `capacity` tokens
        per_expert = dispatch.sum(axis=(0, 2))
        assert (np.asarray(per_expert) <= 8).all()
        # each kept token occupies exactly one (expert, slot)
        per_token = dispatch.sum(axis=(1, 2))
        assert set(np.asarray(per_token).tolist()) <= {0.0, 1.0}

    def test_combine_carries_gate(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
        probs = jax.nn.softmax(logits, -1)
        dispatch, combine = top1_route(logits, capacity=16)
        gates = np.asarray(combine.sum(axis=(1, 2)))
        top = np.asarray(probs.max(axis=-1))
        kept = np.asarray(dispatch.sum(axis=(1, 2))) > 0
        np.testing.assert_allclose(gates[kept], top[kept], rtol=1e-6)


class TestMoELayer:
    def test_matches_dense_single_device(self, comm):
        """EP dispatch over the 8-way mesh == dense per-token expert eval
        with the same router decisions (no drops: generous capacity)."""
        n = comm.size
        ax = comm.axis_name
        tokens = 8 * n
        x = jax.random.normal(jax.random.PRNGKey(0), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(1), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(2), n)

        # --- dense reference: every token through its argmax expert
        logits = x @ router_w
        probs = jax.nn.softmax(logits, -1)
        choice = np.asarray(jnp.argmax(logits, -1))
        ref = np.zeros((tokens, D), np.float32)
        for t in range(tokens):
            e = int(choice[t])
            params_e = jax.tree.map(lambda l: l[e], stacked)
            ref[t] = np.asarray(
                expert_fn(params_e, x[t : t + 1])[0] * probs[t, e]
            )

        # --- distributed: one expert per shard, capacity = all tokens
        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)  # my expert
            return moe_layer_local(
                x, router_w, expert_fn, params, ax,
                capacity_factor=float(n),  # no drops
            )

        out = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )(x, router_w, stacked)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_gradients_flow_to_router_and_experts(self, comm):
        n = comm.size
        ax = comm.axis_name
        tokens = 4 * n
        x = jax.random.normal(jax.random.PRNGKey(3), (tokens, D))
        router_w = jax.random.normal(jax.random.PRNGKey(4), (D, n)) / 4.0
        stacked = make_expert_params(_expert_init, jax.random.PRNGKey(5), n)

        def local(x, router_w, stacked):
            params = jax.tree.map(lambda l: l[0], stacked)
            out = moe_layer_local(
                x, router_w, expert_fn, params, ax, capacity_factor=float(n)
            )
            return jax.lax.pmean((out**2).mean(), ax)

        loss_fn = jax.jit(
            shard_map(
                local,
                mesh=comm.mesh,
                in_specs=(P(), P(), P(ax)),
                out_specs=P(),
                check_vma=False,
            )
        )
        grads = jax.grad(
            lambda rw, st: loss_fn(x, rw, st), argnums=(0, 1)
        )(router_w, stacked)
        g_router, g_experts = grads
        assert float(jnp.abs(g_router).sum()) > 0
        assert all(
            float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(g_experts)
        )