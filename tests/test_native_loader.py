"""Native data-loader tests: C++ threaded prefetch must deliver exactly the
dataset's records (per epoch, shuffled, sharded) with correct field
decoding — the coverage the reference's iterator tests gave its data plane
(SURVEY.md section 4)."""

import numpy as np
import pytest

from chainermn_tpu.native.data_loader import (
    NativeDataLoader,
    write_fixed_records,
)

N, H = 64, 8


@pytest.fixture()
def dataset(tmp_path):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, size=(N, H, H, 3)).astype(np.uint8)
    labels = np.arange(N, dtype=np.int32)  # label == record index
    path = str(tmp_path / "data.bin")
    write_fixed_records(path, images, labels)
    return path, images, labels


FIELDS = [
    ("image", np.uint8, (H, H, 3)),
    ("label", np.int32, ()),
]


def test_batches_decode_fields(dataset):
    path, images, labels = dataset
    dl = NativeDataLoader(path, FIELDS, batch_size=8, shuffle=False, threads=1)
    batch = next(dl)
    assert batch["image"].shape == (8, H, H, 3)
    assert batch["label"].shape == (8,)
    # label i identifies the record; image must be the matching one
    for img, lab in zip(batch["image"], batch["label"]):
        np.testing.assert_array_equal(img, images[lab])
    dl.close()


def test_epoch_covers_every_record_once(dataset):
    path, _, _ = dataset
    dl = NativeDataLoader(
        path, FIELDS, batch_size=8, shuffle=True, threads=3, seed=7
    )
    assert dl.batches_per_epoch == N // 8
    # Workers may interleave batches across the epoch boundary; group by
    # the batch's epoch tag and account for epoch 0 exactly. The bound is
    # generous (20 epochs of nexts): under full-suite CPU contention a
    # worker holding one epoch-0 batch can be starved for several epochs of
    # other workers' output before the scheduler runs it (observed flake at
    # a 3-epoch bound).
    seen = []
    epoch0_batches = 0
    for _ in range(20 * dl.batches_per_epoch):
        batch = next(dl)
        if dl.epoch == 0:
            seen.extend(batch["label"].tolist())
            epoch0_batches += 1
        if epoch0_batches == dl.batches_per_epoch:
            break
    dl.close()
    assert sorted(seen) == list(range(N))


def test_sharding(dataset):
    path, _, _ = dataset
    dl = NativeDataLoader(
        path, FIELDS, batch_size=4, shuffle=True, shard=(16, 32), threads=2
    )
    assert dl.num_records == 16
    labels = set()
    epoch0 = 0
    for _ in range(3 * dl.batches_per_epoch):
        batch = next(dl)
        if dl.epoch == 0:
            labels.update(batch["label"].tolist())
            epoch0 += 1
        if epoch0 == dl.batches_per_epoch:
            break
    dl.close()
    assert labels == set(range(16, 32))


def test_shuffle_deterministic_by_seed(dataset):
    path, _, _ = dataset

    def first_epoch(seed):
        dl = NativeDataLoader(
            path, FIELDS, batch_size=8, shuffle=True, seed=seed, threads=1
        )
        out = []
        for _ in range(dl.batches_per_epoch):
            out.extend(next(dl)["label"].tolist())
        dl.close()
        return out

    assert first_epoch(3) == first_epoch(3)
    assert first_epoch(3) != first_epoch(4)


def test_open_rejects_bad_record_size(dataset):
    path, _, _ = dataset
    with pytest.raises(RuntimeError, match="dl_open failed"):
        NativeDataLoader(path, [("x", np.uint8, (9,))], batch_size=4)