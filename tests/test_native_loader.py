"""Native data-loader tests: C++ threaded prefetch must deliver exactly the
dataset's records (per epoch, shuffled, sharded) with correct field
decoding — the coverage the reference's iterator tests gave its data plane
(SURVEY.md section 4)."""

import numpy as np
import pytest

from chainermn_tpu.native.data_loader import (
    NativeDataLoader,
    write_fixed_records,
)

N, H = 64, 8


@pytest.fixture()
def dataset(tmp_path):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, size=(N, H, H, 3)).astype(np.uint8)
    labels = np.arange(N, dtype=np.int32)  # label == record index
    path = str(tmp_path / "data.bin")
    write_fixed_records(path, images, labels)
    return path, images, labels


FIELDS = [
    ("image", np.uint8, (H, H, 3)),
    ("label", np.int32, ()),
]


def test_batches_decode_fields(dataset):
    path, images, labels = dataset
    dl = NativeDataLoader(path, FIELDS, batch_size=8, shuffle=False, threads=1)
    batch = next(dl)
    assert batch["image"].shape == (8, H, H, 3)
    assert batch["label"].shape == (8,)
    # label i identifies the record; image must be the matching one
    for img, lab in zip(batch["image"], batch["label"]):
        np.testing.assert_array_equal(img, images[lab])
    dl.close()


def test_epoch_covers_every_record_once(dataset):
    path, _, _ = dataset
    dl = NativeDataLoader(
        path, FIELDS, batch_size=8, shuffle=True, threads=3, seed=7
    )
    assert dl.batches_per_epoch == N // 8
    # Workers may interleave batches across the epoch boundary; group by
    # the batch's epoch tag and account for epoch 0 exactly. The bound is
    # generous (20 epochs of nexts): under full-suite CPU contention a
    # worker holding one epoch-0 batch can be starved for several epochs of
    # other workers' output before the scheduler runs it (observed flake at
    # a 3-epoch bound).
    seen = []
    epoch0_batches = 0
    for _ in range(20 * dl.batches_per_epoch):
        batch = next(dl)
        if dl.epoch == 0:
            seen.extend(batch["label"].tolist())
            epoch0_batches += 1
        if epoch0_batches == dl.batches_per_epoch:
            break
    dl.close()
    assert sorted(seen) == list(range(N))


def test_sharding(dataset):
    path, _, _ = dataset
    dl = NativeDataLoader(
        path, FIELDS, batch_size=4, shuffle=True, shard=(16, 32), threads=2
    )
    assert dl.num_records == 16
    labels = set()
    epoch0 = 0
    for _ in range(3 * dl.batches_per_epoch):
        batch = next(dl)
        if dl.epoch == 0:
            labels.update(batch["label"].tolist())
            epoch0 += 1
        if epoch0 == dl.batches_per_epoch:
            break
    dl.close()
    assert labels == set(range(16, 32))


def test_shuffle_deterministic_by_seed(dataset):
    path, _, _ = dataset

    def first_epoch(seed):
        dl = NativeDataLoader(
            path, FIELDS, batch_size=8, shuffle=True, seed=seed, threads=1
        )
        out = []
        for _ in range(dl.batches_per_epoch):
            out.extend(next(dl)["label"].tolist())
        dl.close()
        return out

    assert first_epoch(3) == first_epoch(3)
    assert first_epoch(3) != first_epoch(4)


def test_open_rejects_bad_record_size(dataset):
    path, _, _ = dataset
    with pytest.raises(RuntimeError, match="dl_open failed"):
        NativeDataLoader(path, [("x", np.uint8, (9,))], batch_size=4)

def test_bench_native_loop_child_mode(tmp_path):
    """``bench.py --run native-loop`` (the fresh-process end-to-end input
    benchmark child) runs loader → prefetch_to_device → jitted train step
    and prints a wall-time JSON line. The D2H-free timed region it
    implements is the measurement fix for the tunnelled-TPU H2D
    degradation (docs/benchmarks.md, input-pipeline section)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    try:
        from _driver_env import cpu_scrubbed_env
    finally:
        sys.path.pop(0)

    # Match bench._resnet_setup(on_accel=False) INSIDE THE CHILD: hw=32,
    # batch = 8 * mesh size, where the child's mesh is pinned to 8 by
    # cpu_scrubbed_env(8) below — NOT this process's device count (which
    # an externally-set XLA_FLAGS could make different).
    hw = 32
    batch = 8 * 8
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(batch * 3, hw, hw, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(batch * 3,)).astype(np.int32)
    path = str(tmp_path / "records.bin")
    write_fixed_records(path, images, labels)

    env = cpu_scrubbed_env(8, cache_dir=os.path.join(repo, ".jax_cache"))
    env.update(
        CMN_NATIVE_STEPS="2",
        CMN_NATIVE_RECORDS=path,
        CMN_NATIVE_HW=str(hw),
        CMN_NATIVE_BATCH=str(batch),
        CMN_NATIVE_ACCEL="0",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--run",
         "native-loop"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["steps"] == 2
    assert out["batch"] == batch
    assert out["wall_s"] > 0
