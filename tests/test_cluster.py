"""Cluster serving plane invariants (ISSUE 8).

The load-bearing acceptance pins, asserted structurally:

- **Cluster stream equivalence** — every token stream routed through
  the cluster is bit-identical to sequential ``generate`` on a single
  device, INCLUDING requests whose KV was prefilled on a different
  replica than the one that decoded them (dense == paged == TP
  variants), and including requests re-routed after a replica loss.
- **No new collectives** — the decode replica's compiled step carries
  exactly the pre-cluster collective set (2 all-reduces/layer under
  TP), and the KV handoff's extract/inject programs carry ZERO
  collectives: the handoff is host-plane only.
- **Cross-allocator hygiene** — a serialized block chain adopted into
  a second ``BlockAllocator`` gets fresh physical ids and refcounts;
  release on either side never corrupts the other (the satellite's
  refcount/epoch pin).

Plus router policy units (least-loaded / prefix-aware / sticky /
requeue-on-full / replica loss), the ``Scheduler.run(max_seconds=)``
satellite, and the in-mesh ``ppermute`` rehearsal of the transfer
plane. The fast single-process 2-replica loopback subset here is
tier-1; the true multi-process handoff over the native TCP plane is
``slow`` (see ``cluster_worker.py``).
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.serving import Request, Scheduler, ServingEngine
from chainermn_tpu.serving.cluster import (
    LoopbackHub,
    Router,
    make_replicas,
    mesh_stream_blocks,
    transfer_kv,
)

VOCAB = 32


def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=64, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _requests(n, seed=0, shared=None, max_new=5):
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        p = list(shared) if (shared and i % 2) else []
        p += rs.randint(1, VOCAB, size=int(rs.randint(2, 6))).tolist()
        out.append((p, int(rs.randint(2, max_new))))
    return out


def _submit_all(router, reqs, **kw):
    return [router.submit(Request(prompt=p, max_new_tokens=g, **kw))
            for p, g in reqs]


def _assert_streams(results, ids, reqs, model, params):
    for rid, (p, g) in zip(ids, reqs):
        assert results[rid]["tokens"] == _ref(model, params, p, g), rid


ENGINE_KW = dict(num_slots=2, max_len=64, decode_impl="paged",
                 kv_block_size=8, prefill_buckets=(4, 8, 16))


class TestClusterEquivalence:
    def test_colocated_streams_match_generate(self, lm):
        model, params = lm
        rs = np.random.RandomState(3)
        shared = rs.randint(1, VOCAB, size=16).tolist()
        reps = make_replicas(model, params, 2, prefix_cache="on",
                             **ENGINE_KW)
        router = Router(reps, mode="colocated", policy="prefix_aware")
        reqs = _requests(6, seed=4, shared=shared)
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)
        s = router.summary()
        assert sum(s["routes"].values()) == len(reqs)
        assert s["requests"] == len(reqs)
        assert s["kv_transfer"]["transfers"] == 0  # colocated: no hops

    @pytest.mark.parametrize("impl", ["dense", "paged"])
    def test_disaggregated_streams_match_generate(self, lm, impl):
        """The tentpole pin: prefilled on replica 0, decoded on
        replica 1 — streams identical to sequential generate."""
        model, params = lm
        kw = dict(ENGINE_KW, decode_impl=impl)
        reps = make_replicas(model, params, 2, **kw)
        router = Router(reps, mode="disaggregated",
                        prefill_replicas=[0])
        reqs = _requests(5, seed=5)
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)
        s = router.summary()
        assert s["kv_transfer"]["transfers"] == len(reqs)
        assert s["kv_transfer"]["bytes"] > 0
        # every decode landed on replica 1; every prefill on replica 0
        assert s["replicas"][1]["requests"] == len(reqs)

    def test_disaggregated_tp_matches_single_device(self, lm):
        """TP decode inside each replica (2 AR/layer pinned below) ==
        single-device streams, across the handoff."""
        model, params = lm
        devices = jax.devices("cpu")[:4]
        reps = make_replicas(model, params, 2, tp=2, devices=devices,
                             **ENGINE_KW)
        router = Router(reps, mode="disaggregated",
                        prefill_replicas=[0])
        reqs = _requests(5, seed=6)
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)
        n_dec = reps[1].engine.decode_compile_count()
        assert n_dec in (None, 1), f"decode recompiled: {n_dec}"

    def test_disaggregated_speculative_decode_composes(self, lm):
        """The adopted slot carries its token history, so the decode
        replica's drafter proposes from the full stream — spec ticks
        across a handoff stay bit-identical to plain generate."""
        model, params = lm
        rs = np.random.RandomState(8)
        base = rs.randint(1, VOCAB, size=3).tolist()
        reqs = [((base * 4)[:int(rs.randint(6, 10))],
                 int(rs.randint(3, 6))) for _ in range(4)]
        reps = make_replicas(model, params, 2, spec_tokens=2,
                             **ENGINE_KW)
        router = Router(reps, mode="disaggregated",
                        prefill_replicas=[0])
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)
        n_ver = reps[1].engine.verify_compile_count()
        assert n_ver in (None, 1), f"verify recompiled: {n_ver}"

    def test_requeue_on_full_defers_never_drops(self, lm):
        """A decode replica whose pool cannot hold every handoff at
        once defers adoption (import_kv -> None) and the router
        retries as streams finish — every request still lands, streams
        exact."""
        model, params = lm
        # pool covers ~1 request (plus scratch): handoffs MUST queue
        kw = dict(ENGINE_KW, num_slots=4, num_blocks=4)
        reps = make_replicas(model, params, 2, **kw)
        router = Router(reps, mode="disaggregated",
                        prefill_replicas=[0])
        reqs = _requests(5, seed=7)
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)

    def test_replica_loss_requeues_and_streams_match(self, lm):
        model, params = lm
        reps = make_replicas(model, params, 2, **ENGINE_KW)
        router = Router(reps, mode="colocated", policy="least_loaded")
        reqs = _requests(5, seed=9)
        ids = _submit_all(router, reqs)
        # progress a little so replica 0 holds in-flight work, then
        # kill it mid-stream
        for _ in range(2):
            for rep in reps:
                rep.scheduler.start_window()
                rep.tick()
        moved = router.fail_replica(0)
        assert moved  # it held queued and/or in-flight requests
        results = router.run()
        _assert_streams(results, ids, reqs, model, params)
        ev = [e for e in router._events if e["kind"] == "route"]
        assert any(e["requeue"] for e in ev)
        assert all(e["replica"] == 1 for e in ev if e["requeue"])
        # Accounting survives the failover (review finding): every
        # submitted request counts exactly once — replica 0's stale
        # window (discarded partial streams) must not inflate tokens,
        # and the wiped pre-run events must not deflate requests.
        s = router.summary()
        assert s["requests"] == len(reqs)
        assert s["generated_tokens"] == sum(
            len(results[rid]["generated"]) for rid in ids)
        assert s["replicas"][0]["alive"] is False
        assert s["replicas"][1]["alive"] is True

    def test_fresh_router_over_warm_replicas_returns_only_its_own(
        self, lm
    ):
        """Replica schedulers are cumulative and outlive a router (the
        warm-replica bench pattern): a fresh router's run()/summary()
        must cover ITS requests only (review finding)."""
        model, params = lm
        reps = make_replicas(model, params, 2, **ENGINE_KW)
        r1 = Router(reps, mode="colocated")
        ids1 = _submit_all(r1, _requests(3, seed=15))
        r1.run()
        r2 = Router(reps, mode="colocated")
        reqs2 = _requests(2, seed=16)
        ids2 = _submit_all(r2, reqs2)
        results2 = r2.run()
        assert set(results2) == set(ids2)  # no foreign streams
        assert not set(results2) & set(ids1)
        s2 = r2.summary()
        assert s2["requests"] == len(ids2)
        _assert_streams(results2, ids2, reqs2, model, params)

    def test_failed_replica_gauges_zero_not_freeze(self, lm):
        """A dead replica's rank-labeled gauges drop to 0 with an
        explicit liveness flag — frozen last-breath values would read
        as alive-and-loaded to a monitor (review finding)."""
        from chainermn_tpu.observability import metrics

        model, params = lm
        reg = metrics.registry()
        try:
            reps = make_replicas(model, params, 2, **ENGINE_KW)
            router = Router(reps, mode="colocated",
                            policy="least_loaded")
            ids = _submit_all(router, _requests(4, seed=17))
            g = reg.gauge("serving_replica_queue_depth")
            assert (g.value(rank="0") or 0) > 0
            router.fail_replica(0)
            assert g.value(rank="0") == 0.0
            assert reg.gauge("serving_replica_inflight").value(
                rank="0") == 0.0
            alive = reg.gauge("serving_replica_alive")
            assert alive.value(rank="0") == 0.0
            assert alive.value(rank="1") == 1.0
            results = router.run()
            assert set(ids) <= set(results)
        finally:
            metrics.reset()


class TestRouterPolicies:
    def test_sticky_sessions_pin_a_replica(self, lm):
        model, params = lm
        reps = make_replicas(model, params, 3, **ENGINE_KW)
        router = Router(reps, mode="colocated", policy="least_loaded")
        reqs = _requests(4, seed=10)
        ids = _submit_all(router, reqs)  # no sessions: spread by load
        del ids
        # three turns of one session always land together
        turn_ids = _submit_all(router, _requests(3, seed=11),
                               session_id="conv-1")
        ev = {e["request"]: e for e in router._events
              if e["kind"] == "route"}
        homes = {ev[rid]["replica"] for rid in turn_ids}
        assert len(homes) == 1
        assert ev[turn_ids[1]]["sticky"] and ev[turn_ids[2]]["sticky"]
        router.run()

    def test_prefix_aware_placement_follows_the_warm_trie(self, lm):
        """A replica that already served a prefix wins placement for
        followers of the same prefix, even at equal load."""
        model, params = lm
        rs = np.random.RandomState(12)
        shared = rs.randint(1, VOCAB, size=24).tolist()  # 3 blocks @ 8
        reps = make_replicas(model, params, 2, prefix_cache="on",
                             **ENGINE_KW)
        # warm replica 1's trie directly (bypassing the router)
        reps[1].scheduler.submit(Request(prompt=list(shared) + [5],
                                         max_new_tokens=2))
        reps[1].scheduler.run()
        assert reps[1].prefix_hit_blocks(shared) == 3
        assert reps[0].prefix_hit_blocks(shared) == 0
        router = Router(reps, mode="colocated", policy="prefix_aware")
        rid = router.submit(Request(prompt=list(shared) + [7, 9],
                                    max_new_tokens=2))
        ev = [e for e in router._events if e["kind"] == "route"][-1]
        assert ev["request"] == rid and ev["replica"] == 1
        assert ev["hit_blocks"] == 3
        router.run()

    def test_least_loaded_spreads_a_burst(self, lm):
        model, params = lm
        reps = make_replicas(model, params, 2, **ENGINE_KW)
        router = Router(reps, mode="colocated", policy="least_loaded")
        _submit_all(router, _requests(4, seed=13))
        s_routes = router._route_counts
        assert s_routes.get(0, 0) == 2 and s_routes.get(1, 0) == 2
        router.run()

    def test_router_validation(self, lm):
        model, params = lm
        reps = make_replicas(model, params, 1, **ENGINE_KW)
        with pytest.raises(ValueError, match="policy"):
            Router(reps, policy="round_robin")
        with pytest.raises(ValueError, match="mode"):
            Router(reps, mode="sharded")
        with pytest.raises(ValueError, match=">= 2 replicas"):
            Router(reps, mode="disaggregated")
        # auto on a single replica: forced colocated, with provenance
        r = Router(reps, mode="auto")
        assert r.mode == "colocated"
        assert r.decisions[0]["source"] == "forced:single-replica"
        reps2 = make_replicas(model, params, 2, **ENGINE_KW)
        with pytest.raises(ValueError, match="unknown prefill"):
            Router(reps2, mode="disaggregated", prefill_replicas=[9])
        with pytest.raises(ValueError, match="horizon|max_len"):
            Router(reps2).submit(Request(prompt=[1] * 60,
                                         max_new_tokens=30))

    def test_disagg_refuses_mismatched_layouts(self, lm):
        """Blocks are not portable across differing layouts — the
        router refuses at construction, not mid-handoff."""
        model, params = lm
        a = ServingEngine(model, params, **ENGINE_KW)
        b_kw = dict(ENGINE_KW, kv_block_size=16)
        b = ServingEngine(model, params, **b_kw)
        from chainermn_tpu.serving.cluster import Replica

        reps = [Replica(a, Scheduler(a), 0), Replica(b, Scheduler(b), 1)]
        with pytest.raises(ValueError, match="KV layout"):
            Router(reps, mode="disaggregated", prefill_replicas=[0])

    def test_unplaceable_request_raises_not_hangs(self, lm):
        model, params = lm
        kw = dict(ENGINE_KW, num_blocks=3)  # 2 usable blocks = 16 pos
        reps = make_replicas(model, params, 2, **kw)
        router = Router(reps, mode="colocated")
        router.submit(Request(prompt=[1] * 30, max_new_tokens=2))
        with pytest.raises(RuntimeError, match="stalled|unplaceable"):
            router.run()


class TestKvTransfer:
    def test_cross_allocator_adoption_hygiene(self, lm):
        """The satellite pin: serialize a block chain, adopt into a
        SECOND allocator — fresh ids, refcount 1, version (epoch)
        bumped — and release on either side never corrupts the
        other's stream."""
        model, params = lm
        a = ServingEngine(model, params, **ENGINE_KW)
        b = ServingEngine(model, params, **ENGINE_KW)
        prompt = [3, 7, 1, 9, 2, 8, 4, 6, 5, 3, 2]  # > 1 full block
        n_new = 4
        slot_a, tok_a, _ = a.prefill_join(prompt)
        free_a0 = a._alloc.free_blocks
        v0_b = b._alloc.version
        out = transfer_kv(a, b, slot_a, release=False)
        assert out is not None
        slot_b, tok_b, nbytes, _dur = out
        assert tok_b == tok_a and nbytes > 0
        # fresh ids on B, refcount exactly 1, epoch bumped
        b_blocks = b._alloc.owned_blocks(slot_b)
        assert all(b._alloc.refcounts[blk] == 1 for blk in b_blocks)
        assert b._alloc.version > v0_b
        # A untouched by the adoption
        assert a._alloc.free_blocks == free_a0

        ref = _ref(model, params, prompt, n_new)

        def drain(engine, slot, stream):
            while len(stream) < len(prompt) + n_new:
                toks, _ = engine.decode_step()
                stream.append(int(toks[slot]))
            return stream

        # release on A first — B's adopted blocks must survive
        a.leave(slot_a)
        assert a._alloc.blocks_in_use == 0
        stream_b = drain(b, slot_b, list(prompt) + [tok_b])
        assert stream_b == ref
        b.leave(slot_b)
        assert b._alloc.blocks_in_use == 0

        # ...and the mirror order: release on B never corrupts A
        slot_a2, tok_a2, _ = a.prefill_join(prompt)
        out2 = transfer_kv(a, b, slot_a2, release=False)
        slot_b2, tok_b2 = out2[0], out2[1]
        b.leave(slot_b2)
        stream_a = drain(a, slot_a2, list(prompt) + [tok_a2])
        assert stream_a == ref

    def test_import_defers_on_slot_or_pool_shortage(self, lm):
        model, params = lm
        a = ServingEngine(model, params, **ENGINE_KW)
        kw = dict(ENGINE_KW, num_slots=1, num_blocks=3)
        b = ServingEngine(model, params, **kw)
        s1, _, _ = a.prefill_join([1, 2, 3, 4, 5])
        payload = a.export_kv(s1)
        # pool too small: defers, state untouched
        free0, v0 = b._alloc.free_blocks, b._alloc.version
        big = ServingEngine(model, params, **ENGINE_KW)
        sbig, _, _ = big.prefill_join(list(range(1, 20)))
        assert b.import_kv(big.export_kv(sbig)) is None
        assert (b._alloc.free_blocks, b._alloc.version) == (free0, v0)
        # slot shortage: occupy the only slot, then defer
        res = b.import_kv(payload)
        assert res is not None
        assert b.import_kv(payload if False else a.export_kv(s1)) is None

    def test_signature_mismatch_raises(self, lm):
        model, params = lm
        a = ServingEngine(model, params, **ENGINE_KW)
        s, _, _ = a.prefill_join([1, 2, 3, 4, 5])
        payload = a.export_kv(s)
        b_kw = dict(ENGINE_KW, kv_block_size=16)
        b = ServingEngine(model, params, **b_kw)
        with pytest.raises(ValueError, match="layout mismatch"):
            b.import_kv(payload)
        c_kw = dict(ENGINE_KW, decode_impl="dense")
        c = ServingEngine(model, params, **c_kw)
        with pytest.raises(ValueError, match="layout mismatch"):
            c.import_kv(payload)

    def test_import_into_prefix_trie_serves_followers(self, lm):
        """Adopted full blocks land in the receiver's trie: a follower
        of the same prefix hits locally, no second transfer."""
        model, params = lm
        kw = dict(ENGINE_KW, prefix_cache="on", num_slots=4)
        a = ServingEngine(model, params, **kw)
        b = ServingEngine(model, params, **kw)
        shared = list(range(1, 17))  # 2 full blocks @ 8
        s, _, _ = a.prefill_join(shared + [20, 21])
        assert transfer_kv(a, b, s) is not None
        assert b.prefix_match_depth(shared) == 2

    def test_loopback_transport_fifo_and_bounded_recv(self):
        hub = LoopbackHub()
        e0, e1 = hub.endpoint(0), hub.endpoint(1)
        assert e1.probe(0) is False
        e0.send_obj({"i": 1}, 1)
        e0.send_obj({"i": 2}, 1)
        assert e1.probe(0) is True
        assert e1.recv_obj(0) == {"i": 1}  # per-pair FIFO
        assert e1.recv_obj(0) == {"i": 2}
        with pytest.raises(LookupError, match="nothing pending"):
            e1.recv_obj(0)  # bounded by construction, never a hang

    def test_mesh_rehearsal_streams_blocks_over_ppermute(self):
        """The in-mesh transfer path (functions/point_to_point): one
        ppermute moves the block pytree shard 0 -> 1."""
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("replica",))
        blk = {
            "k": jnp.arange(2 * 1 * 8 * 4 * 4, dtype=jnp.float32
                            ).reshape(2, 1, 8, 4, 4),
            "v": jnp.ones((2, 1, 8, 4, 4), jnp.float32) * 3,
        }
        out = mesh_stream_blocks(blk, 0, 1, mesh)
        for name in ("k", "v"):
            np.testing.assert_array_equal(
                np.asarray(out[name][1]), np.asarray(blk[name][0]))
            # SPMD: non-destination shards receive zeros
            assert not np.asarray(out[name][0]).any()


class TestStructural:
    """No new collectives anywhere: the cluster is a host-plane
    construct over unchanged compiled programs."""

    COLLECTIVES = ("all-reduce(", "all-gather(", "collective-permute(",
                   "all-to-all(", "reduce-scatter(")

    def test_decode_replica_keeps_the_pre_cluster_collective_set(
        self, lm
    ):
        """2 all-reduces per layer on the decode replica's step —
        exactly the PR 4 pin, re-asserted on a replica built through
        the cluster partition."""
        model, params = lm
        devices = jax.devices("cpu")[:4]
        reps = make_replicas(model, params, 2, tp=2, devices=devices,
                             **ENGINE_KW)
        engine = reps[1].engine
        args = (
            engine._cache, engine._vars,
            jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        assert txt.count("all-reduce(") == 2 * model.num_layers
        for op in self.COLLECTIVES[1:]:
            assert txt.count(op) == 0, f"unexpected {op}"

    @pytest.mark.parametrize("tp", [1, 2])
    def test_kv_handoff_programs_carry_zero_collectives(self, lm, tp):
        """extract/inject — the device half of the handoff — compile
        to pure slicing: the KV handoff is host-plane only."""
        model, params = lm
        mesh = (Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
                if tp == 2 else None)
        engine = ServingEngine(model, params, mesh=mesh, **ENGINE_KW)
        extract, inject = engine._kv_io()
        blk = jnp.int32(1)
        ex_txt = extract.lower(engine._cache, blk).compile().as_text()
        payload = jax.eval_shape(extract, engine._cache, blk)
        payload = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), payload)
        in_txt = inject.lower(
            engine._cache, blk, payload).compile().as_text()
        for txt in (ex_txt, in_txt):
            for op in self.COLLECTIVES:
                assert txt.count(op) == 0, f"unexpected {op} in kv io"


class TestSchedulerSatellites:
    def test_run_max_seconds_bounds_an_open_loop(self, lm):
        model, params = lm
        engine = ServingEngine(model, params, **ENGINE_KW)
        sched = Scheduler(engine)
        reqs = _requests(3, seed=14)
        for p, g in reqs:
            sched.submit(Request(prompt=p, max_new_tokens=g))
        t0 = time.perf_counter()
        sched.run(max_seconds=0.0)
        assert time.perf_counter() - t0 < 5.0
        # nothing lost: unfinished work is still queued/in flight...
        assert sched.pending + sched.in_flight == len(reqs)
        # ...and a later unbounded run drains it, streams exact
        results = sched.run()
        assert len(results) == len(reqs)
        for (p, g), (rid, _) in zip(
            reqs, sorted(results.items(),
                         key=lambda kv: int(kv[0][1:]))
        ):
            assert results[rid]["tokens"] == _ref(model, params, p, g)

    def test_admit_prefilled_finishes_a_satisfied_request(self, lm):
        model, params = lm
        a = ServingEngine(model, params, **ENGINE_KW)
        b = ServingEngine(model, params, **ENGINE_KW)
        prompt = [4, 2, 7]
        slot, tok, _ = a.prefill_join(prompt)
        out = transfer_kv(a, b, slot)
        sched = Scheduler(b)
        sched.start_window()
        req = Request(prompt=prompt, max_new_tokens=1,
                      request_id="one")
        sched.admit_prefilled(req, out[0], out[1])
        assert sched.drained  # finished on admission
        assert sched.results["one"]["tokens"] == prompt + [tok]
        ev = [e for e in sched.event_window
              if e.get("phase") == "prefill"]
        assert ev and ev[0]["ttft_s"] is not None


SLOW_WORKER = Path(__file__).resolve().parent / "cluster_worker.py"


@pytest.mark.slow
@pytest.mark.multiprocess
def test_mp_disaggregated_handoff_over_tcp():
    """The true multi-process handoff: rank 0 prefills and streams the
    KV payload over the native TCP plane (send_obj), rank 1 adopts and
    decodes — the stream must equal rank 1's own sequential generate.
    Real OS processes, real sockets; slow-marked (outside tier-1)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, str(SLOW_WORKER), str(r), "2",
             f"127.0.0.1:{port}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
            cwd=str(SLOW_WORKER.parent.parent),
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"CLUSTER_WORKER_OK {r}" in out


class TestMoEResidency:
    """ISSUE 20: expert-shard residency as a HARD router placement
    filter (the adapter-residency pattern) — a dense engine has no
    expert weights, so MoE traffic on it is impossible, not merely
    slow."""

    @pytest.fixture(scope="class")
    def moe_lm(self):
        model = tiny_lm(n_experts=4)
        params = model.init(
            jax.random.PRNGKey(30), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        return model, params

    def _mixed_fleet(self, moe_lm, lm):
        from chainermn_tpu.serving.cluster import Replica

        moe_model, moe_params = moe_lm
        model, params = lm
        reps = make_replicas(moe_model, moe_params, 2, **ENGINE_KW)
        dense_engine = ServingEngine(model, params, **ENGINE_KW)
        reps.append(Replica(dense_engine, Scheduler(dense_engine), 2))
        return reps

    def test_moe_cluster_streams_match_generate(self, moe_lm):
        moe_model, moe_params = moe_lm
        reps = make_replicas(moe_model, moe_params, 2, **ENGINE_KW)
        router = Router(reps, mode="colocated", policy="least_loaded")
        reqs = _requests(5, seed=31)
        ids = _submit_all(router, reqs)
        results = router.run()
        _assert_streams(results, ids, reqs, moe_model, moe_params)

    def test_dense_replica_never_placed_in_moe_fleet(self, moe_lm, lm):
        reps = self._mixed_fleet(moe_lm, lm)
        router = Router(reps, mode="colocated", policy="least_loaded")
        reqs = _requests(6, seed=33)
        _submit_all(router, reqs)
        router.run()
        routes = router.summary()["routes"]
        assert routes.get(2, 0) == 0, (
            "dense replica drew MoE traffic despite hosting no experts"
        )
        assert sum(routes.values()) == len(reqs)

    def test_no_expert_host_left_raises_loudly(self, moe_lm, lm):
        reps = self._mixed_fleet(moe_lm, lm)
        router = Router(reps, mode="colocated", policy="least_loaded")
        router.fail_replica(0)
        # one expert host left: traffic still places
        rid = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
        results = router.run()
        assert rid in results
        router.fail_replica(1)
        # only the dense spare survives: refuse at the front door, with
        # a message that names the actual problem
        with pytest.raises(RuntimeError, match="expert shards"):
            router.submit(Request(prompt=[1, 2], max_new_tokens=2))

    def test_mismatched_expert_fleets_rejected(self, moe_lm):
        from chainermn_tpu.serving.cluster import Replica

        moe_model, moe_params = moe_lm
        other = tiny_lm(n_experts=2)
        other_params = other.init(
            jax.random.PRNGKey(34), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        a = ServingEngine(moe_model, moe_params, **ENGINE_KW)
        b = ServingEngine(other, other_params, **ENGINE_KW)
        reps = [Replica(a, Scheduler(a), 0), Replica(b, Scheduler(b), 1)]
        with pytest.raises(ValueError, match="expert set"):
            Router(reps, mode="colocated")
