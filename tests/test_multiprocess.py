"""True multi-process tests (SURVEY.md section 4(b)): N real processes under
``jax.distributed`` on local CPU, exercising the ``host.size > 1`` branches
the single-process 8-device suite cannot reach — the TPU-native analog of
the reference's ``mpiexec -n 2 pytest`` harness."""

import pytest

from mp_harness import run_workers

pytestmark = pytest.mark.multiprocess


def test_mp_bcast_data_scatter_objs():
    run_workers("bcast_data", n_procs=2)


def test_mp_hierarchical_train_step():
    run_workers("hierarchical", n_procs=2)


def test_mp_iterator():
    run_workers("iterator", n_procs=2)


def test_mp_checkpoint_agreement(tmp_path):
    run_workers(
        "checkpoint", n_procs=2, extra_env={"MP_CKPT_DIR": str(tmp_path)}
    )


def test_mp_split_2x2():
    """4 processes split 2+2: independent per-group host and device
    collectives without deadlock — VERDICT round-1 item 5."""
    from mp_harness import free_ports

    jax_port, tcp_port = free_ports(2)
    run_workers(
        "split", n_procs=4, local_devices=2, timeout=300,
        coord_port=jax_port,
        extra_env={"MP_TCP_COORD": f"127.0.0.1:{tcp_port}"},
    )


def test_mp_trainer_mnist():
    """The mnist example end-to-end (Trainer + scatter + sync iterator +
    evaluator) under 2 real processes, unchanged — VERDICT round-1 item 10."""
    run_workers("trainer_mnist", n_procs=2, timeout=420)
