"""True multi-process tests (SURVEY.md section 4(b)): N real processes under
``jax.distributed`` on local CPU, exercising the ``host.size > 1`` branches
the single-process 8-device suite cannot reach — the TPU-native analog of
the reference's ``mpiexec -n 2 pytest`` harness."""

import pytest

from mp_harness import run_workers

pytestmark = pytest.mark.multiprocess


def test_mp_bcast_data_scatter_objs():
    run_workers("bcast_data", n_procs=2)


def test_mp_hierarchical_train_step():
    run_workers("hierarchical", n_procs=2)


def test_mp_hierarchical_train_step_4proc():
    """inter_size=4: the hierarchical topology the reference's suite
    exercised with ``mpiexec -n 4`` (SURVEY section 4; round-4 VERDICT
    item 4)."""
    run_workers("hierarchical", n_procs=4, timeout=360)


def test_mp_iterator():
    run_workers("iterator", n_procs=2)


def test_mp_shard_level_ef():
    """Round-5 shard-level EF with the inter/DCN leg crossing REAL
    process boundaries (gloo): 4 processes x 2 local devices on the
    two_dimensional mesh, int8 wire + shard-shaped residual through the
    standard trainer — training progresses and the residual is captured."""
    run_workers("shard_ef", n_procs=4, local_devices=2, timeout=360)


def test_mp_scaling_rehearsal():
    """4 processes x 2 local devices running the hierarchical
    ImageNet-style step (VERDICT r2 item 9): collects per-step wall time
    and host-plane (object-collective) overhead — the measured inputs of
    docs/benchmarks.md's analytic scaling model."""
    outs = run_workers(
        "scaling_imagenet", n_procs=4, local_devices=2, timeout=420.0
    )
    metrics = [ln for o in outs for ln in (o or "").splitlines()
               if ln.startswith("MP_METRIC")]
    assert len(metrics) == 4, metrics
    # Host-plane overhead must be a small fraction of the step: the object
    # plane carries only scalars/metadata, never gradients.
    for ln in metrics:
        kv = dict(p.split("=") for p in ln.split()[1:])
        assert float(kv["hostplane_ms"]) < float(kv["step_ms"]), ln
        assert int(kv["inter"]) == 4 and int(kv["intra"]) == 2


def test_mp_assert_same_on_all_hosts():
    """The pickle-hash (generic-object) branch of
    ``assert_same_on_all_hosts`` with real processes, including the
    deliberate-divergence drill: divergence must RAISE (on every rank
    that differs from the root) rather than hang — ISSUE 2 satellite."""
    outs = run_workers("assert_same", n_procs=2)
    flags = [ln for o in outs for ln in (o or "").splitlines()
             if ln.startswith("MP_ASSERT_RAISED=")]
    assert len(flags) == 2, outs
    # at least the non-root rank saw the divergence as an error
    assert "MP_ASSERT_RAISED=True" in "\n".join(flags), flags


def test_mp_checkpoint_agreement(tmp_path):
    run_workers(
        "checkpoint", n_procs=2, extra_env={"MP_CKPT_DIR": str(tmp_path)}
    )


def test_mp_checkpoint_agreement_4proc(tmp_path):
    """max-common-step agreement + round-robin GC with 4 voters (round-4
    VERDICT item 4: the reference ran its checkpoint tests at -n 4)."""
    run_workers(
        "checkpoint", n_procs=4, timeout=360,
        extra_env={"MP_CKPT_DIR": str(tmp_path)},
    )


def test_mp_orbax_checkpoint_agreement(tmp_path):
    """The orbax backend's resume agreement under real processes."""
    pytest.importorskip("orbax.checkpoint")
    run_workers(
        "orbax_checkpoint", n_procs=2,
        extra_env={"MP_CKPT_DIR": str(tmp_path)},
    )


def test_mp_sharded_checkpoint(tmp_path):
    """Each process persists only its addressable shards; restore
    reassembles the global sharded arrays via the template sharding."""
    run_workers(
        "sharded_checkpoint", n_procs=2, local_devices=2,
        extra_env={"MP_CKPT_DIR": str(tmp_path)},
    )



def _fresh_ports():
    """Per-attempt (coord_port, extra_env) — fresh ports on retry."""
    from mp_harness import free_ports

    jax_port, tcp_port = free_ports(2)
    return jax_port, {"MP_TCP_COORD": f"127.0.0.1:{tcp_port}"}

def test_mp_split_2x2():
    """4 processes split 2+2: independent per-group host and device
    collectives without deadlock — VERDICT round-1 item 5."""
    run_workers(
        "split", n_procs=4, local_devices=2, timeout=300,
        setup_factory=_fresh_ports,
    )


def test_mp_array_p2p():
    """Eager ndarray send/recv (MPI parity) across real processes."""
    run_workers(
        "array_p2p", n_procs=2, local_devices=2,
        setup_factory=_fresh_ports,
    )


def test_mp_probe_any_source():
    """MPI_Iprobe / ANY_SOURCE parity over the native TCP backend: 4
    processes (3 concurrent staggered senders — real wildcard
    contention), rank 0 drains via probe + recv_any_obj (VERDICT r2
    missing item 2; widened to 4 procs per round-4 VERDICT item 4)."""
    run_workers(
        "probe_any_source", n_procs=4, local_devices=2, timeout=360,
        setup_factory=_fresh_ports,
    )


def test_mp_async_double_buffer_overlap():
    """Double buffering with the collective genuinely on the critical
    path (round-5 VERDICT ask #6): 4 real processes, ~1 MB of gradients
    per step over the native framed-TCP wire with DCN-scale RTT (the
    payload is kept small so the wire is wait- not CPU-dominated — the
    only thing a single-core host can overlap). The staleness-1 loop with
    the background-thread reduction (parallel/async_host.py) must beat
    the sequential compute->blocking-allreduce loop — identical compute
    and identical wire bytes in both variants by construction, so any
    win is pure overlap."""
    outs = run_workers(
        "async_double_buffer", n_procs=4, local_devices=1, timeout=420,
        setup_factory=_fresh_ports,
    )
    metrics = [ln for o in outs for ln in (o or "").splitlines()
               if ln.startswith("MP_METRIC dbuf")]
    assert len(metrics) == 4, metrics
    for ln in metrics:
        kv = dict(p.split("=") for p in ln.split()[2:])
        assert float(kv["job_speedup"]) > 1.1, ln


def test_mp_fsdp_ring():
    """Declarative FSDP sharding and the flash ring attention with the
    process boundary inside the mesh — collectives ride gloo, not just
    local device transfers."""
    run_workers("fsdp_ring", n_procs=2, local_devices=2, timeout=300)


def test_mp_preemption(tmp_path):
    """SIGTERM on one rank → all ranks checkpoint the same iteration and
    exit 0 (the slice-preemption story, SURVEY §5)."""
    run_workers(
        "preemption", n_procs=2, local_devices=2,
        extra_env={"MP_CKPT_DIR": str(tmp_path)},
    )
    saved = sorted(p.name for p in tmp_path.iterdir())
    assert len(saved) == 2, saved
    # both ranks agreed on the same (first every=5 multiple >= signal) iter
    assert all("_5.npz" in s for s in saved), saved


def test_mp_crash_tears_down_whole_job():
    """The except-hook's MPI_Abort parity, measured on real processes
    (round-4: the unit test only checked installation): rank 1 raises,
    and EVERY rank must exit — promptly and nonzero — with the crasher
    carrying the rank-tagged banner. The harness is expected to REPORT
    failure here; the assertion inspects its evidence."""
    with pytest.raises(AssertionError) as e:
        run_workers("crash_teardown", n_procs=3, local_devices=2,
                    timeout=120, infra_retries=0,
                    setup_factory=_fresh_ports)
    msg = str(e.value)
    assert "failed on 3/3 ranks" in msg, msg[:600]
    assert "uncaught exception on process 1" in msg, msg[:600]
    assert "deliberate crash for the teardown drill" in msg
    # nobody reached past the barrier, and nobody timed out (prompt
    # teardown through the closed sockets, not a 120 s hang)
    assert "MP_CASE_OK" not in msg
    assert "<<TIMED OUT>>" not in msg


def test_mp_resize_restore(tmp_path):
    """Save sharded state with a 2-process world, restore into a
    4-process world with different shard boundaries (round-4 beyond
    -reference: restart-based world resizing; the reference's MPI world
    was static)."""
    env = {"MP_CKPT_DIR": str(tmp_path)}
    run_workers("resize_restore", n_procs=2, local_devices=2,
                extra_env={**env, "MP_PHASE": "1"})
    run_workers("resize_restore", n_procs=4, local_devices=2, timeout=360,
                extra_env={**env, "MP_PHASE": "2"})


def test_mp_preemption_resume(tmp_path):
    """The full drill (round-4 VERDICT item 9): SIGTERM mid-run ->
    trainer-loop checkpoint at the agreed iteration -> REAL process
    restart -> resume at that iteration with deterministic state."""
    env = {"MP_CKPT_DIR": str(tmp_path)}
    run_workers("preemption_resume", n_procs=2, local_devices=2,
                extra_env={**env, "MP_PHASE": "1"})
    saved = sorted(p.name for p in tmp_path.iterdir())
    assert saved and all("_5." in s for s in saved), saved
    run_workers("preemption_resume", n_procs=2, local_devices=2,
                extra_env={**env, "MP_PHASE": "2"})


def test_mp_trainer_mnist():
    """The mnist example end-to-end (Trainer + scatter + sync iterator +
    evaluator) under 2 real processes, unchanged — VERDICT round-1 item 10."""
    run_workers("trainer_mnist", n_procs=2, timeout=420)


def test_mp_trainer_mnist_4proc():
    """The same end-to-end trainer at 4 processes — the reference's
    ``mpiexec -n 4`` coverage (round-4 VERDICT item 4)."""
    run_workers("trainer_mnist", n_procs=4, timeout=600)
