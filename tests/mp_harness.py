"""True multi-process test harness (SURVEY.md section 4(b)).

The reference simulated multi-node with ``mpiexec -n N pytest`` — N real MPI
processes on one host. The TPU-native analog launches N real Python
processes that ``jax.distributed.initialize`` against a local coordinator on
the CPU backend (gloo cross-process collectives), so the ``host.size > 1``
branches — multihost bcast/scatter, hierarchical process meshes, iterator
broadcast, checkpoint agreement — execute for real instead of being dead
code under the single-process 8-device mesh.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_DIR = os.path.dirname(_TESTS_DIR)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def free_ports(n: int) -> list[int]:
    """``n`` distinct free ports, allocated while ALL the probe sockets are
    held open — sequential ``free_port()`` calls can hand the same
    just-released port out twice."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


# Failure signatures of the jax.distributed COORDINATION PLANE itself
# (gRPC heartbeats / barrier timeouts), not of framework logic. Under heavy
# machine load (e.g. the bench and the suite sharing cores) workers can miss
# heartbeats and get their sockets dropped; one bounded retry of the whole
# case is honest for these — a logic failure (assertion, traceback in our
# code) never matches and never retries.
_INFRA_SIGNATURES = (
    "CoordinationService",
    "grpc_status:14",
    "Socket closed",
    "failed to connect to all addresses",
    "DEADLINE_EXCEEDED",
    "<<TIMED OUT>>",
)


def _infra_flake(failing_rank_logs) -> bool:
    """True only when EVERY failing rank looks like coordination-plane
    infrastructure (signature present, no assertion in framework/test
    logic). One rank crashing on a real bug routinely drags its peers
    down with 'Socket closed' — that must classify as a logic failure,
    so a single non-infra rank vetoes the retry."""
    if not failing_rank_logs:
        return False
    for log in failing_rank_logs:
        log = log or ""
        if not any(sig in log for sig in _INFRA_SIGNATURES):
            return False
        if "AssertionError" in log:  # real test-logic failure on a rank
            return False
    return True


def run_workers(
    case: str,
    n_procs: int = 2,
    *,
    local_devices: int = 2,
    timeout: float = 240.0,
    extra_env: dict | None = None,
    coord_port: int | None = None,
    infra_retries: int = 1,
    setup_factory=None,
):
    """Launch ``n_procs`` worker processes running ``case`` from
    ``tests/mp_worker.py``; raise AssertionError with the combined logs if
    any worker fails. Returns each worker's stdout. Coordination-plane
    infrastructure failures (see ``_INFRA_SIGNATURES``) are retried once —
    framework/logic failures are not.

    ``setup_factory``: zero-arg callable returning ``(coord_port,
    extra_env)``, invoked PER ATTEMPT — tests that pin ports must use
    this (not fixed ``coord_port``/``extra_env``) so a retry after a
    port-collision flake binds fresh ports instead of the same busy one."""
    retries = max(0, infra_retries)
    for attempt in range(1 + retries):
        if setup_factory is not None:
            coord_port, extra_env = setup_factory()
        try:
            return _run_workers_once(
                case, n_procs, local_devices=local_devices, timeout=timeout,
                extra_env=extra_env, coord_port=coord_port,
            )
        except _InfraFlake:
            if attempt >= retries:
                raise
            print(
                f"mp_harness: case {case!r} failed with only "
                "coordination-plane/timeout signatures (attempt "
                f"{attempt + 1}) — could be machine load or a genuine "
                "hang; retrying once",
                file=sys.stderr,
            )
            time.sleep(5.0)


class _InfraFlake(AssertionError):
    pass


def _run_workers_once(
    case: str,
    n_procs: int = 2,
    *,
    local_devices: int = 2,
    timeout: float = 240.0,
    extra_env: dict | None = None,
    coord_port: int | None = None,
):
    sys.path.insert(0, _REPO_DIR)
    from _driver_env import cpu_scrubbed_env

    port = coord_port if coord_port is not None else free_port()
    procs = []
    for rank in range(n_procs):
        env = cpu_scrubbed_env(local_devices)
        # Workers derive native-TCP config from MP_* vars themselves; stale
        # CHAINERMN_TPU_* from the developer's shell would make HostComm's
        # strict bootstrap fail on every rank.
        for k in ("CHAINERMN_TPU_RANK", "CHAINERMN_TPU_SIZE",
                  "CHAINERMN_TPU_COORD"):
            env.pop(k, None)
        env["MP_CASE"] = case
        env["MP_RANK"] = str(rank)
        env["MP_SIZE"] = str(n_procs)
        env["MP_COORD"] = f"127.0.0.1:{port}"
        if extra_env:
            env.update({k: str(v) for k, v in extra_env.items()})
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.join(_TESTS_DIR, "mp_worker.py")],
                env=env,
                cwd=_REPO_DIR,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    deadline = time.monotonic() + timeout
    outs = [None] * n_procs
    try:
        for i, p in enumerate(procs):
            remaining = max(1.0, deadline - time.monotonic())
            try:
                outs[i], _ = p.communicate(timeout=remaining)
            except subprocess.TimeoutExpired:
                p.kill()
                outs[i], _ = p.communicate()
                outs[i] = (outs[i] or "") + "\n<<TIMED OUT>>"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    failures = [
        f"--- rank {i} (rc={p.returncode}) ---\n{outs[i]}"
        for i, p in enumerate(procs)
        if p.returncode != 0 or "MP_CASE_OK" not in (outs[i] or "")
    ]
    if failures:
        msg = (
            f"multiprocess case {case!r} failed on {len(failures)}/{n_procs} "
            "ranks:\n" + "\n".join(f[-3000:] for f in failures)
        )
        if _infra_flake(failures):
            raise _InfraFlake(msg)
        raise AssertionError(msg)
    return outs
