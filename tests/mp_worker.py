"""Worker entry for the multi-process harness (``mp_harness.py``).

Each worker: ``jax.distributed.initialize`` on the CPU backend (gloo
cross-process collectives), then runs the case named by ``MP_CASE`` and
prints ``MP_CASE_OK`` on success. Every case exercises code paths that are
dead under the single-process suite (``host.size > 1`` branches).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    os.environ["MP_COORD"],
    num_processes=int(os.environ["MP_SIZE"]),
    process_id=int(os.environ["MP_RANK"]),
)

if os.environ.get("MP_TCP_COORD"):
    # Cases that need the native TCP host plane (split, p2p) get it wired to
    # the same world as the JAX distributed runtime.
    os.environ["CHAINERMN_TPU_RANK"] = os.environ["MP_RANK"]
    os.environ["CHAINERMN_TPU_SIZE"] = os.environ["MP_SIZE"]
    os.environ["CHAINERMN_TPU_COORD"] = os.environ["MP_TCP_COORD"]

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

RANK = jax.process_index()
SIZE = jax.process_count()


def case_bcast_data():
    """base.py multihost bcast_data/bcast/scatter branches + intra ranks."""
    from chainermn_tpu import create_communicator

    comm = create_communicator("xla")
    assert comm.host.size == SIZE

    # All processes share one hostname here, so the intra group is the
    # whole process set (the reference's multi-process-per-node CI shape).
    assert comm.intra_size == SIZE, comm.intra_size
    assert comm.intra_rank == RANK, (comm.intra_rank, RANK)
    # the topology's own intra_rank must agree (hostname-discovery
    # provider, VERDICT r2 weak item 9 — the property must not lie on
    # multi-process-per-host runtimes)
    assert comm.topology.intra_rank == RANK, comm.topology.intra_rank

    # bcast_data: divergent params must converge to process-0's values.
    params = {"w": jnp.full((4, 3), float(RANK + 1)), "b": jnp.arange(3.0) * (RANK + 1)}
    params = comm.bcast_data(params)
    np.testing.assert_allclose(np.asarray(params["w"]), np.full((4, 3), 1.0))
    np.testing.assert_allclose(np.asarray(params["b"]), np.arange(3.0))

    # bcast (plain value): root process's array everywhere.
    x = comm.bcast(jnp.full((5,), float(RANK)))
    np.testing.assert_allclose(np.asarray(x), np.zeros(5))

    # scatter: all processes must agree on the root's stacked buffer. The
    # result is globally sharded — each process can only read the shards it
    # addresses, so compare per addressable shard.
    expected = np.arange(comm.size * 2, dtype=np.float32).reshape(comm.size, 2)
    stacked = expected * (1.0 if RANK == 0 else -99.0)
    shards = comm.scatter(stacked)
    assert shards.shape == expected.shape
    for s in shards.addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), expected[s.index])

    # object collectives through the multihost_utils plane
    got = comm.allgather_obj({"r": RANK})
    assert [g["r"] for g in got] == list(range(SIZE))
    obj = comm.bcast_obj({"v": RANK * 10} if RANK == 0 else None)
    assert obj == {"v": 0}
    total = comm.allreduce_obj({"n": 1, "loss": float(RANK)})
    assert total["n"] == SIZE


def case_hierarchical():
    """xla_communicator.py n_proc>1 hierarchical mesh + 2-axis grad pmean."""
    import optax
    from chainermn_tpu.communicators.xla_communicator import (
        HierarchicalCommunicator,
    )
    from chainermn_tpu.models import MLP
    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    comm = HierarchicalCommunicator()
    assert comm.mesh.shape["inter"] == SIZE
    assert comm.mesh.shape["intra"] == jax.local_device_count()
    assert comm.inter_size == SIZE and comm.inter_rank == RANK

    model = MLP(n_units=8, n_out=4)
    batch = 2 * comm.size
    # Same data on every process (host-local full batch -> global array).
    xl = np.tile(np.arange(10, dtype=np.float32), (batch, 1)) / 10.0
    yl = np.arange(batch, dtype=np.int32) % 4
    x, y = multihost_utils.host_local_array_to_global_array(
        (jnp.asarray(xl), jnp.asarray(yl)), comm.mesh, P()
    )
    variables = model.init(jax.random.PRNGKey(0), xl[:1])

    def loss_fn(params, batch_):
        xb, yb = batch_
        logits = model.apply({"params": params}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = create_train_state(variables["params"], opt, comm)
    step = make_train_step(loss_fn, opt, comm)
    state, metrics = step(state, (x, y))
    jax.block_until_ready(state.params)
    # metrics are pmean-ed over the whole mesh -> fully replicated -> every
    # process can fetch the global value directly.
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    assert int(state.step) == 1


def case_shard_ef():
    """Round-5 shard-level EF across REAL process boundaries: the
    two_dimensional communicator's (inter=processes, intra=local
    devices) mesh with the int8 wire + shard-shaped residual state
    through the standard trainer — the inter/DCN leg (where the EF
    quantization lives) rides gloo between processes here. Several
    steps, finite loss, residual carried and per-slot distinct."""
    import optax
    from chainermn_tpu.communicators.xla_communicator import (
        TwoDimensionalCommunicator,
    )
    from chainermn_tpu.models import MLP
    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    comm = TwoDimensionalCommunicator()
    assert comm.mesh.shape["inter"] == SIZE
    intra_ax, inter_ax = comm.two_level_axes
    assert (intra_ax, inter_ax) == ("intra", "inter")

    model = MLP(n_units=8, n_out=4)
    batch = 2 * comm.size
    rng = np.random.default_rng(3)
    xl = rng.standard_normal((batch, 10)).astype(np.float32)
    yl = (np.arange(batch) % 4).astype(np.int32)
    x, y = multihost_utils.host_local_array_to_global_array(
        (jnp.asarray(xl), jnp.asarray(yl)), comm.mesh, P()
    )
    variables = model.init(jax.random.PRNGKey(0), xl[:1])

    def loss_fn(params, batch_):
        xb, yb = batch_
        logits = model.apply({"params": params}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    opt = create_multi_node_optimizer(
        optax.sgd(0.1), comm,
        allreduce_grad_dtype=jnp.int8, error_feedback=True,
    )
    state = create_train_state(variables["params"], opt, comm)
    res0 = jax.tree.leaves(state.opt_state.residual)[0]
    assert res0.shape[0] == comm.size  # stacked per mesh slot
    step = make_train_step(loss_fn, opt, comm, donate=False)
    first = None
    for _ in range(6):
        state, metrics = step(state, (x, y))
        loss = float(jax.device_get(metrics["loss"]))
        first = loss if first is None else first
    assert np.isfinite(loss)
    assert loss < first, (loss, first)  # it actually trains
    # residual evolved away from the zero init (quantization happened
    # on the inter leg and was captured), and the slots this process
    # addresses hold DISTINCT per-slot values — a replication regression
    # (every slot carrying slot 0's residual) fails here.
    shards = [
        np.asarray(s.data).reshape(-1)
        for s in jax.tree.leaves(
            state.opt_state.residual)[0].addressable_shards
    ]
    assert max(np.abs(v).max() for v in shards) > 0.0
    assert len(shards) >= 2 and not all(
        np.array_equal(v, shards[0]) for v in shards[1:]
    ), [v[:4] for v in shards]


def case_iterator():
    """Multihost master-broadcast iterator: identical batches everywhere."""
    from chainermn_tpu import create_communicator
    from chainermn_tpu.iterators import create_multi_node_iterator

    comm = create_communicator("xla")
    dataset = [(np.full((2,), i, np.float32), i % 3) for i in range(12)]
    it = create_multi_node_iterator(dataset, 4, comm, seed=7)
    batches = [next(it) for _ in range(3)]
    digest = [[int(b[0][0]) for b in batch] for batch in batches]
    everyone = comm.allgather_obj(digest)
    assert all(d == everyone[0] for d in everyone), everyone


def case_checkpoint():
    """Checkpoint max-common-iteration agreement across real processes."""
    import shutil

    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )

    comm = create_communicator("xla")
    path = os.environ["MP_CKPT_DIR"]
    ckpt = create_multi_node_checkpointer("mp", comm, path=path, keep=0)

    state = {"w": jnp.full((3,), float(RANK)), "step": jnp.int32(0)}
    # Rank 0 has iterations {1, 2}; other ranks only {1}: the max COMMON
    # iteration must be 1 on every process.
    ckpt.save({**state, "step": jnp.int32(1)}, 1)
    if RANK == 0:
        ckpt.save({**state, "step": jnp.int32(2)}, 2)
    comm.barrier()

    restored, it = ckpt.maybe_load(state)
    assert it == 1, it
    assert int(restored["step"]) == 1
    np.testing.assert_allclose(np.asarray(restored["w"]), np.full((3,), float(RANK)))


def case_orbax_checkpoint():
    """Orbax backend across real processes: collective saves into the
    shared directory (orbax's native multihost model — replicated
    state), resume through the shared agreement helper. Per-rank-
    DIVERGENT state is the npz backend's contract, covered by
    case_checkpoint."""
    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions import create_orbax_checkpointer

    comm = create_communicator("xla")
    path = os.environ["MP_CKPT_DIR"]
    ckpt = create_orbax_checkpointer("mp", comm, path=path, keep=5)

    state = {"w": jnp.arange(3.0), "step": jnp.int32(0)}
    ckpt.save({**state, "step": jnp.int32(1)}, 1)  # collective
    ckpt.save({**state, "step": jnp.int32(2)}, 2)  # collective
    comm.barrier()

    restored, it = ckpt.maybe_load(state)
    assert it == 2, it
    assert int(restored["step"]) == 2
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(3.0))

    # The replicated-state contract is enforced: a divergent save raises
    # on EVERY rank (the digest allgather is symmetric) instead of
    # silently writing the primary's values.
    try:
        ckpt.save({"w": jnp.full((3,), float(RANK))}, 3)
    except ValueError as e:
        assert "contract violated" in str(e)
    else:
        raise AssertionError("divergent save did not raise")
    ckpt.close()


def case_split():
    """Full-stack multihost split(): independent host-plane and device-plane
    collectives per color group (the branch that raised NotImplementedError
    until round 2). Needs the native TCP backend (set by the harness via
    MP_TCP_COORD before chainermn_tpu import at module bottom)."""
    from chainermn_tpu import create_communicator

    comm = create_communicator("xla")
    assert comm.host.tcp is not None, "case requires the TCP host backend"

    half = SIZE // 2
    color = 0 if RANK < half else 1
    sub = comm.split(color)
    lo, hi = (0, half) if color == 0 else (half, SIZE)
    assert sub.host.size == hi - lo
    assert sub.host.world_members == list(range(lo, hi))

    # Independent host-plane collectives, interleaved across groups in
    # opposite orders (group 1 reduces before it broadcasts) — per-pair
    # channels keep them isolated; a global collective would deadlock here.
    if color == 0:
        got = sub.bcast_obj({"grp": color, "from": RANK} if sub.rank == 0 else None)
        total = sub.allreduce_obj({"n": 1})
    else:
        total = sub.allreduce_obj({"n": 1})
        got = sub.bcast_obj({"grp": color, "from": RANK} if sub.rank == 0 else None)
    assert got == {"grp": color, "from": lo}, got
    assert total == {"n": hi - lo}, total

    # Device plane: each group's mesh covers only its processes' devices.
    n_local = jax.local_device_count()
    assert sub.size == (hi - lo) * n_local, (sub.size, n_local)
    stacked = np.full((sub.size, 3), float(color + 1), np.float32)
    red = sub.allreduce(jnp.asarray(stacked), op="sum")
    np.testing.assert_allclose(
        np.asarray(red), np.full((3,), float((color + 1) * sub.size))
    )

    # bcast_data rides the subgroup host plane (not global multihost_utils).
    params = {"w": jnp.full((2, 2), float(RANK + 10))}
    params = sub.bcast_data(params)
    np.testing.assert_allclose(
        np.asarray(params["w"]), np.full((2, 2), float(lo + 10))
    )
    comm.barrier()


def case_array_p2p():
    """Eager ndarray send/recv over the TCP host plane (reference:
    MpiCommunicatorBase.send/recv with the _MessageType header)."""
    from chainermn_tpu import create_communicator

    comm = create_communicator("xla")
    # Ranks are MESH SLOTS; with several local devices per process the next
    # process's first slot is local_device_count() away.
    ndev = jax.local_device_count()
    nxt = ((RANK + 1) % SIZE) * ndev       # slot on the next process
    prv_proc = (RANK - 1) % SIZE

    base = np.arange(12, dtype=np.float32).reshape(3, 4)
    comm.send(base * (RANK + 1), nxt)
    got = comm.recv(prv_proc * ndev)
    np.testing.assert_allclose(np.asarray(got), base * (prv_proc + 1))

    # tuple message with mixed dtypes + a tag
    comm.send((np.int32([RANK, 7]), np.float64([[1.5 * RANK]])), nxt, tag=3)
    a, b = comm.recv(prv_proc * ndev, tag=3)
    assert a.dtype == jnp.int32.dtype and int(a[0]) == prv_proc
    np.testing.assert_allclose(np.asarray(b), [[1.5 * prv_proc]])

    # self send/recv (slot owned by this process) buffers locally
    comm.send(base, RANK * ndev + 1, tag=9)
    np.testing.assert_allclose(np.asarray(comm.recv(RANK * ndev + 1, tag=9)), base)
    comm.barrier()


def case_sharded_checkpoint():
    """Sharded-params checkpointing: each process saves only its addressable
    shards (keyed by global index); restore reassembles through the
    template's sharding. The npz whole-state path cannot represent
    non-fully-addressable arrays at all — this is the scale story."""
    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    comm = create_communicator("xla")
    sh = NamedSharding(comm.mesh, P("data"))
    rows = comm.size * 3
    global_np = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    arr = jax.make_array_from_callback(
        global_np.shape, sh, lambda idx: global_np[idx]
    )
    assert not arr.is_fully_addressable  # the case the npz path couldn't do

    path = os.environ["MP_CKPT_DIR"]
    ckpt = create_multi_node_checkpointer("shard", comm, path=path, keep=0)
    ckpt.save({"w": arr, "step": jnp.int32(5)}, 1)
    comm.barrier()

    template = {
        "w": jax.make_array_from_callback(
            global_np.shape, sh, lambda idx: np.zeros_like(global_np[idx])
        ),
        "step": jnp.int32(0),
    }
    restored, it = ckpt.maybe_load(template)
    assert it == 1 and int(restored["step"]) == 5
    assert restored["w"].sharding == sh
    for s in restored["w"].addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), global_np[s.index])


def case_crash_teardown():
    """One rank's uncaught Python exception must tear the WHOLE job down
    (the reference's MPI_Abort story, ``global_except_hook.py`` (dagger),
    SURVEY L8): rank 1 raises outside any collective; the peers sit in a
    host-plane barrier whose sockets die with the crashed process, their
    own hook fires, and every rank exits nonzero with the rank-tagged
    banner — promptly, not by coordination-timeout."""
    from chainermn_tpu import create_communicator, global_except_hook

    comm = create_communicator("xla")
    # The prompt-teardown claim rests on the native TCP plane (socket
    # EOF when a peer dies); fail fast if the launcher didn't wire it.
    assert comm.host.tcp is not None, "case needs MP_TCP_COORD"
    global_except_hook._add_hook()
    print("MP_CRASH_READY", flush=True)
    if RANK == 1:
        import time

        time.sleep(0.5)  # let peers reach the barrier first
        raise RuntimeError("deliberate crash for the teardown drill")
    comm.barrier()  # dies when rank 1's sockets close
    print("MP_CASE_OK", flush=True)  # must NOT be reached


def case_resize_restore():
    """World-resize restore (beyond the reference's static MPI world):
    phase 1 saves a SHARDED state from a small world; phase 2 restores
    it into a LARGER world whose template sharding has different shard
    boundaries — `maybe_load(allow_world_resize=True)` reassembles the
    global arrays from all old ranks' files and re-slices."""
    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    comm = create_communicator("xla")
    phase = int(os.environ.get("MP_PHASE", "1"))
    sh = NamedSharding(comm.mesh, P("data"))
    ROWS = 24  # divisible by both worlds' slot counts (4 and 8)
    global_np = np.arange(ROWS * 4, dtype=np.float32).reshape(ROWS, 4)
    path = os.environ["MP_CKPT_DIR"]
    ckpt = create_multi_node_checkpointer("resize", comm, path=path, keep=0)

    if phase == 1:
        arr = jax.make_array_from_callback(
            global_np.shape, sh, lambda idx: global_np[idx]
        )
        assert not arr.is_fully_addressable
        ckpt.save({"w": arr, "step": jnp.int32(7)}, 3)
        comm.barrier()
        return

    # Phase 2: larger world, different shard boundaries.
    template = {
        "w": jax.make_array_from_callback(
            global_np.shape, sh, lambda idx: np.zeros_like(global_np[idx])
        ),
        "step": jnp.int32(0),
    }
    # Without the flag, the new ranks have no files -> no common step.
    _, it_strict = ckpt.maybe_load(template)
    assert it_strict is None, it_strict
    restored, it = ckpt.maybe_load(template, allow_world_resize=True)
    assert it == 3 and int(restored["step"]) == 7
    assert restored["w"].sharding == sh
    for s in restored["w"].addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), global_np[s.index])


def case_fsdp_ring():
    """FSDP auto-sharding and flash-ring attention across REAL processes:
    the declarative param sharding and the ppermute ring both cross the
    process boundary (gloo), not just local devices."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu import create_communicator
    from chainermn_tpu.models import MLP
    from chainermn_tpu.parallel.fsdp import (
        create_fsdp_train_state,
        make_fsdp_train_step,
    )

    comm = create_communicator("xla")
    model = MLP(n_units=32, n_out=4)
    n = comm.size
    # Each process supplies its LOCAL slice of the global batch; the
    # globalized array is sharded over 'data' — what the FSDP step's
    # batch in_shardings expect.
    local_rows = 2 * jax.local_device_count()
    xl = (np.tile(np.arange(10, dtype=np.float32), (local_rows, 1)) / 10.0
          * (RANK + 1))
    yl = (np.arange(local_rows) % 4).astype(np.int32)
    from jax.experimental import multihost_utils

    x, y = multihost_utils.host_local_array_to_global_array(
        (jnp.asarray(xl), jnp.asarray(yl)), comm.mesh, P("data")
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 10)))["params"]

    def loss_fn(p, batch):
        xb, yb = batch
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()

    opt = optax.adamw(1e-2)
    state, shardings = create_fsdp_train_state(params, opt, comm, min_size=4)
    # params really live sharded across the processes
    hidden = state.params["Dense_1"]["kernel"]
    assert not hidden.is_fully_addressable
    step = make_fsdp_train_step(loss_fn, opt, comm, shardings, donate=False)
    state, metrics = step(state, (x, y))
    jax.block_until_ready(state.params)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # Ring attention: KV blocks rotate across the process boundary.
    from jax import shard_map
    from chainermn_tpu.ops.attention import dot_product_attention
    from chainermn_tpu.parallel.ring_attention import ring_attention_local

    B, T, H, D = 1, 4 * n, 2, 8
    qkv = np.random.RandomState(0).randn(3, B, T, H, D).astype(np.float32)
    spec = P(None, "data", None, None)
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention_local(
            q, k, v, "data", causal=True, impl="flash", interpret=True
        ),
        mesh=comm.mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False,
    ))
    q, k, v = (
        jax.device_put(jnp.asarray(a), NamedSharding(comm.mesh, spec))
        for a in qkv
    )
    out = ring(q, k, v)
    ref = dot_product_attention(*(jnp.asarray(a) for a in qkv), causal=True)
    for s in out.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(s.data), np.asarray(ref)[s.index],
            rtol=1e-4, atol=1e-4,
        )

    # Sliding-window SP: the single neighbour-tail ppermute crosses the
    # process boundary; equals the dense windowed reference.
    from chainermn_tpu.parallel.local_attention import (
        sliding_window_attention_local,
    )

    W = 3  # W - 1 = 2 <= T_local = 4
    band = np.where(
        (np.arange(T)[:, None] - np.arange(T)[None, :]) < W, 0.0, -1e30
    )[None, None].astype(np.float32)
    sw = jax.jit(shard_map(
        lambda q, k, v: sliding_window_attention_local(
            q, k, v, "data", window=W, block_q=4, block_k=4,
            interpret=True,
        ),
        mesh=comm.mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False,
    ))
    out_sw = sw(q, k, v)
    ref_sw = dot_product_attention(
        *(jnp.asarray(a) for a in qkv), causal=True,
        bias=jnp.asarray(band),
    )
    for s in out_sw.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(s.data), np.asarray(ref_sw)[s.index],
            rtol=1e-4, atol=1e-4,
        )


def case_preemption():
    """Preemption guard: only rank 0 is signalled; the host-plane agreement
    makes every rank checkpoint the same iteration and exit 0."""
    import signal

    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )
    from chainermn_tpu.utils.preemption import install_preemption_guard

    comm = create_communicator("xla")
    ckpt = create_multi_node_checkpointer(
        "pre", comm, path=os.environ["MP_CKPT_DIR"], keep=0
    )
    guard = install_preemption_guard()

    state = {"w": jnp.zeros((3,))}
    for it in range(1, 200):
        state = {"w": state["w"] + 1.0}
        if it == 3 and RANK == 0:
            os.kill(os.getpid(), signal.SIGTERM)  # rank 0 only
        if guard.should_checkpoint(comm, every=5, iteration=it):
            ckpt.save(state, it)
            print("MP_CASE_OK", flush=True)  # exit_if_preempted never returns
            guard.exit_if_preempted(comm)
    raise AssertionError("preemption never triggered a checkpoint")


def case_preemption_resume():
    """End-to-end preemption drill THROUGH the trainer loop (round-4
    VERDICT item 9): phase 1 — SIGTERM mid-run, guard agreement, all
    ranks checkpoint the same iteration and exit 0; phase 2 — fresh
    processes ``maybe_load`` the agreed snapshot and the trainer resumes
    from exactly that iteration, finishing with deterministic state."""
    import signal

    from chainermn_tpu import create_communicator
    from chainermn_tpu.extensions.checkpoint import (
        create_multi_node_checkpointer,
    )
    from chainermn_tpu.training.trainer import Trainer
    from chainermn_tpu.utils.preemption import install_preemption_guard
    from jax.sharding import PartitionSpec as P

    comm = create_communicator("xla")
    phase = int(os.environ.get("MP_PHASE", "1"))
    ckpt = create_multi_node_checkpointer(
        "pre", comm, path=os.environ["MP_CKPT_DIR"], keep=2
    )

    # w += mean(batch) (= 1.0) per iteration -> w == iteration exactly.
    def step_fn(state, batch):
        w = state["w"] + jnp.mean(jnp.asarray(batch))
        return (
            {"w": w, "step": state["step"] + 1},
            {"loss": jnp.sum(w)},
        )

    template = {"w": jnp.zeros((3,)), "step": jnp.zeros((), jnp.int32)}
    # Every process yields the identical batch (spec P() below).
    data = [[np.ones((2,), np.float32)] * 2 for _ in range(64)]

    if phase == 1:
        guard = install_preemption_guard()
        trainer = Trainer(step_fn, comm.bcast_data(template), data, comm,
                          batch_spec=P(), log_interval=1000)

        def sigterm_rank0(tr):
            if tr.iteration == 3 and RANK == 0:
                os.kill(os.getpid(), signal.SIGTERM)

        def ckpt_on_preempt(tr):
            if guard.should_checkpoint(comm, every=5,
                                       iteration=tr.iteration):
                ckpt.save(tr.state, tr.iteration)
                print("MP_CASE_OK", flush=True)  # exit_ never returns
                guard.exit_if_preempted(comm)

        trainer.extend(sigterm_rank0, interval=1)
        trainer.extend(ckpt_on_preempt, interval=1)
        trainer.run(50)
        raise AssertionError("preemption never triggered a checkpoint")

    state, it = ckpt.maybe_load(template)
    assert it == 5, it  # first every=5 multiple after the signal at 3
    assert int(np.asarray(state["step"])) == 5
    np.testing.assert_allclose(np.asarray(state["w"]), np.full(3, 5.0))
    trainer = Trainer(step_fn, comm.bcast_data(state), data, comm,
                      batch_spec=P(), log_interval=1000)
    trainer.iteration = it
    trainer.run(8)  # resume 5 -> 8: exactly 3 more steps
    assert trainer.iteration == 8
    np.testing.assert_allclose(
        np.asarray(jax.device_get(trainer.state["w"])), np.full(3, 8.0)
    )
    assert int(np.asarray(jax.device_get(trainer.state["step"]))) == 8


def case_trainer_mnist():
    """The mnist example's Trainer path end-to-end under real processes."""
    sys.argv = [
        "train_mnist.py",
        "--communicator", "xla",
        "--iterations", "8",
        "--batchsize", str(4 * SIZE * jax.local_device_count()),
    ]
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "train_mnist",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples", "mnist", "train_mnist.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    result = mod.main()
    assert result is None or np.isfinite(
        float(result.get("val_loss", 0.0))
    )


def case_probe_any_source():
    """MPI_Iprobe / ANY_SOURCE parity over the native TCP host plane
    (VERDICT r2 missing item 2): every non-zero rank sends to rank 0 with
    staggered delays; rank 0 probes (non-blocking, observing both the
    empty and pending states) then drains with recv_any_obj, recovering
    every sender exactly once."""
    import time

    from chainermn_tpu import ANY_SOURCE, create_communicator

    comm = create_communicator("xla")
    ndev = jax.local_device_count()

    if RANK == 0:
        # probe must report False before anything is sent... but a fast
        # sender could already have landed; only assert the True side
        # after a positive probe, and the drain below is the real check.
        t0 = time.time()
        seen = {}
        while len(seen) < SIZE - 1 and time.time() - t0 < 60:
            if comm.probe(ANY_SOURCE, tag=5):
                src, obj = comm.recv_any_obj(tag=5)
                assert src not in seen
                seen[src] = obj
            else:
                time.sleep(0.005)
        assert len(seen) == SIZE - 1, seen
        # sources are the senders' first mesh slots
        assert sorted(seen) == [r * ndev for r in range(1, SIZE)], seen
        for src, obj in seen.items():
            assert obj == {"from": src // ndev}, (src, obj)
        # recv(ANY_SOURCE) for ndarrays — rank 1 sent its tag-6 array
        # IMMEDIATELY after its tag-5 message (out of wanted order): the
        # tag-5 drain above must have BUFFERED it (MPI matching
        # semantics), or it arrives now; either way nothing was lost.
        arr = comm.recv(ANY_SOURCE, tag=6)
        np.testing.assert_allclose(np.asarray(arr), np.arange(3.0))
        # Both senders are now provably quiescent (blocked on the tag-9
        # gate below; their sockets drained) -> targeted probes are
        # deterministic and exact.
        for r in range(1, SIZE):
            assert not comm.probe(r * ndev, tag=5)
        # Release everyone into the barrier only after ALL p2p is done:
        # collectives share the p2p sockets, so a rank entering the
        # barrier early would put tokens where probe/ANY_SOURCE look
        # (documented wildcard-vs-collective constraint).
        for r in range(1, SIZE):
            comm.send_obj("done", r * ndev, tag=9)
    else:
        time.sleep(0.02 * RANK)  # stagger: exercise the polling loop
        comm.send_obj({"from": RANK}, 0, tag=5)
        if RANK == 1:
            # Out-of-order tag: exercises the receive-side tag buffering.
            comm.send(np.arange(3.0), 0, tag=6)
        assert comm.recv_obj(0, tag=9) == "done"
    comm.barrier()


def case_scaling_imagenet():
    """Scaling-efficiency rehearsal (VERDICT r2 item 9): the hierarchical
    ImageNet-style step over a real (inter=processes, intra=local-devices)
    mesh, reporting per-step wall time, HOST-PLANE overhead per step (the
    object-collective cost the analytic model in docs/benchmarks.md needs),
    and the gradient byte volume. Prints one MP_METRIC line per rank."""
    import time

    import optax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.communicators.xla_communicator import (
        HierarchicalCommunicator,
    )
    from chainermn_tpu.models import ResNet18
    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    comm = HierarchicalCommunicator()
    assert comm.inter_size == SIZE

    model = ResNet18(num_classes=10, compute_dtype=jnp.float32)
    hw, per_dev = 32, 2
    batch = per_dev * comm.size
    rng = np.random.default_rng(0)
    xl = rng.standard_normal((batch, hw, hw, 3)).astype(np.float32)
    yl = (rng.integers(0, 10, size=batch)).astype(np.int32)
    x, y = multihost_utils.host_local_array_to_global_array(
        (jnp.asarray(xl), jnp.asarray(yl)), comm.mesh, P()
    )
    variables = model.init(jax.random.PRNGKey(0), xl[:1], train=True)

    def loss_fn(params, batch_, model_state):
        xb, yb = batch_
        logits, mutated = model.apply(
            {"params": params, "batch_stats": model_state}, xb,
            train=True, mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        return loss, ({}, mutated["batch_stats"])

    opt = create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm,
        allreduce_grad_dtype=jnp.bfloat16,
    )
    state = create_train_state(
        variables["params"], opt, comm,
        model_state=variables["batch_stats"],
    )
    step = make_train_step(loss_fn, opt, comm)

    for _ in range(2):  # compile + warm
        state, metrics = step(state, (x, y))
    float(jax.device_get(metrics["loss"]))

    steps = 5
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, (x, y))
    float(jax.device_get(metrics["loss"]))
    step_ms = (time.perf_counter() - t0) / steps * 1e3

    # Host-plane overhead: one object allreduce per step is the logging /
    # evaluator pattern (SURVEY.md section 5 metrics aggregation). Warm
    # once untimed — the first call compiles the process_allgather
    # programs, which would otherwise dominate the 5-round average.
    comm.allreduce_obj({"warm": 1})
    rounds = 5
    t0 = time.perf_counter()
    for _ in range(rounds):
        total = comm.allreduce_obj({"loss": float(RANK), "n": 1})
    hostplane_ms = (time.perf_counter() - t0) / rounds * 1e3
    assert total["n"] == SIZE

    grad_bytes = sum(
        l.size for l in jax.tree.leaves(variables["params"])
    ) * 2  # bf16-compressed allreduce
    print(
        f"MP_METRIC step_ms={step_ms:.1f} hostplane_ms={hostplane_ms:.2f} "
        f"grad_bytes={grad_bytes} inter={SIZE} "
        f"intra={jax.local_device_count()}",
        flush=True,
    )
    assert np.isfinite(step_ms) and hostplane_ms < 10_000


def case_async_double_buffer():
    """Double buffering MEASURED paying (round-5 VERDICT ask #6): the
    staleness-1 loop with the host-plane allreduce on a background
    thread (``parallel/async_host.py``) vs the sequential
    compute-then-blocking-allreduce loop, over real processes and the
    native framed-TCP wire. Both variants run IDENTICAL jitted compute
    and IDENTICAL wire bytes (same reducer path, same payload, same
    count) — the honesty check is by construction; only the schedule
    differs. Prints one MP_METRIC line; asserts the overlap pays."""
    import time

    from chainermn_tpu import create_communicator
    from chainermn_tpu.parallel.async_host import AsyncHostGradReducer

    comm = create_communicator("xla")
    assert comm.host.tcp is not None, "case needs the native TCP plane"

    # The win is bounded by (C + A) / max(C, A): a badly unbalanced
    # compute-vs-wire ratio measures nothing. Wire time is whatever the
    # host plane + this machine deliver (measured below), so the drill
    # SELF-BALANCES: scale the compute batch until C ~ A, the regime the
    # staleness-1 trade targets (docs/benchmarks.md "when to enable it").
    # ~1 MB payload: the loopback wire's own CPU cost (pickle + linear
    # gather) stays ~tens of ms, so the reduction is dominated by the
    # RTT floor below — i.e. by genuine in-flight wait, the only thing
    # a single core can overlap.
    D, H = 1024, 128
    rng = np.random.default_rng(0)  # identical params on every rank
    params = {
        "w1": jnp.asarray(rng.standard_normal((D, H)) * 0.05, jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((H, D)) * 0.05, jnp.float32),
    }

    @jax.jit
    def grad_step(params, x):
        def loss(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.sum(jnp.tanh(h @ p["w2"]) ** 2)

        return jax.grad(loss)(params)

    @jax.jit
    def apply_(params, g):
        return jax.tree.map(lambda p, gg: p - 1e-4 * gg, params, g)

    # This box has ONE core: CPU-bound wire work (pickle/sum) can never
    # overlap CPU-bound compute — only a genuine in-flight WAIT can.
    # The 0.4 s simulated DCN RTT supplies that wait (the VERDICT's
    # sanctioned 'inflated-latency collective'), modelling the
    # cross-host regime the staleness-1 trade exists for; both variants
    # pay it identically.
    reducer = AsyncHostGradReducer(comm, simulated_dcn_latency_s=0.4)
    steps = 8

    def make_x(batch):
        return jnp.asarray(
            np.random.default_rng(RANK + 1).standard_normal((batch, D)),
            jnp.float32,
        )

    # CORRECTNESS first (the suite's core invariant — distributed ==
    # single-process values, here vs the host-gathered numpy mean), then
    # staleness-1 sequencing: exchanges return None, m0, m1, ... and
    # flush returns the last mean — each step's reduction exactly once.
    x = make_x(16)
    g = jax.tree.map(lambda a: np.asarray(a), grad_step(params, x))
    expected = jax.tree.map(
        lambda *leaves: np.mean(leaves, axis=0),
        *comm.host.allgather_obj(g),
    )
    red = reducer.reduce_sync(g)
    for got, want in zip(jax.tree.leaves(red), jax.tree.leaves(expected)):
        # fold-left f32 sum vs numpy's stacked mean: order noise only
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    seq = [jax.tree.map(lambda a, s=s: a * (s + 1.0), g) for s in range(3)]
    means = [reducer.exchange(m) for m in seq] + [reducer.flush()]
    assert means[0] is None
    for s, m in enumerate(means[1:]):
        np.testing.assert_allclose(
            jax.tree.leaves(m)[0], jax.tree.leaves(expected)[0] * (s + 1.0),
            rtol=1e-5, atol=1e-5,
        )

    # Measure the wire (sockets already warm from the checks above).
    t0 = time.perf_counter()
    for _ in range(3):
        reducer.reduce_sync(g)
    a_ms = (time.perf_counter() - t0) / 3 * 1e3
    # One wire-time for everyone: the stop rule below must be COLLECTIVE
    # — the TCP plane's untagged per-pair FIFOs deadlock if ranks make
    # divergent break decisions and issue different collective sequences.
    a_ms = comm.host.allreduce_obj(a_ms, op=max)

    # Scale the batch until compute ~ wire; every rank measures under
    # full contention (all ranks time the same candidate together) and
    # the break tests the collective MAX, so all ranks stop together.
    for cand in (64, 128, 256, 512, 1024, 2048):
        x = make_x(cand)
        jax.block_until_ready(grad_step(params, x))  # compile
        comm.host.barrier()
        t0 = time.perf_counter()
        for _ in range(2):
            jax.block_until_ready(grad_step(params, x))
        c_ms = comm.host.allreduce_obj(
            (time.perf_counter() - t0) / 2 * 1e3, op=max)
        if c_ms >= 0.7 * a_ms:
            break
    B = cand
    x = make_x(B)

    def sync_loop(params):
        for _ in range(steps):
            g = grad_step(params, x)
            red = reducer.reduce_sync(g)
            params = apply_(params, red)
        jax.block_until_ready(params)
        return params

    def async_loop(params):
        for _ in range(steps):
            g = grad_step(params, x)
            stale = reducer.exchange(g)
            if stale is not None:
                params = apply_(params, stale)
        params = apply_(params, reducer.flush())
        jax.block_until_ready(params)
        return params

    # Warm both paths: jit compiles + first TCP round (socket setup).
    sync_loop(params)
    async_loop(params)

    comm.host.barrier()
    t0 = time.perf_counter()
    sync_loop(params)
    comm.host.barrier()
    sync_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    async_loop(params)
    comm.host.barrier()
    async_s = time.perf_counter() - t0

    speedup = sync_s / async_s
    # The ranks are coupled by the collective, but take the
    # whole-job view anyway: max total over ranks for each variant.
    totals = comm.host.allreduce_obj(
        {"sync": sync_s, "async": async_s},
        op=lambda a, b: {k: max(a[k], b[k]) for k in a},
    )
    job_speedup = totals["sync"] / totals["async"]
    print(
        f"MP_METRIC dbuf sync_ms={sync_s * 1e3:.0f} "
        f"async_ms={async_s * 1e3:.0f} speedup={speedup:.2f} "
        f"job_speedup={job_speedup:.2f} steps={steps} batch={B} "
        f"compute_ms={c_ms:.0f} wire_ms={a_ms:.0f} "
        f"payload_mb={sum(v.size for v in params.values()) * 4 / 1e6:.0f}",
        flush=True,
    )
    # Generous bound for a contended CI box; the typical reading is
    # well above it when compute and wire are comparable (theoretical
    # ceiling 2.0). A reading below 1.0 would mean the overlap path
    # COSTS time — the one outcome this drill exists to rule out.
    assert job_speedup > 1.1, (sync_s, async_s, totals)


def case_assert_same():
    """``assert_same_on_all_hosts``'s generic-object (pickle-hash) path
    under REAL processes (ISSUE 2 satellite): agreement passes for the
    scalar AND object branches, and a deliberately divergent object
    RAISES promptly instead of hanging at the next collective."""
    from chainermn_tpu.utils.observability import assert_same_on_all_hosts

    # scalar branch + generic-object (pickle-hash) branch, agreeing
    assert_same_on_all_hosts(5, "resume-step")
    assert_same_on_all_hosts(
        {"batch_spec": (8, 224, 224, 3), "tag": "fingerprint"},
        "program-shape",
    )

    # Deliberate divergence: each rank hashes a DIFFERENT object. The
    # comparison is against the broadcast root value, so every rank
    # whose value differs from rank 0's must raise; rank 0 itself
    # compares equal by construction and may pass. Either way nothing
    # may hang — the broadcast completes on all ranks before comparing.
    raised = False
    try:
        assert_same_on_all_hosts({"resume_step": RANK}, "divergence-drill")
    except AssertionError:
        raised = True
    print(f"MP_ASSERT_RAISED={raised}", flush=True)
    if RANK != 0:
        assert raised, (
            "divergent object did not raise on a non-root rank — the "
            "silent-hang failure mode assert_same_on_all_hosts exists "
            "to prevent"
        )

    # The world must still be usable after the caught divergence (the
    # collectives stayed balanced): one more agreeing check.
    assert_same_on_all_hosts({"ok": True}, "post-divergence")


CASES = {
    name[len("case_"):]: fn
    for name, fn in list(globals().items())
    if name.startswith("case_")
}


if __name__ == "__main__":
    CASES[os.environ["MP_CASE"]]()
    print("MP_CASE_OK", flush=True)
