"""Fused paged-decode Pallas kernel (ISSUE 19).

Two layers of pins, per the repo's conventions:

- **Kernel contract** — :func:`~chainermn_tpu.ops.paged_decode.
  paged_flash_decode` (interpret mode on the CPU mesh) against the XLA
  paged path's own math: allclose at fp32-accumulation tolerance across
  T=1 / verify-span / GQA / MQA / window / stacked-TP variants, and the
  scratch/horizon edge cases BOTH impls must agree on — a released
  slot's scratch-block garbage and a beyond-horizon span must never
  leak into a live row (block 0 is poisoned with 1e9 so a leak is loud,
  not a rounding error).
- **Engine equivalence** — ``decode_attend_impl='fused'`` token streams
  IDENTICAL to sequential ``generate`` across dense == paged == TP ==
  single-device x speculative x chunked x sampled, with the jit caches
  still pinned at 1 and the TP decode HLO still exactly 2
  all-reduces/layer (zero collectives inside the kernel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.ops.paged_decode import (
    dense_flash_decode,
    fused_supported,
    paged_flash_decode,
)
from chainermn_tpu.serving import Request, Scheduler, ServingEngine

pytestmark = pytest.mark.skipif(
    not fused_supported(),
    reason="this jax's Pallas lacks scalar-prefetch grid specs "
    "(the engine falls back with forced:jax-compat)",
)

VOCAB = 32


def _ref_attend(q, keys, vals, positions, live_key_mask, window=None):
    """The XLA slot-decode attend math (transformer._slot_decode_attend)
    over an explicit dense view + key liveness mask — the equivalence
    yardstick for the kernel."""
    B, T, Hq, D = q.shape
    Hkv = keys.shape[2]
    L = keys.shape[1]
    pos_l = np.arange(L)
    qpos = positions[:, None] + np.arange(T)
    mask = pos_l[None, None, :] <= qpos[:, :, None]
    if window is not None:
        mask &= pos_l[None, None, :] > qpos[:, :, None] - window
    mask &= live_key_mask[:, None, :]
    g = Hq // Hkv
    qq = q.reshape(B, T, Hkv, g, D)
    s = np.einsum("btngd,blnd->btngl", qq.astype(np.float64),
                  keys.astype(np.float64)) * (D ** -0.5)
    s = np.where(mask[:, :, None, None, :], s, -np.inf)
    with np.errstate(invalid="ignore"):
        w = np.exp(s - s.max(-1, keepdims=True))
        w = np.nan_to_num(w / w.sum(-1, keepdims=True))
    o = np.einsum("btngl,blnd->btngd", w, vals.astype(np.float64))
    return o.reshape(B, T, Hq, D).astype(np.float32)


def _pool_case(rs, B=3, T=1, Hq=4, Hkv=4, D=8, nb=14, bs=8, M=4,
               poison=1e9):
    """A pool with POISONED scratch block 0 and per-row tables that mix
    live blocks, scratch entries past the live span, and rows at
    different depths."""
    kp = rs.randn(nb, bs, Hkv, D).astype(np.float32)
    vp = rs.randn(nb, bs, Hkv, D).astype(np.float32)
    kp[0] = poison  # released-slot / beyond-horizon garbage by contract
    vp[0] = poison
    tables = np.zeros((B, M), np.int32)
    free = list(range(1, nb))
    positions = np.zeros((B,), np.int32)
    for b in range(B):
        depth = int(rs.randint(0, M * bs - T))
        positions[b] = depth
        n_live = depth // bs + 1
        for j in range(n_live):
            tables[b, j] = free.pop(0)
    q = rs.randn(B, T, Hq, D).astype(np.float32)
    return q, kp, vp, tables, positions

def _dense_view(kp, vp, tables, bs):
    B, M = tables.shape
    keys = kp[tables].reshape(B, M * bs, kp.shape[2], kp.shape[3])
    vals = vp[tables].reshape(B, M * bs, vp.shape[2], vp.shape[3])
    live = np.repeat(tables != 0, bs, axis=1)  # scratch entries dead
    return keys, vals, live


class TestKernelContract:
    @pytest.mark.parametrize("T,Hq,Hkv,window", [
        (1, 4, 4, None),      # plain decode tick
        (3, 4, 4, None),      # verify span (K+1 rows)
        (1, 4, 2, None),      # GQA
        (4, 4, 1, None),      # MQA, chunked-width span
        (2, 4, 2, 6),         # GQA + sliding window
    ])
    def test_matches_xla_math_with_scratch_masking(self, T, Hq, Hkv,
                                                   window):
        rs = np.random.RandomState(hash((T, Hq, Hkv)) % 2**31)
        q, kp, vp, tables, positions = _pool_case(
            rs, T=T, Hq=Hq, Hkv=Hkv)
        got = np.asarray(paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(tables), jnp.asarray(positions), window=window,
        ))
        keys, vals, live = _dense_view(kp, vp, tables, bs=8)
        want = _ref_attend(q, keys, vals, positions, live, window=window)
        # fp32 accumulation both sides; the poisoned scratch block makes
        # any masking leak a ~1e9 error, not a tolerance question.
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_beyond_horizon_span_rows_stay_finite_and_live_rows_exact(
        self,
    ):
        # A verify span straddling the horizon: positions + T - 1 runs
        # past M*bs. Beyond-horizon WRITES went to scratch (paged_update
        # contract); the kernel must keep every in-horizon row exact and
        # every over-the-edge row finite (the engine caps ACCEPTANCE, so
        # those rows are never consumed — but NaN would poison the jit).
        rs = np.random.RandomState(3)
        T, bs, M = 4, 8, 4
        q, kp, vp, tables, positions = _pool_case(rs, T=T)
        positions[0] = M * bs - 2  # rows 2..3 of slot 0 overhang
        tables[0] = [1, 2, 3, 4]
        got = np.asarray(paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(tables), jnp.asarray(positions),
        ))
        assert np.isfinite(got).all()
        keys, vals, live = _dense_view(kp, vp, tables, bs=bs)
        want = _ref_attend(q, keys, vals, positions, live)
        np.testing.assert_allclose(got[:, :2], want[:, :2],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(got[1:], want[1:],
                                   rtol=2e-5, atol=2e-5)

    def test_released_slot_all_scratch_row_emits_zero(self):
        # A released slot's table row is all scratch: every block is
        # masked, l stays 0, and the row must emit EXACT zeros (the
        # fully-masked-row finalize guard) — not 1e9 garbage.
        rs = np.random.RandomState(4)
        q, kp, vp, tables, positions = _pool_case(rs, B=2)
        tables[1] = 0
        positions[1] = 0
        got = np.asarray(paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(tables), jnp.asarray(positions),
        ))
        assert np.all(got[1] == 0.0)
        keys, vals, live = _dense_view(kp, vp, tables, bs=8)
        want = _ref_attend(q, keys, vals, positions, live)
        np.testing.assert_allclose(got[0], want[0], rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("slots", [None, "explicit"])
    def test_dense_wrapper_matches_dense_math(self, slots):
        rs = np.random.RandomState(5)
        B, T, Hq, Hkv, D, L = 3, 2, 4, 2, 8, 32
        n_cache = 5 if slots else B
        ck = rs.randn(n_cache, L, Hkv, D).astype(np.float32)
        cv = rs.randn(n_cache, L, Hkv, D).astype(np.float32)
        q = rs.randn(B, T, Hq, D).astype(np.float32)
        positions = np.array([0, 7, 29], np.int32)
        slot_ids = (np.array([4, 0, 2], np.int32) if slots
                    else np.arange(B, dtype=np.int32))
        got = np.asarray(dense_flash_decode(
            jnp.asarray(q), jnp.asarray(ck), jnp.asarray(cv),
            jnp.asarray(positions),
            slots=None if slots is None else jnp.asarray(slot_ids),
            window=9,
        ))
        keys, vals = ck[slot_ids], cv[slot_ids]
        live = np.ones((B, L), bool)
        want = _ref_attend(q, keys, vals, positions, live, window=9)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_stacked_tp_pools_share_the_program(self):
        # Leading stack axis (the copy_block convention): shared tables/
        # positions, per-shard pools and q — output == per-shard calls,
        # zero collectives by construction (no mesh in sight).
        rs = np.random.RandomState(6)
        q, kp, vp, tables, positions = _pool_case(rs, Hq=4, Hkv=2)
        qs = np.stack([q, 2 * q])
        kps = np.stack([kp, 0.5 * kp])
        vps = np.stack([vp, -vp])
        got = np.asarray(paged_flash_decode(
            jnp.asarray(qs), jnp.asarray(kps), jnp.asarray(vps),
            jnp.asarray(tables), jnp.asarray(positions),
        ))
        assert got.shape == qs.shape
        for s in range(2):
            want = np.asarray(paged_flash_decode(
                jnp.asarray(qs[s]), jnp.asarray(kps[s]),
                jnp.asarray(vps[s]), jnp.asarray(tables),
                jnp.asarray(positions),
            ))
            np.testing.assert_allclose(got[s], want, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Engine equivalence: fused streams == sequential generate
# ---------------------------------------------------------------------------

def tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=16,
               d_ff=32, max_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32), train=False
    )
    return model, params


def _requests(n, seed=0, max_prompt=7, max_new=6):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        p_len = int(rs.randint(1, max_prompt))
        out.append((rs.randint(1, VOCAB, size=p_len).tolist(),
                    int(rs.randint(1, max_new))))
    return out


def _generate_ref(model, params, prompt, n_new):
    return np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32),
        len(prompt) + n_new,
    ))[0].tolist()


def _run_stream(engine, reqs):
    sched = Scheduler(engine, policy="fcfs")
    ids = [sched.submit(Request(prompt=p, max_new_tokens=g))
           for p, g in reqs]
    results = sched.run()
    return [results[rid]["tokens"] for rid in ids]


class TestEngineEquivalence:
    @pytest.mark.parametrize("impl,extra", [
        ("paged", {}),
        ("dense", {}),
        ("paged", {"spec_tokens": 2}),
        ("paged", {"prefill_chunk": 4}),
    ])
    def test_fused_streams_match_generate(self, lm, impl, extra):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl=impl,
            decode_attend_impl="fused", kv_block_size=8,
            prefill_buckets=(4, 8, 16), **extra,
        )
        reqs = _requests(6, seed=0)
        streams = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        # The impl is a static model field: every program's jit cache
        # stays pinned exactly where the xla engine pins it (the spec
        # arm drives the verify program instead of the plain decode).
        if "spec_tokens" in extra:
            assert engine.verify_compile_count() == 1
        else:
            assert engine.decode_compile_count() == 1

    def test_gqa_windowed_fused_stream_matches(self):
        model = tiny_lm(num_kv_heads=2, window=6)
        params = tiny_lm(num_kv_heads=2).init(
            jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32),
            train=False,
        )
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            decode_attend_impl="fused", kv_block_size=8,
            prefill_buckets=(4, 8, 16),
        )
        reqs = _requests(3, seed=5, max_prompt=10, max_new=8)
        streams = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)

    def test_sampled_fused_stream_matches_xla_stream(self, lm):
        # Counter-based keys (ISSUE 18) make the draw depend only on
        # (seed, position, logits); fp32 logits agree to tolerance, so
        # the sampled streams must be IDENTICAL across the impls.
        model, params = lm

        def stream(attend):
            engine = ServingEngine(
                model, params, num_slots=2, max_len=32,
                decode_impl="paged", decode_attend_impl=attend,
                kv_block_size=8, prefill_buckets=(4, 8),
                temperature=0.8, top_k=8, rng=jax.random.PRNGKey(42),
            )
            return _run_stream(engine, _requests(3, seed=9))

        assert stream("fused") == stream("xla")

    def test_tp_fused_stream_and_collective_counts(self, lm):
        model, params = lm
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("model",))
        reqs = _requests(5, seed=11)
        engine = ServingEngine(
            model, params, num_slots=3, max_len=32, decode_impl="paged",
            decode_attend_impl="fused", kv_block_size=8,
            prefill_buckets=(4, 8), mesh=mesh,
        )
        streams = _run_stream(engine, reqs)
        for (prompt, n_new), got in zip(reqs, streams):
            assert got == _generate_ref(model, params, prompt, n_new)
        # Structural pin: the kernel adds NOTHING to the wire — still
        # exactly 2 all-reduces/layer, zero collectives anywhere else.
        args = (
            engine._cache, engine._vars,
            jnp.zeros((3,), jnp.int32), jnp.zeros((3,), jnp.int32),
            jnp.asarray(engine._dummy_tables()),
            jnp.asarray(engine._seeds),
        )
        txt = engine._decode_step_jit.lower(*args).compile().as_text()
        assert txt.count("all-reduce(") == 2 * model.num_layers
        for op in ("all-gather(", "collective-permute(", "all-to-all(",
                   "reduce-scatter("):
            assert txt.count(op) == 0, f"unexpected {op} in decode step"

    def test_decision_provenance_and_validation(self, lm):
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            decode_attend_impl="fused", kv_block_size=8,
            prefill_buckets=(4,),
        )
        recs = [d for d in engine.decisions
                if d["name"] == "decode_attend_impl"]
        assert recs == [{"name": "decode_attend_impl",
                         "key": engine.decision_key, "winner": "fused",
                         "source": "explicit"}]
        with pytest.raises(ValueError, match="decode_attend_impl"):
            ServingEngine(
                model, params, num_slots=2, max_len=32,
                decode_impl="paged", decode_attend_impl="mosaic",
                kv_block_size=8, prefill_buckets=(4,),
            )

    def test_table_default_resolves_xla(self, lm, monkeypatch):
        # conftest pins CHAINERMN_TPU_AUTOTUNE=off → DEFAULT_TABLE: the
        # kernel must EARN adoption, so 'auto' resolves 'xla' here.
        model, params = lm
        engine = ServingEngine(
            model, params, num_slots=2, max_len=32, decode_impl="paged",
            kv_block_size=8, prefill_buckets=(4,),
        )
        assert engine.decode_attend_impl == "xla"
