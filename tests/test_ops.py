"""Op-layer tests: the Pallas flash-attention kernel (interpreter mode — the
CPU analogue of the reference's CPU-only CI paths, SURVEY.md section 4)
against plain attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.ops import (
    blockwise_attention,
    dot_product_attention,
    flash_attention,
)

B, T, H, D = 2, 64, 4, 32


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_full(causal):
    q, k, v = _qkv()
    out = flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_flash_grads_match_full():
    q, k, v = _qkv(1)

    def loss_f(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32, interpret=True
            )
            ** 2
        ).sum()

    def loss_r(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf,
        gr,
    )


def test_flash_adapts_indivisible_blocks():
    """Requested blocks that don't divide T are adapted (halved / collapsed
    to one block), never an error — and numerics are unchanged."""
    q, k, v = _qkv(2)
    out = flash_attention(q, k, v, block_q=48, block_k=48, interpret=True)
    ref = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_flash_attention_odd_sequence_lengths():
    """Sequence lengths not divisible by the large default blocks must
    still run (block sizes adapt by halving, or fall back to one block)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.ops.attention import dot_product_attention
    from chainermn_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(5)
    for T in (96, 136, 768):
        q = jnp.asarray(rng.randn(1, T, 2, 32), jnp.float32)
        out = flash_attention(q, q, q, causal=True)
        ref = dot_product_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )
        g = jax.grad(lambda x: jnp.sum(flash_attention(x, x, x)))(q)
        assert np.isfinite(np.asarray(g)).all()
