"""Op-layer tests: the Pallas flash-attention kernel (interpreter mode — the
CPU analogue of the reference's CPU-only CI paths, SURVEY.md section 4)
against plain attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.ops import (
    blockwise_attention,
    dot_product_attention,
    flash_attention,
)

B, T, H, D = 2, 64, 4, 32


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_full(causal):
    q, k, v = _qkv()
    out = flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_flash_grads_match_full():
    q, k, v = _qkv(1)

    def loss_f(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32, interpret=True
            )
            ** 2
        ).sum()

    def loss_r(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf,
        gr,
    )


def _segments(seed=7):
    """Random packed-segment ids: 3 documents of uneven length per row."""
    rng = np.random.RandomState(seed)
    seg = np.zeros((B, T), np.int32)
    for b in range(B):
        cuts = sorted(rng.choice(np.arange(4, T - 4), 2, replace=False))
        seg[b, cuts[0]:cuts[1]] = 1
        seg[b, cuts[1]:] = 2
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_mask_matches_full(causal):
    """Packed-sequence masking: flash with segment_ids == dense attention
    with the same per-document mask (composed with causal)."""
    q, k, v = _qkv(3)
    seg = _segments()
    out = flash_attention(
        q, k, v, causal=causal, segment_ids=seg,
        block_q=16, block_k=16, interpret=True,
    )
    ref = dot_product_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_segment_grads_match_full():
    q, k, v = _qkv(4)
    seg = _segments(8)

    def loss_f(q, k, v):
        return (flash_attention(
            q, k, v, causal=True, segment_ids=seg,
            block_q=16, block_k=16, interpret=True) ** 2).sum()

    def loss_r(q, k, v):
        return (dot_product_attention(
            q, k, v, causal=True, segment_ids=seg) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf,
        gr,
    )


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_flash_gqa_matches_full(kv_heads):
    """Grouped/multi-query attention: q has H heads, kv has fewer; the
    kernel shares kv blocks across the group via its index map."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, kv_heads, D))
    v = jax.random.normal(ks[2], (B, T, kv_heads, D))
    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_gqa_grads_match_full():
    """GQA backward: dk/dv group-sum across the q heads they serve."""
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, 2, D))
    v = jax.random.normal(ks[2], (B, T, 2, D))

    def loss_f(q, k, v):
        return (flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32,
            interpret=True) ** 2).sum()

    def loss_r(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf,
        gr,
    )


def _alibi_bias(n_heads, T):
    """ALiBi-style additive bias [1, H, T, T]."""
    slopes = 2.0 ** (-np.arange(1, n_heads + 1))
    dist = np.arange(T)[None, :] - np.arange(T)[:, None]
    return jnp.asarray(
        (slopes[:, None, None] * np.minimum(dist, 0)[None])[None],
        jnp.float32,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bias_matches_full(causal):
    """Additive score bias (ALiBi hook): flash == dense with the same
    bias, fwd values and q/k/v grads (static bias — zero cotangent)."""
    q, k, v = _qkv(9)
    bias = _alibi_bias(H, T)
    out = flash_attention(q, k, v, causal=causal, bias=bias,
                          block_q=16, block_k=16, interpret=True)
    ref = dot_product_attention(q, k, v, causal=causal, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    gf = jax.grad(lambda a, b, c: (flash_attention(
        a, b, c, causal=causal, bias=bias, block_q=16, block_k=16,
        interpret=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: (dot_product_attention(
        a, b, c, causal=causal, bias=bias) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf, gr,
    )


def test_flash_bias_grad_opt_in():
    """bias_grad=True materializes the true bias gradient; default is a
    zero cotangent (static-bias contract)."""
    q, k, v = _qkv(10)
    bias = _alibi_bias(H, T)

    def loss(b, grad_flag):
        return (flash_attention(q, k, v, causal=True, bias=b,
                                bias_grad=grad_flag, block_q=16,
                                block_k=16, interpret=True) ** 2).sum()

    def loss_ref(b):
        return (dot_product_attention(q, k, v, causal=True,
                                      bias=b) ** 2).sum()

    g_true = jax.grad(lambda b: loss(b, True))(bias)
    g_ref = jax.grad(loss_ref)(bias)
    np.testing.assert_allclose(np.asarray(g_true), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)
    g_zero = jax.grad(lambda b: loss(b, False))(bias)
    np.testing.assert_allclose(np.asarray(g_zero), 0.0)


def test_flash_bias_shape_validated():
    q, k, v = _qkv(11)
    with pytest.raises(ValueError, match="bias must be"):
        flash_attention(q, k, v, bias=jnp.zeros((2, H, T, T + 1)),
                        interpret=True)
    with pytest.raises(ValueError, match="bias_grad"):
        flash_attention(q, k, v, bias_grad=True, interpret=True)


def test_flash_gqa_head_mismatch_rejected():
    q = jnp.zeros((1, 16, 4, 8))
    kv = jnp.zeros((1, 16, 3, 8))
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, kv, kv, interpret=True)


def test_flash_adapts_indivisible_blocks():
    """Requested blocks that don't divide T are adapted (halved / collapsed
    to one block), never an error — and numerics are unchanged."""
    q, k, v = _qkv(2)
    out = flash_attention(q, k, v, block_q=48, block_k=48, interpret=True)
    ref = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_flash_attention_odd_sequence_lengths():
    """Sequence lengths not divisible by the large default blocks must
    still run (block sizes adapt by halving, or fall back to one block)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.ops.attention import dot_product_attention
    from chainermn_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(5)
    for T in (96, 136, 768):
        q = jnp.asarray(rng.randn(1, T, 2, 32), jnp.float32)
        out = flash_attention(q, q, q, causal=True)
        ref = dot_product_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )
        g = jax.grad(lambda x: jnp.sum(flash_attention(x, x, x)))(q)
        assert np.isfinite(np.asarray(g)).all()


# ---------------------------------------------------------------------------
# Causal sliding window (local attention)
# ---------------------------------------------------------------------------


def _window_bias(window, T):
    """Dense emulation of the sliding window: 0 inside the band
    ``0 <= i - j < window``, -inf outside (the causal flag handles j > i)."""
    i = np.arange(T)[:, None]
    j = np.arange(T)[None, :]
    band = (i - j) < window
    return jnp.asarray(
        np.where(band, 0.0, -1e30)[None, None].astype(np.float32)
    )


@pytest.mark.parametrize("window", [1, 7, 16, 64])
def test_flash_window_matches_masked_full(window):
    q, k, v = _qkv(11)
    out = flash_attention(
        q, k, v, causal=True, window=window,
        block_q=16, block_k=16, interpret=True,
    )
    ref = dot_product_attention(
        q, k, v, causal=True, bias=_window_bias(window, T)
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_flash_window_grads_match_masked_full():
    q, k, v = _qkv(12)
    window = 10

    def loss_f(q, k, v):
        return (flash_attention(
            q, k, v, causal=True, window=window,
            block_q=16, block_k=16, interpret=True,
        ) ** 2).sum()

    def loss_r(q, k, v):
        return (dot_product_attention(
            q, k, v, causal=True, bias=_window_bias(window, T)
        ) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf, gr,
    )


def test_flash_window_geq_T_equals_plain_causal():
    q, k, v = _qkv(13)
    w = flash_attention(q, k, v, causal=True, window=T,
                        block_q=16, block_k=16, interpret=True)
    c = flash_attention(q, k, v, causal=True,
                        block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(w), np.asarray(c),
                               rtol=1e-6, atol=1e-6)


def test_flash_window_composes_with_segments_and_gqa():
    ks = jax.random.split(jax.random.PRNGKey(14), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, 2, D))
    v = jax.random.normal(ks[2], (B, T, 2, D))
    seg = _segments()
    window = 9
    out = flash_attention(
        q, k, v, causal=True, window=window, segment_ids=seg,
        block_q=16, block_k=16, interpret=True,
    )
    ref = dot_product_attention(
        q, k, v, causal=True, segment_ids=seg,
        bias=_window_bias(window, T),
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_flash_window_validation():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=4, interpret=True)
    with pytest.raises(ValueError, match=">= 1"):
        flash_attention(q, k, v, causal=True, window=0, interpret=True)


@pytest.mark.parametrize("bq,bk", [(8, 16), (16, 8), (8, 8)])
def test_flash_window_banded_grid_mixed_blocks(bq, bk):
    """The band-narrowed grid must be exact for unequal block sizes and
    windows that don't align to either block edge. Each case ASSERTS the
    banding is actually active (span < n blocks) — an earlier version of
    this test used block pairs whose spans covered the whole axis, so the
    banded geometry ran nowhere."""
    from chainermn_tpu.ops.flash_attention import _band_k, _band_q

    q, k, v = _qkv(15)
    nq, nk = T // bq, T // bk
    for window in (2, 10):
        span_k, _ = _band_k(bq, bk, window, nk)
        span_q, _ = _band_q(bq, bk, window, nq)
        assert span_k < nk, f"k-banding inactive: {span_k} >= {nk}"
        assert span_q < nq, f"q-banding inactive: {span_q} >= {nq}"
        out = flash_attention(
            q, k, v, causal=True, window=window,
            block_q=bq, block_k=bk, interpret=True,
        )
        ref = dot_product_attention(
            q, k, v, causal=True, bias=_window_bias(window, T)
        )
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=1e-5, atol=1e-5,
            err_msg=f"window={window} bq={bq} bk={bk}",
        )

        def loss_f(q, k, v):
            return (flash_attention(
                q, k, v, causal=True, window=window,
                block_q=bq, block_k=bk, interpret=True,
            ) ** 2).sum()

        def loss_r(q, k, v):
            return (dot_product_attention(
                q, k, v, causal=True, bias=_window_bias(window, T)
            ) ** 2).sum()

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=f"grad window={window} bq={bq} bk={bk}",
            ),
            gf, gr,
        )


def test_flash_window_with_trainable_bias():
    """bias_grad forces the dkv kernel back to the full grid (its dbias
    output tiles every (iq, ik)); the dq kernel stays banded — gradients
    must still be exact."""
    q, k, v = _qkv(16)
    window = 12
    bias = jax.random.normal(jax.random.PRNGKey(17), (1, 1, T, T)) * 0.1

    def loss_f(q, k, v, bias):
        return (flash_attention(
            q, k, v, causal=True, window=window, bias=bias, bias_grad=True,
            block_q=16, block_k=16, interpret=True,
        ) ** 2).sum()

    def loss_r(q, k, v, bias):
        return (dot_product_attention(
            q, k, v, causal=True, bias=bias + _window_bias(window, T)
        ) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(q, k, v, bias)
    # dbias entries outside the window band are zero in the kernel but
    # nonzero-noise in the dense reference only where masked-out -> both
    # are zero there because masked softmax kills the path; compare all.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        gf, gr,
    )
