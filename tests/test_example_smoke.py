"""Smoke coverage for the examples without their own example-level test:
tiny configs, a handful of iterations — proof every documented CLI still
runs end to end on the 8-way CPU mesh (the reference ran its examples
under MPI as its de-facto integration suite, SURVEY.md section 2.8)."""

from conftest import load_example as _load_example


def test_transformer_example_smoke():
    ex = _load_example("transformer", "train_transformer_lm.py")
    ex.main([
        "--iterations", "4", "--batchsize", "8", "--seq-len", "32",
        "--num-layers", "1", "--d-model", "32",
    ])


def test_transformer_example_sequence_parallel_smoke():
    ex = _load_example("transformer", "train_transformer_lm.py")
    ex.main([
        "--iterations", "3", "--batchsize", "8", "--seq-len", "32",
        "--num-layers", "1", "--d-model", "32", "--sequence-parallel",
    ])


def test_transformer_example_rope_sp_smoke():
    """RoPE + ring sequence parallelism through the CLI (per-shard global
    positions, no table rolling)."""
    ex = _load_example("transformer", "train_transformer_lm.py")
    ex.main([
        "--iterations", "3", "--seq-len", "32", "--num-layers", "1",
        "--d-model", "32", "--sequence-parallel", "--pos-encoding", "rope",
    ])


def test_transformer_example_packed_smoke():
    """Packed-sequence LM with segment-masked flash attention AND GQA
    (VERDICT r2 item 5's done-condition: a packed-sequence LM example
    trains with flash)."""
    ex = _load_example("transformer", "train_transformer_lm.py")
    ex.main([
        "--iterations", "3", "--batchsize", "8", "--seq-len", "64",
        "--num-layers", "1", "--d-model", "32", "--packed",
        "--num-kv-heads", "2",
    ])


def test_seq2seq_example_smoke_with_bleu():
    import examples.seq2seq.seq2seq as ex

    ex.main([
        "--iterations", "30", "--batchsize", "16", "--eval",
        "--eval-size", "32",
    ])


def test_parallel_conv_example_smoke():
    import examples.parallel_convolution.train_parallel_conv as ex

    ex.main(["--iterations", "5"])


def test_imagenet_example_native_loader(tmp_path):
    """ImageNet example fed by the C++ threaded prefetch loader end to end
    (VERDICT r2 item 6: the MultiprocessIterator role exercised through the
    benchmark workload, not just unit-tested)."""
    import numpy as np

    from chainermn_tpu.native.data_loader import write_fixed_records

    hw, n = 32, 128
    rng = np.random.default_rng(0)
    path = str(tmp_path / "records.bin")
    write_fixed_records(
        path,
        rng.integers(0, 256, size=(n, hw, hw, 3), dtype=np.uint8),
        rng.integers(0, 1000, size=(n,)).astype(np.int32),
    )
    ex = _load_example("imagenet", "train_imagenet.py")
    ex.main([
        "--arch", "resnet50", "--communicator", "naive", "--iterations", "2",
        "--batchsize", "1", "--image-size", str(hw),
        "--native-loader", path,
        # the roofline's byte-cutting remat mode rides along so the
        # documented CLI path stays wired (round-4)
        "--remat", "conv",
    ])


def test_transformer_sweep_tool_smoke():
    """The MFU sweep tool (perf methodology for the tracked
    transformer_mfu metric) runs a two-variant grid on the CPU mesh —
    the legacy 'true' remat spelling (compat) and the round-4 'nothing'
    granularity — and reports step_ms + tokens/s."""
    ex = _load_example("transformer", "sweep_mfu.py")
    results = ex.main([
        "--communicator", "naive", "--layers", "2", "--d-model", "64",
        "--heads", "2", "--d-ff", "128", "--seq-len", "128",
        "--batch", "1", "--steps", "2", "--chunks", "2",
        "--blocks", "64x128", "--remat", "true,nothing",
    ])
    assert len(results) == 2
    assert all(r["tokens_per_sec"] > 0 for r in results)
    assert {r["remat"] for r in results} == {"dots", "nothing"}


def test_resnet_sweep_tool_smoke():
    """The ResNet MFU sweep tool (stage 2 of the on-chip capture; the
    remat-byte-reduction methodology behind the docs/benchmarks.md
    roofline) runs a one-variant grid on the CPU mesh."""
    ex = _load_example("imagenet", "sweep_mfu.py")
    results = ex.main([
        "--communicator", "naive", "--batches", "1", "--steps", "1",
        "--stems", "standard", "--remat", "conv",
    ])
    assert results and results[0]["images_per_sec"] > 0
    assert results[0]["remat"] == "conv"


def test_transformer_example_mlm_smoke():
    """--mlm: the bidirectional-encoder pretraining mode (round 5)."""
    ex = _load_example("transformer", "train_transformer_lm.py")
    ex.main([
        "--iterations", "3", "--batchsize", "8", "--seq-len", "32",
        "--num-layers", "1", "--d-model", "32", "--mlm",
    ])


def test_mnist_example_local_sgd_smoke():
    """--local-sgd: periodic parameter averaging through the standard
    trainer (round 5)."""
    ex = _load_example("mnist", "train_mnist.py")
    ex.main([
        "--communicator", "naive", "--iterations", "12",
        "--local-sgd", "3", "--batchsize", "64",
    ])
