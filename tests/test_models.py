"""Model-zoo tests: shapes, dtypes, and distributed-vs-single-device
equivalence for the flagship ResNet (SURVEY.md section 4's key invariant)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import MLP, ResNet18, ResNet50


class TestResNetForward:
    def test_resnet18_shapes(self):
        model = ResNet18(num_classes=10, compute_dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_resnet50_param_count(self):
        """ResNet-50/ImageNet has the canonical ~25.5M parameters."""
        x = jax.ShapeDtypeStruct((1, 224, 224, 3), jnp.float32)
        variables = jax.eval_shape(
            lambda x: ResNet50(num_classes=1000).init(
                jax.random.PRNGKey(0), x, train=False
            ),
            x,
        )
        n = sum(np.prod(l.shape) for l in jax.tree.leaves(variables["params"]))
        assert 25.4e6 < n < 25.7e6, n

    def test_bf16_compute_f32_params(self):
        model = ResNet18(num_classes=10)  # default compute_dtype=bf16
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        for leaf in jax.tree.leaves(variables["params"]):
            assert leaf.dtype == jnp.float32
        logits = model.apply(variables, x, train=False)
        assert logits.dtype == jnp.float32

    def test_remat_policy_conv_matches_plain_remat(self):
        """``remat_policy='conv'`` must change only WHAT is saved for the
        backward pass, never the math: gradients match plain remat=True
        (and the no-remat gradients) exactly. Also pins the validation of
        the knob combinations."""
        import pytest

        x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 32, 3))
        y = jax.nn.one_hot(jnp.array([0, 1, 2, 3]), 10)
        base = ResNet18(num_classes=10, compute_dtype=jnp.float32)
        base_vars = base.init(jax.random.PRNGKey(0), x, train=True)

        def grads_of(**kw):
            model = ResNet18(num_classes=10, compute_dtype=jnp.float32,
                             **kw)
            # remat renames modules (BasicBlock_N ->
            # CheckpointBasicBlock_N), which would also change flax's
            # per-module init RNG folding — so share ONE set of weights,
            # renamed to the wrapped model's keys.
            pfx = "Checkpoint" if kw.get("remat") else ""

            def rename(d):
                return {
                    (pfx + k if k.startswith("BasicBlock") else k): v
                    for k, v in d.items()
                }

            variables = {c: rename(base_vars[c]) for c in base_vars}

            def loss(params):
                logits, _ = model.apply(
                    {"params": params,
                     "batch_stats": variables["batch_stats"]},
                    x, train=True, mutable=["batch_stats"],
                )
                return jnp.mean((jax.nn.softmax(logits) - y) ** 2)

            return jax.grad(loss)(variables["params"])

        g_plain = grads_of()
        g_remat = grads_of(remat=True)
        g_conv = grads_of(remat=True, remat_policy="conv")
        # remat renames modules (BasicBlock_N -> CheckpointBasicBlock_N),
        # so compare leaves positionally (same registration order).
        for other in (g_remat, g_conv):
            a_leaves = jax.tree.leaves(g_plain)
            b_leaves = jax.tree.leaves(other)
            assert len(a_leaves) == len(b_leaves)
            for a, b in zip(a_leaves, b_leaves):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
                )

        with pytest.raises(ValueError, match="remat_policy requires"):
            ResNet18(num_classes=10, remat_policy="conv").init(
                jax.random.PRNGKey(0), x, train=False
            )
        with pytest.raises(ValueError, match="unknown remat_policy"):
            ResNet18(num_classes=10, remat=True,
                     remat_policy="covn").init(
                jax.random.PRNGKey(0), x, train=False
            )

    def test_train_mode_updates_batch_stats(self):
        model = ResNet18(num_classes=10, compute_dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        _, mutated = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        old = jax.tree.leaves(variables["batch_stats"])
        new = jax.tree.leaves(mutated["batch_stats"])
        assert any(
            not np.allclose(o, m) for o, m in zip(old, new)
        ), "batch stats should move in train mode"


class TestResNetDistributed:
    def test_sync_bn_train_step_matches_single_device(self, comm):
        """Data-parallel ResNet step over the 8-way CPU mesh == the same step
        on one device with the full batch (sync-BN makes BN stats global)."""
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        batch = 16
        x = jax.random.normal(jax.random.PRNGKey(0), (batch, 16, 16, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, 10)

        def build(axis_name):
            model = ResNet18(
                num_classes=10,
                compute_dtype=jnp.float32,
                bn_axis_name=axis_name,
            )
            variables = model.init(
                jax.random.PRNGKey(42), x[:2], train=True
            )
            return model, variables

        # --- distributed: 8-shard mesh, sync-BN over 'data'
        model_d, vars_d = build(comm.bn_axis_name)

        def loss_fn(params, batch_, model_state):
            xb, yb = batch_
            logits, mutated = model_d.apply(
                {"params": params, "batch_stats": model_state},
                xb,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()
            return loss, ({}, mutated["batch_stats"])

        opt = optax.sgd(0.1)

        # --- single device reference first (the distributed step donates and
        # consumes its input buffers): full batch, local BN
        model_s, _ = build(None)

        def loss_s(params, model_state):
            logits, mutated = model_s.apply(
                {"params": params, "batch_stats": model_state},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mutated["batch_stats"]

        grads, _ = jax.grad(loss_s, has_aux=True)(
            vars_d["params"], vars_d["batch_stats"]
        )
        updates, _ = opt.update(grads, opt.init(vars_d["params"]))
        expected_params = optax.apply_updates(vars_d["params"], updates)

        # --- distributed step
        state = create_train_state(
            vars_d["params"], opt, model_state=vars_d["batch_stats"]
        )
        step = make_train_step(loss_fn, opt, comm)
        new_state, metrics = step(state, (x, y))

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
            new_state.params,
            expected_params,
        )


class TestImageNetFamily:
    def test_alexnet_shapes(self):
        from chainermn_tpu.models import AlexNet

        model = AlexNet(num_classes=10, compute_dtype=jnp.float32,
                        dropout_rate=0.0)
        x = jnp.ones((2, 224, 224, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)

    def test_googlenet_shapes(self):
        from chainermn_tpu.models import GoogLeNet

        model = GoogLeNet(num_classes=10, compute_dtype=jnp.float32)
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)

    def test_googlenetbn_has_batch_stats(self):
        from chainermn_tpu.models import GoogLeNet

        model = GoogLeNet(num_classes=10, use_bn=True,
                          compute_dtype=jnp.float32)
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        assert "batch_stats" in variables


def test_resnet_space_to_depth_stem_shapes():
    """s2d stem: same [H/4, W/4, 64] stem output contract as the standard
    7x7+maxpool stem; full model trains a step with finite grads."""
    import optax

    from chainermn_tpu.models import ResNet18

    model = ResNet18(num_classes=10, compute_dtype=jnp.float32,
                     stem="space_to_depth")
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=True)

    def loss(p):
        logits, _ = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]}, x,
            train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray([1, 2])
        ).mean()

    g = jax.grad(loss)(variables["params"])
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
    # indivisible spatial dims are rejected loudly
    import pytest

    bad = jnp.zeros((1, 30, 32, 3))
    with pytest.raises(ValueError, match="divisible by 4"):
        model.init(jax.random.key(0), bad, train=True)


class TestVisionTransformer:
    def test_shapes_and_pooling(self):
        from chainermn_tpu.models import VisionTransformer

        x = jnp.ones((2, 32, 32, 3))
        for pool, n_extra in (("mean", 0), ("cls", 1)):
            m = VisionTransformer(
                num_classes=10, num_layers=2, d_model=64, num_heads=2,
                d_ff=128, patch_size=8, compute_dtype=jnp.float32,
                pool=pool,
            )
            p = m.init(jax.random.PRNGKey(0), x, train=False)
            assert m.apply(p, x, train=False).shape == (2, 10)
            assert p["params"]["pos_embed"].shape == (1, 16 + n_extra, 64)

    def test_vit_s16_canonical_param_count(self):
        """Default config is ViT-S/16: ~22M params at 224² (the public
        figure — a wiring bug in the patch/pos/block composition would
        move it)."""
        from chainermn_tpu.models import VisionTransformer

        shapes = jax.eval_shape(
            lambda k: VisionTransformer().init(
                k, jnp.zeros((1, 224, 224, 3)), train=False
            ),
            jax.random.PRNGKey(0),
        )
        n = sum(v.size for v in jax.tree.leaves(shapes))
        assert 21.5e6 < n < 22.5e6, n

    def test_remat_matches_plain(self):
        from chainermn_tpu.models import VisionTransformer

        x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 32, 3))
        m = VisionTransformer(
            num_classes=10, num_layers=2, d_model=64, num_heads=2,
            d_ff=128, patch_size=8, compute_dtype=jnp.float32,
        )
        p = m.init(jax.random.PRNGKey(0), x, train=False)
        plain = m.apply(p, x, train=False)
        for policy in ("dots", "nothing"):
            rem = m.clone(remat=True, remat_policy=policy)
            np.testing.assert_allclose(
                np.asarray(rem.apply(p, x, train=False)),
                np.asarray(plain), rtol=1e-6, atol=1e-6,
            )

    def test_rejects_indivisible_image(self):
        from chainermn_tpu.models import VisionTransformer

        m = VisionTransformer(patch_size=16)
        with pytest.raises(ValueError, match="divisible"):
            m.init(jax.random.PRNGKey(0), jnp.ones((1, 30, 30, 3)),
                   train=False)

    def test_dp_train_step_matches_single_device(self, comm):
        """The suite invariant for the new family: one data-parallel step
        over the 8-way mesh == the same step on one device with the full
        batch (values AND grads — the step compares updated params)."""
        from chainermn_tpu.models import VisionTransformer
        from chainermn_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
        model = VisionTransformer(
            num_classes=10, num_layers=2, d_model=64, num_heads=2,
            d_ff=128, patch_size=8, compute_dtype=jnp.float32,
        )
        variables = model.init(jax.random.PRNGKey(42), x[:2], train=True)
        opt = optax.sgd(0.1)

        def loss_of(params, xb, yb):
            logits = model.apply({"params": params}, xb, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        grads = jax.grad(loss_of)(variables["params"], x, y)
        updates, _ = opt.update(grads, opt.init(variables["params"]))
        expected = optax.apply_updates(variables["params"], updates)

        def loss_fn(params, batch_, model_state):
            xb, yb = batch_
            return loss_of(params, xb, yb), ({}, model_state)

        state = create_train_state(variables["params"], opt,
                                   model_state={})
        step = make_train_step(loss_fn, opt, comm)
        new_state, _ = step(state, (x, y))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=2e-4, atol=2e-5),
            new_state.params, expected,
        )


def test_vit_with_flash_attention_matches_reference(comm):
    """ViT + the flash kernel in its non-causal form (interpret mode):
    the pluggable-attention contract across families — outputs must
    match the materialised reference attention to bf16-accumulation
    tolerance."""
    from chainermn_tpu.models import VisionTransformer
    from chainermn_tpu.ops.flash_attention import flash_attention

    def flash(q, k, v, *, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=8, block_k=16, interpret=True)

    x = jax.random.normal(jax.random.PRNGKey(5), (2, 32, 32, 3))
    kw = dict(num_classes=10, num_layers=2, d_model=64, num_heads=2,
              d_ff=128, patch_size=8, compute_dtype=jnp.float32)
    ref = VisionTransformer(**kw)
    fl = VisionTransformer(**kw, attention_fn=flash)
    p = ref.init(jax.random.PRNGKey(0), x, train=False)
    np.testing.assert_allclose(
        np.asarray(fl.apply(p, x, train=False)),
        np.asarray(ref.apply(p, x, train=False)),
        rtol=2e-4, atol=2e-4,
    )
