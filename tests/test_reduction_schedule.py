"""ISSUE 3 — the overlapped hierarchical gradient-reduction pipeline.

Covers, per the repo's conventions (dist==single equivalence for every
distributed feature; structural/HLO-level assertions for communication
claims; measured, not asserted in prose):

- bucket-partition edge contract (zero-size leaves, sub-bucket
  payloads, oversized leaves — the satellite fix's unit cases);
- dist == single equivalence (values AND gradients) for all three
  schedules (flat / two_level / zero), through the real train step;
- double-buffered mode bit-matches a hand-rolled one-step-stale
  reference loop (the reference ``double_buffering_optimizer.py``
  (dagger) semantics, as an executable model rather than prose);
- compiled-HLO collective counts pinned per schedule (the
  ppermute-count convention);
- per-bucket ``wire`` trace events (layout + overlapped flag) and the
  eager :class:`OverlappedBucketReducer`'s measured events feeding
  ``summarize_overlap``;
- the ``'auto'`` schedule resolution through the tuning registry.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.observability import trace
from chainermn_tpu.parallel.reduction_schedule import (
    SCHEDULES,
    OverlappedBucketReducer,
    bucket_partition,
    reduce_tree,
    resolve_schedule,
)

N = 8


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


@pytest.fixture(autouse=True)
def _recorder_off():
    trace.disable()
    yield
    trace.disable()


# ----------------------------------------------------------------------
# Bucket partition edge contract (satellite fix)
# ----------------------------------------------------------------------


class TestBucketPartition:
    def test_payload_smaller_than_bucket_is_one_bucket(self):
        out = bucket_partition([0, 1, 2], [10, 20, 30], 4, 1 << 20)
        assert out == [[0, 1, 2]]

    def test_zero_size_entries_are_skipped_never_empty_buckets(self):
        # all-zero payload: NO buckets (the old code emitted one bucket
        # whose concatenated payload was empty — no max-abs for the
        # int8 scale)
        assert bucket_partition([0, 1], [0, 0], 4, 1 << 20) == []
        # mixed: zero-size entries vanish, the rest keep their layout
        out = bucket_partition([0, 1, 2, 3], [5, 0, 7, 0], 4, 1 << 20)
        assert out == [[0, 2]]
        assert all(b for b in out)  # no empty bucket, ever

    def test_oversized_entry_gets_its_own_bucket_unsplit(self):
        big = (1 << 20)  # 4 MB at itemsize 4 vs 1 MB bucket
        out = bucket_partition([0, 1, 2], [4, big, 4], 4, 1 << 20)
        assert out == [[0], [1], [2]]

    def test_no_degenerate_tail_after_oversized_entry(self):
        big = (1 << 20)
        out = bucket_partition([0, 1], [big, 4], 4, 1 << 20)
        assert out == [[0], [1]]
        assert all(b for b in out)

    def test_float_bucket_partition_wrapper_shares_the_contract(self):
        from chainermn_tpu.optimizers import _float_bucket_partition

        assert _float_bucket_partition([0, 1], [0, 3]) == [[1]]
        assert _float_bucket_partition([0], [0]) == []

    def test_ef_optimizer_survives_zero_size_float_leaf(self, comm):
        """The regression the fix exists for: an EF int8 optimizer with
        a zero-size float leaf must not quantize an empty bucket."""
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm,
            allreduce_grad_dtype=jnp.int8, error_feedback=True,
        )
        params = {"w": jnp.zeros((4,), jnp.float32),
                  "empty": jnp.zeros((0,), jnp.float32)}
        grads = {"w": jnp.full((4,), 0.5, jnp.float32),
                 "empty": jnp.zeros((0,), jnp.float32)}
        state = opt.init(params)

        @jax.jit
        def step(g):
            def body(g):
                updates, _ = opt.update(g, state, params)
                return updates

            return shard_map(
                body, mesh=comm.mesh, in_specs=P(),
                out_specs=P(), check_vma=False,
            )(g)

        updates = step(grads)
        np.testing.assert_allclose(
            np.asarray(updates["w"]), -0.5 * np.ones(4), rtol=2e-2
        )
        assert updates["empty"].shape == (0,)


# ----------------------------------------------------------------------
# dist == single equivalence, all schedules (values AND gradients)
# ----------------------------------------------------------------------


def _loss_fn(p, batch):
    xb, yb = batch
    logits = xb @ p["w"] + p["b"]
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, yb
    ).mean()


def _train(c, params, batch, *, steps=3, inner=None, **opt_kwargs):
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    opt = create_multi_node_optimizer(
        inner if inner is not None else optax.adam(1e-2), c, **opt_kwargs
    )
    state = create_train_state(params, opt, c)
    step = make_train_step(_loss_fn, opt, c, donate=False)
    for _ in range(steps):
        state, m = step(state, batch)
    return jax.device_get(state.params), float(m["loss"])


class TestScheduleEquivalence:
    @pytest.fixture(scope="class")
    def problem(self, comm):
        rs = np.random.RandomState(0)
        params = {"w": jnp.asarray(rs.randn(5, 3), jnp.float32),
                  "b": jnp.asarray(rs.randn(3), jnp.float32)}
        x = jnp.asarray(rs.randn(16, 5), jnp.float32)
        y = jnp.asarray(np.arange(16) % 3, np.int32)
        return params, (x, y)

    @pytest.mark.parametrize("schedule", SCHEDULES)
    def test_dist_equals_single_values_and_gradients(
        self, comm, problem, schedule
    ):
        """The suite's core invariant, per schedule: the 8-slot
        distributed trajectory (gradients reduced by THIS schedule)
        equals the single-slot one and the legacy default."""
        params, batch = problem
        dist_p, dist_l = _train(comm, params, batch,
                                reduction_schedule=schedule)
        single_p, single_l = _train(comm.sub_communicator([0]), params,
                                    batch, reduction_schedule=schedule)
        legacy_p, legacy_l = _train(comm, params, batch)
        for k in params:
            np.testing.assert_allclose(dist_p[k], single_p[k],
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(dist_p[k], legacy_p[k],
                                       rtol=1e-5, atol=1e-6)
        assert abs(dist_l - single_l) < 1e-6
        assert abs(dist_l - legacy_l) < 1e-6

    def test_two_level_matches_on_two_axis_mesh(self, problem):
        from jax.sharding import Mesh
        from chainermn_tpu.communicators.xla_communicator import (
            HierarchicalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        c2 = HierarchicalCommunicator(mesh=Mesh(devs, ("inter", "intra")))
        params, batch = problem
        p2, l2 = _train(c2, params, batch, reduction_schedule="two_level")
        p1, l1 = _train(c2, params, batch)  # legacy fused pmean
        for k in params:
            np.testing.assert_allclose(p2[k], p1[k], rtol=1e-5, atol=1e-6)
        assert abs(l2 - l1) < 1e-6

    def test_zero_schedule_state_is_sharded_1_over_n(self, comm, problem):
        """The point of 'zero': each shard holds 1/n of the adam state
        (stacked [n, ceil(size/n)] leaves, sharded over the data axis)."""
        from chainermn_tpu.training.train_step import create_train_state

        params, _ = problem
        opt = create_multi_node_optimizer(
            optax.adam(1e-2), comm, reduction_schedule="zero"
        )
        state = create_train_state(params, opt, comm)
        mu = state.opt_state.inner[0].mu
        for k, leaf in params.items():
            chunk = -(-leaf.size // N)
            assert mu[k].shape == (N, chunk), (k, mu[k].shape)
        spec = opt.opt_state_spec()
        assert spec.inner == P(comm.grad_axes[-1])

    def test_zero_schedule_eager_degrade_matches_full_update(
        self, comm, problem
    ):
        """Outside any named-axis context the zero schedule runs the
        vectorised per-chunk update with NO collective — elementwise
        inner => exactly the full-parameter update."""
        params, _ = problem
        g = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)
        opt = create_multi_node_optimizer(
            optax.adam(1e-2), comm, reduction_schedule="zero"
        )
        ref = optax.adam(1e-2)
        state, rstate = opt.init(params), ref.init(params)
        for _ in range(2):
            u, state = jax.jit(opt.update)(g, state, params)
            ru, rstate = ref.update(g, rstate, params)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
                ),
                u, ru,
            )

    def test_zero_schedule_rejects_unsharded_state_in_context(
        self, comm, problem
    ):
        """A replicated (closed-over) zero state inside shard_map would
        silently update the WRONG chunk — the guard must name the fix."""
        params, _ = problem
        opt = create_multi_node_optimizer(
            optax.adam(1e-2), comm, reduction_schedule="zero"
        )
        state = opt.init(params)  # stacked [n, ...], NOT sharded
        g = jax.tree.map(jnp.ones_like, params)

        def body(gg):
            return opt.update(gg, state, params)[0]

        with pytest.raises(ValueError, match="opt_state_spec"):
            jax.jit(shard_map(
                body, mesh=comm.mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            ))(g)

    def test_zero_rejects_incompatible_compositions(self, comm):
        with pytest.raises(ValueError, match="double_buffering"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm, reduction_schedule="zero",
                double_buffering=True,
            )
        with pytest.raises(ValueError, match="int8"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm, reduction_schedule="zero",
                allreduce_grad_dtype=jnp.int8,
            )
        with pytest.raises(ValueError, match="error_feedback"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm, reduction_schedule="two_level",
                allreduce_grad_dtype=jnp.int8, error_feedback=True,
            )
        with pytest.raises(ValueError, match="reduction_schedule"):
            create_multi_node_optimizer(
                optax.sgd(0.1), comm, reduction_schedule="ring"
            )


# ----------------------------------------------------------------------
# Double buffering: the stale-update reference model, bit-matched
# ----------------------------------------------------------------------


def test_double_buffer_matches_stale_update_reference_model(comm):
    """An EXECUTABLE reference model of chainermn's documented one-step
    staleness (``double_buffering_optimizer.py`` (dagger)): a
    hand-rolled loop carrying ``bank`` — step t applies ``bank`` (the
    t-1 mean), then banks step t's mean — must bit-match the
    double-buffered optimizer over multiple steps of VARYING gradients.
    The per-step means come from the eager communicator (identical
    psum arithmetic), so the model is independent of the optimizer
    wrapper under test."""
    rs = np.random.RandomState(7)
    steps = 4
    grads_per_step = [rs.randn(N, 6).astype(np.float32) for _ in range(steps)]
    params0 = jnp.zeros((6,), jnp.float32)
    lr = 1.0

    opt = create_multi_node_optimizer(
        optax.sgd(lr), comm, double_buffering=True
    )
    mesh, axes = comm.mesh, comm.grad_axes
    state = opt.init(params0)
    params = params0

    @jax.jit
    def step(params, state, gstack):
        def body(gl):
            updates, new_state = opt.update(gl[0], state, params)
            return optax.apply_updates(params, updates), new_state

        return shard_map(body, mesh=mesh, in_specs=P(axes),
                         out_specs=P(), check_vma=False)(gstack)

    for g in grads_per_step:
        params, state = step(params, state, jnp.asarray(g))

    # Hand-rolled stale-update loop: identical reduction arithmetic via
    # the eager wire, staleness written out literally.
    bank = np.zeros((6,), np.float32)
    ref = np.zeros((6,), np.float32)
    for g in grads_per_step:
        ref = ref - lr * bank                       # apply step t-1's mean
        bank = np.asarray(comm.allreduce_grad(jnp.asarray(g)))  # bank t's
    np.testing.assert_array_equal(np.asarray(params), ref)
    # and the bank in the optimizer state is the LAST step's mean, exactly
    np.testing.assert_array_equal(
        np.asarray(state.communicated_grads), bank
    )


# ----------------------------------------------------------------------
# Structural: compiled-HLO collective counts per schedule
# ----------------------------------------------------------------------


def _compiled_counts(comm, fn, tree, spec_tree=None):
    """Compile fn under shard_map over comm's mesh; count collectives."""
    axes = comm.grad_axes

    def local(t):
        sq = jax.tree.map(lambda l: l[0], t)
        out = fn(sq)
        return jax.tree.map(lambda l: l[None], out)

    spec = jax.tree.map(
        lambda l: P(axes, *([None] * (l.ndim - 1))), tree
    )
    f = jax.jit(shard_map(local, mesh=comm.mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False))
    txt = f.lower(tree).compile().as_text()
    return {op: txt.count(op) for op in
            ("reduce-scatter(", "all-gather(", "all-reduce(")}


class TestStructural:
    def test_flat_schedule_is_one_allreduce_per_bucket(self, comm):
        tree = {"w": jnp.ones((N, 64, 32)), "b": jnp.ones((N, 32))}
        counts = _compiled_counts(
            comm,
            lambda t: reduce_tree(t, schedule="flat", axes=comm.grad_axes,
                                  compress_dtype=jnp.bfloat16),
            tree,
        )
        assert counts == {"reduce-scatter(": 0, "all-gather(": 0,
                          "all-reduce(": 1}, counts

    def test_two_level_on_flat_mesh_is_rs_plus_ag(self, comm):
        """On a 1-axis mesh the two_level schedule pins the decomposed
        reduce-scatter -> all-gather form: NO all-reduce survives."""
        tree = {"w": jnp.ones((N, 64, 32)), "b": jnp.ones((N, 32))}
        counts = _compiled_counts(
            comm,
            lambda t: reduce_tree(t, schedule="two_level",
                                  axes=comm.grad_axes,
                                  compress_dtype=jnp.bfloat16),
            tree,
        )
        assert counts == {"reduce-scatter(": 1, "all-gather(": 1,
                          "all-reduce(": 0}, counts

    def test_two_level_on_two_axis_mesh_is_rs_ar_ag(self):
        """2-axis mesh: intra reduce-scatter -> inter all-reduce of the
        shard -> intra all-gather, exactly once per bucket (the existing
        TwoDimensionalCommunicator pins, now via the shared layer)."""
        from jax.sharding import Mesh
        from chainermn_tpu.communicators.xla_communicator import (
            TwoDimensionalCommunicator,
        )

        devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
        c2 = TwoDimensionalCommunicator(
            mesh=Mesh(devs, ("inter", "intra"))
        )
        tree = {"w": jnp.ones((8, 16, 8)), "b": jnp.ones((8, 8))}

        def local(t):
            sq = jax.tree.map(lambda l: l[0], t)
            out = reduce_tree(sq, schedule="two_level", axes=c2.grad_axes,
                              compress_dtype=jnp.bfloat16)
            return jax.tree.map(lambda l: l[None], out)

        spec = jax.tree.map(
            lambda l: P(("inter", "intra"), *([None] * (l.ndim - 1))),
            tree,
        )
        f = jax.jit(shard_map(local, mesh=c2.mesh, in_specs=(spec,),
                              out_specs=spec, check_vma=False))
        txt = f.lower(tree).compile().as_text()
        counts = {op: txt.count(op) for op in
                  ("reduce-scatter(", "all-gather(", "all-reduce(")}
        assert counts == {"reduce-scatter(": 1, "all-gather(": 1,
                          "all-reduce(": 1}, counts

    def test_zero_schedule_is_rs_plus_ag_per_leaf_no_allreduce(self, comm):
        """The sharded-update pipeline: one reduce-scatter in, one
        all-gather out per parameter leaf, and NO gradient all-reduce
        anywhere in the reduction+update program."""
        from chainermn_tpu.testing import count_primitives

        params = {"w": jnp.ones((5, 3), jnp.float32),
                  "b": jnp.ones((3,), jnp.float32)}
        opt = create_multi_node_optimizer(
            optax.adam(1e-2), comm, reduction_schedule="zero"
        )
        full = opt.init(params)
        sliced = jax.tree.map(lambda e: e[:1], full)
        g = jax.tree.map(jnp.ones_like, params)
        counts = count_primitives(
            lambda gg: opt.update(gg, sliced, params)[0], g,
            axis_env=[(comm.axis_name, N)],
        )
        assert counts.get("reduce_scatter") == 2    # one per leaf
        assert counts.get("all_gather") == 2
        assert not counts.get("psum")               # no grad all-reduce

    def test_wire_events_record_bucket_layout_and_overlap_flag(self, comm):
        """Per-bucket, per-STAGE trace-time wire events: schedule label,
        composition signature, stage payload bytes, and overlapped=True
        exactly under double buffering."""
        from chainermn_tpu.testing import count_primitives

        rec = trace.enable(None)
        tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
        env = [(comm.axis_name, N)]
        count_primitives(
            lambda t: reduce_tree(t, schedule="two_level",
                                  axes=comm.grad_axes,
                                  compress_dtype=jnp.bfloat16),
            tree, axis_env=env,
        )
        wires = [e for e in rec.events if e["kind"] == "wire"]
        # on the flat mesh two_level IS rs(data)>ag(data): one wire
        # event per stage, both carrying the composition signature
        assert len(wires) == 2
        assert [w["stage"] for w in wires] == ["rs(data)", "ag(data)"]
        assert all(w["schedule"] == "two_level" for w in wires)
        assert all(w["composition"] == "rs(data)>ag(data)" for w in wires)
        # both stages carry the full bucket payload (in / out of the
        # scatter frame) on the bf16 wire
        assert all(w["nbytes"] == (64 * 32 + 32) * 2 for w in wires)
        assert all(w["overlapped"] is False for w in wires)

        # the double-buffered optimizer tags its buckets overlapped
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm, double_buffering=True
        )
        state = opt.init(jnp.zeros((8,)))
        count_primitives(
            lambda g: opt.update(g, state, jnp.zeros((8,)))[0],
            jnp.ones((8,)), axis_env=env,
        )
        wires = [e for e in rec.events if e["kind"] == "wire"]
        assert wires[-1]["overlapped"] is True
        assert wires[-1]["schedule"] == "flat"

    def test_recorder_does_not_change_the_scheduled_program(self, comm):
        """The observability invariant holds for the new schedules:
        identical jaxpr with the recorder on and off."""
        from chainermn_tpu.testing import count_primitives

        tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
        env = [(comm.axis_name, N)]

        def counts(schedule):
            return count_primitives(
                lambda t: reduce_tree(t, schedule=schedule,
                                      axes=comm.grad_axes),
                tree, axis_env=env,
            )

        off = {s: counts(s) for s in ("flat", "two_level")}
        trace.enable(None)
        on = {s: counts(s) for s in ("flat", "two_level")}
        assert on == off


# ----------------------------------------------------------------------
# 'auto' resolution + provenance
# ----------------------------------------------------------------------


class TestAutoResolution:
    def test_table_default_is_flat_with_provenance(self, comm, monkeypatch):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
        winner, rec = resolve_schedule("cpu", 3 << 20, (8,))
        assert winner == "flat"
        assert rec["name"] == "reduction_schedule"
        assert rec["source"] == "table"
        assert rec["key"].endswith("|sched")

    def test_forced_override_reaches_the_optimizer(self, comm, monkeypatch):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "reduction_schedule=zero")
        opt = create_multi_node_optimizer(
            optax.adam(1e-2), comm, reduction_schedule="auto"
        )
        params = {"w": jnp.ones((6,), jnp.float32)}
        state = opt.init(params)
        from chainermn_tpu.optimizers import _ZeroShardState

        assert isinstance(state, _ZeroShardState)
        assert opt._auto_resolved == "zero"
        assert opt._schedule_provenance["source"] == "forced"
        # resolution is one-shot: spec agrees with the state layout
        assert opt.opt_state_spec().inner == P(comm.grad_axes[-1])

    def test_auto_excludes_zero_under_double_buffering(
        self, comm, monkeypatch
    ):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "reduction_schedule=zero")
        opt = create_multi_node_optimizer(
            optax.sgd(0.1), comm, reduction_schedule="auto",
            double_buffering=True,
        )
        assert "zero" not in opt._auto_candidates
        # the forced override names a non-candidate -> loud error, not
        # a silently wrong layout
        with pytest.raises(ValueError):
            opt.init({"w": jnp.ones((4,))})


# ----------------------------------------------------------------------
# The eager overlapped per-bucket reducer (measured wire events)
# ----------------------------------------------------------------------


class TestOverlappedBucketReducer:
    def test_mean_correct_and_events_measured(self, comm):
        rec = trace.enable(None)
        rs = np.random.RandomState(1)
        stacked = {
            "a": jnp.asarray(rs.randn(N, 100), jnp.float32),
            "b": jnp.asarray(rs.randn(N, 7, 3), jnp.float32),
            "empty": jnp.zeros((N, 0), jnp.float32),
        }
        red = OverlappedBucketReducer(comm, bucket_bytes=100 * 4)
        n_buckets = red.dispatch(stacked)
        assert n_buckets == 2  # 'a' fills one bucket, 'b' the next
        assert red.in_flight
        out = red.collect()
        assert not red.in_flight
        np.testing.assert_allclose(
            np.asarray(out["a"]), np.asarray(stacked["a"]).mean(0),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(out["b"]), np.asarray(stacked["b"]).mean(0),
            rtol=1e-5, atol=1e-6,
        )
        assert out["empty"].shape == (0,)
        wires = [e for e in rec.events if e["kind"] == "wire"]
        assert len(wires) == 2
        for w in wires:
            assert w["schedule"] == "overlap_eager"
            assert w["dur_s"] >= w["blocked_s"] >= 0
        # the rollup trace_report consumes
        ov = trace.summarize_overlap(rec.events)
        assert ov["measured"]["n"] == 2
        assert 0.0 <= ov["measured"]["hidden_fraction"] <= 1.0

    def test_double_dispatch_raises(self, comm):
        red = OverlappedBucketReducer(comm)
        red.dispatch({"g": jnp.ones((N, 4))})
        with pytest.raises(RuntimeError, match="in flight"):
            red.dispatch({"g": jnp.ones((N, 4))})
        red.collect()
        with pytest.raises(RuntimeError, match="no dispatched"):
            red.collect()

    def test_measured_composed_reducer(self, comm):
        """ISSUE 13 satellite (PR 11 follow-up): the eager per-STAGE
        composed executor — mean correct for every derived pipeline,
        one measured ``wire`` event per stage carrying the composition
        signature + ``dur_s``, and the overlap rollup's per-signature
        stage rows gain the measured ``dur_ms`` column."""
        from chainermn_tpu.parallel.reduction_schedule import (
            MeasuredComposedReducer,
        )

        rec = trace.enable(None)
        rs = np.random.RandomState(5)
        stacked = {
            "a": jnp.asarray(rs.randn(N, 33), jnp.float32),
            "b": jnp.asarray(rs.randn(N, 4, 2), jnp.float32),
        }
        for sched, n_stages in (("flat", 1), ("two_level", 2)):
            red = MeasuredComposedReducer(comm, schedule=sched)
            out = red.reduce(stacked)
            jax.tree.map(
                lambda o, g: np.testing.assert_allclose(
                    np.asarray(o), np.asarray(g).mean(0),
                    rtol=1e-5, atol=1e-6,
                ),
                out, stacked,
            )
            sig = red.comp.signature()
            wires = [e for e in rec.events
                     if e["kind"] == "wire"
                     and e.get("composition") == sig]
            assert len(wires) == n_stages, (sig, wires)
            for i, w in enumerate(wires):
                assert w["schedule"] == "composed_eager"
                assert w["stage_index"] == i
                assert w["dur_s"] >= 0
                assert w["nbytes"] > 0
        ov = trace.summarize_overlap(rec.events)
        for sig, row in ov["compositions"].items():
            for st, srow in row["stages"].items():
                assert srow.get("dur_ms") is not None, (sig, st)

    def test_measured_composed_refuses_update_stage(self, comm):
        from chainermn_tpu.parallel.composition import CompositionError
        from chainermn_tpu.parallel.reduction_schedule import (
            MeasuredComposedReducer,
        )

        with pytest.raises(CompositionError, match="sharded_update"):
            MeasuredComposedReducer(comm, schedule="zero")

    def test_staleness_one_loop_matches_reference(self, comm):
        """The reducer's intended double-buffered usage: dispatch step
        t, collect at t+1 — each step's mean arrives exactly once, one
        step late (the async-host reducer's contract, device plane)."""
        rs = np.random.RandomState(3)
        gs = [jnp.asarray(rs.randn(N, 5), jnp.float32) for _ in range(3)]
        red = OverlappedBucketReducer(comm)
        got = []
        for g in gs:
            if red.in_flight:
                got.append(np.asarray(red.collect()))
            red.dispatch(g)
        got.append(np.asarray(red.collect()))
        for g, m in zip(gs, got):
            np.testing.assert_allclose(
                m, np.asarray(g).mean(0), rtol=1e-5, atol=1e-6
            )


# ----------------------------------------------------------------------
# overlap_config plumbing (train step -> trainer -> trace)
# ----------------------------------------------------------------------


def test_trainer_emits_overlap_config(comm):
    from chainermn_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )
    from chainermn_tpu.training.trainer import Trainer

    rec = trace.enable(None)
    params = {"w": jnp.zeros((4, 3), jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}
    opt = create_multi_node_optimizer(
        optax.sgd(0.1), comm, double_buffering=True,
        reduction_schedule="two_level",
    )
    state = create_train_state(params, opt, comm)
    step = make_train_step(_loss_fn, opt, comm, donate=False)
    data = [
        [(np.ones((4,), np.float32), np.int32(0)) for _ in range(8)]
        for _ in range(2)
    ]

    class It:
        def __iter__(self):
            return iter(data)

    def collate(batch):
        x = np.stack([b[0] for b in batch])
        y = np.stack([b[1] for b in batch])
        return x, y

    tr = Trainer(step, state, It(), comm, collate=collate,
                 out=open(os.devnull, "w"))
    tr.run(2)
    cfgs = [e for e in rec.events if e["kind"] == "overlap_config"]
    assert len(cfgs) == 1
    assert cfgs[0]["double_buffering"] is True
    assert cfgs[0]["staleness"] == 1
    assert cfgs[0]["schedule"] == "two_level"


# ----------------------------------------------------------------------
# ISSUE 15: sliced eager reducers + the comp_slices decision
# ----------------------------------------------------------------------


class TestSlicedEagerReducers:
    def test_overlapped_reducer_sliced_mean_and_slice_events(self, comm):
        """slices=4: one collective flies PER SLICE (the real async
        interleave), each wire event carries its slice address beside
        dur_s/blocked_s, the mean is exact, and the rollup still
        yields a hidden_fraction."""
        rec = trace.enable(None)
        rs = np.random.RandomState(2)
        stacked = {
            "a": jnp.asarray(rs.randn(N, 100), jnp.float32),
            "b": jnp.asarray(rs.randn(N, 7, 3), jnp.float32),
            "empty": jnp.zeros((N, 0), jnp.float32),
        }
        red = OverlappedBucketReducer(comm, bucket_bytes=100 * 4,
                                      slices=4)
        n_buckets = red.dispatch(stacked)
        assert n_buckets == 2
        out = red.collect()
        for k in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(out[k]), np.asarray(stacked[k]).mean(0),
                rtol=1e-5, atol=1e-6,
            )
        assert out["empty"].shape == (0,)
        wires = [e for e in rec.events if e["kind"] == "wire"]
        assert len(wires) == 8  # 2 buckets x 4 slices
        for w in wires:
            assert w["schedule"] == "overlap_eager"
            assert w["n_slices"] == 4 and 0 <= w["slice"] < 4
            assert w["dur_s"] >= w["blocked_s"] >= 0
        ov = trace.summarize_overlap(rec.events)
        assert ov["measured"]["n"] == 8
        assert 0.0 <= ov["measured"]["hidden_fraction"] <= 1.0

    def test_overlapped_reducer_slice_degrade(self, comm):
        """A 3-element bucket under slices=8 flies 3 collectives —
        min(S, elements), never a zero-size one (the zero-leaf
        contract on the eager path)."""
        rec = trace.enable(None)
        red = OverlappedBucketReducer(comm, slices=8)
        red.dispatch({"g": jnp.ones((N, 3), jnp.float32)})
        out = red.collect()
        np.testing.assert_allclose(np.asarray(out["g"]),
                                   np.ones(3), rtol=1e-6)
        wires = [e for e in rec.events if e["kind"] == "wire"]
        assert len(wires) == 3
        assert all(w["n_slices"] == 3 and w["nbytes"] > 0
                   for w in wires)
        with pytest.raises(ValueError, match="slices"):
            OverlappedBucketReducer(comm, slices=0)

    def test_measured_composed_reducer_sliced(self, comm):
        """The sliced measured executor: 3 stages x 4 slices of wire
        events in skewed order, every one carrying slice address +
        dur_s + blocked_s, the mean exact, and summarize_overlap's
        per-signature stage rows growing the per-slice sub-table with
        measured dur_ms/blocked_ms."""
        from chainermn_tpu.parallel.reduction_schedule import (
            MeasuredComposedReducer,
        )

        rec = trace.enable(None)
        rs = np.random.RandomState(6)
        stacked = {
            "a": jnp.asarray(rs.randn(N, 33), jnp.float32),
            "b": jnp.asarray(rs.randn(N, 4, 2), jnp.float32),
        }
        red = MeasuredComposedReducer(comm, schedule="two_level",
                                      slices=4)
        sig = red.comp.signature()
        assert "[s0..3]" in sig
        out = red.reduce(stacked)
        jax.tree.map(
            lambda o, g: np.testing.assert_allclose(
                np.asarray(o), np.asarray(g).mean(0),
                rtol=1e-5, atol=1e-6,
            ),
            out, stacked,
        )
        wires = [e for e in rec.events
                 if e["kind"] == "wire" and e.get("composition") == sig]
        n_stages = len(red.comp.stages)
        assert len(wires) == n_stages * 4
        for i, w in enumerate(wires):
            assert w["stage_index"] == i
            assert w["n_slices"] == 4 and 0 <= w["slice"] < 4
            assert w["dur_s"] >= 0 and w["blocked_s"] >= 0
            assert w["nbytes"] > 0
        # skew: slice 1's rs event precedes slice 0's inter-level ar
        stages_in_order = [(w["stage"], w["slice"]) for w in wires]
        rs_name = red.comp.stages[0].signature()
        ar_name = red.comp.stages[1].signature()
        assert stages_in_order.index((rs_name, 1)) < \
            stages_in_order.index((ar_name, 0))
        ov = trace.summarize_overlap(rec.events)
        row = ov["compositions"][sig]
        for st, srow in row["stages"].items():
            assert srow["n"] == 4, (st, srow)
            slices = srow["slices"]
            assert set(slices) == {"s0", "s1", "s2", "s3"}
            for sl in slices.values():
                assert sl.get("dur_ms") is not None
                assert sl.get("blocked_ms") is not None

    def test_measured_composed_reducer_zigzag(self, comm):
        """ISSUE 16: the eager measured executor honors the zigzag cut
        — strided slice membership on the way in, comb reassembly on
        the way out, mean still exact."""
        from chainermn_tpu.parallel.reduction_schedule import (
            MeasuredComposedReducer,
        )

        rs = np.random.RandomState(16)
        stacked = {"a": jnp.asarray(rs.randn(N, 37), jnp.float32)}
        sig = "rs(a0)[z0..3]>ag(a0)"
        red = MeasuredComposedReducer(comm, schedule=sig)
        assert red.comp.slice_layout == "zigzag"
        out = red.reduce(stacked)
        np.testing.assert_allclose(
            np.asarray(out["a"]), np.asarray(stacked["a"]).mean(0),
            rtol=1e-5, atol=1e-6,
        )

    def test_measured_composed_sliced_degrade(self, comm):
        from chainermn_tpu.parallel.reduction_schedule import (
            MeasuredComposedReducer,
        )

        rec = trace.enable(None)
        red = MeasuredComposedReducer(comm, schedule="two_level",
                                      slices=8)
        out = red.reduce({"g": jnp.ones((N, 3), jnp.float32)})
        np.testing.assert_allclose(np.asarray(out["g"]), np.ones(3),
                                   rtol=1e-6)
        wires = [e for e in rec.events
                 if e["kind"] == "wire" and e.get("composition")]
        # min(8, 3) slices x the pipeline's stages (2 on a flat mesh)
        assert len(wires) == 3 * len(red.comp.stages)
        assert all(w["n_slices"] == 3 for w in wires)


class TestCompSlicesDecision:
    def test_table_default_is_one(self, monkeypatch):
        from chainermn_tpu.parallel.reduction_schedule import (
            resolve_comp_slices,
        )

        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
        assert resolve_comp_slices("cpu", 3 << 20, (2, 2, 2)) == 1
        # ...and the auto schedule resolution stays unsliced
        winner, rec = resolve_schedule("cpu", 3 << 20, (2, 2, 2),
                                       slices="auto")
        assert winner == "flat"
        assert "comp_slices" not in (rec or {})

    def test_forced_slices_slice_the_auto_winner(self, monkeypatch):
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "comp_slices=4")
        winner, rec = resolve_schedule("cpu", 3 << 20, (2, 2, 2),
                                       slices="auto")
        assert winner == "ar(a0+a1+a2)[s0..3]"
        assert rec["comp_slices"] == 4
        assert rec["composition"] == winner
        # an explicit integer pins without consulting the registry
        winner2, rec2 = resolve_schedule("cpu", 3 << 20, (2, 2, 2),
                                         slices=2)
        assert winner2 == "ar(a0+a1+a2)[s0..1]"
        # slices=None (the default) is the pre-ISSUE-15 behaviour
        winner3, _ = resolve_schedule("cpu", 3 << 20, (2, 2, 2))
        assert winner3 == "flat"

    def test_sliced_auto_winner_runs_through_the_optimizer(
        self, comm, monkeypatch
    ):
        """End to end: a forced comp_slices=2 'auto' optimizer reduces
        a dyadic tree identically to the flat schedule — the sliced
        winner compiles and runs through the standard update path."""
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "table")
        monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE_FORCE",
                           "comp_slices=2")
        opt = create_multi_node_optimizer(
            optax.sgd(0.5), comm, reduction_schedule="auto"
        )
        params = {"w": jnp.asarray(
            np.arange(N * 24).reshape(N, 24) % 8, jnp.float32) / 8.0}

        def local(p):
            sq = {"w": p["w"][0]}
            sched = opt._effective_schedule(sq)
            out = opt._reduce_scheduled(sq, sched)
            return {"w": out["w"][None]}

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        f = jax.jit(shard_map(
            local, mesh=comm.mesh,
            in_specs=({"w": P(comm.grad_axes, None)},),
            out_specs={"w": P(comm.grad_axes, None)},
            check_vma=False,
        ))
        out = jax.device_get(f(params))
        assert "[s0..1]" in opt._auto_resolved
        assert opt._schedule_provenance["comp_slices"] == 2
        ref = np.asarray(params["w"]).reshape(N, -1).mean(0)
        np.testing.assert_array_equal(out["w"].reshape(N, -1)[0], ref)


def test_sliced_wire_events_and_pack_degrade_note(comm):
    """ISSUE 15: trace-time events of a SLICED in-jit schedule — one
    wire event per stage per slice (slice/n_slices fields, per-slice
    payloads summing to the unsliced stage bytes), and the pack event
    carrying the requested slice count plus the LOUD min(S, elements)
    degrade provenance when a bucket is smaller than S."""
    from chainermn_tpu.testing import count_primitives

    rec = trace.enable(None)
    tree = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
    env = [(comm.axis_name, N)]
    sig = "rs(data)[s0..3]>ag(data)"
    count_primitives(
        lambda t: reduce_tree(t, schedule=sig, axes=comm.grad_axes,
                              compress_dtype=jnp.bfloat16),
        tree, axis_env=env,
    )
    wires = [e for e in rec.events if e["kind"] == "wire"]
    assert len(wires) == 8  # 2 stages x 4 slices
    assert all(w["composition"] == sig for w in wires)
    assert all(w["n_slices"] == 4 and 0 <= w["slice"] < 4
               for w in wires)
    per_stage: dict = {}
    for w in wires:
        per_stage[w["stage"]] = per_stage.get(w["stage"], 0) + w["nbytes"]
    total = (64 * 32 + 32) * 2  # the unsliced bucket on the bf16 wire
    assert per_stage == {"rs(data)": total, "ag(data)": total}
    pack = [e for e in rec.events if e["kind"] == "pack"][-1]
    assert pack["comp_slices"] == 4
    assert "comp_slices_degraded" not in pack  # 2080 elems >> 4

    # degrade: a 3-element payload under S=4 → 3 slices, loud note
    rec2 = trace.enable(None)
    count_primitives(
        lambda t: reduce_tree(t, schedule=sig, axes=comm.grad_axes),
        {"b": jnp.zeros((3,))}, axis_env=env,
    )
    pack2 = [e for e in rec2.events if e["kind"] == "pack"][-1]
    assert pack2["comp_slices"] == 4
    assert pack2["comp_slices_degraded"] == {0: 3}
    assert "min(S, elements)" in pack2["comp_slices_note"]
    wires2 = [e for e in rec2.events if e["kind"] == "wire"]
    assert len(wires2) == 6  # 2 stages x min(4, 3) slices
    assert all(w["n_slices"] == 3 and w["nbytes"] > 0 for w in wires2)
